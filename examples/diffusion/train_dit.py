"""Minimal DiT diffusion training example (reference §2.4: diffusion row —
examples/diffusion + NeMoAutoDiffusionPipeline).

Trains a small class-conditional DiT with the DDPM epsilon loss on random
latents (swap `make_batch` for a real latent dataset). Runs on CPU devices
or the chip:

    python examples/diffusion/train_dit.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.diffusion import AutoDiffusionPipeline, DiTConfig, DiTModel, make_diffusion_loss
from automodel_tpu.models.common.config import BackendConfig
from automodel_tpu.optim.builders import build_optimizer
from automodel_tpu.parallel.mesh import MeshConfig, build_mesh
from automodel_tpu.training.train_state import TrainState
from automodel_tpu.training.train_step import build_train_step


def make_batch(rng, b, cfg):
    return {
        "x": np.asarray(rng.normal(size=(1, b, cfg.image_size, cfg.image_size, cfg.in_channels)), np.float32),
        "y": np.asarray(rng.integers(0, cfg.num_classes, (1, b)), np.int32),
        "step_seed": np.asarray(rng.integers(0, 1 << 30, (1, 1)), np.int32),
    }


def main():
    ctx = build_mesh(MeshConfig(dp_shard=-1))
    cfg = DiTConfig(image_size=32, patch_size=4, in_channels=4,
                    hidden_size=256, num_layers=4, num_heads=4, num_classes=10)
    model = DiTModel(cfg, BackendConfig(param_dtype="float32", compute_dtype="float32"))
    pipe = AutoDiffusionPipeline.from_components(
        {"transformer": (model, model.init(jax.random.PRNGKey(0)))}, ctx,
    )
    model, params = pipe["transformer"]
    loss_fn = make_diffusion_loss(model)
    opt = build_optimizer(name="adamw", lr=1e-4)
    state = TrainState.create(params, jax.jit(opt.init)(params))
    step = build_train_step(loss_fn, opt)
    rng = np.random.default_rng(0)
    for i in range(20):
        state, m = step(state, make_batch(rng, 8, cfg))
        if i % 5 == 0:
            print(f"step {i}: loss={float(m['loss']):.4f}")
    print("final loss:", float(m["loss"]))


if __name__ == "__main__":
    main()
