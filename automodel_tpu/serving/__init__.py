"""Serving subsystem: continuous batching + paged KV-cache scheduler over
the generation engine (docs/serving.md).

- :mod:`block_pool` — ref-counted fixed-size KV block allocator with
  chain-hashed prefix caching.
- :mod:`paged` — jitted chunked-prefill, paged-decode (fused Pallas
  kernel or XLA-gather fallback, bf16/int8 pools), and the speculative
  draft-propose/verify programs.
- :mod:`engine` — the continuous-batching scheduler (admission queue,
  chunked prefill interleaved with the decode wave, mid-flight slot
  refill, speculative decoding with per-slot accept/rollback).
- :mod:`server` — the `automodel_tpu serve` CLI (stdin-JSONL + local HTTP).
"""

from automodel_tpu.serving.block_pool import BlockPool, BlockPoolError
from automodel_tpu.serving.engine import (
    COMPLETION_REASONS,
    DrainConfig,
    EngineDraining,
    LimitsConfig,
    QueueFull,
    ServeConfig,
    ServingEngine,
    SpeculativeConfig,
    StallConfig,
)

__all__ = [
    "BlockPool",
    "BlockPoolError",
    "COMPLETION_REASONS",
    "DrainConfig",
    "EngineDraining",
    "LimitsConfig",
    "QueueFull",
    "ServeConfig",
    "ServingEngine",
    "SpeculativeConfig",
    "StallConfig",
]
