"""Serving subsystem: continuous batching + paged KV-cache scheduler over
the generation engine (docs/serving.md).

- :mod:`block_pool` — ref-counted fixed-size KV block allocator with
  chain-hashed prefix caching.
- :mod:`paged` — jitted chunked-prefill, paged-decode (fused Pallas
  kernel or XLA-gather fallback, bf16/int8 pools), and the speculative
  draft-propose/verify programs.
- :mod:`engine` — the continuous-batching scheduler (admission queue,
  chunked prefill interleaved with the decode wave, mid-flight slot
  refill, speculative decoding with per-slot accept/rollback).
- :mod:`server` — the `automodel_tpu serve` CLI (stdin-JSONL + local HTTP).
- :mod:`fleet` — the multi-replica tier: the `automodel_tpu route` router
  (prefix-affinity placement, disaggregated prefill/decode, failure-aware
  retry) and the prefill→decode KV socket transport.

Exports resolve lazily (PEP 562): the fleet router imports
``serving.block_pool.prompt_chain`` through this package and must NOT drag
in :mod:`engine`'s jax import — a router pod needs no accelerator and
starts in milliseconds.
"""

import importlib

_EXPORTS = {
    "BlockPool": "block_pool",
    "BlockPoolError": "block_pool",
    "prompt_chain": "block_pool",
    "COMPLETION_REASONS": "engine",
    "DrainConfig": "engine",
    "EngineDraining": "engine",
    "KVTransferConfig": "engine",
    "LimitsConfig": "engine",
    "QueueFull": "engine",
    "ServeConfig": "engine",
    "ServingEngine": "engine",
    "SpeculativeConfig": "engine",
    "StallConfig": "engine",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(
        importlib.import_module(f"{__name__}.{mod}"), name
    )
    globals()[name] = value  # cache: resolve once per process
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
