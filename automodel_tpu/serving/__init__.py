"""Serving subsystem: continuous batching + paged KV-cache scheduler over
the generation engine (docs/serving.md).

- :mod:`block_pool` — ref-counted fixed-size KV block allocator with
  chain-hashed prefix caching.
- :mod:`paged` — jitted chunked-prefill and paged-decode programs
  (block-table gather feeding the existing cached-attention path).
- :mod:`engine` — the continuous-batching scheduler (admission queue,
  chunked prefill interleaved with the decode wave, mid-flight slot refill).
- :mod:`server` — the `automodel_tpu serve` CLI (stdin-JSONL + local HTTP).
"""

from automodel_tpu.serving.block_pool import BlockPool, BlockPoolError
from automodel_tpu.serving.engine import (
    COMPLETION_REASONS,
    DrainConfig,
    EngineDraining,
    LimitsConfig,
    QueueFull,
    ServeConfig,
    ServingEngine,
    StallConfig,
)

__all__ = [
    "BlockPool",
    "BlockPoolError",
    "COMPLETION_REASONS",
    "DrainConfig",
    "EngineDraining",
    "LimitsConfig",
    "QueueFull",
    "ServeConfig",
    "ServingEngine",
    "StallConfig",
]
