"""`automodel_tpu route` — the fleet router above N serving replicas.

One ``ServingEngine`` is bounded by one chip's HBM; the router is the tier
that spreads heavy traffic over a fleet (docs/serving.md "Fleet"). It
keeps the SAME HTTP front contract as a single replica (POST /generate,
GET /stats /healthz /readyz /metrics), so a client — or a load balancer —
cannot tell a routed fleet from one engine.

Placement is **prefix affinity first, load second**:

1. The prompt's block chain is hashed with the SAME chain rule the
   replica's prefix cache keys its blocks under
   (:func:`automodel_tpu.serving.block_pool.prompt_chain` — deterministic
   across processes), and the replica whose advertised hot-prefix set
   (the ``hot_prefixes`` /stats field) contains the LONGEST match wins:
   its pool already holds the prompt's KV, so routing there turns a
   per-replica coin flip into a guaranteed hit.
2. No match → power-of-two-choices: two random ready replicas, the less
   loaded one (queue depth + busy slots from /stats) takes the request —
   near-best-of-N balancing at O(1) probe cost.

**Disaggregated prefill/decode** (Splitwise/DistServe): replicas declare a
role (``serving.role: prefill|decode|mixed``). When the fleet has prefill
replicas, a long prompt's math runs on one of them (POST /prefill), the
finished KV block rows stream to the chosen decode replica over the
:mod:`kv_transfer` socket transport, and the decode replica starts the
request directly in decode — long prompts never steal decode throughput.
A strong affinity hit (the decode replica already holds ≥ half the prompt)
bypasses the handoff entirely: recomputing the short tail is cheaper than
shipping it.

**Failure-aware retry**: every replica-side terminal record carries
``completion_reason`` + ``retriable`` (PR 9). The router resubmits
retriable failures (replica death, ``engine_stall``, shed, draining) to a
DIFFERENT replica within a bounded per-request ``retry_budget`` — a
replica killed mid-decode loses zero requests (tests/test_serving_chaos.py
pins it). Client-budget expiries (``timeout``) are never retried.

This module imports no jax: a router pod needs no accelerator.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import zlib
from typing import Any, Callable, Optional, Sequence

from automodel_tpu.serving.block_pool import prompt_chain

logger = logging.getLogger(__name__)

RETRY_AFTER_S = 5

# QoS tier order — MUST match serving/engine.py TIERS (not imported: the
# engine module pulls jax, and a router pod needs no accelerator). Unknown
# tiers rank as interactive here; the replica rejects them with a 400.
_TIER_ORDER = {"interactive": 0, "batch": 1, "best_effort": 2}


def _tier_label(tier: Any) -> str:
    """Bounded metrics label for a request's tier (arbitrary client
    strings must not mint label values)."""
    return tier if tier in _TIER_ORDER else "interactive"


def _tier_retry_after(tier: Any) -> int:
    """Tier-scaled Retry-After advice (mirror of serving/server.py):
    lower tiers back off longer, so freed capacity goes uphill first."""
    return RETRY_AFTER_S * (_TIER_ORDER.get(tier, 0) + 1)


def aggregate_qos(snapshots: Sequence[dict]) -> dict:
    """Fleet-wide QoS rollup: sum the per-replica /stats ``qos`` blocks
    (engine ``qos_snapshot``) into one queued/outcome view by tier and by
    tenant. Pure — fleet-status and its unit tests call it directly."""
    agg: dict = {
        "enabled": False,
        "queued_by_tier": {},
        "queued_by_tenant": {},
        "tiers": {},
        "tenants": {},
    }

    def _merge(dst: dict, src: dict) -> None:
        for k, v in (src or {}).items():
            if isinstance(v, dict):
                _merge(dst.setdefault(k, {}), v)
            elif v is not None:
                dst[k] = dst.get(k, 0) + v

    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        agg["enabled"] = agg["enabled"] or bool(snap.get("enabled"))
        for key in ("queued_by_tier", "queued_by_tenant", "tiers", "tenants"):
            _merge(agg[key], snap.get(key) or {})
    return agg


class ReplicaUnreachable(RuntimeError):
    """TCP-level failure talking to a replica (dead pod, reset socket):
    always retriable — the request never reached a scheduler."""


def _trace_headers(ctx) -> Optional[dict]:
    """traceparent header for a forward, or None when untraced. Lazy
    import: the tracing module is stdlib-only, but the router's import
    graph stays as small as it was."""
    if ctx is None:
        return None
    from automodel_tpu.telemetry.tracing import to_traceparent

    return {"traceparent": to_traceparent(ctx)}


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """One static ``fleet.replicas:`` entry."""

    url: str
    name: Optional[str] = None  # metrics label; default r0, r1, ...
    role: Optional[str] = None  # pin prefill|decode|mixed; None = from /stats

    def __post_init__(self):
        if self.role not in (None, "mixed", "prefill", "decode"):
            raise ValueError(
                f"fleet replica role={self.role!r} "
                "(want mixed|prefill|decode or omit)"
            )

    @classmethod
    def from_value(cls, v: Any, index: int) -> "ReplicaSpec":
        if isinstance(v, str):
            return cls(url=v, name=f"r{index}")
        d = dict(v)
        d.pop("_target_", None)
        unknown = set(d) - {"url", "name", "role"}
        if unknown:
            raise TypeError(f"unknown fleet replica keys: {sorted(unknown)}")
        if "url" not in d:
            raise TypeError("fleet replica needs a url")
        d.setdefault("name", f"r{index}")
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """The ``fleet:`` YAML section — the router's whole world."""

    replicas: tuple = ()  # static registry: urls or {url, name?, role?}
    dns: Optional[str] = None  # k8s headless service; re-resolved per probe
    dns_port: int = 8100  # replica HTTP port behind the DNS name
    port: Optional[int] = None  # router front port (`automodel_tpu route`)
    host: str = "127.0.0.1"
    block_size: int = 16  # MUST match the replicas' serving.block_size
    probe_interval_s: float = 2.0
    probe_timeout_s: float = 2.0
    # probe backoff: a replica that failed this many CONSECUTIVE probes
    # moves to an exponential schedule (doubling from probe_interval_s,
    # bounded by probe_backoff_max_s, deterministically jittered) instead
    # of costing a probe_timeout_s thread every sweep; first success snaps
    # it back to every sweep
    probe_backoff_after: int = 3
    probe_backoff_max_s: float = 30.0
    request_timeout_s: float = 300.0
    retry_budget: int = 2  # resubmissions per request (0 = never retry)
    affinity: bool = True  # prefix-affinity placement (else pure load)
    disaggregate: Optional[bool] = None  # null = auto (prefill replicas seen)
    drain_grace_s: float = 10.0  # SIGTERM → in-flight forward budget
    seed: int = 0  # power-of-two-choices rng
    # routed bench sub-leg knobs (recipes/benchmark.py _fleet_leg)
    bench_replicas: int = 2
    bench_num_blocks: Optional[int] = None  # default: serving.num_blocks // N

    def __post_init__(self):
        if self.retry_budget < 0:
            raise ValueError(f"fleet.retry_budget={self.retry_budget}")
        if self.block_size < 1:
            raise ValueError(f"fleet.block_size={self.block_size}")
        if self.probe_backoff_after < 1:
            raise ValueError(
                f"fleet.probe_backoff_after={self.probe_backoff_after}"
            )
        if self.probe_backoff_max_s < self.probe_interval_s:
            raise ValueError(
                f"fleet.probe_backoff_max_s={self.probe_backoff_max_s} must "
                f"be >= probe_interval_s={self.probe_interval_s} — a backoff "
                "shorter than the sweep cadence is no backoff at all"
            )
        if self.bench_replicas < 2:
            raise ValueError(
                f"fleet.bench_replicas={self.bench_replicas} (want >= 2 — "
                "a one-replica fleet measures nothing the serving leg "
                "doesn't)"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "FleetConfig":
        d = dict(d or {})
        d.pop("_target_", None)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise TypeError(f"unknown fleet keys: {sorted(unknown)}")
        reps = d.get("replicas")
        if reps is not None:
            d["replicas"] = tuple(
                r if isinstance(r, ReplicaSpec) else ReplicaSpec.from_value(r, i)
                for i, r in enumerate(reps)
            )
            # the registry is keyed by name: a duplicate (copy-paste typo)
            # would silently collapse two replicas into one and halve the
            # fleet — refuse loudly instead
            names = [r.name or r.url for r in d["replicas"]]
            dupes = sorted({n for n in names if names.count(n) > 1})
            if dupes:
                raise ValueError(f"duplicate fleet replica names: {dupes}")
        return cls(**d)


@dataclasses.dataclass
class _Replica:
    """Runtime state the probe thread maintains per replica."""

    spec: ReplicaSpec
    alive: bool = False
    ready: bool = False
    role: str = "mixed"
    stats: dict = dataclasses.field(default_factory=dict)
    hot: frozenset = frozenset()  # advertised prefix-cache chain heads
    kv_port: Optional[int] = None
    block_size_ok: bool = True
    last_probe_t: Optional[float] = None
    # rolling weight update: traffic is shifted off an updating replica
    # (excluded from placement) while its weights swap — the drain
    # primitive that keeps in-flight requests alive through the update
    updating: bool = False
    # probe-backoff state: failures since the last success, and (once past
    # fleet.probe_backoff_after) the monotonic time the next probe is due
    consecutive_failures: int = 0
    next_probe_t: Optional[float] = None

    @property
    def name(self) -> str:
        return self.spec.name or self.spec.url

    @property
    def url(self) -> str:
        return self.spec.url.rstrip("/")

    @property
    def load(self) -> float:
        return float(
            (self.stats.get("queue_depth") or 0)
            + (self.stats.get("busy_slots") or 0)
        )

    def decode_capable(self) -> bool:
        return self.role in ("mixed", "decode")


def _http_json(
    url: str,
    obj: Optional[dict],
    timeout_s: float,
    headers: Optional[dict] = None,
) -> tuple[int, dict]:
    """One GET (obj None) or POST (obj) → (status, parsed body). HTTP error
    statuses return normally (the body carries the replica's structured
    rejection); TCP-level failures raise :class:`ReplicaUnreachable`.
    ``headers`` adds to the defaults (the tracing ``traceparent`` rides
    here)."""
    data = None if obj is None else json.dumps(obj).encode()
    hdrs = {} if data is None else {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=data, headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            body = json.loads(raw or b"{}")
        except ValueError:
            body = {"error": raw.decode(errors="replace")}
        return e.code, body
    except (OSError, urllib.error.URLError, ValueError) as e:
        raise ReplicaUnreachable(f"{url}: {e}") from e


def _prefix_hit_rate(stats: dict) -> Optional[float]:
    """Token-weighted prefix-hit rate from a replica's /stats allocator
    counters (None until the replica saw matchable prompt tokens)."""
    alloc = stats.get("allocator") or {}
    hit = alloc.get("prefix_hit_tokens") or 0
    miss = alloc.get("prefix_miss_tokens") or 0
    return hit / (hit + miss) if (hit + miss) > 0 else None


def _http_text(url: str, timeout_s: float) -> str:
    """One GET → decoded body (the replica /metrics scrape — Prometheus
    text, not JSON). TCP-level failures raise :class:`ReplicaUnreachable`;
    a non-200 answer does too (there is no structured body to salvage)."""
    req = urllib.request.Request(url)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            if resp.status != 200:
                raise ReplicaUnreachable(f"{url}: HTTP {resp.status}")
            return resp.read().decode("utf-8", errors="replace")
    except (OSError, urllib.error.URLError, ValueError) as e:
        raise ReplicaUnreachable(f"{url}: {e}") from e


def probe_backoff_s(
    failures: int, after: int, base_s: float, max_s: float, salt: str = ""
) -> float:
    """Delay before the NEXT probe of a replica with ``failures``
    consecutive probe failures. 0.0 below the ``after`` threshold (keep
    probing every sweep — fast detection of a blip); past it, exponential
    doubling from ``base_s`` bounded by ``max_s``, with ±25% deterministic
    jitter (crc32 of salt+failures) so a rack of replicas that died
    together does not re-probe in lockstep. Pure — the unit tests walk the
    whole schedule without a fleet."""
    if failures < after:
        return 0.0
    exp = min(failures - after, 16)  # bound the shift itself, not just
    delay = min(base_s * (2.0**exp), max_s)  # the product: 2**1000 is inf
    frac = (zlib.crc32(f"{salt}:{failures}".encode()) % 1000) / 1000.0
    return min(delay * (0.75 + 0.5 * frac), max_s)


class RouterMetrics:
    """The router's /metrics surface (telemetry/prometheus.py registry):
    the ISSUE-named counters plus per-replica health gauges."""

    def __init__(self):
        from automodel_tpu.telemetry.prometheus import (
            LATENCY_BUCKETS,
            MetricsRegistry,
        )

        self.registry = MetricsRegistry()
        # outcome label (ok / retried / unroutable / the terminal
        # completion_reason, e.g. timeout or shed): retries and failure
        # classes are visible at scrape time, not just in the JSONL
        self.requests = self.registry.labeled_counter(
            "automodel_route_requests",
            "Requests routed to a terminal response, by replica and outcome "
            "(ok | retried | unroutable | terminal completion_reason)",
            ("replica", "outcome"),
        )
        self.prefix_hits = self.registry.counter(
            "automodel_route_prefix_hits",
            "Requests placed by prefix affinity (>= 1 matched chain block)",
        )
        self.retries = self.registry.counter(
            "automodel_route_retries",
            "Retriable replica failures resubmitted to a different replica",
        )
        self.unroutable = self.registry.counter(
            "automodel_route_unroutable",
            "Requests that exhausted the retry budget or found no replica",
        )
        self.handoffs = self.registry.counter(
            "automodel_route_kv_handoffs",
            "Disaggregated prefill->decode KV transfers orchestrated",
        )
        self.replica_up = self.registry.labeled_gauge(
            "automodel_route_replica_up",
            "1 when the replica answered its last /readyz probe, else 0",
            "replica",
        )
        self.replicas_ready = self.registry.gauge(
            "automodel_route_replicas_ready",
            "Ready replicas in the registry right now",
        )
        # elastic fleet (serving/fleet/autoscale.py): target vs actual is
        # the first thing to look at when a fleet feels the wrong size
        self.autoscale_target = self.registry.gauge(
            "automodel_route_autoscale_target_replicas",
            "Replica count the autoscaler currently wants (0 until the "
            "first tick; tracks actual between scale events)",
        )
        self.autoscale_events = self.registry.labeled_counter(
            "automodel_route_autoscale_events",
            "Scale events executed by the autoscaler, by direction "
            "(up | down)",
            "direction",
        )
        # multi-tenant QoS: terminal outcomes by tier — the router-front
        # mirror of the replicas' automodel_serve_tier_requests, so
        # per-tier burn is observable even for requests no replica ever
        # accepted (unroutable)
        self.tier_requests = self.registry.labeled_counter(
            "automodel_route_tier_requests",
            "Requests routed to a terminal response, by QoS tier and "
            "outcome (ok | retried | unroutable | terminal "
            "completion_reason)",
            ("tier", "outcome"),
        )
        self.latency = self.registry.labeled_histogram(
            "automodel_route_request_seconds",
            "Router-observed request latency (submit to terminal response), "
            "by outcome",
            "outcome",
            buckets=LATENCY_BUCKETS,
        )
        # per-stage latency from the router's trace spans (placement /
        # prefill_rpc / forward / probe_sweep) — the router-front mirror of
        # the replicas' automodel_serve_stage_seconds
        self.stage_seconds = self.registry.labeled_histogram(
            "automodel_route_stage_seconds",
            "Per-stage latency from router trace spans, by stage name",
            "stage",
            buckets=LATENCY_BUCKETS,
        )

    def observe_stage(self, stage: str, duration_s: float) -> None:
        """Tracer ``observe`` hook — every emitted router span lands in the
        per-stage histogram."""
        if duration_s < 0:
            return
        self.stage_seconds.observe(stage, duration_s)


class Router:
    """Replica registry + placement + retry. Thread-safe: HTTP handler
    threads call :meth:`handle_generate` concurrently while the probe
    thread refreshes replica state."""

    def __init__(
        self,
        config: FleetConfig,
        tokenizer: Any = None,
        on_record: Optional[Callable[[dict], None]] = None,
        tracer: Any = None,
        slo_config: Any = None,
        flight_recorder: Any = None,
        autoscale_config: Any = None,
        scale_backend: Any = None,
    ):
        self.config = config
        self.tokenizer = tokenizer
        self.on_record = on_record
        self.metrics = RouterMetrics()
        # request tracing: the router MINTS the trace for each request
        # (unless the client already sent a traceparent) and propagates it
        # on every forward — spans ride on_record like route_request records
        self.tracer = tracer
        if tracer is not None and tracer.observe is None:
            tracer.observe = self.metrics.observe_stage
        # one wall anchor per process (shared with the tracer when there is
        # one): record timestamps are monotonic-derived, never raw wall
        from automodel_tpu.telemetry.tracing import WallAnchor

        self._clock = tracer.clock if tracer is not None else WallAnchor()
        # fleet health plane (telemetry/federation.py + slo.py): every
        # probe sweep also scrapes each replica's /metrics, rolls the
        # snapshots into fleet-level series, and (when an `slo:` section is
        # configured) evaluates the burn-rate objectives against them
        from automodel_tpu.telemetry.federation import Federation

        retention = (
            slo_config.retention_s if slo_config is not None else 900.0
        )
        self.federation = Federation(retention_s=retention)
        self.slo = None
        if slo_config is not None and slo_config.objectives:
            from automodel_tpu.telemetry.slo import SLOEngine

            self.slo = SLOEngine(
                slo_config,
                self.federation,
                registry=self.metrics.registry,
                emit=on_record,
                flight_recorder=flight_recorder,
                wall=self._clock.wall,
            )
        self._lock = threading.Lock()
        self._replicas: dict[str, _Replica] = {}
        for spec in config.replicas:
            self._replicas[spec.name or spec.url] = _Replica(
                spec=spec, role=spec.role or "mixed"
            )
        if not self._replicas and not config.dns:
            raise ValueError(
                "fleet: needs replicas (static list) or dns (k8s headless "
                "service) — the router has nothing to route to"
            )
        self._rng = random.Random(config.seed)
        self._ids = itertools.count()
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self.draining = False
        # plain-int mirrors of the /metrics counters for /stats + bench
        self.requests_total = 0
        self.completed_total = 0
        self.retries_total = 0
        self.prefix_hits_total = 0
        self.unroutable_total = 0
        self.handoffs_total = 0
        self.peer_hints_total = 0  # forwarded kv_peer prefix-fetch hints
        self._warned_block_size: set[str] = set()
        # rolling weight update progress, surfaced on /stats while (and
        # after) an update runs so version skew is observable fleet-wide
        self._rolling: Optional[dict] = None
        # elastic fleet: the hysteresis state machine rides the probe
        # sweep; the backend (local subprocesses or kubectl scale) is how
        # decisions become replicas (serving/fleet/autoscale.py)
        self.autoscaler = None
        self.scale_backend = scale_backend
        if autoscale_config is not None and autoscale_config.enabled:
            from automodel_tpu.serving.fleet.autoscale import Autoscaler

            self.autoscaler = Autoscaler(autoscale_config)

    # -- registry / probing ---------------------------------------------------
    def _resolve_dns(self) -> None:
        """k8s headless-service discovery: every A record behind
        ``fleet.dns`` is a replica pod. Re-resolved each probe cycle so
        scale-ups join and deleted pods leave without a router restart."""
        import socket as socket_mod

        try:
            infos = socket_mod.getaddrinfo(
                self.config.dns, self.config.dns_port,
                proto=socket_mod.IPPROTO_TCP,
            )
        except OSError as e:
            logger.warning("fleet.dns %s resolution failed: %s", self.config.dns, e)
            return
        ips = sorted({info[4][0] for info in infos})
        current = {f"dns-{ip.replace('.', '-').replace(':', '-')}": ip for ip in ips}
        with self._lock:
            for name in [
                n for n, r in self._replicas.items()
                if n.startswith("dns-") and n not in current
            ]:
                del self._replicas[name]
            for name, ip in current.items():
                if name not in self._replicas:
                    host = f"[{ip}]" if ":" in ip else ip
                    self._replicas[name] = _Replica(
                        spec=ReplicaSpec(
                            url=f"http://{host}:{self.config.dns_port}",
                            name=name,
                        )
                    )

    def probe_once(self) -> None:
        """One probe sweep: /readyz for health, /stats for load + roles +
        hot prefixes + the KV-transfer port. Replicas probe CONCURRENTLY:
        sequentially, every dead pod would cost a full probe_timeout_s and
        a large fleet's sweep (and the synchronous ``start()``) would take
        O(N × timeout) — instead the whole sweep is bounded at roughly one
        probe timeout."""
        t_probe0 = time.perf_counter()
        if self.config.dns:
            self._resolve_dns()
        now = time.monotonic()
        with self._lock:
            all_reps = list(self._replicas.values())
            # probe backoff: replicas deep in consecutive failure are only
            # due on their exponential schedule — the rest of the sweep
            # stops paying a probe_timeout_s thread for every corpse
            reps = [
                r for r in all_reps
                if r.next_probe_t is None or now >= r.next_probe_t
            ]
        threads = [
            threading.Thread(
                target=self._probe_replica, args=(rep,), daemon=True
            )
            for rep in reps
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ready = sum(1 for r in all_reps if r.ready)
        self.metrics.replicas_ready.set(ready)
        # health plane tick: fold this sweep's scrapes into the fleet
        # series, then judge the SLO objectives against them. Both are
        # bounded host-side work; a bug in either must not kill probing.
        try:
            self.federation.roll(time.monotonic())
            if self.slo is not None:
                self.slo.evaluate(time.monotonic())
        except Exception:
            logger.exception("fleet health-plane tick failed")
        # elastic fleet: the autoscaler evaluates once per sweep, right
        # after the federation rolled this sweep's scrapes in — its
        # signals are at most one sweep stale. Its bugs must not kill
        # probing either.
        try:
            self._autoscale_tick(time.monotonic())
        except Exception:
            logger.exception("autoscale tick failed")
        if self.tracer is not None:
            # probe sweeps are router-lifecycle work, not request work:
            # each sweep is its own single-span trace (sampled like any
            # root), so sweep latency shows up in the stage histogram and
            # the span JSONL without polluting request waterfalls
            self.tracer.record(
                self.tracer.start(), "probe_sweep", t_probe0,
                replicas=len(reps), ready=ready,
            )

    def _probe_replica(self, rep: "_Replica") -> None:
        alive, ready, stats = False, False, rep.stats
        try:
            code, _ = _http_json(
                rep.url + "/readyz", None, self.config.probe_timeout_s
            )
            alive = True
            _, stats = _http_json(
                rep.url + "/stats", None, self.config.probe_timeout_s
            )
            # ready only when BOTH legs answered: a replica that died
            # between /readyz and /stats must not be published as ready
            # with stale stats for a whole probe interval
            ready = code == 200
        except ReplicaUnreachable:
            alive, ready = False, False
        # fleet health plane: the /metrics scrape rides the same sweep — a
        # replica that answers probes but whose scrape fails (or fails to
        # parse) just drops out of this sweep's rollup; routing is
        # unaffected
        if alive:
            try:
                body = _http_text(
                    rep.url + "/metrics", self.config.probe_timeout_s
                )
                self.federation.ingest(rep.name, body, time.monotonic())
            except ReplicaUnreachable as e:
                logger.warning("replica %s /metrics scrape failed: %s", rep.name, e)
                self.federation.mark_down(rep.name)
            except ValueError as e:  # ExpositionParseError — counted inside
                logger.warning("replica %s /metrics unparseable: %s", rep.name, e)
        else:
            self.federation.mark_down(rep.name)
        with self._lock:
            rep.alive, rep.ready = alive, ready
            rep.last_probe_t = time.monotonic()
            if alive:
                # first success snaps a backed-off replica straight back
                # to every-sweep probing — recovery is never rate-limited
                rep.consecutive_failures = 0
                rep.next_probe_t = None
            else:
                rep.consecutive_failures += 1
                delay = probe_backoff_s(
                    rep.consecutive_failures,
                    self.config.probe_backoff_after,
                    self.config.probe_interval_s,
                    self.config.probe_backoff_max_s,
                    salt=rep.name,
                )
                rep.next_probe_t = (
                    time.monotonic() + delay if delay > 0 else None
                )
            if alive:
                rep.stats = stats
                rep.role = rep.spec.role or stats.get("role") or rep.role
                rep.kv_port = stats.get("kv_transfer_port")
                hot = stats.get("hot_prefixes")
                rep.hot = (
                    frozenset(int(h) for h in hot)
                    if isinstance(hot, list) else frozenset()
                )
                rbs = stats.get("block_size")
                rep.block_size_ok = (
                    rbs is None or int(rbs) == self.config.block_size
                )
                if (
                    not rep.block_size_ok
                    and rep.name not in self._warned_block_size
                ):
                    self._warned_block_size.add(rep.name)
                    logger.warning(
                        "replica %s serves block_size=%s but "
                        "fleet.block_size=%d — prefix affinity is OFF "
                        "for it (chain hashes cannot match)",
                        rep.name, rbs, self.config.block_size,
                    )
        self.metrics.replica_up.set(rep.name, 1.0 if ready else 0.0)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # a probe bug must not kill routing
                logger.exception("replica probe sweep failed")
            self._stop.wait(self.config.probe_interval_s)

    def start(self) -> "Router":
        """Probe immediately (so the first request can route), then keep
        probing in the background."""
        self.probe_once()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-probe", daemon=True
        )
        self._probe_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)

    def _mark_down(self, rep: _Replica) -> None:
        with self._lock:
            rep.alive = False
            rep.ready = False
        self.metrics.replica_up.set(rep.name, 0.0)

    # -- elastic fleet (serving/fleet/autoscale.py, docs/serving.md) ----------
    def add_replica(
        self, name: str, url: str, role: Optional[str] = None
    ) -> None:
        """Join a replica at runtime (autoscaler spawn, tests). It becomes
        routable at its first successful probe, not here."""
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = _Replica(
                spec=ReplicaSpec(url=url, name=name, role=role),
                role=role or "mixed",
            )

    def remove_replica(self, name: str) -> None:
        """Drop a replica from the registry (autoscaler retire). In-flight
        forwards to it finish or fail retriable on their own."""
        with self._lock:
            self._replicas.pop(name, None)
        self.metrics.replica_up.set(name, 0.0)
        self.federation.mark_down(name)

    def _kv_peer(self, exclude: frozenset = frozenset()) -> Optional[dict]:
        """Least-loaded ready replica with a KV-transfer listener, as the
        ``{"host", "port"}`` address warm-start and prefix migration both
        take — or None when the fleet has nothing to stream from."""
        with self._lock:
            peers = [
                r for r in self._replicas.values()
                if r.ready and r.kv_port and r.name not in exclude
            ]
        if not peers:
            return None
        rep = min(peers, key=lambda r: r.load)
        host = urllib.parse.urlsplit(rep.url).hostname or "127.0.0.1"
        return {"host": host, "port": int(rep.kv_port)}

    def _fleet_signals(self, now: float):
        """This sweep's federation rollup as :class:`FleetSignals`. Fleet
        gauges are SUMS across scraped replicas (federation.py), so the
        per-replica means divide by the scraped count; unknowns stay None
        and never trigger a scale."""
        from automodel_tpu.serving.fleet.autoscale import FleetSignals

        with self._lock:
            ready = sum(1 for r in self._replicas.values() if r.ready)
        fed = self.federation
        window_s = self.autoscaler.config.window_s
        n = int(fed.status().get("replicas_scraped") or 0) or max(ready, 1)
        qd = fed.latest("automodel_fleet_serve_queue_depth")
        occ = fed.latest("automodel_fleet_serve_block_occupancy")
        shed = fed.increase(
            "automodel_fleet_serve_requests_shed", window_s, now
        )
        return FleetSignals(
            ready_replicas=ready,
            queue_depth=None if qd is None else qd / n,
            shed_rate=None if shed is None else shed / window_s,
            occupancy=None if occ is None else occ / n,
            slos_firing=(
                len(self.slo.firing()) if self.slo is not None else 0
            ),
        )

    def _autoscale_tick(self, now: float) -> None:
        """One autoscaler evaluation, at the tail of each probe sweep."""
        if self.autoscaler is None or self.draining:
            return
        cfg = self.autoscaler.config
        # backfill the last scale-up's time_to_ready_s once the spawned
        # replica reports it (/stats) — fleet-status shows the number the
        # elastic fleet exists to improve
        last = self.autoscaler.last_event
        if (
            last is not None
            and last.get("direction") == "up"
            and "time_to_ready_s" not in last
        ):
            with self._lock:
                rep = self._replicas.get(last.get("replica"))
                ttr = (
                    rep.stats.get("time_to_ready_s")
                    if rep is not None and rep.ready else None
                )
                if ttr is not None:
                    last["time_to_ready_s"] = round(float(ttr), 6)
                    last["boot_source"] = rep.stats.get("boot_source")
        signals = self._fleet_signals(now)
        with self._lock:
            actual = len(self._replicas)
        direction, trigger = self.autoscaler.decide(signals, actual, now)
        if direction is None:
            self.metrics.autoscale_target.set(actual)
            return
        try:
            replica = (
                self._scale_up(cfg) if direction == "up"
                else self._scale_down(cfg)
            )
        except Exception as e:
            # backend failure: no note_scaled, so no cooldown — the streak
            # is still live and the next sweep retries
            logger.warning("autoscale %s failed: %s", direction, e)
            return
        if replica is None:
            return
        with self._lock:
            after = len(self._replicas)
        event = {
            "event": "scale_event",
            "ts": self._wall_ts(),
            "direction": direction,
            "trigger": trigger,
            "replica": replica,
            "replicas_before": actual,
            "replicas_after": after,
        }
        self.autoscaler.note_scaled(event, now)
        self.metrics.autoscale_events.inc(direction)
        self.metrics.autoscale_target.set(after)
        self._emit(event)
        logger.warning(
            "autoscale %s (trigger=%s): %d -> %d replicas (%s)",
            direction, trigger, actual, after, replica,
        )

    def _scale_up(self, cfg) -> Optional[str]:
        if self.scale_backend is None:
            logger.warning(
                "autoscale: scale up wanted but no backend is configured "
                "(k8s_fleet: section, or an injected LocalProcessBackend)"
            )
            return None
        warm = self._kv_peer() if cfg.warm_start else None
        name, url = self.scale_backend.spawn(warm)
        if name and getattr(self.scale_backend, "registry_managed", True):
            self.add_replica(name, url)
        return name or "(dns)"  # k8s: membership arrives via DNS discovery

    def _scale_down(self, cfg) -> Optional[str]:
        if self.scale_backend is None:
            logger.warning(
                "autoscale: scale down wanted but no backend is configured"
            )
            return None
        with self._lock:
            victims = [
                r for r in self._replicas.values()
                if r.ready and r.decode_capable()
            ]
        if len(victims) <= cfg.min_replicas:
            return None  # ready count sits at the floor even if the
            # registry is larger (dead entries don't make retiring safe)
        # least-loaded victim: fewest in-flight requests to drain, and the
        # load it sheds redistributes most easily
        victim = min(victims, key=lambda r: r.load)
        migrate = (
            self._kv_peer(exclude=frozenset({victim.name}))
            if cfg.migrate_on_scale_down else None
        )
        self.scale_backend.retire(
            victim.name, victim.url, migrate, cfg.retire_deadline_s
        )
        if getattr(self.scale_backend, "registry_managed", True):
            self.remove_replica(victim.name)
        return victim.name

    # -- placement ------------------------------------------------------------
    def _candidates(
        self, exclude: set, pool: str
    ) -> list[_Replica]:
        with self._lock:
            reps = list(self._replicas.values())
        if pool == "prefill":
            return [
                r for r in reps
                if r.ready and r.role == "prefill"
                and not r.updating and r.name not in exclude
            ]
        return [
            r for r in reps
            if r.ready and r.decode_capable()
            and not r.updating and r.name not in exclude
        ]

    def _match_blocks(self, rep: _Replica, chains: Sequence[int]) -> int:
        """Longest CONSECUTIVE chain prefix this replica's hot set covers —
        consecutive because ``match_prefix`` walks from block 1 and stops
        at the first miss; an orphaned deeper hash is unreachable there."""
        if not rep.block_size_ok:
            return 0
        n = 0
        for h in chains:
            if h not in rep.hot:
                break
            n += 1
        return n

    def place_decode(
        self,
        chains: Sequence[int],
        exclude: Optional[set] = None,
        tier_idx: int = 0,
    ) -> tuple[Optional[_Replica], int]:
        """→ (replica, matched chain blocks). Affinity first (longest
        advertised prefix match, ties to the least loaded), else
        power-of-two-choices on load — except for non-interactive tiers
        (``tier_idx > 0``), which take a FULL least-loaded scan: batch and
        best_effort work is latency-insensitive, so it can afford the O(N)
        probe to land on the true minimum and keep the lightly-loaded tail
        of the fleet absorbing it instead of contending with interactive
        traffic on a random pair."""
        cands = self._candidates(exclude or set(), "decode")
        if not cands:
            return None, 0
        if self.config.affinity and chains:
            matched = [(self._match_blocks(r, chains), r) for r in cands]
            best = max(m for m, _ in matched)
            if best > 0:
                tied = [r for m, r in matched if m == best]
                return min(tied, key=lambda r: r.load), best
        if tier_idx > 0 or len(cands) <= 2:
            return min(cands, key=lambda r: r.load), 0
        with self._lock:
            two = self._rng.sample(cands, 2)
        return min(two, key=lambda r: r.load), 0

    def place_prefill(self, exclude: Optional[set] = None) -> Optional[_Replica]:
        cands = self._candidates(exclude or set(), "prefill")
        return min(cands, key=lambda r: r.load) if cands else None

    def _peer_hint(
        self, chains: Sequence[int], rep: _Replica, match: int, exclude: set
    ) -> Optional[dict]:
        """Prefix-fetch hint for an affinity miss (docs/serving.md
        "Hierarchical KV cache"): when another ready replica advertises a
        DEEPER consecutive chain match than the chosen one AND runs a KV
        listener, the chosen replica can ``/kv_fetch`` the missing prefix
        blocks from it instead of re-prefilling. The hint is best-effort —
        the replica recomputes the chains itself (hashing is deterministic
        cross-process) and falls back to local prefill on any fetch
        failure. → ``{"host", "port"}`` or None.

        ``exclude`` should name only replicas whose KV listener is
        suspect (e.g. a failed transfer target) — NOT every replica a
        retry skipped: a shedding replica (503, queue full) refuses new
        decodes but its listener still serves prefix reads, and that
        shed-then-retry hop is exactly when the hint earns its keep
        (placement lands on a cold replica while the hot one stays the
        source of truth). Dead replicas drop out via ``ready``."""
        if not chains:
            return None
        best, peer = match, None
        for r in self._candidates(exclude | {rep.name}, "decode"):
            if not r.kv_port:
                continue
            m = self._match_blocks(r, chains)
            if m > best:
                best, peer = m, r
        if peer is None:
            return None
        host = urllib.parse.urlsplit(peer.url).hostname
        return {"host": host, "port": int(peer.kv_port)}

    def _disaggregate_active(self) -> bool:
        if self.config.disaggregate is False:
            return False
        return self.place_prefill() is not None

    # -- request path ---------------------------------------------------------
    def _encode(self, req: dict) -> Optional[list[int]]:
        """Token ids for chain hashing (and forwarded so every replica in a
        retry chain sees identical ids). None = unhashable here (text
        prompt, no router-side tokenizer): the request forwards verbatim
        and placement falls back to load-only."""
        if req.get("prompt_ids") is not None:
            return [int(t) for t in req["prompt_ids"]]
        prompt = req.get("prompt")
        if prompt is None:
            return None
        if self.tokenizer is not None:
            if callable(self.tokenizer):
                return self.tokenizer(str(prompt), add_special_tokens=True)[
                    "input_ids"
                ]
            return self.tokenizer.encode(str(prompt))
        try:  # token-id mode (tiny from-config fleets)
            return [int(t) for t in str(prompt).replace(",", " ").split()]
        except ValueError:
            return None

    def _wall_ts(self) -> float:
        return round(self._clock.wall(), 6)

    def _emit(self, rec: dict) -> None:
        if self.on_record is not None:
            try:
                self.on_record(dict(rec))
            except Exception:  # telemetry must never break routing
                pass

    def _count_retry(self) -> None:
        """One resubmission: the /metrics counter and its /stats mirror
        move together, always."""
        self.metrics.retries.inc()
        with self._lock:
            self.retries_total += 1

    def handle_generate(self, req: dict) -> tuple[int, dict]:
        """Route one request to a terminal response. → (HTTP status, body).
        The body is the winning replica's response verbatim (plus the
        router's ``route`` provenance block)."""
        t0 = time.perf_counter()
        rid = str(req.get("id")) if req.get("id") is not None else (
            f"route-{next(self._ids)}"
        )
        # trace root: continue the client's trace when a traceparent came in
        # (HTTP header, stashed into the body by the front), mint otherwise
        # — the router is where fleet traces are born
        tr = self.tracer
        client_tp = req.pop("traceparent", None)
        root = tr.start(parent=tr.parse(client_tp)) if tr is not None else None

        def _finish_span(outcome: str, **attrs) -> None:
            if tr is not None:
                tr.record(
                    root, "route", t0,
                    request_id=rid, outcome=outcome, **attrs,
                )

        if self.draining:
            _finish_span("draining")
            return 503, {
                "error": "router is draining — retry against another router",
                "retriable": True, "reason": "draining", "id": rid,
            }
        ids = self._encode(req)
        chains = (
            prompt_chain(ids, self.config.block_size)
            if ids and self.config.affinity else []
        )
        # multi-tenant QoS: tenant/tier ride the body (the HTTP front
        # stashes the X-Tenant-Id / X-Tier headers there, same vehicle as
        # traceparent) and forward to every replica in the retry chain
        tenant = str(req["tenant"]) if req.get("tenant") is not None else None
        tier = str(req["tier"]) if req.get("tier") is not None else None
        tier_label = _tier_label(tier)
        tier_idx = _TIER_ORDER.get(tier, 0)
        # tier-aware retry budget: best_effort work is exactly the traffic
        # the fleet sheds first under pressure — burning the full budget
        # re-offering it to replicas that just refused it steals forward
        # capacity from the tiers the operator ranked higher
        retry_budget = self.config.retry_budget
        if tier_idx >= _TIER_ORDER["best_effort"]:
            retry_budget = min(retry_budget, 1)
        with self._lock:
            self.requests_total += 1
        tried: set = set()
        tried_prefill: set = set()
        kv_suspect: set = set()  # replicas whose KV LISTENER failed us
        retries = 0
        last_error = "no ready decode-capable replica"
        rep = None
        match = 0
        # the forward timeout must EXCEED the replica-side budget (the
        # replica's submit_blocking answers 504 within the client's
        # timeout_s): if the two raced at the same value, a long-but-legal
        # decode would read as replica death — mark-down, resubmit, and the
        # same request terminalized on two replicas
        fwd_timeout = max(
            self.config.request_timeout_s,
            float(req.get("timeout_s") or 300.0) + 30.0,
        )
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        while retries <= retry_budget:
            t_place0 = time.perf_counter()
            if inj is not None:
                inj.maybe_trace_delay("placement")
            rep, match = self.place_decode(
                chains, exclude=tried, tier_idx=tier_idx
            )
            if tr is not None and rep is not None:
                # the placement decision, incl. WHY: affinity (and how deep
                # the match) vs pure load — one span per retry attempt
                tr.child(
                    root, "placement", t_place0,
                    request_id=rid, attempt=retries, replica=rep.name,
                    policy="affinity" if match > 0 else "load",
                    prefix_match_blocks=match,
                )
            if rep is None:
                break
            fwd = {k: v for k, v in req.items() if k != "prompt_ids"}
            if req.get("prompt_ids") is not None:
                fwd["prompt_ids"] = ids
            elif ids is not None and self.tokenizer is not None:
                # router-side tokenization: every replica in a retry chain
                # sees identical ids. WITHOUT a tokenizer a text prompt
                # forwards verbatim (docs/serving.md) — the token-id parse
                # is for affinity hashing only, and a numeric-looking text
                # prompt must not silently bypass the replica's tokenizer
                fwd.pop("prompt", None)
                fwd["prompt_ids"] = ids
            fwd["id"] = rid
            if self.config.affinity:
                hint = self._peer_hint(chains, rep, match, kv_suspect)
                if hint is not None:
                    fwd["kv_peer"] = hint
                    with self._lock:
                        self.peer_hints_total += 1
            used_prefill = None
            if (
                ids is not None
                # disaggregation needs ids BOTH sides agree on: client-sent
                # prompt_ids or a router-side tokenizer. Fallback-parsed
                # text must NOT disaggregate — /prefill would compute KV
                # for the parse while the decode replica re-encodes the
                # forwarded text with its own tokenizer
                and (
                    req.get("prompt_ids") is not None
                    or self.tokenizer is not None
                )
                and self._disaggregate_active()
                # strong affinity hit: the decode replica already holds at
                # least half the prompt — recomputing the tail beats a
                # whole-prompt KV transfer
                and match * self.config.block_size < max(len(ids) // 2, 1)
                and rep.kv_port
            ):
                pre = self.place_prefill(exclude=tried_prefill)
                if pre is not None:
                    handoff_id = uuid.uuid4().hex
                    host = urllib.parse.urlsplit(rep.url).hostname
                    pre_ctx = tr.start(parent=root) if tr is not None else None
                    t_pre0 = time.perf_counter()
                    try:
                        code, body = _http_json(
                            pre.url + "/prefill",
                            {
                                "prompt_ids": ids, "id": rid,
                                "transfer": {
                                    "host": host, "port": int(rep.kv_port),
                                    "handoff_id": handoff_id,
                                },
                            },
                            fwd_timeout,
                            headers=_trace_headers(pre_ctx),
                        )
                    except ReplicaUnreachable as e:
                        if tr is not None:
                            tr.record(
                                pre_ctx, "prefill_rpc", t_pre0,
                                request_id=rid, replica=pre.name,
                                attempt=retries, error="unreachable",
                            )
                        self._mark_down(pre)
                        tried_prefill.add(pre.name)
                        retries += 1
                        self._count_retry()
                        last_error = f"prefill replica unreachable: {e}"
                        continue
                    if tr is not None:
                        tr.record(
                            pre_ctx, "prefill_rpc", t_pre0,
                            request_id=rid, replica=pre.name,
                            attempt=retries, status=code,
                        )
                    if code != 200 or not body.get("ok"):
                        last_error = (
                            f"prefill on {pre.name} failed: "
                            f"{body.get('error', code)}"
                        )
                        if code == 502:
                            # 502 = the TRANSFER to the decode replica
                            # failed (server.py wraps KVTransferError as
                            # 502): the suspect is the decode target's
                            # listener (stale kv_port after a restart),
                            # not the prefill replica that ran the prompt
                            # — exclude the decode replica and keep the
                            # prefill pool intact
                            tried.add(rep.name)
                            kv_suspect.add(rep.name)
                            retries += 1
                            self._count_retry()
                            continue
                        if body.get("retriable", code == 503):
                            tried_prefill.add(pre.name)
                            retries += 1
                            self._count_retry()
                            continue
                        # terminal prefill failure (client budget expiry,
                        # bad request): one route_request record per
                        # terminal outcome — this path counts too
                        outcome = str(
                            body.get("completion_reason") or "prefill_failed"
                        )
                        self.metrics.requests.inc((pre.name, outcome))
                        self.metrics.tier_requests.inc((tier_label, outcome))
                        self.metrics.latency.observe(
                            outcome, time.perf_counter() - t0
                        )
                        _finish_span(
                            outcome, replica=pre.name, attempt=retries
                        )
                        self._emit({
                            "event": "route_request",
                            "request_id": rid,
                            "replica": pre.name,
                            "retries": retries,
                            "prefix_match_blocks": match,
                            "disaggregated": True,
                            "prefill_replica": pre.name,
                            "completion_reason": body.get(
                                "completion_reason", "prefill_failed"
                            ),
                            "tenant": tenant,
                            "tier": tier_label,
                            "status": code,
                            "route_s": round(time.perf_counter() - t0, 6),
                            "ts": self._wall_ts(),
                        })
                        return code, {**body, "id": rid}
                    fwd["handoff_id"] = handoff_id
                    used_prefill = pre.name
                    self.metrics.handoffs.inc()
                    with self._lock:
                        self.handoffs_total += 1
            fwd_ctx = tr.start(parent=root) if tr is not None else None
            t_fwd0 = time.perf_counter()
            if inj is not None:
                inj.maybe_trace_delay("forward")
            # tenant/tier forward as headers AND body fields: headers keep
            # the contract visible to middleboxes, the body survives
            # header-stripping fronts
            fwd_headers = dict(_trace_headers(fwd_ctx) or {})
            if tenant is not None:
                fwd_headers["X-Tenant-Id"] = tenant
            if tier is not None:
                fwd_headers["X-Tier"] = tier
            try:
                code, body = _http_json(
                    rep.url + "/generate", fwd, fwd_timeout,
                    headers=fwd_headers or None,
                )
            except ReplicaUnreachable as e:
                # TCP-level death: the replica never answered — always
                # retriable, and the registry marks it down until a probe
                # sees it healthy again
                if tr is not None:
                    tr.record(
                        fwd_ctx, "forward", t_fwd0,
                        request_id=rid, replica=rep.name,
                        attempt=retries, error="unreachable",
                    )
                self._mark_down(rep)
                tried.add(rep.name)
                retries += 1
                self._count_retry()
                last_error = f"replica {rep.name} unreachable: {e}"
                continue
            if tr is not None:
                # one forward span per retry attempt — the retry trail is
                # readable off the waterfall, not just the retries counter
                tr.record(
                    fwd_ctx, "forward", t_fwd0,
                    request_id=rid, replica=rep.name,
                    attempt=retries, status=code,
                )
            # 503 = shed/draining/engine down; 409 = the claimed handoff
            # never arrived or expired on that decode replica — both
            # resubmit elsewhere (the next round redoes prefill+transfer)
            if code in (503, 409) and body.get("retriable"):
                tried.add(rep.name)
                retries += 1
                self._count_retry()
                last_error = (
                    f"{rep.name} rejected retriable: "
                    f"{body.get('reason') or body.get('error')}"
                )
                continue
            # terminal — success (200), client-budget expiry (504), bad
            # request (400), or a non-retriable replica error
            if match > 0:
                self.metrics.prefix_hits.inc()
                with self._lock:
                    self.prefix_hits_total += 1
            if code == 200:
                outcome = "ok" if retries == 0 else "retried"
            else:
                outcome = str(
                    body.get("completion_reason")
                    or body.get("reason") or f"http_{code}"
                )
            self.metrics.requests.inc((rep.name, outcome))
            self.metrics.tier_requests.inc((tier_label, outcome))
            self.metrics.latency.observe(outcome, time.perf_counter() - t0)
            if code == 200:
                with self._lock:
                    self.completed_total += 1
            body = dict(body)
            body["id"] = rid
            body["route"] = {
                "replica": rep.name, "retries": retries,
                "prefix_match_blocks": match,
                "prefill_replica": used_prefill,
            }
            _finish_span(
                outcome, replica=rep.name, attempt=retries,
                completion_reason=body.get("completion_reason"),
            )
            self._emit({
                "event": "route_request",
                "request_id": rid,
                "replica": rep.name,
                "retries": retries,
                "prefix_match_blocks": match,
                "disaggregated": used_prefill is not None,
                "prefill_replica": used_prefill,
                "completion_reason": body.get("completion_reason"),
                "n_generated": body.get("n_generated"),
                "tenant": tenant,
                "tier": tier_label,
                "status": code,
                "route_s": round(time.perf_counter() - t0, 6),
                "ts": self._wall_ts(),
            })
            return code, body
        # exhausted: budget spent or nothing to route to — an explicit
        # retriable answer, never a silent drop
        self.metrics.unroutable.inc()
        self.metrics.requests.inc(
            (rep.name if rep is not None else "none", "unroutable")
        )
        self.metrics.tier_requests.inc((tier_label, "unroutable"))
        self.metrics.latency.observe("unroutable", time.perf_counter() - t0)
        with self._lock:
            self.unroutable_total += 1
        _finish_span(
            "unroutable",
            replica=rep.name if rep is not None else None, attempt=retries,
        )
        self._emit({
            "event": "route_request",
            "request_id": rid,
            "replica": rep.name if rep is not None else None,
            "retries": retries,
            "prefix_match_blocks": match,
            "completion_reason": "unroutable",
            "tenant": tenant,
            "tier": tier_label,
            "status": 503,
            "route_s": round(time.perf_counter() - t0, 6),
            "ts": self._wall_ts(),
        })
        return 503, {
            "error": (
                f"no replica could serve the request after {retries} "
                f"retr{'y' if retries == 1 else 'ies'}: {last_error}"
            ),
            "retriable": True, "reason": "unroutable", "id": rid,
            "tier": tier_label,
        }

    # -- fronts ---------------------------------------------------------------
    # -- rolling weight update (docs/posttrain.md) -----------------------------
    def rolling_update(
        self,
        peer: dict,
        timeout_s: float = 120.0,
        drain_timeout_s: float = 60.0,
    ) -> dict:
        """Fleet-wide weight hot-swap with zero dropped requests: one
        decode-capable replica at a time — shift traffic off it (the
        ``updating`` placement exclusion; retries re-place in-flight
        resubmissions onto siblings), wait for its slots and queue to
        empty, POST its /swap_weights at ``peer`` (the trainer's AKV1
        ``weights_fetch`` listener), confirm the version bump, re-admit.
        Progress lands in ``stats()["rolling_update"]`` and one
        ``rolling_update`` record per phase rides on_record, so the
        per-replica version skew window is observable while it closes.

        → summary dict {updated: [name], failed: [name], weights_version}.
        A replica that fails to drain or swap is re-admitted on its OLD
        weights and reported — a stalled update degrades loudly, never
        into dropped traffic."""
        with self._lock:
            targets = [
                r for r in self._replicas.values()
                if r.ready and r.decode_capable()
            ]
        self._rolling = {
            "active": True, "total": len(targets), "done": 0,
            "current": None, "updated": [], "failed": [],
        }
        self._emit({
            "event": "rolling_update", "phase": "start",
            "replicas": len(targets), "ts": self._wall_ts(),
        })
        probe_t = self.config.probe_timeout_s
        version: Optional[int] = None
        for rep in targets:
            t_rep0 = time.perf_counter()
            self._rolling["current"] = rep.name
            with self._lock:
                rep.updating = True
            err = None
            try:
                # traffic is off; wait for the replica to run dry (its own
                # queue keeps absorbing nothing new, in-flight finish)
                deadline = time.perf_counter() + drain_timeout_s
                while True:
                    _, st = _http_json(
                        rep.url + "/stats", None, timeout_s=probe_t
                    )
                    if (
                        not (st.get("busy_slots") or 0)
                        and not (st.get("queue_depth") or 0)
                    ):
                        break
                    if time.perf_counter() >= deadline:
                        raise TimeoutError(
                            f"{rep.name} still busy after {drain_timeout_s}s "
                            "traffic shift-off"
                        )
                    time.sleep(0.05)
                code, body = _http_json(
                    rep.url + "/swap_weights",
                    {"peer": dict(peer), "timeout_s": timeout_s},
                    timeout_s=timeout_s + probe_t,
                )
                if code != 200 or not body.get("ok"):
                    raise RuntimeError(
                        f"swap_weights on {rep.name} answered {code}: "
                        f"{body.get('error')}"
                    )
                version = int(body["weights_version"])
                _, st = _http_json(
                    rep.url + "/stats", None, timeout_s=probe_t
                )
                with self._lock:
                    rep.stats = st
            except (ReplicaUnreachable, RuntimeError, TimeoutError,
                    ValueError, KeyError) as e:
                err = f"{type(e).__name__}: {e}"
            finally:
                with self._lock:
                    rep.updating = False
            self._rolling["done"] += 1
            self._rolling["current"] = None
            if err is None:
                self._rolling["updated"].append(rep.name)
            else:
                self._rolling["failed"].append(rep.name)
                logger.error(
                    "rolling update: %s failed (%s) — re-admitted on its "
                    "old weights", rep.name, err,
                )
            rec = {
                "event": "rolling_update", "phase": "replica",
                "replica": rep.name, "ok": err is None,
                "duration_s": round(time.perf_counter() - t_rep0, 6),
                "ts": self._wall_ts(),
            }
            if err is None:
                rec["weights_version"] = version
            else:
                rec["detail"] = err
            self._emit(rec)
        self._rolling["active"] = False
        if version is not None:
            self._rolling["weights_version"] = version
        self._emit({
            "event": "rolling_update", "phase": "done",
            "updated": len(self._rolling["updated"]),
            "failed": len(self._rolling["failed"]),
            "weights_version": version, "ts": self._wall_ts(),
        })
        return {
            "updated": list(self._rolling["updated"]),
            "failed": list(self._rolling["failed"]),
            "weights_version": version,
        }

    def begin_drain(self) -> None:
        self.draining = True

    def ready(self) -> bool:
        """The router is ready while >= 1 decode-capable replica is — ONE
        replica down must not drop the whole fleet out of a load balancer."""
        return not self.draining and bool(self._candidates(set(), "decode"))

    def healthy(self) -> bool:
        return self._probe_thread is None or self._probe_thread.is_alive()

    def stats(self) -> dict:
        with self._lock:
            reps = {
                r.name: {
                    "url": r.url,
                    "role": r.role,
                    "alive": r.alive,
                    "ready": r.ready,
                    "queue_depth": r.stats.get("queue_depth"),
                    "busy_slots": r.stats.get("busy_slots"),
                    "block_occupancy": r.stats.get("block_occupancy"),
                    "shed_total": r.stats.get("shed_total"),
                    "quota_total": r.stats.get("quota_total"),
                    # multi-tenant QoS: this replica's qos_snapshot block
                    "qos": r.stats.get("qos"),
                    "hot_prefixes": len(r.hot),
                    "kv_transfer_port": r.kv_port,
                    # fleet-status columns (serving/fleet/status.py)
                    "spec_accept_rate": r.stats.get("spec_accept_rate"),
                    "prefix_hit_rate": _prefix_hit_rate(r.stats),
                    # rolling update: per-replica weights generation — the
                    # version skew window is these values disagreeing
                    "weights_version": r.stats.get("weights_version"),
                    "updating": r.updating,
                }
                for r in self._replicas.values()
            }
            out = {
                "replicas": reps,
                "replicas_ready": sum(1 for r in reps.values() if r["ready"]),
                "requests_total": self.requests_total,
                "completed_total": self.completed_total,
                "retries_total": self.retries_total,
                "prefix_hits_total": self.prefix_hits_total,
                "unroutable_total": self.unroutable_total,
                "kv_handoffs_total": self.handoffs_total,
                "kv_peer_hints_total": self.peer_hints_total,
                "disaggregated": self._disaggregate_active_unlocked(),
                "draining": self.draining,
            }
            if self._rolling is not None:
                out["rolling_update"] = dict(self._rolling)
        # fleet-wide QoS rollup: the per-replica qos blocks summed — the
        # numbers fleet-status's TIER/TENANT summary renders
        out["qos"] = aggregate_qos(
            [v.get("qos") for v in reps.values() if v.get("qos")]
        )
        out["federation"] = self.federation.status()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
            out["alerts_firing"] = self.slo.firing()
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.status()
        return out

    def _disaggregate_active_unlocked(self) -> bool:
        if self.config.disaggregate is False:
            return False
        return any(
            r.ready and r.role == "prefill" for r in self._replicas.values()
        )

    # -- workload driver (routed bench sub-leg + chaos tests) ------------------
    def run_workload(
        self, arrivals: Sequence[tuple[float, Sequence[int], Optional[int]]]
    ) -> tuple[list[dict], dict]:
        """Drive the same timed-arrival workload shape as
        ``ServingEngine.run_workload``, but through the ROUTER: one thread
        per request submits at its offset and blocks on the routed
        response. → (terminal bodies, aggregate stats)."""
        arrivals = sorted(arrivals, key=lambda a: a[0])
        results: list[Optional[tuple[int, dict]]] = [None] * len(arrivals)
        req0 = {
            "retries": self.retries_total,
            "hits": self.prefix_hits_total,
            "handoffs": self.handoffs_total,
        }
        t0 = time.perf_counter()

        durations: list[Optional[float]] = [None] * len(arrivals)

        def worker(i: int, offset: float, ids, max_new) -> None:
            delay = offset - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            body = {"prompt_ids": list(ids), "id": f"bench-{i}"}
            if max_new is not None:
                body["max_new_tokens"] = int(max_new)
            t_req = time.perf_counter()
            results[i] = self.handle_generate(body)
            durations[i] = time.perf_counter() - t_req

        threads = [
            threading.Thread(target=worker, args=(i, off, ids, mn), daemon=True)
            for i, (off, ids, mn) in enumerate(arrivals)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        done = [r for r in results if r is not None]
        out = [body for _, body in done]
        completions = [
            b for s, b in done
            if s == 200 and b.get("completion_reason") in ("stop", "length")
        ]
        gen = sum(int(b.get("n_generated") or 0) for b in completions)
        routed = len(completions)
        from automodel_tpu.telemetry.report import percentile

        route_durs = [d for d in durations if d is not None]
        # token-weighted hit rate: prompt tokens served from a replica's
        # cache hierarchy over all prompt tokens routed — the per-request
        # `prefix_hits` counter overstates 1-block matches
        hit_toks = sum(
            int(b.get("prefix_hit_tokens") or 0) for b in completions
        )
        prompt_toks = sum(
            int(b.get("prompt_tokens") or 0) for b in completions
        )
        stats = {
            "requests": routed,
            "gen_tokens": gen,
            "wall_s": wall,
            "fleet_tokens_per_s": gen / wall if wall > 0 else 0.0,
            "retries": self.retries_total - req0["retries"],
            "prefix_hits": self.prefix_hits_total - req0["hits"],
            "kv_handoffs": self.handoffs_total - req0["handoffs"],
            "prefix_hit_rate": (
                hit_toks / prompt_toks if prompt_toks else 0.0
            ),
            "prefix_hit_tokens": hit_toks,
            "prompt_tokens": prompt_toks,
            # shared linear-interpolation percentile (telemetry/report.py)
            # — the same rule every other p50/p99 in the tree uses
            "route_p50_s": percentile(route_durs, 0.50),
            "route_p99_s": percentile(route_durs, 0.99),
            "failed_requests": len(arrivals) - routed,
        }
        return out, stats


def serve_router_http(
    router: Router, port: int, host: str = "127.0.0.1"
):
    """→ started ThreadingHTTPServer exposing the router with the SAME
    front contract as a single replica (serving/server.py)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("router http: " + fmt, *args)

        def _json(self, code: int, obj: dict, retry_after: Any = False):
            # retry_after: False = no header, True = flat advice, a
            # number = that many seconds (tier-scaled QoS advice)
            body = (json.dumps(obj) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after:
                secs = (
                    RETRY_AFTER_S if retry_after is True else int(retry_after)
                )
                self.send_header("Retry-After", str(secs))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                from automodel_tpu.telemetry.prometheus import CONTENT_TYPE

                # the router's own registry, then the federation block:
                # every replica sample re-exported with a `replica` label
                # plus the automodel_fleet_* aggregates (name sets are
                # disjoint, so the concatenation stays one valid exposition)
                body = (
                    router.metrics.registry.render()
                    + router.federation.render_federated()
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/healthz":
                ok = router.healthy()
                return self._json(200 if ok else 503, {
                    "ok": ok, "probe_thread_alive": ok,
                })
            if self.path == "/readyz":
                ready = router.ready()
                return self._json(200 if ready else 503, {
                    "ready": ready,
                    "draining": router.draining,
                    "replicas_ready": len(router._candidates(set(), "decode")),
                })
            if self.path != "/stats":
                return self._json(404, {"error": f"unknown path {self.path}"})
            return self._json(200, router.stats())

        def do_POST(self):
            if self.path == "/rolling_update":
                # fleet-wide weight hot-swap: ``{"peer": {"host", "port"},
                # "timeout_s": s, "drain_timeout_s": s}``. Responds 200
                # IMMEDIATELY and runs the sequential update on a
                # background thread (mirror of a replica's /retire) — the
                # caller polls /stats rolling_update for progress.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError("request body is not a JSON object")
                except (ValueError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                peer = req.get("peer")
                if not (
                    isinstance(peer, dict)
                    and peer.get("host")
                    and peer.get("port") is not None
                ):
                    return self._json(400, {
                        "error": "rolling_update needs peer.{host, port}"
                    })
                if router._rolling is not None and router._rolling.get("active"):
                    return self._json(409, {
                        "error": "a rolling update is already in progress",
                        "rolling_update": dict(router._rolling),
                    })
                kw = {}
                if req.get("timeout_s") is not None:
                    kw["timeout_s"] = float(req["timeout_s"])
                if req.get("drain_timeout_s") is not None:
                    kw["drain_timeout_s"] = float(req["drain_timeout_s"])
                threading.Thread(
                    target=router.rolling_update, args=(peer,), kwargs=kw,
                    name="router-rolling-update", daemon=True,
                ).start()
                return self._json(200, {"ok": True, "started": True})
            if self.path != "/generate":
                return self._json(404, {"error": f"unknown path {self.path}"})
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError("request body is not a JSON object")
            except (ValueError, TypeError) as e:
                return self._json(400, {"error": str(e)})
            # a client-sent traceparent continues the client's trace (the
            # body-field form also works for tests/curl without headers)
            tp = self.headers.get("traceparent")
            if tp is not None and "traceparent" not in req:
                req["traceparent"] = tp
            # tenant/tier headers stash into the body the same way (body
            # fields from bare-bones clients stay authoritative)
            for header, field in (("X-Tenant-Id", "tenant"), ("X-Tier", "tier")):
                hv = self.headers.get(header)
                if hv is not None and req.get(field) is None:
                    req[field] = hv
            code, body = router.handle_generate(req)
            self._json(
                code, body,
                retry_after=(
                    _tier_retry_after(req.get("tier"))
                    if code in (429, 503) else False
                ),
            )

    server = ThreadingHTTPServer((host, port), Handler)
    return server


def main(cfg: Any) -> int:
    """`automodel_tpu route -c cfg.yaml` — run the fleet router. The config
    needs a ``fleet:`` section (static ``replicas:`` or ``dns:``); the
    ``model:`` section is only consulted for an optional router-side
    tokenizer (text-prompt affinity hashing) and never built."""
    from automodel_tpu.loggers.log_utils import setup_logging

    setup_logging()
    fleet_section = dict(cfg.get("fleet", {}) or {})
    fcfg = FleetConfig.from_dict(fleet_section)
    if fcfg.port is None:
        print(
            "fleet.port is required for `automodel_tpu route` "
            "(the router's HTTP front)",
        )
        return 2
    tokenizer = None
    gen_section = dict(cfg.get("generation", {}) or {})
    if gen_section.get("tokenizer") is not None:
        # imports jax transitively — only paid when text-prompt affinity
        # hashing is actually configured
        from automodel_tpu.generation.engine import resolve_tokenizer

        tokenizer = resolve_tokenizer(gen_section.get("tokenizer"), None)
    on_record = None
    metric_logger = None
    logging_section = dict(cfg.get("logging", {}) or {})
    if logging_section.get("metrics_path"):
        from automodel_tpu.loggers.metric_logger import MetricLogger

        metric_logger = MetricLogger(logging_section["metrics_path"])
        on_record = metric_logger.log
    # request tracing: the router is where fleet traces are minted; spans
    # ride the same metrics JSONL as route_request records
    import os as os_mod

    from automodel_tpu.telemetry.tracing import Tracer, TracingConfig

    tracing_cfg = TracingConfig.from_dict(dict(cfg.get("tracing", {}) or {}))
    tracer = Tracer.from_config(
        tracing_cfg, process=f"router-{os_mod.getpid()}", emit=on_record
    )
    # fleet health plane: a strict `slo:` section arms burn-rate alerting
    # over the federated replica scrapes; alert transitions land in the
    # metrics JSONL and a flight-recorder ring next to it
    slo_cfg = None
    slo_section = dict(cfg.get("slo", {}) or {})
    if slo_section:
        from automodel_tpu.telemetry.slo import SLOConfig

        slo_cfg = SLOConfig.from_dict(slo_section)
    flight_recorder = None
    if slo_cfg is not None and logging_section.get("metrics_path"):
        from pathlib import Path as _Path

        from automodel_tpu.telemetry.flight_recorder import FlightRecorder

        flight_recorder = FlightRecorder(
            capacity=64,
            path=str(
                _Path(logging_section["metrics_path"]).parent
                / "router_flight_recorder.json"
            ),
        )
    # elastic fleet: a strict `autoscale:` section arms the closed-loop
    # controller; a `k8s_fleet:` section beside it gives it the kubectl
    # backend (otherwise decisions log but cannot act — tests inject a
    # LocalProcessBackend through the Router constructor instead)
    autoscale_cfg = None
    autoscale_section = dict(cfg.get("autoscale", {}) or {})
    if autoscale_section:
        from automodel_tpu.serving.fleet.autoscale import AutoscaleConfig

        autoscale_cfg = AutoscaleConfig.from_dict(autoscale_section)
    scale_backend = None
    if autoscale_cfg is not None and autoscale_cfg.enabled:
        k8s_section = dict(cfg.get("k8s_fleet", {}) or {})
        if k8s_section:
            from automodel_tpu.launcher.k8s import K8sFleetConfig
            from automodel_tpu.serving.fleet.autoscale import K8sFleetBackend

            k8s_section.pop("_target_", None)
            known = {f.name for f in dataclasses.fields(K8sFleetConfig)}
            unknown = set(k8s_section) - known
            if unknown:
                raise TypeError(f"unknown k8s_fleet keys: {sorted(unknown)}")
            kcfg = K8sFleetConfig(**k8s_section)
            role = "decode" if kcfg.mixed == 0 and kcfg.decode > 0 else "mixed"
            scale_backend = K8sFleetBackend(kcfg, role=role)
        else:
            logger.warning(
                "autoscale.enabled without a k8s_fleet: section — the "
                "controller will evaluate and log decisions but has no "
                "backend to act through"
            )
    router = Router(
        fcfg, tokenizer=tokenizer, on_record=on_record, tracer=tracer,
        slo_config=slo_cfg, flight_recorder=flight_recorder,
        autoscale_config=autoscale_cfg, scale_backend=scale_backend,
    )
    router.start()
    server = serve_router_http(router, fcfg.port, host=fcfg.host)

    def _drain_then_stop():
        router.begin_drain()
        deadline = time.monotonic() + fcfg.drain_grace_s
        while time.monotonic() < deadline:
            time.sleep(0.05)
        server.shutdown()

    def _on_term():
        threading.Thread(
            target=_drain_then_stop, name="route-drain", daemon=True
        ).start()

    handler = None
    if threading.current_thread() is threading.main_thread():
        from automodel_tpu.resilience.preemption import PreemptionHandler

        handler = PreemptionHandler(
            signals=("SIGTERM",), on_preempt=_on_term,
            log_message=(
                "router drain: rejecting new requests retriable, letting "
                f"in-flight forwards finish within {fcfg.drain_grace_s}s"
            ),
        )
        handler.install()
    print(
        json.dumps({
            "event": "route_listening",
            "host": fcfg.host, "port": server.server_address[1],
            "replicas": len(router._replicas), "dns": fcfg.dns,
        }),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        router.close()
        if handler is not None:
            handler.restore()
        if metric_logger is not None:
            metric_logger.close()
    return 0
