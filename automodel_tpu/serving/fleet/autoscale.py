"""Closed-loop fleet autoscaling: federation signals → hysteresis → scale.

The PR 17 health plane already federates every replica's /metrics into
fleet series on the router (telemetry/federation.py) and evaluates SLO
burn rates against them (telemetry/slo.py). This module closes the loop:
a controller on the router consumes those EXISTING signals — fleet queue
depth, windowed shed rate, block occupancy, firing SLOs — through a
hysteresis/cooldown state machine and changes the replica count when the
fleet is persistently over- or under-provisioned.

The state machine (``Autoscaler.decide``, pure — unit-testable without a
fleet):

- **classification** — a signal snapshot is OVER when any scale-up
  trigger trips (queue depth, shed rate, occupancy above their high-water
  marks, or an SLO firing), UNDER when every scale-down condition holds
  (queue + occupancy below their low-water marks, zero sheds in the
  window, no SLO firing), HOLD otherwise. High != low water marks are the
  first hysteresis band: a fleet sitting between them never oscillates.
- **consecutive-evaluation debounce** — the second hysteresis stage: a
  direction must classify identically for ``scale_up_consecutive`` /
  ``scale_down_consecutive`` probe sweeps in a row before it acts. One
  noisy sweep (a burst absorbed by the queue, a scrape gap) resets the
  streak.
- **cooldown** — after ANY scale event, ``cooldown_s`` of wall clock must
  pass before the next one: a freshly spawned replica needs time to reach
  ready and absorb load before the same signals can justify another step,
  and a freshly retired one needs its load to redistribute. Streaks keep
  accumulating during cooldown; action is what is deferred.

Acting on a decision is the ROUTER's job (``Router._autoscale_tick``): it
picks the scale-down victim (least-loaded ready replica) and the
migration target, and executes through a :class:`ScaleBackend` —
:class:`LocalProcessBackend` (spawn/retire local replica subprocesses;
the CPU e2e harness) or :class:`K8sFleetBackend` (``kubectl scale`` on
the role StatefulSets ``launcher/k8s.py`` renders). Every scale event
emits one ``scale_event`` JSONL record (direction, trigger signal,
replicas before/after) and bumps the ``automodel_route_autoscale_*``
/metrics families.

Scaling is always one replica per event: the cooldown makes the loop a
damped integrator, and single steps keep a mis-tuned threshold from
flapping the whole fleet at once.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)


def _cfg_dict(cls, d: Optional[dict], section: str):
    d = dict(d or {})
    d.pop("_target_", None)
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - known
    if unknown:
        raise TypeError(f"unknown {section} keys: {sorted(unknown)}")
    return cls(**d)


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The top-level ``autoscale:`` YAML section (lives beside ``fleet:``
    in a router config). Thresholds are FLEET-MEAN per-ready-replica
    values (a 3-replica fleet with 30 queued requests has queue depth 10),
    so the same config works at any fleet size."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up triggers (any one trips an OVER classification)
    queue_depth_high: float = 8.0  # fleet mean queued per ready replica
    shed_rate_high: float = 0.5  # fleet sheds/second over window_s
    occupancy_high: float = 0.92  # mean block-pool occupancy
    slo_firing_scales_up: bool = True
    # scale-down conditions (ALL must hold for an UNDER classification)
    queue_depth_low: float = 0.5
    occupancy_low: float = 0.35
    # hysteresis: consecutive identical classifications before acting
    scale_up_consecutive: int = 2
    scale_down_consecutive: int = 5
    cooldown_s: float = 30.0  # wall clock between scale events
    window_s: float = 30.0  # shed-rate measurement window
    # scale-down robustness: drain + hot-prefix migration semantics
    migrate_on_scale_down: bool = True
    retire_deadline_s: float = 30.0  # drain + migrate must fit inside this
    # scale-up robustness: new replicas peer-warm-start when a serving
    # peer advertises a KV listener (LocalProcessBackend honors this; on
    # k8s the replica template's own serving.warm_start config decides)
    warm_start: bool = True

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"autoscale.min_replicas={self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"autoscale.max_replicas={self.max_replicas} < "
                f"min_replicas={self.min_replicas}"
            )
        if self.queue_depth_low >= self.queue_depth_high:
            raise ValueError(
                f"autoscale.queue_depth_low={self.queue_depth_low} must sit "
                f"below queue_depth_high={self.queue_depth_high} — the gap "
                "IS the hysteresis band"
            )
        if self.occupancy_low >= self.occupancy_high:
            raise ValueError(
                f"autoscale.occupancy_low={self.occupancy_low} must sit "
                f"below occupancy_high={self.occupancy_high}"
            )
        if self.scale_up_consecutive < 1 or self.scale_down_consecutive < 1:
            raise ValueError(
                "autoscale.scale_up_consecutive/scale_down_consecutive "
                "must be >= 1"
            )
        if self.cooldown_s < 0 or self.window_s <= 0:
            raise ValueError(
                f"autoscale: cooldown_s={self.cooldown_s} (want >= 0), "
                f"window_s={self.window_s} (want > 0)"
            )
        if self.retire_deadline_s <= 0:
            raise ValueError(
                f"autoscale.retire_deadline_s={self.retire_deadline_s}"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "AutoscaleConfig":
        return _cfg_dict(cls, d, "autoscale")


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """One probe sweep's signal snapshot, as fed to ``Autoscaler.decide``.
    ``None`` means the federation has no data for that signal yet (cold
    start, every replica down) — an unknown never triggers a scale."""

    ready_replicas: int
    queue_depth: Optional[float] = None  # fleet mean per ready replica
    shed_rate: Optional[float] = None  # fleet sheds/second over window_s
    occupancy: Optional[float] = None  # fleet mean block occupancy
    slos_firing: int = 0


class Autoscaler:
    """The hysteresis/cooldown state machine. ``decide`` is the whole
    behavior — pure in (signals, actual, now), with only the streak
    counters and last-event stamp as state."""

    def __init__(self, config: AutoscaleConfig):
        self.config = config
        self._over_streak = 0
        self._under_streak = 0
        self._last_event_t: Optional[float] = None
        self.last_event: Optional[dict] = None  # fleet-status display
        self.events_total = {"up": 0, "down": 0}

    # -- classification (pure) ------------------------------------------------
    def classify(self, s: FleetSignals) -> tuple[str, Optional[str]]:
        """→ ("over"|"under"|"hold", trigger signal name). Unknown signals
        (None) neither trip a trigger nor satisfy a scale-down condition."""
        c = self.config
        if s.ready_replicas < 1:
            # an all-down fleet is an availability incident, not load:
            # scaling on it would race replica startup/probe recovery
            return "hold", None
        if s.queue_depth is not None and s.queue_depth > c.queue_depth_high:
            return "over", "queue_depth"
        if s.shed_rate is not None and s.shed_rate > c.shed_rate_high:
            return "over", "shed_rate"
        if s.occupancy is not None and s.occupancy > c.occupancy_high:
            return "over", "occupancy"
        if c.slo_firing_scales_up and s.slos_firing > 0:
            return "over", "slo_firing"
        under = (
            s.queue_depth is not None
            and s.queue_depth < c.queue_depth_low
            and s.occupancy is not None
            and s.occupancy < c.occupancy_low
            and (s.shed_rate is not None and s.shed_rate == 0.0)
            and s.slos_firing == 0
        )
        return ("under", "idle") if under else ("hold", None)

    # -- the state machine ----------------------------------------------------
    def decide(
        self, signals: FleetSignals, actual: int, now: float
    ) -> tuple[Optional[str], Optional[str]]:
        """One probe sweep's evaluation. → ``(direction, trigger)`` where
        direction is ``"up"``/``"down"`` when a scale should happen NOW
        and None otherwise. The caller MUST follow a non-None direction
        with ``note_scaled`` once the action lands (that is what starts
        the cooldown and resets the streaks)."""
        c = self.config
        if not c.enabled:
            return None, None
        state, trigger = self.classify(signals)
        self._over_streak = self._over_streak + 1 if state == "over" else 0
        self._under_streak = self._under_streak + 1 if state == "under" else 0
        if (
            self._last_event_t is not None
            and now - self._last_event_t < c.cooldown_s
        ):
            return None, None  # streaks accumulate; action is deferred
        if self._over_streak >= c.scale_up_consecutive:
            if actual >= c.max_replicas:
                return None, None  # at the ceiling: keep shedding loudly
            return "up", trigger
        if self._under_streak >= c.scale_down_consecutive:
            if actual <= c.min_replicas:
                return None, None
            return "down", trigger
        return None, None

    def note_scaled(self, event: dict, now: float) -> None:
        """Record a landed scale event: starts the cooldown, resets both
        streaks, and keeps the event for fleet-status display."""
        self._last_event_t = now
        self._over_streak = 0
        self._under_streak = 0
        self.last_event = dict(event)
        d = event.get("direction")
        if d in self.events_total:
            self.events_total[d] += 1

    def status(self) -> dict:
        """The /stats ``autoscale`` block (fleet-status renders it)."""
        c = self.config
        return {
            "enabled": c.enabled,
            "min_replicas": c.min_replicas,
            "max_replicas": c.max_replicas,
            "over_streak": self._over_streak,
            "under_streak": self._under_streak,
            "scale_ups": self.events_total["up"],
            "scale_downs": self.events_total["down"],
            "last_event": self.last_event,
        }


# -- backends ------------------------------------------------------------------


class ScaleBackendError(RuntimeError):
    """A backend action failed — the autoscaler logs, skips the event, and
    re-evaluates at the next sweep (no cooldown is started)."""


class LocalProcessBackend:
    """Scale by spawning/retiring local replica subprocesses — the CPU
    e2e harness's backend, and the reference for what any backend owes
    the router:

    - ``spawn(warm_peer)`` → ``(name, url)`` of a NEW replica already
      listening (the callable owns process creation, port discovery, and
      wiring ``serving.warm_start`` at the given ``{"host", "port"}``
      peer when one is offered).
    - ``retire(name, url, migrate, deadline_s)`` → POST /retire on the
      victim (the serve front owns drain → migrate → exit from there).
    """

    registry_managed = True  # the router adds/removes what this spawns

    def __init__(self, spawn: Any, retire: Any = None):
        self._spawn = spawn
        self._retire = retire

    def spawn(self, warm_peer: Optional[dict]) -> tuple[str, str]:
        try:
            name, url = self._spawn(warm_peer)
        except Exception as e:
            raise ScaleBackendError(f"replica spawn failed: {e}") from e
        return str(name), str(url)

    def retire(
        self, name: str, url: str, migrate: Optional[dict], deadline_s: float
    ) -> None:
        if self._retire is not None:
            try:
                self._retire(name, url, migrate, deadline_s)
                return
            except Exception as e:
                raise ScaleBackendError(
                    f"replica retire failed: {e}"
                ) from e
        # default: the serve front's own /retire endpoint
        from automodel_tpu.serving.fleet.router import (  # lazy: no cycle
            ReplicaUnreachable,
            _http_json,
        )

        try:
            code, body = _http_json(
                url.rstrip("/") + "/retire",
                {"migrate": migrate, "deadline_s": deadline_s},
                timeout_s=5.0,
            )
        except ReplicaUnreachable as e:
            raise ScaleBackendError(f"retire POST to {url} failed: {e}") from e
        if code != 200:
            raise ScaleBackendError(
                f"{url} refused /retire ({code}): {body.get('error')}"
            )


class K8sFleetBackend:
    """Scale a ``launcher/k8s.py`` fleet by resizing one role's
    StatefulSet (``kubectl scale``). The k8s control plane owns pod
    lifecycle: a scale-down removes the HIGHEST ordinal, whose preStop/
    SIGTERM path runs the serve front's normal drain; the router observes
    membership change through its DNS/probe sweep rather than through
    add_replica/remove_replica, so ``spawn``/``retire`` here only change
    the desired count."""

    registry_managed = False  # membership arrives/leaves by probe sweep

    def __init__(self, cfg: Any, role: str = "mixed", current: int = None):
        self.cfg = cfg
        self.role = role
        # desired-count bookkeeping: kubectl is the source of truth, but
        # the backend tracks what it last requested so consecutive events
        # compose without a kubectl round trip per sweep
        self.desired = int(
            current if current is not None else getattr(cfg, role, 1)
        )

    def spawn(self, warm_peer: Optional[dict]) -> tuple[str, str]:
        from automodel_tpu.launcher.k8s import scale_fleet_role

        self.desired += 1
        try:
            scale_fleet_role(self.cfg, self.role, self.desired)
        except Exception as e:
            self.desired -= 1
            raise ScaleBackendError(f"kubectl scale up failed: {e}") from e
        # the pod joins through DNS discovery; there is no URL to return —
        # the router treats an empty name as "membership arrives by probe"
        return "", ""

    def retire(
        self, name: str, url: str, migrate: Optional[dict], deadline_s: float
    ) -> None:
        from automodel_tpu.launcher.k8s import scale_fleet_role

        self.desired = max(self.desired - 1, 0)
        try:
            scale_fleet_role(self.cfg, self.role, self.desired)
        except Exception as e:
            self.desired += 1
            raise ScaleBackendError(f"kubectl scale down failed: {e}") from e
