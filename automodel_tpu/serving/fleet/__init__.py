"""Fleet tier: multi-replica serving above :mod:`automodel_tpu.serving`
(docs/serving.md "Fleet").

- :mod:`router` — the `automodel_tpu route` process: replica registry
  (static ``fleet:`` list or k8s DNS), /readyz + /stats probing,
  prefix-affinity placement (the block pool's chain rule) with
  power-of-two-choices fallback, disaggregated prefill→decode
  orchestration, and bounded failure-aware retry. Same HTTP front
  contract as a single replica (POST /generate, GET /stats /healthz
  /readyz /metrics).
- :mod:`kv_transfer` — the length-prefixed socket transport a prefill
  replica streams finished KV block rows over to its assigned decode
  replica (bf16 rows, or (int8 values, fp32 scales) pairs — bit-identical
  round trip by construction).

The router process deliberately imports NO jax: placement hashes ride
:func:`automodel_tpu.serving.block_pool.prompt_chain` (pure python), so a
router pod needs no accelerator and starts in milliseconds.
"""

from automodel_tpu.serving.fleet.router import (
    FleetConfig,
    ReplicaSpec,
    Router,
    serve_router_http,
)

__all__ = ["FleetConfig", "ReplicaSpec", "Router", "serve_router_http"]
