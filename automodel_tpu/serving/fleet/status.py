"""`automodel_tpu fleet-status` — the live fleet-status surface.

Renders the per-replica health table (role, readiness, queue depth, block
occupancy, prefix-hit rate, speculative accept rate, firing SLOs) either
point-in-time or live (``--watch``). Two sources, tried in this order:

- **router mode** (``--router URL``, or the ``fleet.port`` of ``-c``):
  one GET /stats against the router returns the federated view the probe
  loop already maintains — per-replica load + the SLO engine's alert
  states. This is the normal operator path.
- **direct mode** (no router listening, or ``--direct``): the CLI probes
  each ``fleet.replicas`` URL's /readyz + /stats itself. No SLO column —
  objectives are judged by the router's health loop, not per replica.

jax-free by construction (same rule as the router): importable and
runnable on a laptop against a remote fleet.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Optional

from automodel_tpu.serving.fleet.router import (
    FleetConfig,
    ReplicaUnreachable,
    _http_json,
    _prefix_hit_rate,
    aggregate_qos,
)

_COLUMNS = (
    "REPLICA", "ROLE", "READY", "QUEUE", "BUSY", "OCC", "HIT%", "ACC%",
    "WVER", "ALERTS",
)


def _fmt_pct(v: Optional[float]) -> str:
    return "-" if v is None else f"{100.0 * v:.0f}%"


def _fmt_num(v: Any) -> str:
    return "-" if v is None else str(v)


def _fmt_occ(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.2f}"


def _router_snapshot(router_url: str, timeout_s: float) -> dict:
    _, stats = _http_json(router_url + "/stats", None, timeout_s)
    return stats


def _direct_snapshot(fcfg: FleetConfig, timeout_s: float) -> dict:
    """The router-/stats shape, assembled by probing replicas directly —
    the table renderer sees one format either way."""
    reps: dict[str, dict] = {}
    for spec in fcfg.replicas:
        name = spec.name or spec.url
        row: dict[str, Any] = {
            "url": spec.url, "role": spec.role or "mixed",
            "alive": False, "ready": False,
            "queue_depth": None, "busy_slots": None,
            "block_occupancy": None, "prefix_hit_rate": None,
            "spec_accept_rate": None, "shed_total": None,
            "quota_total": None, "qos": None,
            "weights_version": None,
        }
        try:
            code, _ = _http_json(spec.url + "/readyz", None, timeout_s)
            row["alive"] = True
            row["ready"] = code == 200
            _, stats = _http_json(spec.url + "/stats", None, timeout_s)
            row.update({
                "role": spec.role or stats.get("role") or row["role"],
                "queue_depth": stats.get("queue_depth"),
                "busy_slots": stats.get("busy_slots"),
                "block_occupancy": stats.get("block_occupancy"),
                "shed_total": stats.get("shed_total"),
                "quota_total": stats.get("quota_total"),
                "qos": stats.get("qos"),
                "prefix_hit_rate": _prefix_hit_rate(stats),
                "spec_accept_rate": stats.get("spec_accept_rate"),
                "weights_version": stats.get("weights_version"),
            })
        except ReplicaUnreachable:
            pass
        reps[name] = row
    return {
        "replicas": reps,
        "replicas_ready": sum(1 for r in reps.values() if r["ready"]),
        "qos": aggregate_qos(
            [r.get("qos") for r in reps.values() if r.get("qos")]
        ),
        "source": "direct",
    }


def _alerts_for(stats: dict) -> str:
    slo = stats.get("slo")
    if not slo:
        return "-"
    firing = sorted(
        name for name, st in slo.items() if st.get("state") == "firing"
    )
    pending = sorted(
        name for name, st in slo.items() if st.get("state") == "pending"
    )
    parts = [f"{n}!" for n in firing] + [f"{n}?" for n in pending]
    return ",".join(parts) if parts else "ok"


_TIER_ROWS = ("interactive", "batch", "best_effort")
_TOP_TENANTS = 5


def qos_summary_lines(stats: dict) -> list[str]:
    """The TIER/TENANT summary block: per-tier queued/outcome rollups and
    the top tenants by queued then shed. Empty when no replica reports an
    enabled ``serving.qos`` (the table stays exactly as it was)."""
    qos = stats.get("qos") or {}
    if not qos.get("enabled"):
        return []
    lines = ["", "QoS tiers:"]
    queued = qos.get("queued_by_tier") or {}
    tiers = qos.get("tiers") or {}
    header = ("TIER", "QUEUED", "DONE", "SHED", "QUOTA", "TIMEOUT")
    rows = [header]
    for tier in _TIER_ROWS:
        c = tiers.get(tier) or {}
        rows.append((
            tier, str(queued.get(tier, 0)), str(c.get("completed", 0)),
            str(c.get("shed", 0)), str(c.get("quota", 0)),
            str(c.get("timeout", 0)),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    lines += [
        "  " + "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        for r in rows
    ]
    tenants = qos.get("tenants") or {}
    queued_t = qos.get("queued_by_tenant") or {}
    names = sorted(
        set(tenants) | set(queued_t),
        key=lambda n: (
            -queued_t.get(n, 0),
            -(tenants.get(n) or {}).get("shed", 0),
            n,
        ),
    )[:_TOP_TENANTS]
    if names:
        lines.append(f"QoS tenants (top {len(names)} by queued/shed):")
        header = ("TENANT", "QUEUED", "DONE", "SHED", "QUOTA", "TIMEOUT")
        rows = [header]
        for name in names:
            c = tenants.get(name) or {}
            rows.append((
                name, str(queued_t.get(name, 0)),
                str(c.get("completed", 0)), str(c.get("shed", 0)),
                str(c.get("quota", 0)), str(c.get("timeout", 0)),
            ))
        widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
        lines += [
            "  " + "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
            for r in rows
        ]
    return lines


def render_table(stats: dict) -> str:
    """The per-replica table + an SLO footer, as one printable block."""
    rows = [list(_COLUMNS)]
    alerts = _alerts_for(stats)
    for name, r in sorted((stats.get("replicas") or {}).items()):
        rows.append([
            name,
            str(r.get("role") or "-"),
            "yes" if r.get("ready") else ("down" if not r.get("alive") else "no"),
            _fmt_num(r.get("queue_depth")),
            _fmt_num(r.get("busy_slots")),
            _fmt_occ(r.get("block_occupancy")),
            _fmt_pct(r.get("prefix_hit_rate")),
            _fmt_pct(r.get("spec_accept_rate")),
            # a stalled rolling update is visible here: versions disagree,
            # the mid-swap replica shows a trailing "*"
            _fmt_num(r.get("weights_version"))
            + ("*" if r.get("updating") else ""),
            alerts,
        ])
    widths = [max(len(row[i]) for row in rows) for i in range(len(_COLUMNS))]
    lines = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in rows
    ]
    slo = stats.get("slo")
    if slo:
        lines.append("")
        lines.append("SLO objectives:")
        for name, st in sorted(slo.items()):
            v = st.get("value")
            th = st.get("threshold")
            lines.append(
                f"  {name:<24} {st.get('state', '?'):<9} "
                f"value={'-' if v is None else f'{v:.4g}'} "
                f"threshold={'-' if th is None else f'{th:.4g}'} "
                f"fired={st.get('fired_count', 0)}"
            )
    lines.extend(qos_summary_lines(stats))
    ready = stats.get("replicas_ready")
    total = len(stats.get("replicas") or {})
    lines.append("")
    lines.append(f"{ready}/{total} replicas ready")
    ru = stats.get("rolling_update")
    if ru:
        lines.append(
            f"rolling update: {'ACTIVE' if ru.get('active') else 'done'} "
            f"{ru.get('done', 0)}/{ru.get('total', 0)}"
            + (f", updating {ru['current']}" if ru.get("current") else "")
            + (
                f", failed: {','.join(ru['failed'])}"
                if ru.get("failed") else ""
            )
        )
    asc = stats.get("autoscale")
    if asc:
        # elastic fleet footer: what the controller wants vs has, and the
        # last thing it did (docs/serving.md "Elastic fleet")
        lines.append(
            f"autoscale: {total} replicas "
            f"(bounds {asc.get('min_replicas')}..{asc.get('max_replicas')}), "
            f"{asc.get('scale_ups', 0)} up / {asc.get('scale_downs', 0)} "
            "down events"
        )
        last = asc.get("last_event")
        if last:
            ttr = last.get("time_to_ready_s")
            lines.append(
                f"  last scale: {last.get('direction')} "
                f"(trigger={last.get('trigger')}) "
                f"{last.get('replicas_before')} -> "
                f"{last.get('replicas_after')} replicas"
                + (f", time_to_ready={ttr:.2f}s" if ttr is not None else "")
            )
    return "\n".join(lines)


def _load_fleet_config(path: str) -> FleetConfig:
    from automodel_tpu.config.loader import load_yaml_config

    cfg = load_yaml_config(path)
    return FleetConfig.from_dict(dict(cfg.get("fleet", {}) or {}))


def snapshot(
    router_url: Optional[str],
    fcfg: Optional[FleetConfig],
    timeout_s: float,
    direct: bool = False,
) -> dict:
    """One status snapshot: router /stats when a router answers, else a
    direct replica sweep (the no-router path the docstring promises)."""
    if router_url and not direct:
        try:
            stats = _router_snapshot(router_url, timeout_s)
            stats["source"] = "router"
            return stats
        except ReplicaUnreachable:
            if fcfg is None or not fcfg.replicas:
                raise
    if fcfg is None or not fcfg.replicas:
        raise ReplicaUnreachable(
            "no router answered and no fleet.replicas to probe directly"
        )
    return _direct_snapshot(fcfg, timeout_s)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="automodel_tpu fleet-status",
        description="Per-replica fleet health table (router-federated or "
        "probed directly).",
    )
    p.add_argument("-c", "--config", help="YAML with a fleet: section")
    p.add_argument(
        "--router",
        help="router base URL (default: http://127.0.0.1:<fleet.port> "
        "from -c)",
    )
    p.add_argument(
        "--direct", action="store_true",
        help="skip the router, probe fleet.replicas directly",
    )
    p.add_argument("--json", action="store_true", help="raw snapshot JSON")
    p.add_argument(
        "--watch", action="store_true", help="refresh every --interval s"
    )
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument(
        "--iterations", type=int, default=0,
        help="with --watch: stop after N refreshes (0 = until ^C)",
    )
    p.add_argument("--timeout", type=float, default=3.0)
    args = p.parse_args(argv)

    fcfg = None
    router_url = args.router
    if args.config:
        try:
            fcfg = _load_fleet_config(args.config)
        except (OSError, TypeError, ValueError) as e:
            print(f"fleet-status: bad config {args.config}: {e}", file=sys.stderr)
            return 2
        if router_url is None and fcfg.port is not None:
            router_url = f"http://{fcfg.host}:{fcfg.port}"
    if router_url is None and fcfg is None:
        print(
            "fleet-status: need --router URL or -c config.yaml with a "
            "fleet: section", file=sys.stderr,
        )
        return 2

    n = 0
    while True:
        try:
            stats = snapshot(router_url, fcfg, args.timeout, direct=args.direct)
        except ReplicaUnreachable as e:
            print(f"fleet-status: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(stats, indent=2, default=str))
        else:
            print(render_table(stats))
        n += 1
        if not args.watch or (args.iterations and n >= args.iterations):
            return 0
        print(f"--- refresh in {args.interval:g}s (^C to stop) ---")
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
