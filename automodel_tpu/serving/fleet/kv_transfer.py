"""Prefill→decode KV handoff: a length-prefixed socket transport.

The disaggregated fleet (docs/serving.md "Fleet") separates prompt math
from token math: a PREFILL replica runs chunked prefill only, then streams
the request's finished KV block rows to its assigned DECODE replica, which
scatters them into its own pool through the same ``paged_write_targets``
cell addressing chunk prefill uses — both backends land rows in the same
cells by construction, and the decode replica starts the request directly
in decode.

Framing (one frame per handoff, one TCP connection per frame):

    b"AKV1" | u32 header_len | header JSON | (u64 buf_len | raw bytes) × N

The header carries the handoff id, prompt metadata, the POOL GEOMETRY both
sides must agree on (layers, block size, kv heads, head dim, kv dtype —
mismatch is a loud refusal, never a silent corrupt scatter), and an array
manifest ``[{key, shape, dtype}, ...]`` naming the N raw buffers in order.
bf16 pools ship one array per side (``k``/``v``, each ``[L, nb, BS, Nkv,
H]``); int8 pools ship ``(values, scales)`` pairs (``k_values``/
``k_scales``/``v_values``/``v_scales``) byte-for-byte — the round trip is
bit-identical (pinned by tests/test_fleet.py).

The receiver replies ``u32 len | JSON {"ok": true}`` (or ``{"ok": false,
"error": ...}``) AFTER the payload is parked in its bounded
:class:`HandoffStore`, so a prefill replica's ack to the router means the
decode replica really holds the bytes — the router's follow-up
POST /generate with the handoff id can never race an in-flight transfer.

**Prefix fetch (``op: kv_fetch``)** generalizes the same listener from a
disagg handoff sink into a prefix-sharing fabric (docs/serving.md
"Hierarchical KV cache"): a requester sends an array-less AKV1 frame whose
header carries ``op: "kv_fetch"``, the prompt's chain hashes, and its pool
geometry; the serving replica looks the hashes up in its OWN prefix cache
+ host spill tier (an engine-backed ``fetch_handler``) and answers with a
FULL AKV1 frame — ``{"ok": true, "blocks": n, ...}`` plus the block-row
arrays for the longest consecutive run it holds from hash 0. Geometry
mismatch, a missing handler, or zero matching blocks all answer loudly in
the response header; any transport death raises on the requester, whose
fallback is always local recompute.

**Weights fetch (``op: weights_fetch``)** is the elastic-fleet warm-start
path (docs/serving.md "Elastic fleet"): a JOINING replica asks a serving
peer for its whole param tree instead of paying the cold HF load. The
requester sends an array-less frame; the peer answers with a full AKV1
frame whose header carries the param-tree SIGNATURE (the PR 6 checkpoint
guard's ``{n_leaves, digest, entries}``) and whose arrays are the leaves,
keyed by tree path, streamed ONE LEAF AT A TIME (the ``hf_io`` shard-by-
shard idiom: peak host memory on the serving side is one leaf, not the
model). The requester validates the digest against its OWN structurally
built tree before swapping a single weight in; any failure — transport
death, refusal, digest mismatch — raises, and the joiner's fallback ladder
lands on the cold load it was trying to skip.

**Prefix push (``op: kv_push``)** is the scale-down migration path: a
RETIRING replica, drained, ships its hot prefix blocks (same chain-hash
keys, eviction-distance order) to a survivor's listener as one full AKV1
frame; the survivor parks whatever it can in its host spill tier and acks
``{"ok": true, "blocks": accepted}``. Push failure never blocks
retirement — the retiring side degrades to plain drain.

This module imports no jax: numpy (+ ml_dtypes for bf16) only, so the
router and tests can exercise the wire format without a device runtime.
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"AKV1"
_MAX_HEADER_BYTES = 1 << 20  # 1 MiB of JSON header is already absurd

GEOMETRY_KEYS = (
    "layers", "block_size", "num_kv_heads", "head_dim", "kv_cache_dtype"
)


class KVTransferError(RuntimeError):
    """Transport or validation failure — the handoff did not land."""


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def flatten_kv(kv: dict) -> list[tuple[str, np.ndarray]]:
    """``{"k": rows|(values, scales), "v": ...}`` → ordered named arrays."""
    out: list[tuple[str, np.ndarray]] = []
    for side in ("k", "v"):
        rows = kv[side]
        if isinstance(rows, tuple):
            out.append((f"{side}_values", np.asarray(rows[0])))
            out.append((f"{side}_scales", np.asarray(rows[1])))
        else:
            out.append((side, np.asarray(rows)))
    return out


def unflatten_kv(named: dict[str, np.ndarray]) -> dict:
    """Inverse of :func:`flatten_kv`."""
    out: dict[str, Any] = {}
    for side in ("k", "v"):
        if side in named:
            out[side] = named[side]
        else:
            out[side] = (named[f"{side}_values"], named[f"{side}_scales"])
    return out


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise KVTransferError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(
    sock: socket.socket, max_frame_bytes: Optional[int] = None
) -> tuple[dict, dict[str, np.ndarray]]:
    magic = _recv_exact(sock, 4)
    if magic != MAGIC:
        raise KVTransferError(f"bad magic {magic!r} (want {MAGIC!r})")
    (hlen,) = struct.unpack("<I", _recv_exact(sock, 4))
    if hlen > _MAX_HEADER_BYTES:
        raise KVTransferError(f"header length {hlen} exceeds the sane bound")
    header = json.loads(_recv_exact(sock, hlen))
    arrays: dict[str, np.ndarray] = {}
    total = 0
    for spec in header.get("arrays", []):
        (blen,) = struct.unpack("<Q", _recv_exact(sock, 8))
        # the wire length is untrusted until it matches what the manifest's
        # shape × dtype implies, and the frame total is capped (the
        # receiver's bound: one pool's worth of bytes) — a corrupt or
        # hostile length claim must fail loudly BEFORE any allocation, not
        # OOM the decode replica
        try:
            want = int(np.prod([int(d) for d in spec["shape"]], dtype=np.int64))
            want *= _np_dtype(spec["dtype"]).itemsize
        except (TypeError, ValueError) as e:
            raise KVTransferError(f"bad array manifest {spec!r}: {e}")
        if blen != want:
            raise KVTransferError(
                f"array {spec.get('key')!r} claims {blen} bytes but its "
                f"manifest shape/dtype implies {want}"
            )
        total += blen
        if max_frame_bytes is not None and total > max_frame_bytes:
            raise KVTransferError(
                f"frame exceeds the receiver's bound ({total} > "
                f"{max_frame_bytes} bytes — more than this pool could hold)"
            )
        raw = _recv_exact(sock, blen)
        arr = np.frombuffer(raw, dtype=_np_dtype(spec["dtype"]))
        arrays[spec["key"]] = arr.reshape([int(d) for d in spec["shape"]])
    return header, arrays


def _write_frame(sock: socket.socket, header: dict, arrays) -> None:
    specs = []
    bufs = []
    for key, arr in arrays:
        arr = np.ascontiguousarray(arr)
        specs.append(
            {"key": key, "shape": list(arr.shape), "dtype": arr.dtype.name}
        )
        bufs.append(arr.tobytes())
    hdr = json.dumps({**header, "arrays": specs}).encode()
    sock.sendall(MAGIC + struct.pack("<I", len(hdr)) + hdr)
    for raw in bufs:
        sock.sendall(struct.pack("<Q", len(raw)) + raw)


def _write_response(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(body)) + body)


def _read_response(sock: socket.socket) -> dict:
    (blen,) = struct.unpack("<I", _recv_exact(sock, 4))
    return json.loads(_recv_exact(sock, blen))


def send_kv(
    addr: tuple[str, int],
    meta: dict,
    kv: dict,
    timeout_s: float = 30.0,
) -> dict:
    """Ship one handoff payload to a decode replica's listener. ``meta``
    must carry ``handoff_id``/``prompt_len``/``first_token`` and the
    sender's pool ``geometry``. → the receiver's ack dict; raises
    :class:`KVTransferError` when the transfer or validation failed."""
    from automodel_tpu.resilience.fault_injection import active_injector

    inj = active_injector()
    if inj is not None:
        inj.maybe_trace_delay("kv_send")
    try:
        with socket.create_connection(addr, timeout=timeout_s) as sock:
            _write_frame(sock, dict(meta), flatten_kv(kv))
            resp = _read_response(sock)
    except (OSError, ValueError) as e:
        raise KVTransferError(f"KV transfer to {addr} failed: {e}") from e
    if not resp.get("ok"):
        raise KVTransferError(
            f"decode replica at {addr} refused the handoff: "
            f"{resp.get('error', 'unknown error')}"
        )
    return resp


def fetch_kv(
    addr: tuple[str, int],
    chain_hashes: Sequence[int],
    geometry: dict,
    timeout_s: float = 5.0,
    max_frame_bytes: Optional[int] = None,
    traceparent: Optional[str] = None,
) -> tuple[int, Optional[dict]]:
    """Ask the peer at ``addr`` for the prefix blocks named by
    ``chain_hashes`` (consecutive chain order, hash 0 first). → ``(blocks,
    kv)`` — the longest consecutive run the peer holds and its rows
    (``(0, None)`` when it holds nothing). Raises :class:`KVTransferError`
    on transport death, a refused request, or a malformed reply; the
    caller's fallback is always local recompute."""
    from automodel_tpu.resilience.fault_injection import active_injector

    inj = active_injector()
    if inj is not None:
        inj.maybe_trace_delay("kv_fetch")
    header = {
        "op": "kv_fetch",
        "chain_hashes": [int(h) for h in chain_hashes],
        "geometry": {k: geometry[k] for k in GEOMETRY_KEYS},
    }
    if traceparent:
        header["traceparent"] = traceparent
    try:
        with socket.create_connection(addr, timeout=timeout_s) as sock:
            _write_frame(sock, header, [])
            resp, arrays = _read_frame(sock, max_frame_bytes=max_frame_bytes)
    except (OSError, ValueError) as e:
        raise KVTransferError(f"KV fetch from {addr} failed: {e}") from e
    if not resp.get("ok"):
        raise KVTransferError(
            f"peer at {addr} refused the prefix fetch: "
            f"{resp.get('error', 'unknown error')}"
        )
    n = resp.get("blocks")
    if not isinstance(n, int) or n < 0 or n > len(chain_hashes):
        raise KVTransferError(f"peer at {addr} claims a bad block count {n!r}")
    if n == 0:
        return 0, None
    kv = unflatten_kv(arrays)
    for key, arr in arrays.items():
        if int(arr.shape[1]) != n:
            raise KVTransferError(
                f"fetch reply array {key} carries {arr.shape[1]} blocks "
                f"but the header claims {n}"
            )
    return n, kv


def fetch_weights(
    addr: tuple[str, int],
    timeout_s: float = 60.0,
    max_frame_bytes: Optional[int] = None,
) -> tuple[dict, dict[str, np.ndarray]]:
    """Ask the serving peer at ``addr`` for its whole param tree (the
    warm-start path). → ``(signature, arrays)`` — the peer's param-tree
    signature dict (``{n_leaves, digest, entries}``) and the leaves keyed
    by tree path. Raises :class:`KVTransferError` on transport death, a
    refusal, or a malformed reply; the caller's fallback ladder lands on
    the cold HF load."""
    from automodel_tpu.resilience.fault_injection import active_injector

    inj = active_injector()
    if inj is not None:
        inj.maybe_trace_delay("weights_fetch")
    try:
        with socket.create_connection(addr, timeout=timeout_s) as sock:
            _write_frame(sock, {"op": "weights_fetch"}, [])
            resp, arrays = _read_frame(sock, max_frame_bytes=max_frame_bytes)
    except (OSError, ValueError) as e:
        raise KVTransferError(f"weights fetch from {addr} failed: {e}") from e
    if not resp.get("ok"):
        raise KVTransferError(
            f"peer at {addr} refused the weights fetch: "
            f"{resp.get('error', 'unknown error')}"
        )
    sig = resp.get("signature")
    if not isinstance(sig, dict) or "digest" not in sig:
        raise KVTransferError(
            f"peer at {addr} sent no param-tree signature with its weights"
        )
    n = sig.get("n_leaves")
    if isinstance(n, int) and n != len(arrays):
        raise KVTransferError(
            f"peer at {addr} signed {n} leaves but shipped {len(arrays)}"
        )
    return sig, arrays


def push_kv(
    addr: tuple[str, int],
    chain_hashes: Sequence[int],
    kv: dict,
    geometry: dict,
    timeout_s: float = 10.0,
) -> int:
    """Ship the prefix blocks named by ``chain_hashes`` (consecutive chain
    order) to the survivor at ``addr`` — the scale-down migration path.
    ``kv`` carries ``len(chain_hashes)`` block rows. → the number of
    blocks the survivor accepted into its spill tier. Raises
    :class:`KVTransferError` on transport death or refusal; the retiring
    caller's fallback is plain drain, never a blocked exit."""
    from automodel_tpu.resilience.fault_injection import active_injector

    inj = active_injector()
    if inj is not None:
        inj.maybe_trace_delay("kv_push")
    header = {
        "op": "kv_push",
        "chain_hashes": [int(h) for h in chain_hashes],
        "geometry": {k: geometry[k] for k in GEOMETRY_KEYS},
    }
    try:
        with socket.create_connection(addr, timeout=timeout_s) as sock:
            _write_frame(sock, header, flatten_kv(kv))
            resp = _read_response(sock)
    except (OSError, ValueError) as e:
        raise KVTransferError(f"KV push to {addr} failed: {e}") from e
    if not resp.get("ok"):
        raise KVTransferError(
            f"survivor at {addr} refused the prefix push: "
            f"{resp.get('error', 'unknown error')}"
        )
    n = resp.get("blocks")
    if not isinstance(n, int) or n < 0 or n > len(chain_hashes):
        raise KVTransferError(
            f"survivor at {addr} claims a bad accepted count {n!r}"
        )
    return n


class HandoffStore:
    """Bounded host-side parking lot for received payloads between the
    transfer landing and the router's POST /generate claiming it. TTL +
    max_pending keep an orphaned handoff (router died in between) from
    pinning prompt-KV bytes forever."""

    def __init__(self, max_pending: int = 32, ttl_s: float = 120.0):
        self.max_pending = max(int(max_pending), 1)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[float, dict]] = {}

    def put(self, handoff_id: str, entry: dict) -> None:
        now = time.monotonic()
        with self._lock:
            expired = [
                h for h, (t, _) in self._entries.items()
                if now - t > self.ttl_s
            ]
            for h in expired:
                del self._entries[h]
                logger.warning("KV handoff %s expired unclaimed", h)
            while len(self._entries) >= self.max_pending:
                oldest = min(self._entries, key=lambda h: self._entries[h][0])
                del self._entries[oldest]
                logger.warning("KV handoff %s evicted (store full)", oldest)
            self._entries[handoff_id] = (now, entry)

    def pop(self, handoff_id: str) -> dict:
        with self._lock:
            try:
                _, entry = self._entries.pop(handoff_id)
            except KeyError:
                raise KeyError(
                    f"no pending KV handoff {handoff_id!r} (never arrived, "
                    "expired, or already claimed)"
                )
        return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class KVTransferServer:
    """The decode replica's listener: one thread-per-connection TCP server
    validating each frame's geometry against THIS replica's pool and
    parking accepted payloads in the :class:`HandoffStore`."""

    def __init__(
        self,
        expected_geometry: dict,
        store: Optional[HandoffStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_pending: int = 32,
        ttl_s: float = 120.0,
        max_frame_bytes: Optional[int] = None,
        tracer: Any = None,
        fetch_handler: Any = None,
        weights_handler: Any = None,
        push_handler: Any = None,
    ):
        self.expected = {k: expected_geometry[k] for k in GEOMETRY_KEYS}
        self.store = store or HandoffStore(max_pending=max_pending, ttl_s=ttl_s)
        self.max_frame_bytes = max_frame_bytes
        # prefix-fetch lookup: ``fetch_handler(chain_hashes) -> (n, kv)``
        # returning the longest consecutive run of blocks this replica holds
        # (resident prefix cache or host spill tier) for the hashes, as one
        # ``{"k": ..., "v": ...}`` inject payload. Settable after
        # construction (the serving front wires it once the engine lock
        # exists); None = this listener serves handoffs only.
        self.fetch_handler = fetch_handler
        # warm-start source: ``weights_handler() -> (signature, leaves)``
        # where leaves is an ordered ``[(tree_path, array), ...]`` — the
        # reply streams one leaf at a time so the serving side's peak host
        # cost is a single leaf. None = this listener serves no weights.
        self.weights_handler = weights_handler
        # migration sink: ``push_handler(chain_hashes, kv) -> accepted`` —
        # parks what it can in the spill tier. None = pushes are refused.
        self.push_handler = push_handler
        # request tracing: when the sender's AKV1 header carries a
        # `traceparent`, the receive (frame read + validation + store.put)
        # is recorded as a kv_receive span on THIS replica's tracer,
        # parented under the sender's kv_send span — the transfer leaves
        # evidence on both sides of the wire
        self.tracer = tracer
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                t0 = time.perf_counter()
                from automodel_tpu.resilience.fault_injection import (
                    active_injector,
                )

                inj = active_injector()
                if inj is not None:
                    inj.maybe_trace_delay("kv_receive")
                try:
                    header, arrays = _read_frame(
                        self.request, max_frame_bytes=outer.max_frame_bytes
                    )
                except KVTransferError as e:
                    logger.warning("bad KV transfer frame: %s", e)
                    try:
                        _write_response(self.request, {"ok": False, "error": str(e)})
                    except OSError:
                        pass
                    return
                if header.get("op") == "kv_fetch":
                    outer._handle_fetch(self.request, header, t0)
                    return
                if header.get("op") == "weights_fetch":
                    outer._handle_weights(self.request, header, t0)
                    return
                if header.get("op") == "kv_push":
                    outer._handle_push(self.request, header, arrays, t0)
                    return
                err = outer._validate(header, arrays)
                if err is not None:
                    outer._record_receive(header, t0, error=err[:200])
                    _write_response(self.request, {"ok": False, "error": err})
                    return
                outer.store.put(str(header["handoff_id"]), {
                    "meta": {
                        k: header.get(k)
                        for k in ("request_id", "prompt_len", "first_token")
                    },
                    "kv": unflatten_kv(arrays),
                })
                outer._record_receive(
                    header, t0,
                    bytes=sum(a.nbytes for a in arrays.values()),
                )
                _write_response(
                    self.request, {"ok": True, "handoff_id": header["handoff_id"]}
                )

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, int(port)), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="kv-transfer", daemon=True
        )

    def _handle_fetch(self, sock, header: dict, t0: float) -> None:
        """Answer one ``op: kv_fetch`` request with a full AKV1 frame —
        the longest consecutive run of requested prefix blocks this
        replica's cache hierarchy holds (``blocks: 0`` + no arrays on a
        clean miss)."""

        def refuse(error: str) -> None:
            logger.warning("refusing KV fetch: %s", error)
            self._record_span("kv_fetch", header, t0, error=error[:200])
            try:
                _write_frame(sock, {"ok": False, "error": error}, [])
            except OSError:
                pass

        if self.fetch_handler is None:
            return refuse("this replica serves no prefix fetches")
        geom = header.get("geometry") or {}
        got = {k: geom.get(k) for k in GEOMETRY_KEYS}
        if got != self.expected:
            return refuse(
                f"pool geometry mismatch: requester {got} != holder "
                f"{self.expected} — fetched rows would scatter corrupt"
            )
        hashes = header.get("chain_hashes")
        if not isinstance(hashes, list) or not all(
            isinstance(h, int) for h in hashes
        ):
            return refuse(f"bad chain_hashes {type(hashes).__name__}")
        try:
            n, kv = self.fetch_handler(hashes)
        except Exception as e:  # the lookup must never kill the listener
            logger.warning("KV fetch handler failed", exc_info=True)
            return refuse(f"fetch handler failed: {e}")
        arrays = flatten_kv(kv) if n else []
        self._record_span(
            "kv_fetch", header, t0,
            blocks=int(n), bytes=sum(a.nbytes for _, a in arrays),
        )
        try:
            _write_frame(sock, {"ok": True, "blocks": int(n)}, arrays)
        except OSError as e:
            logger.warning("KV fetch reply failed mid-frame: %s", e)

    def _handle_weights(self, sock, header: dict, t0: float) -> None:
        """Answer one ``op: weights_fetch`` request: signature header, then
        the param-tree leaves streamed one at a time (peak host cost on
        this side is a single leaf, never the whole model)."""

        def refuse(error: str) -> None:
            logger.warning("refusing weights fetch: %s", error)
            self._record_span("weights_fetch", header, t0, error=error[:200])
            try:
                _write_frame(sock, {"ok": False, "error": error}, [])
            except OSError:
                pass

        if self.weights_handler is None:
            return refuse("this replica serves no weights")
        try:
            signature, leaves = self.weights_handler()
        except Exception as e:  # the source must never kill the listener
            logger.warning("weights handler failed", exc_info=True)
            return refuse(f"weights handler failed: {e}")
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        specs = []
        for key, leaf in leaves:
            dtype = getattr(leaf, "dtype", None)
            name = getattr(dtype, "name", None) or str(dtype)
            specs.append({
                "key": key,
                "shape": [int(d) for d in leaf.shape],
                "dtype": name,
            })
        hdr = json.dumps(
            {"ok": True, "signature": signature, "arrays": specs}
        ).encode()
        total = 0
        try:
            sock.sendall(MAGIC + struct.pack("<I", len(hdr)) + hdr)
            for sent, (key, leaf) in enumerate(leaves):
                if inj is not None and inj.should_abort_weights_stream(sent):
                    # chaos: the peer "dies" mid-stream — close without the
                    # remaining leaves so the joiner sees a truncated frame
                    logger.warning(
                        "injected weights-stream abort after %d leaves", sent
                    )
                    return
                raw = np.ascontiguousarray(np.asarray(leaf)).tobytes()
                total += len(raw)
                sock.sendall(struct.pack("<Q", len(raw)) + raw)
        except OSError as e:
            logger.warning("weights reply failed mid-stream: %s", e)
            return
        self._record_span(
            "weights_fetch", header, t0, leaves=len(leaves), bytes=total
        )

    def _handle_push(
        self, sock, header: dict, arrays: dict, t0: float
    ) -> None:
        """Park one ``op: kv_push`` migration frame in this replica's
        spill tier and ack how many blocks were accepted."""

        def refuse(error: str) -> None:
            logger.warning("refusing KV push: %s", error)
            self._record_span("kv_push", header, t0, error=error[:200])
            try:
                _write_response(sock, {"ok": False, "error": error})
            except OSError:
                pass

        if self.push_handler is None:
            return refuse("this replica accepts no prefix pushes")
        geom = header.get("geometry") or {}
        got = {k: geom.get(k) for k in GEOMETRY_KEYS}
        if got != self.expected:
            return refuse(
                f"pool geometry mismatch: pusher {got} != receiver "
                f"{self.expected} — migrated rows would reload corrupt"
            )
        hashes = header.get("chain_hashes")
        if not isinstance(hashes, list) or not all(
            isinstance(h, int) for h in hashes
        ):
            return refuse(f"bad chain_hashes {type(hashes).__name__}")
        for key, arr in arrays.items():
            if int(arr.shape[1]) != len(hashes):
                return refuse(
                    f"array {key} carries {arr.shape[1]} blocks for "
                    f"{len(hashes)} chain hashes"
                )
        try:
            accepted = int(self.push_handler(hashes, unflatten_kv(arrays)))
        except Exception as e:  # the sink must never kill the listener
            logger.warning("KV push handler failed", exc_info=True)
            return refuse(f"push handler failed: {e}")
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        if inj is not None and inj.should_drop_kv_push():
            # chaos: the migration target "dies" before acking — close the
            # socket so the retiring pusher sees a dead transfer
            logger.warning("injected KV push drop before ack")
            return
        self._record_span(
            "kv_push", header, t0, blocks=accepted,
            bytes=sum(a.nbytes for a in arrays.values()),
        )
        try:
            _write_response(sock, {"ok": True, "blocks": accepted})
        except OSError as e:
            logger.warning("KV push ack failed: %s", e)

    def _record_receive(self, header: dict, t0: float, **attrs) -> None:
        """kv_receive span for a frame whose header carried a traceparent
        (sampled-out or untraced sends record nothing)."""
        self._record_span("kv_receive", header, t0, **attrs)

    def _record_span(self, stage: str, header: dict, t0: float, **attrs) -> None:
        if self.tracer is None:
            return
        parent = self.tracer.parse(header.get("traceparent"))
        if parent is None:
            return
        try:
            self.tracer.record(
                self.tracer.start(parent=parent), stage, t0,
                request_id=header.get("request_id"),
                handoff_id=header.get("handoff_id"), **attrs,
            )
        except Exception:  # telemetry must never break the transfer
            pass

    def _validate(self, header: dict, arrays: dict) -> Optional[str]:
        if "handoff_id" not in header:
            return "frame header has no handoff_id"
        geom = header.get("geometry") or {}
        got = {k: geom.get(k) for k in GEOMETRY_KEYS}
        if got != self.expected:
            return (
                f"pool geometry mismatch: sender {got} != receiver "
                f"{self.expected} — prefill and decode replicas must share "
                "layers/block_size/num_kv_heads/head_dim/kv_cache_dtype"
            )
        p = header.get("prompt_len")
        if not isinstance(p, int) or p < 1:
            return f"bad prompt_len {p!r}"
        bs = int(self.expected["block_size"])
        nb = -(-p // bs)
        for key, arr in arrays.items():
            if int(arr.shape[1]) != nb:
                return (
                    f"array {key} carries {arr.shape[1]} blocks for a "
                    f"{p}-token prompt (expected ceil({p}/{bs}) = {nb})"
                )
        return None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "KVTransferServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
