"""Paged KV-cache block allocator (host side).

The vLLM idea (Kwon et al., PagedAttention) translated to the existing
generation cache: HBM holds ONE fixed pool of fixed-size blocks
(``[L, num_blocks, block_size, N_kv, H]`` per of k/v — serving/paged.py owns
the arrays); each sequence owns a **block table** (a list of block ids) and
long and short requests share the pool without fragmentation — a finished
short completion returns its blocks immediately instead of stranding a
contiguous ``[L, B, C, ...]`` region until the longest sequence in the wave
finishes.

This module is the pure-python accountant: free list, per-block reference
counts, and the **prefix cache** — completed prompt blocks are retained
(keyed on a CHAIN hash of their token contents, so a hit guarantees the
whole prefix matches) and a new request with the same prompt prefix shares
them by incref instead of recomputing their K/V. Zero-ref cached blocks sit
in an LRU and are evicted only when the free list runs dry, so prefix
caching never makes an allocation fail that would otherwise succeed.

Block 0 is a reserved SCRATCH block: the jitted paged decode step always
writes its token somewhere (XLA has no conditional scatter), so inactive
slots are pointed at block 0 and their junk writes land where no sequence
ever reads. The allocator never hands block 0 out.

Invariants (``check_invariants`` — the property tests drive a randomized
admit/finish schedule against them):
- every non-scratch block is in exactly ONE of {free list, LRU, in use
  (ref > 0)};
- free/LRU blocks have ref == 0; freeing a ref-0 block raises (double
  free), as does freeing scratch;
- ``counters`` account allocations/frees/hits/evictions exactly.

**Host spill tier** (``serving.kv_spill:``, docs/serving.md "Hierarchical
KV cache"): when a zero-ref prefix block is evicted from the LRU, the
engine's spill hook copies its rows device→host into a bounded
:class:`HostSpillTier` keyed by the SAME chain hash the prefix cache used
— an evicted prefix is then a host-RAM reload (``paged.inject_blocks``,
the disagg-handoff seam) instead of a full re-prefill. The tier is an
opaque byte store to this module (payloads are whatever
``paged.extract_blocks`` returned — pool-native bytes, so reload is
bit-identical to recompute by construction); its byte accounting and
counters are audited by ``check_invariants`` alongside the pool's.
"""

from __future__ import annotations

import hashlib
import struct
from collections import OrderedDict
from typing import Optional, Sequence


class BlockPoolError(RuntimeError):
    """Allocator misuse: double free, freeing scratch, corrupt accounting."""


def _chain_hash(parent: Optional[int], tokens: Sequence[int]) -> int:
    """One chain link: hash of (parent chain hash, this block's tokens).

    Deterministic ACROSS PROCESSES and Python versions (unlike builtin
    ``hash``, whose ``None``/str hashing varies per interpreter): the fleet
    router (serving/fleet/router.py) hashes a prompt's block chain in its
    own process and matches it against the chain heads a REPLICA's prefix
    cache advertised over /stats — the two sides must agree bit-for-bit or
    prefix-affinity placement never hits."""
    buf = struct.pack("<q", -1 if parent is None else int(parent))
    buf += struct.pack(f"<{len(tokens)}q", *(int(t) for t in tokens))
    return int.from_bytes(
        hashlib.blake2b(buf, digest_size=8).digest(), "little", signed=True
    )


def prompt_chain(tokens: Sequence[int], block_size: int) -> list[int]:
    """Cumulative chain hashes of a prompt's matchable full blocks — the
    hashes ``match_prefix`` would look up, in order, capped at ``len(tokens)
    - 1`` tokens (the last prompt token is always recomputed: its logits
    seed the first sampled token). ``prompt_chain(p, bs)[i]`` equals the key
    ``register_prefix(p, ...)`` filed block ``i`` under, by construction —
    the router-side spelling of the replica-side chain rule."""
    bs = int(block_size)
    out: list[int] = []
    parent: Optional[int] = None
    for i in range((max(len(tokens) - 1, 0)) // bs):
        parent = _chain_hash(parent, tokens[i * bs : (i + 1) * bs])
        out.append(parent)
    return out


def blocks_needed(total_tokens: int, block_size: int, write_overhang: int = 0) -> int:
    """Whole-budget block count for a request: ``ceil((tokens + overhang) /
    block_size)``. ``write_overhang`` covers positions a program may WRITE
    past the committed budget — speculative decoding's verify forward puts
    up to ``spec_k`` rejected-draft rows beyond the final length (they are
    rolled back by a length decrement, never attended, but the table must
    point their writes at real blocks, not out of range). One spelling
    shared by submit-time validation and admission so the two can't drift."""
    return -(-(int(total_tokens) + int(write_overhang)) // int(block_size))


class HostSpillTier:
    """Bounded host-RAM parking lot for evicted prefix blocks.

    One entry per chain hash, holding the opaque per-block KV payload the
    engine extracted at eviction time (pool-native bytes: int8 values +
    fp32 scales for int8 pools, bf16 rows otherwise). LRU within the byte
    budget: a ``put`` past ``max_bytes`` evicts the least recently touched
    entries; a payload larger than the whole budget is rejected (counted,
    never stored). ``get`` refreshes recency and leaves the entry resident
    — the tier is a cache, not a queue: one spilled prefix can serve many
    reloads across its lifetime."""

    def __init__(self, max_bytes: int):
        if max_bytes < 1:
            raise ValueError(f"HostSpillTier(max_bytes={max_bytes})")
        self.max_bytes = int(max_bytes)
        self.bytes = 0
        self._entries: "OrderedDict[int, tuple[int, object]]" = OrderedDict()
        self.counters = {
            "spill_puts": 0,  # blocks copied in (overwrites included)
            "spill_gets": 0,  # reload lookups that hit
            "spill_evicted": 0,  # entries dropped to fit the byte budget
            "spill_rejected": 0,  # payloads larger than the whole budget
        }

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, h: int) -> bool:
        return h in self._entries

    def put(self, h: int, payload: object, nbytes: int) -> bool:
        """Park one evicted block's rows under its chain hash. → False when
        the payload alone exceeds the byte budget (rejected, counted)."""
        nbytes = int(nbytes)
        if nbytes > self.max_bytes:
            self.counters["spill_rejected"] += 1
            return False
        old = self._entries.pop(h, None)
        if old is not None:
            self.bytes -= old[0]
        while self.bytes + nbytes > self.max_bytes:
            _, (evicted_bytes, _) = self._entries.popitem(last=False)
            self.bytes -= evicted_bytes
            self.counters["spill_evicted"] += 1
        self._entries[h] = (nbytes, payload)
        self.bytes += nbytes
        self.counters["spill_puts"] += 1
        return True

    def get(self, h: int):
        """→ the parked payload (recency refreshed), or None on a miss."""
        entry = self._entries.get(h)
        if entry is None:
            return None
        self._entries.move_to_end(h)
        self.counters["spill_gets"] += 1
        return entry[1]

    def chain_hashes(self) -> list[int]:
        """Resident chain hashes, most recently touched first — the order
        ``hot_prefixes`` advertisement wants (the MRU end is farthest from
        eviction, so advertising it promises affinity the tier will keep)."""
        return list(reversed(self._entries))

    def clear(self) -> None:
        self._entries.clear()
        self.bytes = 0

    def check_invariants(self) -> None:
        if self.bytes != sum(n for n, _ in self._entries.values()):
            raise BlockPoolError(
                f"host spill tier byte ledger desynced: {self.bytes} != "
                f"sum of entry sizes"
            )
        if self.bytes > self.max_bytes:
            raise BlockPoolError(
                f"host spill tier over budget: {self.bytes} > {self.max_bytes}"
            )
        if any(v < 0 for v in self.counters.values()):
            raise BlockPoolError(f"negative spill counter: {self.counters}")
        if self.counters["spill_puts"] < len(self._entries):
            raise BlockPoolError(
                "host spill tier holds more entries than were ever put"
            )


class BlockPool:
    def __init__(
        self, num_blocks: int, block_size: int, prefix_cache: bool = True
    ):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks={num_blocks}: need >= 2 (block 0 is scratch)"
            )
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_cache_enabled = bool(prefix_cache)
        # LIFO free list: recently freed blocks are re-handed first (warm)
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: dict[int, int] = {b: 0 for b in range(num_blocks)}
        self._cached: dict[int, int] = {}  # chain hash -> block id
        self._hash_of: dict[int, int] = {}  # block id -> chain hash
        self._lru: "OrderedDict[int, int]" = OrderedDict()  # hash -> ref-0 bid
        # host spill tier (attached by the engine when serving.kv_spill is
        # enabled) + the eviction hook that feeds it: called with a list of
        # (chain_hash, block_id) pairs BEFORE allocate() returns the evicted
        # blocks, while their device rows are still intact
        self.spill: Optional[HostSpillTier] = None
        self.on_evict = None
        self.counters = {
            "allocated": 0,
            "freed": 0,
            "prefix_hits": 0,  # requests that matched >= 1 block
            "prefix_blocks_reused": 0,
            "prefix_tokens_reused": 0,
            # token-weighted prefix accounting: matchable prompt tokens
            # served from cache (resident hit, host-tier reload, or peer
            # fetch) vs recomputed — the request-count `prefix_hits` above
            # overstates 1-block matches; effective hit rate is
            # hit_tokens / (hit_tokens + miss_tokens)
            "prefix_hit_tokens": 0,
            "prefix_miss_tokens": 0,
            "evictions": 0,
            "failed_allocs": 0,
            # hierarchical tier traffic (docs/serving.md "Hierarchical KV
            # cache"): blocks spilled device→host at eviction, blocks
            # reloaded host→device at admission, reload admissions, peer
            # blocks fetched over /kv_fetch, and failed peer fetches (each
            # one fell back to local recompute)
            "spilled_blocks": 0,
            "spill_reloaded_blocks": 0,
            "spill_reloads": 0,
            "peer_fetch_blocks": 0,
            "peer_fetches": 0,
            "peer_fetch_failures": 0,
        }

    # -- capacity -------------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # minus scratch

    def available(self) -> int:
        """Blocks an allocate() could hand out right now (free + evictable)."""
        return len(self._free) + len(self._lru)

    def in_use(self) -> int:
        return self.usable_blocks - self.available()

    def occupancy(self) -> float:
        """Fraction of the usable pool referenced by live sequences (cached
        ref-0 blocks count as available — they are reclaimable on demand)."""
        return self.in_use() / max(self.usable_blocks, 1)

    # -- prefix cache ---------------------------------------------------------
    @staticmethod
    def _chain(parent: Optional[int], tokens: tuple) -> int:
        return _chain_hash(parent, tokens)

    def match_prefix(self, tokens: Sequence[int]) -> tuple[list[int], int]:
        """→ (block ids, matched token count) for the longest cached
        block-aligned prefix of ``tokens``, each hit INCREF'd for the caller.
        Capped at ``len(tokens) - 1`` tokens: the last prompt token must
        always be recomputed — its logits seed the first sampled token."""
        if not self.prefix_cache_enabled:
            return [], 0
        bs = self.block_size
        hits: list[int] = []
        parent: Optional[int] = None
        for i in range((max(len(tokens) - 1, 0)) // bs):
            h = self._chain(parent, tuple(tokens[i * bs : (i + 1) * bs]))
            bid = self._cached.get(h)
            if bid is None:
                break
            if self._ref[bid] == 0:
                self._lru.pop(h)
            self._ref[bid] += 1
            hits.append(bid)
            parent = h
        if hits:
            self.counters["prefix_hits"] += 1
            self.counters["prefix_blocks_reused"] += len(hits)
            self.counters["prefix_tokens_reused"] += len(hits) * bs
        return hits, len(hits) * bs

    def register_prefix(self, tokens: Sequence[int], blocks: Sequence[int]) -> None:
        """Make a prefilled prompt's FULL blocks matchable by later requests
        (no refcount is taken — a registered block freed to ref 0 parks in
        the LRU, matchable until evicted). ``blocks`` is the sequence's block
        table; only the ``len(tokens) // block_size`` full blocks register."""
        if not self.prefix_cache_enabled:
            return
        bs = self.block_size
        parent: Optional[int] = None
        for i in range(len(tokens) // bs):
            bid = blocks[i]
            h = self._chain(parent, tuple(tokens[i * bs : (i + 1) * bs]))
            # first writer wins: an existing mapping (another request computed
            # the same prefix concurrently) or a block already registered
            # under a different hash is left alone
            if h not in self._cached and bid not in self._hash_of:
                self._cached[h] = bid
                self._hash_of[bid] = h
            parent = h

    def cached_block(self, h: int) -> Optional[int]:
        """Block id currently caching chain hash ``h`` (resident tier only,
        no refcount taken) — the engine's /kv_fetch handler peeks with this
        to extract a peer-requested block without admitting anything."""
        return self._cached.get(int(h))

    def cached_chain_hashes(self, limit: Optional[int] = None) -> list[int]:
        """The chain hashes this pool's prefix cache can currently serve —
        what a replica advertises over /stats (``hot_prefixes``) for the
        fleet router's affinity placement and for peer /kv_fetch. ``limit``
        bounds the advertisement by eviction distance: chains whose blocks
        are REFERENCED right now cannot be evicted at all and always
        advertise; the remaining budget fills from the most recently parked
        end of the LRU — the parked-longest entries are the next evicted,
        so advertising them would promise affinity the pool is about to
        break. With a host spill tier attached, its resident chains (MRU
        first) fill any leftover budget: a spilled prefix is still
        servable — by reload locally, by /kv_fetch to a peer."""
        pinned = [h for h in self._cached if h not in self._lru]
        parked = list(self._lru)
        seen = set(pinned) | set(parked)
        spilled = (
            [h for h in self.spill.chain_hashes() if h not in seen]
            if self.spill is not None
            else []
        )
        if limit is None:
            return pinned + parked + spilled
        n = int(limit)
        room = max(n - len(pinned), 0)
        out = (pinned + (parked[-room:] if room else []))[:n]
        return out + spilled[: n - len(out)]

    def note_prefix_tokens(self, hit_tokens: int, miss_tokens: int) -> None:
        """Token-weighted prefix accounting, stamped ONCE per admission by
        the engine AFTER spill-reload/peer-fetch resolution (the pool alone
        cannot know how many missed tokens the hierarchy recovered):
        ``hit_tokens`` = matchable prompt tokens served from any tier,
        ``miss_tokens`` = matchable tokens that recompute."""
        if hit_tokens < 0 or miss_tokens < 0:
            raise ValueError(
                f"note_prefix_tokens({hit_tokens}, {miss_tokens})"
            )
        self.counters["prefix_hit_tokens"] += int(hit_tokens)
        self.counters["prefix_miss_tokens"] += int(miss_tokens)

    def clear_prefix_cache(self) -> None:
        """Forget every cached prefix — the serving engine calls this when
        it rebuilds after a stalled/failed program, because the pool's K/V
        contents can no longer be trusted. Ref-0 parked blocks return to
        the free list; a registered block still referenced by a live
        sequence merely loses its hash mapping and frees normally later.
        The host spill tier is dropped too: its payloads were extracted
        from the pool this rebuild just declared untrusted."""
        for bid in self._lru.values():
            self._free.append(bid)
        self._lru.clear()
        self._cached.clear()
        self._hash_of.clear()
        if self.spill is not None:
            self.spill.clear()

    # -- allocate / free ------------------------------------------------------
    def allocate(self, n: int) -> Optional[list[int]]:
        """n fresh blocks (ref = 1 each), or None when the pool can't satisfy
        the request (caller leaves the sequence queued). Evicts LRU cached
        blocks only when the free list is empty."""
        if n < 0:
            raise ValueError(f"allocate({n})")
        if n > self.available():
            self.counters["failed_allocs"] += 1
            return None
        out: list[int] = []
        evicted: list[tuple[int, int]] = []
        for _ in range(n):
            if self._free:
                bid = self._free.pop()
            else:
                h, bid = self._lru.popitem(last=False)  # oldest cached
                del self._cached[h]
                del self._hash_of[bid]
                self.counters["evictions"] += 1
                evicted.append((h, bid))
            self._ref[bid] = 1
            out.append(bid)
        if evicted and self.on_evict is not None:
            # spill hook: the engine copies the evicted blocks' rows
            # device→host in one bucketed batch. The blocks are already
            # handed out above, but nothing writes them until this
            # allocate()'s caller injects/prefills — extraction here is
            # strictly before any overwrite. A spill failure loses cached
            # bytes, never correctness, so it must not fail the allocation.
            try:
                self.on_evict(evicted)
            except Exception:  # pragma: no cover - defensive
                import logging

                logging.getLogger(__name__).warning(
                    "KV spill hook failed; %d evicted blocks not spilled",
                    len(evicted),
                    exc_info=True,
                )
        self.counters["allocated"] += n
        return out

    def free(self, blocks: Sequence[int]) -> None:
        """Decref every block; a block reaching ref 0 returns to the free
        list, or parks in the LRU when it is prefix-cache registered."""
        for bid in blocks:
            if bid == 0:
                raise BlockPoolError("freeing the scratch block")
            if self._ref.get(bid, 0) <= 0:
                raise BlockPoolError(f"double free of block {bid}")
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                h = self._hash_of.get(bid)
                if h is not None:
                    self._lru[h] = bid
                else:
                    self._free.append(bid)
        self.counters["freed"] += len(blocks)

    # -- audit ----------------------------------------------------------------
    def check_invariants(self) -> None:
        free_set = set(self._free)
        lru_set = set(self._lru.values())
        used_set = {b for b in range(1, self.num_blocks) if self._ref[b] > 0}
        if free_set & lru_set or free_set & used_set or lru_set & used_set:
            raise BlockPoolError("block in two states at once")
        if free_set | lru_set | used_set != set(range(1, self.num_blocks)):
            raise BlockPoolError(
                f"leaked blocks: {set(range(1, self.num_blocks)) - (free_set | lru_set | used_set)}"
            )
        for b in free_set | lru_set:
            if self._ref[b] != 0:
                raise BlockPoolError(f"available block {b} has ref {self._ref[b]}")
        if self._ref[0] != 0:
            raise BlockPoolError("scratch block acquired a refcount")
        for h, bid in self._cached.items():
            if self._hash_of.get(bid) != h:
                raise BlockPoolError(f"cache maps desynced on block {bid}")
        for h in self._lru:
            if h not in self._cached:
                raise BlockPoolError("LRU entry not in prefix cache")
        for key in ("prefix_hit_tokens", "prefix_miss_tokens"):
            if self.counters[key] < 0:
                raise BlockPoolError(f"negative counter {key}")
        if self.counters["spill_reloaded_blocks"] < self.counters["spill_reloads"]:
            raise BlockPoolError(
                "spill_reloads admissions exceed spill_reloaded_blocks — "
                "every reload admission moves >= 1 block"
            )
        if self.spill is not None:
            self.spill.check_invariants()
            if self.counters["spilled_blocks"] != self.spill.counters["spill_puts"]:
                raise BlockPoolError(
                    f"spill ledger desynced: pool spilled "
                    f"{self.counters['spilled_blocks']} blocks but the host "
                    f"tier recorded {self.spill.counters['spill_puts']} puts"
                )
            if self.counters["spill_reloaded_blocks"] > self.spill.counters["spill_gets"]:
                raise BlockPoolError(
                    "more blocks reloaded than the host tier ever served"
                )
