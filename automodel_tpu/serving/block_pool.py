"""Paged KV-cache block allocator (host side).

The vLLM idea (Kwon et al., PagedAttention) translated to the existing
generation cache: HBM holds ONE fixed pool of fixed-size blocks
(``[L, num_blocks, block_size, N_kv, H]`` per of k/v — serving/paged.py owns
the arrays); each sequence owns a **block table** (a list of block ids) and
long and short requests share the pool without fragmentation — a finished
short completion returns its blocks immediately instead of stranding a
contiguous ``[L, B, C, ...]`` region until the longest sequence in the wave
finishes.

This module is the pure-python accountant: free list, per-block reference
counts, and the **prefix cache** — completed prompt blocks are retained
(keyed on a CHAIN hash of their token contents, so a hit guarantees the
whole prefix matches) and a new request with the same prompt prefix shares
them by incref instead of recomputing their K/V. Zero-ref cached blocks sit
in an LRU and are evicted only when the free list runs dry, so prefix
caching never makes an allocation fail that would otherwise succeed.

Block 0 is a reserved SCRATCH block: the jitted paged decode step always
writes its token somewhere (XLA has no conditional scatter), so inactive
slots are pointed at block 0 and their junk writes land where no sequence
ever reads. The allocator never hands block 0 out.

Invariants (``check_invariants`` — the property tests drive a randomized
admit/finish schedule against them):
- every non-scratch block is in exactly ONE of {free list, LRU, in use
  (ref > 0)};
- free/LRU blocks have ref == 0; freeing a ref-0 block raises (double
  free), as does freeing scratch;
- ``counters`` account allocations/frees/hits/evictions exactly.
"""

from __future__ import annotations

import hashlib
import struct
from collections import OrderedDict
from typing import Optional, Sequence


class BlockPoolError(RuntimeError):
    """Allocator misuse: double free, freeing scratch, corrupt accounting."""


def _chain_hash(parent: Optional[int], tokens: Sequence[int]) -> int:
    """One chain link: hash of (parent chain hash, this block's tokens).

    Deterministic ACROSS PROCESSES and Python versions (unlike builtin
    ``hash``, whose ``None``/str hashing varies per interpreter): the fleet
    router (serving/fleet/router.py) hashes a prompt's block chain in its
    own process and matches it against the chain heads a REPLICA's prefix
    cache advertised over /stats — the two sides must agree bit-for-bit or
    prefix-affinity placement never hits."""
    buf = struct.pack("<q", -1 if parent is None else int(parent))
    buf += struct.pack(f"<{len(tokens)}q", *(int(t) for t in tokens))
    return int.from_bytes(
        hashlib.blake2b(buf, digest_size=8).digest(), "little", signed=True
    )


def prompt_chain(tokens: Sequence[int], block_size: int) -> list[int]:
    """Cumulative chain hashes of a prompt's matchable full blocks — the
    hashes ``match_prefix`` would look up, in order, capped at ``len(tokens)
    - 1`` tokens (the last prompt token is always recomputed: its logits
    seed the first sampled token). ``prompt_chain(p, bs)[i]`` equals the key
    ``register_prefix(p, ...)`` filed block ``i`` under, by construction —
    the router-side spelling of the replica-side chain rule."""
    bs = int(block_size)
    out: list[int] = []
    parent: Optional[int] = None
    for i in range((max(len(tokens) - 1, 0)) // bs):
        parent = _chain_hash(parent, tokens[i * bs : (i + 1) * bs])
        out.append(parent)
    return out


def blocks_needed(total_tokens: int, block_size: int, write_overhang: int = 0) -> int:
    """Whole-budget block count for a request: ``ceil((tokens + overhang) /
    block_size)``. ``write_overhang`` covers positions a program may WRITE
    past the committed budget — speculative decoding's verify forward puts
    up to ``spec_k`` rejected-draft rows beyond the final length (they are
    rolled back by a length decrement, never attended, but the table must
    point their writes at real blocks, not out of range). One spelling
    shared by submit-time validation and admission so the two can't drift."""
    return -(-(int(total_tokens) + int(write_overhang)) // int(block_size))


class BlockPool:
    def __init__(
        self, num_blocks: int, block_size: int, prefix_cache: bool = True
    ):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks={num_blocks}: need >= 2 (block 0 is scratch)"
            )
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_cache_enabled = bool(prefix_cache)
        # LIFO free list: recently freed blocks are re-handed first (warm)
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: dict[int, int] = {b: 0 for b in range(num_blocks)}
        self._cached: dict[int, int] = {}  # chain hash -> block id
        self._hash_of: dict[int, int] = {}  # block id -> chain hash
        self._lru: "OrderedDict[int, int]" = OrderedDict()  # hash -> ref-0 bid
        self.counters = {
            "allocated": 0,
            "freed": 0,
            "prefix_hits": 0,  # requests that matched >= 1 block
            "prefix_blocks_reused": 0,
            "prefix_tokens_reused": 0,
            "evictions": 0,
            "failed_allocs": 0,
        }

    # -- capacity -------------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # minus scratch

    def available(self) -> int:
        """Blocks an allocate() could hand out right now (free + evictable)."""
        return len(self._free) + len(self._lru)

    def in_use(self) -> int:
        return self.usable_blocks - self.available()

    def occupancy(self) -> float:
        """Fraction of the usable pool referenced by live sequences (cached
        ref-0 blocks count as available — they are reclaimable on demand)."""
        return self.in_use() / max(self.usable_blocks, 1)

    # -- prefix cache ---------------------------------------------------------
    @staticmethod
    def _chain(parent: Optional[int], tokens: tuple) -> int:
        return _chain_hash(parent, tokens)

    def match_prefix(self, tokens: Sequence[int]) -> tuple[list[int], int]:
        """→ (block ids, matched token count) for the longest cached
        block-aligned prefix of ``tokens``, each hit INCREF'd for the caller.
        Capped at ``len(tokens) - 1`` tokens: the last prompt token must
        always be recomputed — its logits seed the first sampled token."""
        if not self.prefix_cache_enabled:
            return [], 0
        bs = self.block_size
        hits: list[int] = []
        parent: Optional[int] = None
        for i in range((max(len(tokens) - 1, 0)) // bs):
            h = self._chain(parent, tuple(tokens[i * bs : (i + 1) * bs]))
            bid = self._cached.get(h)
            if bid is None:
                break
            if self._ref[bid] == 0:
                self._lru.pop(h)
            self._ref[bid] += 1
            hits.append(bid)
            parent = h
        if hits:
            self.counters["prefix_hits"] += 1
            self.counters["prefix_blocks_reused"] += len(hits)
            self.counters["prefix_tokens_reused"] += len(hits) * bs
        return hits, len(hits) * bs

    def register_prefix(self, tokens: Sequence[int], blocks: Sequence[int]) -> None:
        """Make a prefilled prompt's FULL blocks matchable by later requests
        (no refcount is taken — a registered block freed to ref 0 parks in
        the LRU, matchable until evicted). ``blocks`` is the sequence's block
        table; only the ``len(tokens) // block_size`` full blocks register."""
        if not self.prefix_cache_enabled:
            return
        bs = self.block_size
        parent: Optional[int] = None
        for i in range(len(tokens) // bs):
            bid = blocks[i]
            h = self._chain(parent, tuple(tokens[i * bs : (i + 1) * bs]))
            # first writer wins: an existing mapping (another request computed
            # the same prefix concurrently) or a block already registered
            # under a different hash is left alone
            if h not in self._cached and bid not in self._hash_of:
                self._cached[h] = bid
                self._hash_of[bid] = h
            parent = h

    def cached_chain_hashes(self, limit: Optional[int] = None) -> list[int]:
        """The chain hashes this pool's prefix cache can currently serve —
        what a replica advertises over /stats (``hot_prefixes``) for the
        fleet router's affinity placement. ``limit`` bounds the
        advertisement by eviction distance: chains whose blocks are
        REFERENCED right now cannot be evicted at all and always advertise;
        the remaining budget fills from the most recently parked end of the
        LRU — the parked-longest entries are the next evicted, so
        advertising them would promise affinity the pool is about to
        break."""
        pinned = [h for h in self._cached if h not in self._lru]
        parked = list(self._lru)
        if limit is None:
            return pinned + parked
        n = int(limit)
        room = max(n - len(pinned), 0)
        return (pinned + (parked[-room:] if room else []))[:n]

    def clear_prefix_cache(self) -> None:
        """Forget every cached prefix — the serving engine calls this when
        it rebuilds after a stalled/failed program, because the pool's K/V
        contents can no longer be trusted. Ref-0 parked blocks return to
        the free list; a registered block still referenced by a live
        sequence merely loses its hash mapping and frees normally later."""
        for bid in self._lru.values():
            self._free.append(bid)
        self._lru.clear()
        self._cached.clear()
        self._hash_of.clear()

    # -- allocate / free ------------------------------------------------------
    def allocate(self, n: int) -> Optional[list[int]]:
        """n fresh blocks (ref = 1 each), or None when the pool can't satisfy
        the request (caller leaves the sequence queued). Evicts LRU cached
        blocks only when the free list is empty."""
        if n < 0:
            raise ValueError(f"allocate({n})")
        if n > self.available():
            self.counters["failed_allocs"] += 1
            return None
        out: list[int] = []
        for _ in range(n):
            if self._free:
                bid = self._free.pop()
            else:
                h, bid = self._lru.popitem(last=False)  # oldest cached
                del self._cached[h]
                del self._hash_of[bid]
                self.counters["evictions"] += 1
            self._ref[bid] = 1
            out.append(bid)
        self.counters["allocated"] += n
        return out

    def free(self, blocks: Sequence[int]) -> None:
        """Decref every block; a block reaching ref 0 returns to the free
        list, or parks in the LRU when it is prefix-cache registered."""
        for bid in blocks:
            if bid == 0:
                raise BlockPoolError("freeing the scratch block")
            if self._ref.get(bid, 0) <= 0:
                raise BlockPoolError(f"double free of block {bid}")
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                h = self._hash_of.get(bid)
                if h is not None:
                    self._lru[h] = bid
                else:
                    self._free.append(bid)
        self.counters["freed"] += len(blocks)

    # -- audit ----------------------------------------------------------------
    def check_invariants(self) -> None:
        free_set = set(self._free)
        lru_set = set(self._lru.values())
        used_set = {b for b in range(1, self.num_blocks) if self._ref[b] > 0}
        if free_set & lru_set or free_set & used_set or lru_set & used_set:
            raise BlockPoolError("block in two states at once")
        if free_set | lru_set | used_set != set(range(1, self.num_blocks)):
            raise BlockPoolError(
                f"leaked blocks: {set(range(1, self.num_blocks)) - (free_set | lru_set | used_set)}"
            )
        for b in free_set | lru_set:
            if self._ref[b] != 0:
                raise BlockPoolError(f"available block {b} has ref {self._ref[b]}")
        if self._ref[0] != 0:
            raise BlockPoolError("scratch block acquired a refcount")
        for h, bid in self._cached.items():
            if self._hash_of.get(bid) != h:
                raise BlockPoolError(f"cache maps desynced on block {bid}")
        for h in self._lru:
            if h not in self._cached:
                raise BlockPoolError("LRU entry not in prefix cache")
