"""Continuous-batching serving engine over the paged KV-cache pool.

The scheduler loop (one ``step()`` = one engine iteration):

1. **admit** — pop queued requests into free decode slots. A request's
   WHOLE block budget (``ceil((prompt + max_new) / block_size)``) is
   allocated at admission (minus any prefix-cache hit), so a running
   sequence never needs a mid-flight allocation and the engine cannot
   deadlock on a full pool: if the pool can't cover the head-of-queue
   request it simply stays queued until completions free blocks.
2. **prefill tick** — every mid-prefill slot advances ONE chunk
   (``prefill_chunk`` tokens) through the jitted chunked-prefill program.
   Bounding per-iteration prefill work is what keeps time-to-first-token of
   queued requests from stalling behind a single long prompt: the decode
   wave below still runs every iteration.
3. **decode tick** — one jitted paged decode step over all slots; active
   slots each advance one token. Slots whose token hits a stop id or whose
   budget is spent COMPLETE: their blocks decref back to the pool (prompt
   blocks stay matchable in the prefix cache) and the slot refills from the
   queue on the next iteration — mid-flight, without waiting for the rest
   of the wave.

Greedy decode through this path is token-parity with the single-wave
``generation.GenerationEngine`` (tests/test_serving.py pins it, full and
ring-model layouts); sampled decode draws from the same per-host base key
but a GLOBAL step counter, so streams differ from the single-wave engine by
construction (documented in docs/serving.md).

Windowed (mistral-style) models run on the FULL paged layout with the
per-layer window masks narrowing attention — unlike the single-wave ring
layout there is no wraparound hazard, so ragged windowed batches are fine
here. HBM cost is bounded by ``max_seq_len``, not the window.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.generation.engine import (
    GenerationConfig,
    GenerationUnsupported,
    _model_max_positions,
)
from automodel_tpu.generation.sampling import sample
from automodel_tpu.serving import paged
from automodel_tpu.serving.block_pool import BlockPool
from automodel_tpu.training.rng import sampling_key


class QueueFull(RuntimeError):
    """Admission queue at max_queue: the caller must apply backpressure —
    the engine never silently drops a request."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The `serving:` YAML section (scheduler/allocator knobs; sampling and
    stop tokens come from the `generation:` section)."""

    slots: int = 4  # decode batch width
    block_size: int = 16  # tokens per KV block
    num_blocks: int = 512  # pool size (block 0 is scratch)
    prefill_chunk: int = 64  # prompt tokens per engine iteration per slot
    max_seq_len: int = 1024  # per-request prompt + generated cap
    max_queue: int = 4096
    prefix_cache: bool = True
    # sustained-throughput bench knobs (recipes/benchmark.py serving leg)
    bench_requests: int = 16
    bench_rate: float = 8.0  # Poisson arrival rate, requests/second
    bench_prompt_len_min: int = 8
    bench_prompt_len_max: int = 48
    bench_max_new_tokens: int = 16

    def __post_init__(self):
        if self.slots < 1 or self.block_size < 1 or self.prefill_chunk < 1:
            raise ValueError(
                f"serving: slots/block_size/prefill_chunk must be >= 1 "
                f"({self.slots}/{self.block_size}/{self.prefill_chunk})"
            )
        if self.max_seq_len < 2:
            raise ValueError(f"serving.max_seq_len={self.max_seq_len}")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ServeConfig":
        d = dict(d or {})
        d.pop("_target_", None)
        d.pop("http", None)  # server-level section (serving/server.py)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise TypeError(f"unknown serving keys: {sorted(unknown)}")
        return cls(**d)

    @property
    def table_blocks(self) -> int:
        """Static per-sequence block-table width. The extra prefill_chunk of
        headroom keeps the chunk program's dynamic_update_slice from ever
        clamping (paged.py view-position invariant)."""
        return -(-(self.max_seq_len + self.prefill_chunk) // self.block_size)


@dataclasses.dataclass
class _Slot:
    request_id: str
    prompt: list[int]
    max_new: int
    blocks: list[int]  # every block this sequence holds a ref on
    hit_tokens: int  # prefix-cache reused tokens
    prefill_pos: int  # next absolute prompt position to compute
    t_submit: float
    t_admit: float
    decoding: bool = False
    generated: Optional[list[int]] = None
    t_first: Optional[float] = None


class ServingEngine:
    """Facade over (AutoModel, ServeConfig, GenerationConfig).

    ``submit`` enqueues token-id prompts; ``step`` runs one scheduler
    iteration and returns the requests that completed in it; ``run`` drains
    everything. ``on_record`` (optional) receives one telemetry dict per
    completed request (the serve CLI points it at the metrics JSONL)."""

    def __init__(
        self,
        auto: Any,
        config: Optional[ServeConfig] = None,
        gen_config: Optional[GenerationConfig] = None,
        on_record: Optional[Callable[[dict], None]] = None,
    ):
        if not getattr(auto.model, "supports_kv_cache", False):
            raise GenerationUnsupported(
                f"{type(auto.model).__name__} has no KV-cache decode path; "
                "cache-capable families: llama-generic (llama/qwen2/qwen3/"
                "mistral/phi3), gpt2, qwen3_moe"
            )
        self.auto = auto
        self.model = auto.model
        self.config = config or ServeConfig()
        self.gen_config = gen_config or GenerationConfig()
        self.on_record = on_record
        mcfg = self.model.config
        self._max_positions = _model_max_positions(mcfg)
        if self._max_positions and self.config.max_seq_len > self._max_positions:
            raise ValueError(
                f"serving.max_seq_len={self.config.max_seq_len} exceeds the "
                f"model context limit {self._max_positions}"
            )
        self.pool = BlockPool(
            self.config.num_blocks, self.config.block_size,
            prefix_cache=self.config.prefix_cache,
        )
        self._pool_k, self._pool_v = paged.init_pool(
            int(mcfg.num_layers), self.config.num_blocks,
            self.config.block_size, int(mcfg.num_kv_heads),
            int(mcfg.head_dim), dtype=self.model.backend.compute_jnp_dtype,
        )
        self._pool_k, self._pool_v = paged.place_pool(
            self._pool_k, self._pool_v, auto.mesh_ctx
        )
        constrain = auto.constrain

        def apply(params, ids, **kw):
            return self.model(params, ids, constrain=constrain, **kw)

        self._chunk = paged.build_chunk_prefill_fn(
            apply, self.config.prefill_chunk
        )
        self._decode = paged.build_paged_decode_fn(
            apply, self.gen_config.sampling,
            pad_id=self.gen_config.pad_token_id,
        )
        self._base_key = sampling_key(self.gen_config.seed)
        self._eos = set(self.gen_config.eos_ids)

        B, NB = self.config.slots, self.config.table_blocks
        self._tables = np.zeros((B, NB), np.int32)
        self._lengths = np.zeros((B,), np.int32)
        self._cur = np.full((B,), self.gen_config.pad_token_id, np.int32)
        self._active = np.zeros((B,), bool)
        self._slots: list[Optional[_Slot]] = [None] * B
        self._queue: deque = deque()
        self._ids = itertools.count()
        self._step_counter = 0
        self.completed_total = 0
        # /metrics exposition (telemetry/prometheus.py): histograms are
        # observed per completion (cheap, python dict ops); gauges + pool
        # counters sync at scrape time so the scheduler loop pays nothing
        from automodel_tpu.telemetry.prometheus import ServingMetrics

        self.metrics = ServingMetrics()
        # cost attribution (telemetry/profiling/): when armed, the first
        # chunk-prefill/paged-decode call also records the program's
        # measured FLOPs/bytes (abstract host trace, one-time)
        self.collect_program_costs = False
        self.program_costs: dict = {}

    # -- stats ---------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def busy_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def pool_bytes(self) -> int:
        return int(self._pool_k.nbytes + self._pool_v.nbytes)

    def idle(self) -> bool:
        return not self._queue and self.busy_slots == 0

    # -- submission -----------------------------------------------------------
    def submit(
        self,
        prompt_ids: Sequence[int],
        request_id: Optional[str] = None,
        max_new_tokens: Optional[int] = None,
        t_submit: Optional[float] = None,
    ) -> str:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt (every request needs >= 1 token)")
        max_new = (
            self.gen_config.max_new_tokens
            if max_new_tokens is None
            else int(max_new_tokens)  # explicit 0 must hit the guard below
        )
        if max_new < 1:
            raise ValueError(f"max_new_tokens={max_new}")
        total = len(prompt) + max_new
        cap = min(
            self.config.max_seq_len,
            self._max_positions or self.config.max_seq_len,
        )
        if total > cap:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) = "
                f"{total} exceeds the serving limit {cap}"
            )
        if -(-total // self.config.block_size) > self.pool.usable_blocks:
            raise ValueError(
                f"request needs {-(-total // self.config.block_size)} blocks "
                f"but the pool only has {self.pool.usable_blocks} — raise "
                "serving.num_blocks"
            )
        if len(self._queue) >= self.config.max_queue:
            raise QueueFull(
                f"admission queue at serving.max_queue={self.config.max_queue}"
            )
        rid = request_id if request_id is not None else f"req-{next(self._ids)}"
        self._queue.append(
            (rid, prompt, max_new, time.perf_counter() if t_submit is None else t_submit)
        )
        return rid

    # -- scheduler ------------------------------------------------------------
    def _admit(self) -> None:
        for b in range(self.config.slots):
            if self._slots[b] is not None or not self._queue:
                continue
            rid, prompt, max_new, t_sub = self._queue[0]
            hits, hit_tokens = self.pool.match_prefix(prompt)
            need = -(-(len(prompt) + max_new) // self.config.block_size)
            fresh = self.pool.allocate(need - len(hits))
            if fresh is None:
                # pool can't cover the head of the queue: undo the hit refs
                # and keep FIFO order (no overtaking — ttft fairness)
                if hits:
                    self.pool.free(hits)
                break
            self._queue.popleft()
            blocks = hits + fresh
            row = np.zeros((self.config.table_blocks,), np.int32)
            row[: len(blocks)] = blocks
            self._tables[b] = row
            self._lengths[b] = hit_tokens
            self._active[b] = False
            self._slots[b] = _Slot(
                request_id=rid, prompt=prompt, max_new=max_new,
                blocks=blocks, hit_tokens=hit_tokens,
                prefill_pos=hit_tokens, t_submit=t_sub,
                t_admit=time.perf_counter(),
            )

    def _prefill_tick(self) -> list[dict]:
        done: list[dict] = []
        chunk_len = self.config.prefill_chunk
        pad = self.gen_config.pad_token_id
        for b, slot in enumerate(self._slots):
            if slot is None or slot.decoding:
                continue
            p = len(slot.prompt)
            start = slot.prefill_pos
            real = min(chunk_len, p - start)
            ids = np.full((chunk_len,), pad, np.int32)
            ids[:real] = slot.prompt[start : start + real]
            if self.collect_program_costs and "chunk_prefill" not in self.program_costs:
                self._record_cost(
                    "chunk_prefill", self._chunk,
                    self.auto.params, self._pool_k, self._pool_v,
                    jnp.asarray(self._tables[b]), jnp.asarray(ids),
                    jnp.int32(start), jnp.int32(real),
                )
            last, self._pool_k, self._pool_v = self._chunk(
                self.auto.params,
                self._pool_k, self._pool_v,
                jnp.asarray(self._tables[b]), jnp.asarray(ids),
                jnp.int32(start), jnp.int32(real),
            )
            slot.prefill_pos = start + real
            self._lengths[b] = slot.prefill_pos
            if slot.prefill_pos < p:
                continue
            # prompt fully in: sample the first token (charged to ttft),
            # publish the prompt blocks to the prefix cache, flip to decode
            first = int(
                sample(
                    last[None, :],
                    jax.random.fold_in(self._base_key, self._step_counter),
                    self.gen_config.sampling,
                )[0]
            )
            self.pool.register_prefix(slot.prompt, slot.blocks)
            slot.t_first = time.perf_counter()
            slot.generated = [first]
            slot.decoding = True
            self._cur[b] = first
            self._active[b] = True
            self._lengths[b] = p
            if first in self._eos or slot.max_new <= 1:
                done.append(self._finish(b))
        return done

    def _decode_tick(self) -> list[dict]:
        if not self._active.any():
            return []
        params = self.auto.params
        if self.collect_program_costs and "paged_decode" not in self.program_costs:
            self._record_cost(
                "paged_decode", self._decode,
                params, self._pool_k, self._pool_v,
                jnp.asarray(self._tables), jnp.asarray(self._lengths),
                jnp.asarray(self._cur), jnp.asarray(self._active),
                self._base_key, jnp.int32(self._step_counter),
            )
        tokens, self._pool_k, self._pool_v = self._decode(
            params, self._pool_k, self._pool_v,
            jnp.asarray(self._tables), jnp.asarray(self._lengths),
            jnp.asarray(self._cur), jnp.asarray(self._active),
            self._base_key, jnp.int32(self._step_counter),
        )
        tokens = np.asarray(jax.device_get(tokens))
        done: list[dict] = []
        for b, slot in enumerate(self._slots):
            if slot is None or not self._active[b]:
                continue
            tok = int(tokens[b])
            slot.generated.append(tok)
            self._lengths[b] += 1
            self._cur[b] = tok
            if tok in self._eos or len(slot.generated) >= slot.max_new:
                done.append(self._finish(b))
        return done

    def _finish(self, b: int) -> dict:
        slot = self._slots[b]
        now = time.perf_counter()
        n_gen = len(slot.generated)
        decode_s = now - slot.t_first
        self.pool.free(slot.blocks)
        self._slots[b] = None
        self._tables[b] = 0
        self._lengths[b] = 0
        self._active[b] = False
        self._cur[b] = self.gen_config.pad_token_id
        self.completed_total += 1
        rec = {
            "event": "serve_request",
            "request_id": slot.request_id,
            "tokens": list(slot.generated),
            "n_generated": n_gen,
            "prompt_tokens": len(slot.prompt),
            "prefix_hit_tokens": slot.hit_tokens,
            "ttft_s": slot.t_first - slot.t_submit,
            "queue_s": slot.t_admit - slot.t_submit,
            # the first token is charged to ttft, like the single-wave engine
            "decode_tps": (n_gen - 1) / decode_s if decode_s > 0 and n_gen > 1 else 0.0,
            "queue_depth": self.queue_depth,
            "block_occupancy": round(self.pool.occupancy(), 4),
            "ts": time.time(),
        }
        try:
            self.metrics.observe_request(rec)
        except Exception:  # telemetry must never break serving
            pass
        if self.on_record is not None:
            try:
                self.on_record(dict(rec))
            except Exception:  # telemetry must never break serving
                pass
        return rec

    def _record_cost(self, name: str, jit_fn, *args) -> None:
        from automodel_tpu.telemetry.profiling import record_program_cost

        record_program_cost(self.program_costs, name, jit_fn, *args)

    def step(self) -> list[dict]:
        """One scheduler iteration → the requests that completed in it."""
        self._admit()
        done = self._prefill_tick()
        done += self._decode_tick()
        self._step_counter += 1
        return done

    def run(self, max_iterations: Optional[int] = None) -> list[dict]:
        """Drain the queue and every running slot. ``max_iterations`` guards
        against scheduler bugs (default: a generous analytic bound)."""
        if max_iterations is None:
            n_req = len(self._queue) + self.busy_slots
            per_req = (
                -(-self.config.max_seq_len // self.config.prefill_chunk)
                + self.config.max_seq_len
            )
            max_iterations = 64 + (n_req + 1) * (per_req + 2)
        out: list[dict] = []
        for _ in range(max_iterations):
            if self.idle():
                return out
            out.extend(self.step())
        raise RuntimeError(
            f"serving engine failed to drain within {max_iterations} "
            f"iterations (queue={self.queue_depth}, busy={self.busy_slots})"
        )

    # -- workload driver (bench leg + sustained-throughput tests) -------------
    def run_workload(
        self, arrivals: Sequence[tuple[float, Sequence[int], Optional[int]]]
    ) -> tuple[list[dict], dict]:
        """Drive a timed workload: ``arrivals`` is [(offset_s, prompt_ids,
        max_new_tokens|None)] sorted by offset. Requests are submitted when
        their offset elapses (wall clock); the engine steps continuously in
        between. → (completions, aggregate stats: sustained tokens/s, ttft
        p50/p99, peak occupancy/queue depth)."""
        arrivals = sorted(arrivals, key=lambda a: a[0])
        t0 = time.perf_counter()
        pending = deque(arrivals)
        out: list[dict] = []
        occ_peak, q_peak = 0.0, 0
        while pending or not self.idle():
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                _, prompt, max_new = pending.popleft()
                self.submit(prompt, max_new_tokens=max_new)
            if self.idle():
                if pending:
                    time.sleep(min(0.001, max(pending[0][0] - now, 0.0)))
                continue
            out.extend(self.step())
            occ_peak = max(occ_peak, self.pool.occupancy())
            q_peak = max(q_peak, self.queue_depth)
        dt = time.perf_counter() - t0
        gen = sum(r["n_generated"] for r in out)
        ttfts = sorted(r["ttft_s"] for r in out)
        pct = lambda q: ttfts[min(int(q * len(ttfts)), len(ttfts) - 1)] if ttfts else None
        stats = {
            "requests": len(out),
            "gen_tokens": gen,
            "wall_s": dt,
            "sustained_tokens_per_s": gen / dt if dt > 0 else 0.0,
            "ttft_p50_s": pct(0.50),
            "ttft_p99_s": pct(0.99),
            "block_occupancy_peak": round(occ_peak, 4),
            "queue_depth_peak": q_peak,
            "prefix_cache": dict(self.pool.counters),
        }
        return out, stats
