"""Continuous-batching serving engine over the paged KV-cache pool.

The scheduler loop (one ``step()`` = one engine iteration):

1. **expire** — requests whose ``deadline_s``/``max_queue_wait_s`` elapsed
   are cancelled wherever they are (queued, prefilling, decoding): blocks
   freed, a ``timeout`` completion reason recorded. Nothing is ever
   silently dropped — every submitted request produces exactly one
   terminal record.
2. **admit** — pop queued requests into free decode slots. A request's
   WHOLE block budget (``ceil((prompt + max_new) / block_size)``) is
   allocated at admission (minus any prefix-cache hit), so a running
   sequence never needs a mid-flight allocation and the engine cannot
   deadlock on a full pool: if the pool can't cover the head-of-queue
   request it simply stays queued until completions free blocks. While
   **draining** nothing admits: the queue is flushed with retriable
   ``draining`` rejections and only in-flight requests keep running.
3. **prefill tick** — every mid-prefill slot advances ONE chunk
   (``prefill_chunk`` tokens) through the jitted chunked-prefill program.
   Bounding per-iteration prefill work is what keeps time-to-first-token of
   queued requests from stalling behind a single long prompt: the decode
   wave below still runs every iteration.
4. **decode tick** — one jitted paged decode step over all slots; active
   slots each advance one token — or, with ``serving.speculative:``, one
   draft-propose + ONE batched verify forward advancing each slot by 1 to
   k+1 tokens (rollback of rejected drafts is a host-side length
   decrement; no copies). Slots whose token hits a stop id or whose
   budget is spent COMPLETE: their blocks decref back to the pool (prompt
   blocks stay matchable in the prefix cache) and the slot refills from the
   queue on the next iteration — mid-flight, without waiting for the rest
   of the wave.

Failure containment (the PR 3/5 doctrine ported to serving): a wedged
jitted step is detected by the :class:`EngineWatchdog` (adaptive EMA
deadline — resilience/watchdog.py) which dumps stacks + flight recorder
and flags the engine; when the blocked call returns (or an exception
escapes a tick) the engine fails ONLY the affected wave's requests with an
``engine_stall``/``engine_error`` reason, re-initializes the pool arrays
(the failed program may have left its donated buffers in an arbitrary
state), clears the prefix cache (contents no longer trusted), audits the
allocator invariants, and keeps serving the queue. Repeated back-to-back
rebuilds are a systemic fault and re-raise loudly instead of looping.

Greedy decode through this path is token-parity with the single-wave
``generation.GenerationEngine`` (tests/test_serving.py pins it, full and
ring-model layouts); sampled decode draws from the same per-host base key
but a GLOBAL step counter, so streams differ from the single-wave engine by
construction (documented in docs/serving.md).

Windowed (mistral-style) models run on the FULL paged layout with the
per-layer window masks narrowing attention — unlike the single-wave ring
layout there is no wraparound hazard, so ragged windowed batches are fine
here. HBM cost is bounded by ``max_seq_len``, not the window.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.generation.engine import (
    GenerationConfig,
    GenerationUnsupported,
    _model_max_positions,
)
from automodel_tpu.generation.sampling import sample
from automodel_tpu.serving import paged
from automodel_tpu.serving.block_pool import (
    BlockPool,
    HostSpillTier,
    blocks_needed,
    prompt_chain,
)
from automodel_tpu.telemetry.tracing import SpanContext, Tracer, WallAnchor
from automodel_tpu.training.rng import sampling_key

logger = logging.getLogger(__name__)

# terminal `completion_reason` values every request record carries exactly
# one of (docs/observability.md glossary):
#   stop         — hit a configured eos id
#   length       — spent its max_new_tokens budget
#   prefilled    — a prefill-only request (disaggregated fleet: the KV
#                  payload was extracted for transfer to a decode replica)
#                  finished its prompt; a completion, not a failure
#   timeout      — deadline_s / max_queue_wait_s expired (not retriable:
#                  the client's own budget ran out)
#   shed         — rejected at submit, admission queue full (retriable)
#   quota        — rejected at submit, the tenant's token-bucket quota is
#                  exhausted (retriable — after the Retry-After window the
#                  bucket has refilled)
#   draining     — rejected because the server is draining (retriable)
#   cancelled    — in flight when the drain grace expired (retriable)
#   engine_stall — failed by a watchdog-detected wedged step (retriable)
#   engine_error — failed by a scheduler/program exception (retriable)
COMPLETION_REASONS = (
    "stop", "length", "prefilled", "timeout", "shed", "quota", "draining",
    "cancelled", "engine_stall", "engine_error",
)
_COMPLETED_REASONS = frozenset({"stop", "length", "prefilled"})
_RETRIABLE_REASONS = frozenset(
    {"shed", "quota", "draining", "cancelled", "engine_stall", "engine_error"}
)

# QoS tiers, highest priority first (serving.qos / docs/serving.md
# "Multi-tenant QoS"): admission, shedding, and Retry-After scaling all key
# off the tier's INDEX in this tuple — interactive work is admitted first
# and shed last.
TIERS = ("interactive", "batch", "best_effort")
_TIER_INDEX = {t: i for i, t in enumerate(TIERS)}


def tier_index(tier: str) -> int:
    """Priority rank of a tier (0 = highest). Unknown tiers raise — a typo
    must never silently demote (or promote) a tenant."""
    try:
        return _TIER_INDEX[tier]
    except KeyError:
        raise ValueError(
            f"unknown QoS tier {tier!r} (want one of {'|'.join(TIERS)})"
        ) from None


class QueueFull(RuntimeError):
    """Admission queue at max_queue: overload is SHED back to the caller as
    an explicit retriable signal (HTTP 503 + Retry-After, stdin-JSONL error
    record) — the engine never silently drops or silently queues-forever."""


class EngineDraining(RuntimeError):
    """Submissions rejected while the server drains (SIGTERM received):
    retriable — the client should go to another replica. HTTP maps this to
    503 + Retry-After, stdin-JSONL to an error record."""


class QuotaExceeded(RuntimeError):
    """The tenant's token-bucket quota (requests/s or decode-tokens/s) is
    exhausted: retriable after the bucket refills. HTTP maps this to 429 +
    a tier-scaled Retry-After with ``reason: quota``. Carries ``tenant`` and
    ``tier`` so the front can label the rejection."""

    def __init__(self, message: str, tenant: str, tier: str):
        super().__init__(message)
        self.tenant = tenant
        self.tier = tier


def _cfg_dict(cls, d: Optional[dict], section: str):
    """Strict nested-section constructor shared by limits/drain/watchdog."""
    d = dict(d or {})
    d.pop("_target_", None)
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - known
    if unknown:
        raise TypeError(f"unknown {section} keys: {sorted(unknown)}")
    return cls(**d)


@dataclasses.dataclass(frozen=True)
class LimitsConfig:
    """The ``serving.limits:`` section — per-request time budgets. 0/None
    disables a bound. Per-request ``deadline_s``/``max_queue_wait_s`` on
    submit (or the request JSON) override these defaults."""

    deadline_s: Optional[float] = None  # submit → completion wall cap
    max_queue_wait_s: Optional[float] = None  # submit → admission wall cap

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "LimitsConfig":
        return _cfg_dict(cls, d, "serving.limits")


@dataclasses.dataclass(frozen=True)
class DrainConfig:
    """The ``serving.drain:`` section — graceful-shutdown semantics.

    SIGTERM (chained through the PR 3 ``PreemptionHandler``) flips the
    server to draining: new and queued requests are rejected retriable,
    in-flight requests finish within ``grace_s``, then the scheduler exits
    cleanly. ``requeue_exit`` picks the exit code: ``auto`` exits 75
    (EX_TEMPFAIL — the launchers' requeue code) when running under slurm/
    k8s and 0 otherwise; ``always``/``never`` force it."""

    grace_s: float = 30.0
    install_signal_handler: bool = True
    requeue_exit: str = "auto"  # auto | always | never

    def __post_init__(self):
        if self.requeue_exit not in ("auto", "always", "never"):
            raise ValueError(
                f"serving.drain.requeue_exit={self.requeue_exit!r} "
                "(want auto|always|never)"
            )
        if self.grace_s < 0:
            raise ValueError(f"serving.drain.grace_s={self.grace_s}")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "DrainConfig":
        return _cfg_dict(cls, d, "serving.drain")


@dataclasses.dataclass(frozen=True)
class StallConfig:
    """The ``serving.watchdog:`` section — scheduler-level stall detection
    (maps onto resilience.watchdog.EngineWatchdog). The watchdog thread is
    started by the serving fronts (``start_watchdog``), not by engine
    construction — batch ``run()`` drains own their own lifetime."""

    enabled: bool = True
    multiplier: float = 20.0
    min_deadline_s: float = 30.0
    max_deadline_s: float = 600.0
    ema_alpha: float = 0.2
    compile_grace_s: float = 1800.0  # first prefill/decode compile
    poll_interval_s: float = 0.25
    stacks_path: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "StallConfig":
        return _cfg_dict(cls, d, "serving.watchdog")


@dataclasses.dataclass(frozen=True)
class KVTransferConfig:
    """The ``serving.kv_transfer:`` section — the prefill→decode KV handoff
    listener (serving/fleet/kv_transfer.py). A DECODE-role replica starts
    it by default (``enabled: null`` = auto); a mixed replica only when
    explicitly enabled. ``port: 0`` binds an ephemeral port, advertised to
    the router via the ``kv_transfer_port`` /stats field."""

    enabled: Optional[bool] = None  # null = auto (on when role == decode)
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral, advertised via /stats
    max_pending: int = 32  # undelivered handoff payloads held host-side
    ttl_s: float = 120.0  # a payload never claimed by /generate expires

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "KVTransferConfig":
        return _cfg_dict(cls, d, "serving.kv_transfer")


@dataclasses.dataclass(frozen=True)
class KVSpillConfig:
    """The ``serving.kv_spill:`` section — the hierarchical KV cache
    (docs/serving.md "Hierarchical KV cache"). When enabled, prefix blocks
    evicted from the HBM pool's LRU spill device→host into a bounded
    host-RAM tier keyed by the same chain hashes the prefix cache uses;
    an admission whose prefix extends past resident blocks reloads the
    spilled rows through ``paged.inject_blocks`` instead of re-prefilling
    (greedy output bit-identical). ``peer_fetch`` extends the hierarchy
    fleet-wide: a router-hinted replica pulls missing prefix blocks from
    the peer that advertises them over AKV1 ``kv_fetch``, falling back to
    local recompute on any failure within the request's deadline."""

    enabled: bool = False
    max_host_mb: float = 256.0  # host tier budget (LRU beyond this)
    peer_fetch: bool = True  # honor router kv_peer hints via /kv_fetch
    fetch_timeout_s: float = 5.0  # per-fetch cap (also clamped to deadline)

    def __post_init__(self):
        if self.max_host_mb <= 0:
            raise ValueError(
                f"serving.kv_spill.max_host_mb={self.max_host_mb} (want > 0)"
            )
        if self.fetch_timeout_s <= 0:
            raise ValueError(
                f"serving.kv_spill.fetch_timeout_s={self.fetch_timeout_s} "
                "(want > 0)"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "KVSpillConfig":
        return _cfg_dict(cls, d, "serving.kv_spill")


@dataclasses.dataclass(frozen=True)
class WarmStartConfig:
    """The ``serving.warm_start:`` section — elastic-fleet peer warm-start
    (docs/serving.md "Elastic fleet"). When a peer is named, a starting
    replica builds its model STRUCTURALLY (shapes + sharding, seeded
    params) and then streams the actual weights from that peer's AKV1
    listener (``op: weights_fetch``) instead of paying the cold HF load,
    validating the peer's param-tree signature (the PR 6 checkpoint guard)
    against its own tree before swapping a single leaf. ANY failure —
    transport death, refusal, digest mismatch — falls back to the cold
    load path unchanged; warm-start is an optimization, never a
    correctness dependency. The boot source actually taken is recorded as
    ``boot_source`` (``cold_hf`` | ``peer_warm_start``) beside
    ``time_to_ready_s`` on /stats and the metrics JSONL."""

    peer_host: Optional[str] = None
    peer_port: Optional[int] = None  # the peer's kv_transfer listener port
    timeout_s: float = 60.0  # whole-tree stream budget

    def __post_init__(self):
        if (self.peer_host is None) != (self.peer_port is None):
            raise ValueError(
                "serving.warm_start needs BOTH peer_host and peer_port "
                f"(got host={self.peer_host!r}, port={self.peer_port!r})"
            )
        if self.timeout_s <= 0:
            raise ValueError(
                f"serving.warm_start.timeout_s={self.timeout_s} (want > 0)"
            )

    @property
    def enabled(self) -> bool:
        return self.peer_host is not None

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "WarmStartConfig":
        return _cfg_dict(cls, d, "serving.warm_start")


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """The ``serving.speculative:`` section — draft-and-verify speculative
    decoding (Leviathan et al. 2023). A small draft model proposes ``k``
    tokens per slot per engine iteration; ONE batched verify forward
    through the paged path accepts a prefix + one correction/bonus token.
    Greedy output is bit-identical to non-speculative decoding (the
    exactness rule); sampled output preserves the target distribution.

    ``draft`` is a ``model:``-shaped section (``hf_config`` + ``backend``
    or ``pretrained_model_name_or_path``) built onto the target's mesh via
    the ``build_auto_from_model_section`` ladder. The draft must be
    cache-capable and share the target's vocabulary."""

    enabled: bool = False
    k: int = 4  # draft tokens proposed per slot per engine step
    draft: Optional[Any] = None  # model: section for the draft

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"serving.speculative.k={self.k} (want >= 1)")
        if self.enabled and not self.draft:
            raise ValueError(
                "serving.speculative.enabled needs a draft model section "
                "(serving.speculative.draft: {hf_config: ...} or "
                "{pretrained_model_name_or_path: ...})"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SpeculativeConfig":
        return _cfg_dict(cls, d, "serving.speculative")


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """One ``serving.qos.tenants:`` entry — the tenant's default tier, its
    weighted-fair-queuing share, and its token-bucket quotas. A quota of
    None means unlimited (the bucket never rejects)."""

    tier: Optional[str] = None  # default tier; null = qos.default_tier
    weight: float = 1.0  # WFQ share within the tenant's tier
    requests_per_s: Optional[float] = None  # admission token bucket
    decode_tokens_per_s: Optional[float] = None  # decode-budget bucket
    burst_s: float = 2.0  # bucket depth, in seconds of the rate

    def __post_init__(self):
        if self.tier is not None:
            tier_index(self.tier)  # raises on a typo
        if self.weight <= 0:
            raise ValueError(f"qos tenant weight={self.weight} (want > 0)")
        for name in ("requests_per_s", "decode_tokens_per_s"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"qos tenant {name}={v} (want > 0 or null)")
        if self.burst_s <= 0:
            raise ValueError(f"qos tenant burst_s={self.burst_s} (want > 0)")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TenantConfig":
        return _cfg_dict(cls, d, "serving.qos.tenants entry")


@dataclasses.dataclass(frozen=True)
class QoSConfig:
    """The ``serving.qos:`` section — multi-tenant quality of service
    (docs/serving.md "Multi-tenant QoS"). When enabled, the admission queue
    becomes priority-tiered (``TIERS`` order) with EDF ordering inside each
    tier and weighted fair queuing across tenants; per-tenant token buckets
    reject over-quota submissions with the retriable ``quota`` reason; a
    full queue sheds strictly lowest-tier-first; and ``aging_s`` bounds
    starvation by promoting long-waiting low-tier work to the top tier.
    Disabled (the default), admission is exactly the FIFO it always was."""

    enabled: bool = False
    default_tier: str = "interactive"  # tier when request + tenant name none
    default_tenant: str = "anonymous"  # tenant when the request names none
    aging_s: float = 30.0  # queued longer than this → ordered as top tier
    tenants: Any = dataclasses.field(default_factory=dict)  # name → TenantConfig

    def __post_init__(self):
        tier_index(self.default_tier)
        if self.aging_s <= 0:
            raise ValueError(f"serving.qos.aging_s={self.aging_s} (want > 0)")
        from automodel_tpu.telemetry.prometheus import _LABEL_VALUE_OK

        for name in list(self.tenants) + [self.default_tenant]:
            # tenant names become /metrics label values — refuse anything
            # the exposition sanitizer would mangle, loudly and up front
            if not _LABEL_VALUE_OK.match(str(name)):
                raise ValueError(
                    f"qos tenant name {name!r} is not a valid metrics label "
                    "value (want [a-zA-Z0-9_.+-]+)"
                )

    def tenant(self, name: str) -> TenantConfig:
        return self.tenants.get(name) or TenantConfig()

    def tier_for(self, name: str) -> str:
        t = self.tenants.get(name)
        return t.tier if t is not None and t.tier else self.default_tier

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "QoSConfig":
        d = dict(d or {})
        tenants = d.get("tenants")
        if tenants is not None:
            d["tenants"] = {
                str(name): (
                    sub if isinstance(sub, TenantConfig)
                    else TenantConfig.from_dict(dict(sub or {}))
                )
                for name, sub in dict(tenants).items()
            }
        return _cfg_dict(cls, d, "serving.qos")


class _TokenBucket:
    """Per-tenant rate limiter: ``rate`` units/s refill into a bucket of
    ``rate * burst_s`` depth; ``take`` spends or refuses. rate None =
    unlimited. Timestamps are the caller's perf_counter values."""

    def __init__(self, rate: Optional[float], burst_s: float):
        self.rate = rate
        self.capacity = (rate or 0.0) * burst_s
        self.tokens = self.capacity
        self.t_last: Optional[float] = None

    def take(self, n: float, now: float) -> bool:
        if self.rate is None:
            return True
        if self.t_last is not None and now > self.t_last:
            self.tokens = min(
                self.capacity, self.tokens + (now - self.t_last) * self.rate
            )
        self.t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The `serving:` YAML section (scheduler/allocator knobs; sampling and
    stop tokens come from the `generation:` section)."""

    slots: int = 4  # decode batch width
    block_size: int = 16  # tokens per KV block
    num_blocks: int = 512  # pool size (block 0 is scratch)
    prefill_chunk: int = 64  # prompt tokens per engine iteration per slot
    max_seq_len: int = 1024  # per-request prompt + generated cap
    max_queue: int = 4096
    prefix_cache: bool = True
    # per-token math (docs/serving.md "Raw speed"): pool precision + which
    # decode backend runs the per-token attention
    kv_cache_dtype: str = "bf16"  # bf16 (model compute dtype) | int8
    decode_kernel: str = "auto"  # auto | fused (Pallas paged kernel) | gather
    # fleet tier (docs/serving.md "Fleet"): what this replica does in a
    # disaggregated pool and how much of its prefix cache it advertises
    role: str = "mixed"  # mixed | prefill | decode
    hot_prefix_advertise: int = 512  # cached chain heads exposed via /stats
    # sustained-throughput bench knobs (recipes/benchmark.py serving leg)
    bench_requests: int = 16
    bench_rate: float = 8.0  # Poisson arrival rate, requests/second
    bench_prompt_len_min: int = 8
    bench_prompt_len_max: int = 48
    bench_max_new_tokens: int = 16
    # production-hardening sections (docs/serving.md runbook)
    limits: LimitsConfig = dataclasses.field(default_factory=LimitsConfig)
    drain: DrainConfig = dataclasses.field(default_factory=DrainConfig)
    watchdog: StallConfig = dataclasses.field(default_factory=StallConfig)
    speculative: SpeculativeConfig = dataclasses.field(
        default_factory=SpeculativeConfig
    )
    kv_transfer: KVTransferConfig = dataclasses.field(
        default_factory=KVTransferConfig
    )
    kv_spill: KVSpillConfig = dataclasses.field(default_factory=KVSpillConfig)
    warm_start: WarmStartConfig = dataclasses.field(
        default_factory=WarmStartConfig
    )
    qos: QoSConfig = dataclasses.field(default_factory=QoSConfig)

    def __post_init__(self):
        if self.slots < 1 or self.block_size < 1 or self.prefill_chunk < 1:
            raise ValueError(
                f"serving: slots/block_size/prefill_chunk must be >= 1 "
                f"({self.slots}/{self.block_size}/{self.prefill_chunk})"
            )
        if self.max_seq_len < 2:
            raise ValueError(f"serving.max_seq_len={self.max_seq_len}")
        if self.kv_cache_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"serving.kv_cache_dtype={self.kv_cache_dtype!r} "
                "(want bf16|int8)"
            )
        if self.decode_kernel not in ("auto", "fused", "gather"):
            raise ValueError(
                f"serving.decode_kernel={self.decode_kernel!r} "
                "(want auto|fused|gather)"
            )
        if self.role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"serving.role={self.role!r} (want mixed|prefill|decode)"
            )
        if self.hot_prefix_advertise < 0:
            raise ValueError(
                f"serving.hot_prefix_advertise={self.hot_prefix_advertise}"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ServeConfig":
        d = dict(d or {})
        d.pop("_target_", None)
        d.pop("http", None)  # server-level section (serving/server.py)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise TypeError(f"unknown serving keys: {sorted(unknown)}")
        for key, sub in (
            ("limits", LimitsConfig),
            ("drain", DrainConfig),
            ("watchdog", StallConfig),
            ("speculative", SpeculativeConfig),
            ("kv_transfer", KVTransferConfig),
            ("kv_spill", KVSpillConfig),
            ("warm_start", WarmStartConfig),
            ("qos", QoSConfig),
        ):
            v = d.get(key)
            if v is not None and not isinstance(v, sub):
                d[key] = sub.from_dict(dict(v))
        return cls(**d)

    @property
    def spec_overhang(self) -> int:
        """Positions a speculative verify may WRITE past a sequence's final
        committed length (rejected-draft rows, rolled back by length
        decrement): the admission block budget and the table width both
        cover it so those writes always land in owned blocks."""
        return self.speculative.k if self.speculative.enabled else 0

    @property
    def table_blocks(self) -> int:
        """Static per-sequence block-table width. The extra headroom
        (prefill_chunk, or the speculative verify chunk when larger) keeps
        per-slot writes from ever clamping past the table (paged.py
        view-position invariant)."""
        headroom = max(self.prefill_chunk, self.spec_overhang + 1)
        return -(-(self.max_seq_len + headroom) // self.block_size)


@dataclasses.dataclass
class _Queued:
    rid: str
    prompt: list[int]
    max_new: int
    t_submit: float
    deadline_at: Optional[float] = None  # perf_counter absolute
    queue_deadline_at: Optional[float] = None
    # disaggregated fleet (docs/serving.md "Fleet"):
    prefill_only: bool = False  # prefill-role replica: extract KV, no decode
    payload: Optional[dict] = None  # decode-role replica: injected prompt KV
    # hierarchical KV cache: router hint naming the peer replica whose
    # prefix cache covers this prompt ({"host": ..., "port": ...}) — the
    # admission path /kv_fetch-es missing blocks from it, best-effort
    kv_peer: Optional[dict] = None
    # request tracing: this request's ROOT span context on this process
    # (child of the router's forward span when one propagated in)
    trace: Optional[SpanContext] = None
    # behavior-policy logprob capture (posttrain/grpo.py): record the
    # sampled sequence's per-token logprobs on the terminal record
    return_logprobs: bool = False
    # multi-tenant QoS (serving.qos): who submitted, at what priority —
    # stamped on the terminal record and on every tier/tenant metric label
    tenant: str = "anonymous"
    tier: str = "interactive"
    tier_idx: int = 0


@dataclasses.dataclass
class _Slot:
    request_id: str
    prompt: list[int]
    max_new: int
    blocks: list[int]  # every block this sequence holds a ref on
    hit_tokens: int  # prefix-cache reused tokens
    prefill_pos: int  # next absolute prompt position to compute
    t_submit: float
    t_admit: float
    deadline_at: Optional[float] = None
    decoding: bool = False
    generated: Optional[list[int]] = None
    t_first: Optional[float] = None
    prefill_only: bool = False
    spec_proposed: int = 0  # draft tokens proposed for this request
    spec_accepted: int = 0  # draft tokens accepted by the verify rule
    trace: Optional[SpanContext] = None
    # parallel to ``generated`` when the request asked for logprobs: the
    # behavior policy's own log π(token) at each sampled position
    logprobs: Optional[list[float]] = None
    tenant: str = "anonymous"
    tier: str = "interactive"


def _tree_path_name(path) -> str:
    """The param-tree leaf naming rule — MUST match
    ``checkpoint.checkpointer.param_tree_signature`` exactly, so signature
    entries and hot-swapped/wire-transferred leaves line up one-to-one
    (server._warm_start_params applies the same rule)."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


class ServingEngine:
    """Facade over (AutoModel, ServeConfig, GenerationConfig).

    ``submit`` enqueues token-id prompts; ``step`` runs one scheduler
    iteration and returns the requests that reached a terminal state in it
    (completed, timed out, rejected, failed — every record carries a
    ``completion_reason``); ``run`` drains everything. ``on_record``
    (optional) receives one telemetry dict per terminal request (the serve
    CLI points it at the metrics JSONL)."""

    # back-to-back rebuild budget: a fault that survives this many fresh
    # pools in a row is systemic (bad params, broken backend) — fail the
    # scheduler loudly instead of rebuild-looping forever
    MAX_CONSECUTIVE_REBUILDS = 8

    def __init__(
        self,
        auto: Any,
        config: Optional[ServeConfig] = None,
        gen_config: Optional[GenerationConfig] = None,
        on_record: Optional[Callable[[dict], None]] = None,
        tracer: Optional[Tracer] = None,
    ):
        if not getattr(auto.model, "supports_kv_cache", False):
            raise GenerationUnsupported(
                f"{type(auto.model).__name__} has no KV-cache decode path; "
                "cache-capable families: llama-generic (llama/qwen2/qwen3/"
                "mistral/phi3), gpt2, qwen3_moe"
            )
        self.auto = auto
        self.model = auto.model
        self.config = config or ServeConfig()
        self.gen_config = gen_config or GenerationConfig()
        self.on_record = on_record
        mcfg = self.model.config
        self._max_positions = _model_max_positions(mcfg)
        if self._max_positions and self.config.max_seq_len > self._max_positions:
            raise ValueError(
                f"serving.max_seq_len={self.config.max_seq_len} exceeds the "
                f"model context limit {self._max_positions}"
            )
        self.pool = BlockPool(
            self.config.num_blocks, self.config.block_size,
            prefix_cache=self.config.prefix_cache,
        )
        # per-token math levers (docs/serving.md "Raw speed"): pool
        # precision, decode backend, speculative draft
        self._quantized = self.config.kv_cache_dtype == "int8"
        self._compute_dtype = self.model.backend.compute_jnp_dtype
        from automodel_tpu.ops.attention import _interpret_requested

        self._interpret = _interpret_requested()
        self.decode_backend = self._resolve_decode_backend()
        spec = self.config.speculative
        self._spec_enabled = bool(spec.enabled)
        sp = self.config.kv_spill
        if sp.enabled and self._spec_enabled:
            # same reason submit_prefilled refuses spec engines: a reloaded
            # prefix fills only the TARGET pool — the draft's parallel pool
            # would miss the prompt KV and proposals would attend garbage
            raise ValueError(
                "serving.kv_spill cannot be enabled together with "
                "serving.speculative: the draft pool has no spill tier, so "
                "a reloaded prefix would leave it without the prompt KV"
            )
        if sp.enabled:
            self.pool.spill = HostSpillTier(
                max(int(sp.max_host_mb * 1024 * 1024), 1)
            )
            self.pool.on_evict = self._spill_evicted
        self.draft_auto = None
        if self._spec_enabled:
            from automodel_tpu.generation.engine import (
                build_auto_from_model_section,
            )

            self.draft_auto = build_auto_from_model_section(
                spec.draft, auto.mesh_ctx, seed=self.gen_config.seed
            )
            if not getattr(self.draft_auto.model, "supports_kv_cache", False):
                raise GenerationUnsupported(
                    "serving.speculative.draft model "
                    f"{type(self.draft_auto.model).__name__} has no KV-cache "
                    "decode path"
                )
            dv = int(self.draft_auto.model.config.vocab_size)
            tv = int(mcfg.vocab_size)
            if dv != tv:
                raise ValueError(
                    f"speculative draft vocab_size {dv} != target vocab_size "
                    f"{tv} — draft and target must share a vocabulary"
                )
            dmax = _model_max_positions(self.draft_auto.model.config)
            if dmax and self.config.max_seq_len + spec.k > dmax:
                # same loud refusal the target gets at line one of __init__:
                # a too-short draft context would silently extrapolate RoPE
                # past dmax and collapse the accept rate without ever erroring
                raise ValueError(
                    f"serving.max_seq_len={self.config.max_seq_len} + "
                    f"speculative.k={spec.k} exceeds the draft model's "
                    f"context limit {dmax}"
                )
        self._init_pool_arrays()
        constrain = auto.constrain

        def apply(params, ids, **kw):
            return self.model(params, ids, constrain=constrain, **kw)

        pk = dict(
            backend=self.decode_backend,
            block_size=self.config.block_size,
            compute_dtype=self._compute_dtype,
            interpret=self._interpret,
        )
        self._chunk = paged.build_chunk_prefill_fn(
            apply, self.config.prefill_chunk, self._compute_dtype
        )
        # the decode program always computes the sampled token's logprob
        # beside the token (one extra gather off logits already in hand);
        # whether it lands on the record is per-request (return_logprobs)
        self._decode = paged.build_paged_decode_fn(
            apply, self.gen_config.sampling,
            pad_id=self.gen_config.pad_token_id, with_logprobs=True, **pk,
        )
        if self._spec_enabled:
            d_model = self.draft_auto.model
            d_constrain = self.draft_auto.constrain

            def draft_apply(params, ids, **kw):
                return d_model(params, ids, constrain=d_constrain, **kw)

            d_pk = dict(pk, compute_dtype=d_model.backend.compute_jnp_dtype)
            self._draft_chunk = paged.build_chunk_prefill_fn(
                draft_apply, self.config.prefill_chunk,
                d_model.backend.compute_jnp_dtype,
            )
            self._propose = paged.build_draft_propose_fn(
                draft_apply, self.gen_config.sampling, spec.k,
                pad_id=self.gen_config.pad_token_id, **d_pk,
            )
            self._verify = paged.build_verify_fn(
                apply, self.gen_config.sampling, spec.k,
                pad_id=self.gen_config.pad_token_id, **pk,
            )
        self._base_key = sampling_key(self.gen_config.seed)
        self._eos = set(self.gen_config.eos_ids)
        # speculative accounting (accept-rate gauge + bench keys)
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_rounds = 0

        B, NB = self.config.slots, self.config.table_blocks
        self._tables = np.zeros((B, NB), np.int32)
        self._lengths = np.zeros((B,), np.int32)
        self._cur = np.full((B,), self.gen_config.pad_token_id, np.int32)
        self._active = np.zeros((B,), bool)
        self._slots: list[Optional[_Slot]] = [None] * B
        self._queue: deque[_Queued] = deque()
        self._ids = itertools.count()
        self._step_counter = 0
        # live weight hot-swap (swap_weights): monotonic version tag
        # advertised on /stats + /metrics, and the validated replacement
        # tree staged until a step boundary with zero busy slots
        self.weights_version = 0
        self._pending_swap: Optional[Any] = None
        self.completed_total = 0  # stop/length completions
        self.failed_total = 0  # timeout/cancelled/stall/error terminations
        self.shed_total = 0
        self.quota_total = 0  # tenant token-bucket rejections
        self.timeout_total = 0
        # multi-tenant QoS (serving.qos): per-tenant token buckets (lazily
        # built from TenantConfig on first submission), per-(tier, tenant)
        # weighted-fair-queuing service accumulators (request token cost /
        # weight — reset never; relative order is all WFQ needs), and
        # cumulative per-tier / per-tenant terminal-outcome rollups for
        # /stats (the labeled /metrics families mirror them)
        self._req_buckets: dict[str, _TokenBucket] = {}
        self._decode_buckets: dict[str, _TokenBucket] = {}
        self._wfq_served: dict[tuple[str, str], float] = {}
        self.tier_counters: dict[str, dict[str, int]] = {
            t: {"completed": 0, "shed": 0, "timeout": 0, "quota": 0}
            for t in TIERS
        }
        self.tenant_counters: dict[str, dict[str, int]] = {}
        self.stall_total = 0  # watchdog-detected wedged steps
        self.error_total = 0  # recovered scheduler exceptions
        # drain state (begin_drain / drain_complete)
        self.draining = False
        self.drain_duration_s: Optional[float] = None
        self._drain_started: Optional[float] = None
        self._drain_deadline: Optional[float] = None
        # stall watchdog (start_watchdog): evidence handed over from the
        # watchdog thread, consumed at the next step boundary
        self._watchdog = None
        self._stall_evidence: Optional[dict] = None
        self._consecutive_rebuilds = 0
        self._exhaust_hold: Optional[tuple[list[int], int]] = None  # injection
        # disaggregated fleet: extracted prefill payloads awaiting pickup by
        # the /prefill handler (bounded — an abandoned payload must not pin
        # host memory forever), and the advertised KV-transfer listener port
        self._prefill_payloads: "OrderedDict[str, dict]" = OrderedDict()
        self.kv_transfer_port: Optional[int] = None  # set by the server front
        self.kv_injected_total = 0  # handoffs admitted into this pool
        self.first_decode_done = False  # readiness: first compiled decode
        self.last_step_t: Optional[float] = None  # monotonic, health age
        # elastic-fleet boot provenance: the server front stamps boot_t
        # (perf_counter at process start, BEFORE the model build — load
        # time is the whole point of the measurement) and boot_source;
        # note_ready() computes time_to_ready_s at first readiness
        self.boot_t: Optional[float] = None
        self.boot_source = "cold_hf"  # cold_hf | peer_warm_start
        self.time_to_ready_s: Optional[float] = None
        # /metrics exposition (telemetry/prometheus.py): histograms are
        # observed per completion (cheap, python dict ops); gauges + pool
        # counters sync at scrape time so the scheduler loop pays nothing
        from automodel_tpu.telemetry.prometheus import ServingMetrics

        self.metrics = ServingMetrics()
        # request tracing (telemetry/tracing.py): spans ride on_record like
        # every other telemetry record; every emitted span also observes
        # the /metrics per-stage histogram. All record timestamps derive
        # from ONE wall anchor + the monotonic clock — `ts` can never
        # disagree with the monotonic-difference durations it sits beside.
        self._clock = WallAnchor()
        self.tracer = tracer
        if tracer is not None:
            tracer.clock = self._clock  # one anchor per process, shared
            if tracer.observe is None:
                tracer.observe = self.metrics.observe_stage
        # cost attribution (telemetry/profiling/): when armed, the first
        # chunk-prefill/paged-decode call also records the program's
        # measured FLOPs/bytes (abstract host trace, one-time)
        self.collect_program_costs = False
        self.program_costs: dict = {}

    def _resolve_decode_backend(self) -> str:
        """fused (Pallas paged kernel) vs gather (XLA baseline):
        ``AUTOMODEL_PAGED_DECODE`` env beats ``serving.decode_kernel``
        beats the autotune table entry (``autotune.paged_key``, raced by
        tools/kernel_bench.py) beats the platform default (fused wherever
        the kernel can run — TPU or interpret mode — else gather)."""
        import os

        env = os.environ.get("AUTOMODEL_PAGED_DECODE", "").strip().lower()
        mode = env if env in ("fused", "gather") else self.config.decode_kernel
        if mode in ("fused", "gather"):
            return mode
        from automodel_tpu.ops import autotune

        entry = autotune.lookup(
            autotune.paged_key(
                int(self.model.config.head_dim), self.config.block_size,
                self.config.kv_cache_dtype,
            )
        )
        if entry is not None and entry.get("backend") in ("fused", "gather"):
            return entry["backend"]
        from automodel_tpu.ops.platform_check import is_tpu_platform

        on_kernel_platform = self._interpret or is_tpu_platform(
            getattr(self.model.backend, "platform", None)
        )
        return "fused" if on_kernel_platform else "gather"

    def _init_pool_arrays(self) -> None:
        """(Re)create the HBM pool arrays — at construction, and on a
        rebuild after a stalled/failed program whose donated buffers can no
        longer be trusted (or were consumed by the failed call). With
        speculative decoding the draft model's parallel pool (same block
        geometry, its own layer/head dims) rebuilds in the same breath —
        a stall mid-verify must never leave half-trusted draft state."""
        mcfg = self.model.config
        self._pool = paged.place_pool(
            paged.init_pool(
                int(mcfg.num_layers), self.config.num_blocks,
                self.config.block_size, int(mcfg.num_kv_heads),
                int(mcfg.head_dim), dtype=self._compute_dtype,
                quantized=self._quantized,
            ),
            self.auto.mesh_ctx,
        )
        if self._spec_enabled:
            dcfg = self.draft_auto.model.config
            self._draft_pool = paged.place_pool(
                paged.init_pool(
                    int(dcfg.num_layers), self.config.num_blocks,
                    self.config.block_size, int(dcfg.num_kv_heads),
                    int(dcfg.head_dim),
                    dtype=self.draft_auto.model.backend.compute_jnp_dtype,
                    quantized=self._quantized,
                ),
                self.auto.mesh_ctx,
            )

    def release_pools(self) -> None:
        """Drop the engine's HBM pool arrays (target + draft). For callers
        that are DONE with this engine but keep the process alive — e.g.
        the bench A/B sub-leg, which builds a second chip-sized engine and
        must not hold two resident pools. The engine is unusable after."""
        self._pool = None
        if self._spec_enabled:
            self._draft_pool = None

    # -- stats ---------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def busy_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def pool_bytes(self) -> int:
        return self._pool.nbytes

    @property
    def draft_pool_bytes(self) -> int:
        return self._draft_pool.nbytes if self._spec_enabled else 0

    @property
    def spec_accept_rate(self) -> Optional[float]:
        """Engine-lifetime draft acceptance rate (None when speculative
        decoding is off or no round has run yet)."""
        if not self._spec_enabled or not self.spec_proposed_total:
            return None
        return self.spec_accepted_total / self.spec_proposed_total

    @property
    def watchdog(self):
        return self._watchdog

    @property
    def last_step_age_s(self) -> Optional[float]:
        return (
            time.monotonic() - self.last_step_t
            if self.last_step_t is not None else None
        )

    def idle(self) -> bool:
        return not self._queue and self.busy_slots == 0

    def note_ready(self) -> None:
        """Stamp ``time_to_ready_s`` at this replica's FIRST readiness
        (idempotent; called after warmup and from the /readyz handler so
        warmup-disabled servers still stamp on their first true probe).
        Emits one ``replica_ready`` record — the elastic fleet's
        warm-vs-cold A/B number, labeled with the boot source taken."""
        if (
            self.time_to_ready_s is not None
            or not self.first_decode_done
            or self.boot_t is None
        ):
            return
        self.time_to_ready_s = time.perf_counter() - self.boot_t
        logger.info(
            "replica ready in %.3fs (boot source: %s)",
            self.time_to_ready_s, self.boot_source,
        )
        if self.on_record is not None:
            self.on_record({
                "event": "replica_ready",
                "ts": self._wall_ts(),
                "boot_source": self.boot_source,
                "time_to_ready_s": round(self.time_to_ready_s, 6),
            })

    # -- stall watchdog -------------------------------------------------------
    def start_watchdog(self, flight_recorder: Any = None,
                       metric_logger: Any = None,
                       stacks_path: Optional[str] = None):
        """Arm the scheduler-level stall watchdog (serving fronts call this;
        batch ``run()`` drains don't need a thread). → the EngineWatchdog,
        or None when serving.watchdog.enabled is false."""
        c = self.config.watchdog
        if not c.enabled or self._watchdog is not None:
            return self._watchdog
        from automodel_tpu.resilience.watchdog import EngineWatchdog, WatchdogConfig

        wcfg = WatchdogConfig(
            enabled=True, multiplier=c.multiplier,
            min_deadline_s=c.min_deadline_s, max_deadline_s=c.max_deadline_s,
            ema_alpha=c.ema_alpha, compile_grace_s=c.compile_grace_s,
            poll_interval_s=c.poll_interval_s,
            stacks_path=c.stacks_path or stacks_path,
            exit_on_hang=False,
        )
        self._watchdog = EngineWatchdog(
            wcfg, flight_recorder=flight_recorder, metric_logger=metric_logger,
            on_hang=self._note_stall,
        )
        self._watchdog.start()
        return self._watchdog

    def stop_watchdog(self) -> None:
        wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.stop()

    def touch_watchdog(self) -> None:
        """Idle heartbeat: the serving loop calls this when there is no work
        so an empty server never reads as a wedged one."""
        if self._watchdog is not None:
            self._watchdog.touch()

    def _note_stall(self, rec: dict) -> None:
        # called from the WATCHDOG thread while the scheduler thread is
        # blocked inside the wedged call; consumed at the next step boundary
        self._stall_evidence = dict(rec)

    # -- drain ----------------------------------------------------------------
    def begin_drain(self) -> None:
        """Flip to draining: new submissions raise ``EngineDraining``, the
        queue is flushed with retriable rejections at the next step, and
        in-flight requests get ``drain.grace_s`` to finish before they are
        cancelled. Idempotent."""
        if self.draining:
            return
        self.draining = True
        self._drain_started = time.perf_counter()
        self._drain_deadline = self._drain_started + max(
            self.config.drain.grace_s, 0.0
        )
        logger.warning(
            "serving drain started: %d queued rejected retriable, %d in "
            "flight, grace %.1fs",
            self.queue_depth, self.busy_slots, self.config.drain.grace_s,
        )

    def drain_complete(self) -> bool:
        """True once every in-flight request reached a terminal state after
        ``begin_drain``. Stamps ``drain_duration_s`` (and the /metrics
        gauge) on first observation."""
        done = self.draining and self.idle()
        if done and self.drain_duration_s is None:
            self.drain_duration_s = time.perf_counter() - self._drain_started
            logger.warning(
                "serving drain complete in %.3fs", self.drain_duration_s
            )
        return done

    # -- live weight hot-swap (docs/posttrain.md) -----------------------------
    def swap_weights(self, params: Any) -> int:
        """Stage a full replacement of the policy weights without a restart.

        The incoming tree is validated against the CURRENT tree's
        param-tree signature (path/shape/dtype set — the same guard
        warm-start and checkpoint restore use) before a single leaf is
        touched; a mismatch raises ``ValueError`` loudly with the old
        params bit-intact. A valid tree is device_put to the live leaves'
        shardings and staged; the scheduler applies it at a step boundary
        with ZERO busy slots, so every in-flight request finishes under
        the weights it started with, and new admissions hold (the queue
        keeps absorbing — nothing drops) until the swap lands. If no
        request is in flight the swap applies immediately. → the
        ``weights_version`` the engine advertises once the swap is live.

        Same shapes/dtypes means the already-compiled prefill/decode
        programs are reused as-is — a swap never recompiles."""
        from automodel_tpu.checkpoint.checkpointer import param_tree_signature

        cur_sig = param_tree_signature(self.auto.params)
        new_sig = param_tree_signature(params)
        if new_sig["digest"] != cur_sig["digest"]:
            cur_e, new_e = set(cur_sig["entries"]), set(new_sig["entries"])
            detail = (
                f"current digest {cur_sig['digest']} != incoming "
                f"{new_sig['digest']}; missing {sorted(cur_e - new_e)[:4]}, "
                f"unexpected {sorted(new_e - cur_e)[:4]}"
            )
            self._emit_event({
                "event": "weight_swap", "ok": False,
                "weights_version": self.weights_version,
                "detail": detail, "ts": self._wall_ts(),
            })
            raise ValueError(
                f"swap_weights refused: param tree signature mismatch "
                f"({detail}) — serving weights unchanged"
            )
        incoming = {
            _tree_path_name(path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
        }
        cur_leaves, treedef = jax.tree_util.tree_flatten_with_path(
            self.auto.params
        )
        staged = jax.tree_util.tree_unflatten(
            treedef,
            [
                jax.device_put(incoming[_tree_path_name(path)], leaf.sharding)
                for path, leaf in cur_leaves
            ],
        )
        self._pending_swap = staged
        target = self.weights_version + 1
        if self.busy_slots == 0:
            self._apply_pending_swap()
        return target

    def _apply_pending_swap(self) -> None:
        """Flip the staged tree in (scheduler thread / caller under the
        serving lock): one attribute assignment — the next tick's fresh
        ``self.auto.params`` read picks it up."""
        if self._pending_swap is None:
            return
        self.auto.params = self._pending_swap
        self._pending_swap = None
        self.weights_version += 1
        # every cached prefix (and its host-spilled copies) holds K/V
        # computed under the OLD policy — serving it to a request running
        # the new weights would silently mix two policies in one sequence
        self.pool.clear_prefix_cache()
        logger.info(
            "weights hot-swapped: now serving weights_version=%d",
            self.weights_version,
        )
        self._emit_event({
            "event": "weight_swap", "ok": True,
            "weights_version": self.weights_version, "ts": self._wall_ts(),
        })

    def _emit_event(self, rec: dict) -> None:
        """on_record for non-request events (no completion_reason, so the
        per-request metrics observers are wrong for these)."""
        if self.on_record is not None:
            try:
                self.on_record(dict(rec))
            except Exception:  # telemetry must never break serving
                pass

    # -- submission -----------------------------------------------------------
    def submit(
        self,
        prompt_ids: Sequence[int],
        request_id: Optional[str] = None,
        max_new_tokens: Optional[int] = None,
        t_submit: Optional[float] = None,
        deadline_s: Optional[float] = None,
        max_queue_wait_s: Optional[float] = None,
        prefill_only: bool = False,
        trace: Optional[SpanContext] = None,
        kv_peer: Optional[dict] = None,
        return_logprobs: bool = False,
        tenant: Optional[str] = None,
        tier: Optional[str] = None,
        _payload: Optional[dict] = None,
    ) -> str:
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt (every request needs >= 1 token)")
        qos = self.config.qos
        tenant = str(tenant) if tenant is not None else qos.default_tenant
        tier = str(tier) if tier is not None else qos.tier_for(tenant)
        tier_idx = tier_index(tier)  # raises 400-ably on a typo
        if qos.enabled:
            from automodel_tpu.telemetry.prometheus import _LABEL_VALUE_OK

            if not _LABEL_VALUE_OK.match(tenant):
                raise ValueError(
                    f"tenant {tenant!r} is not a valid metrics label value "
                    "(want [a-zA-Z0-9_.+-]+)"
                )
        if return_logprobs and self._spec_enabled:
            # speculative commits draft+correction tokens whose per-token
            # behavior logprobs are not the target's sampling logprobs —
            # refuse rather than report numbers a ratio can't trust
            raise GenerationUnsupported(
                "return_logprobs is not supported on a speculative engine: "
                "committed tokens mix draft proposals and verify "
                "corrections, so no single behavior-policy logprob exists"
            )
        max_new = (
            self.gen_config.max_new_tokens
            if max_new_tokens is None
            else int(max_new_tokens)  # explicit 0 must hit the guard below
        )
        if max_new < 1:
            raise ValueError(f"max_new_tokens={max_new}")
        # a prefill-only request never decodes: its budget is the prompt
        # alone (positions 0..p-1), and its cap check ignores max_new
        total = len(prompt) if prefill_only else len(prompt) + max_new
        cap = min(
            self.config.max_seq_len,
            self._max_positions or self.config.max_seq_len,
        )
        if total > cap:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({0 if prefill_only else max_new}) = {total} exceeds the "
                f"serving limit {cap}"
            )
        need = blocks_needed(
            total, self.config.block_size,
            0 if prefill_only else self.config.spec_overhang,
        )
        if need > self.pool.usable_blocks:
            raise ValueError(
                f"request needs {need} blocks but the pool only has "
                f"{self.pool.usable_blocks} — raise serving.num_blocks"
            )
        now = time.perf_counter() if t_submit is None else t_submit
        rid = request_id if request_id is not None else f"req-{next(self._ids)}"
        lim = self.config.limits
        ddl = lim.deadline_s if deadline_s is None else float(deadline_s)
        qw = (
            lim.max_queue_wait_s
            if max_queue_wait_s is None else float(max_queue_wait_s)
        )
        # the engine's ROOT span for this request: child of the propagated
        # context (a router forward span) when one came in, a freshly
        # minted trace otherwise (the engine front IS the entry point for
        # direct requests). Unsampled contexts flow through but emit nothing.
        root = self.tracer.start(parent=trace) if self.tracer is not None else None
        q = _Queued(
            rid=rid, prompt=prompt, max_new=max_new, t_submit=now,
            deadline_at=now + ddl if ddl and ddl > 0 else None,
            queue_deadline_at=now + qw if qw and qw > 0 else None,
            prefill_only=prefill_only, payload=_payload, trace=root,
            kv_peer=kv_peer if kv_peer else None,
            return_logprobs=return_logprobs,
            tenant=tenant, tier=tier, tier_idx=tier_idx,
        )
        if self.draining:
            # no terminal record here (mirror of the shed seam): the
            # rejection is returned to the client directly, and a client
            # honoring Retry-After would otherwise inflate failed_total and
            # the JSONL with one synthetic record per retry attempt.
            # ACCEPTED-then-drained requests do get records (step's queue
            # flush) — that is the no-silent-drop contract's scope. The
            # draining check comes BEFORE any priority handling: no tier,
            # however high, jumps a drain (tests/test_qos.py pins it).
            raise EngineDraining(
                "server is draining — retry against another replica"
            )
        if qos.enabled:
            # token-bucket quotas, charged up front: one admission token and
            # the request's whole decode budget (max_new) — a worst-case
            # reservation, so a flooding tenant is bounded by what it COULD
            # decode, not by what its requests happen to generate
            tc = qos.tenant(tenant)
            rb = self._req_buckets.get(tenant)
            if rb is None:
                rb = self._req_buckets[tenant] = _TokenBucket(
                    tc.requests_per_s, tc.burst_s
                )
            db = self._decode_buckets.get(tenant)
            if db is None:
                db = self._decode_buckets[tenant] = _TokenBucket(
                    tc.decode_tokens_per_s, tc.burst_s
                )
            if not rb.take(1.0, now):
                raise QuotaExceeded(
                    f"tenant {tenant!r} over requests_per_s="
                    f"{tc.requests_per_s} quota",
                    tenant=tenant, tier=tier,
                )
            if not db.take(float(0 if prefill_only else max_new), now):
                raise QuotaExceeded(
                    f"tenant {tenant!r} over decode_tokens_per_s="
                    f"{tc.decode_tokens_per_s} quota",
                    tenant=tenant, tier=tier,
                )
        if len(self._queue) >= self.config.max_queue:
            if not qos.enabled:
                raise QueueFull(
                    "admission queue at serving.max_queue="
                    f"{self.config.max_queue}"
                )
            # overload sheds strictly lowest-tier-first: evict the worst
            # queued entry (lowest EFFECTIVE tier — aging promotion counts —
            # latest-submitted among those) when it ranks strictly below the
            # newcomer; otherwise the newcomer IS the lowest tier and is
            # refused. The evicted entry was accepted earlier, so the
            # no-silent-drop contract owes it a terminal `shed` record here.
            victim = self._shed_victim(tier_idx, now)
            if victim is None:
                raise QueueFull(
                    "admission queue at serving.max_queue="
                    f"{self.config.max_queue} (tier {tier!r} sheds first)"
                )
            self._queue.remove(victim)
            self.shed_total += 1
            self._rejection_record(victim, "shed")
        self._queue.append(q)
        return rid

    def _effective_tier(self, q: _Queued, now: float) -> int:
        """Tier rank used for ordering and shedding: the anti-starvation
        aging bound promotes work queued past ``qos.aging_s`` to the top
        tier, so a busy high tier can delay low-tier work but never starve
        it (and an aged entry is never the preferred shed victim)."""
        if now - q.t_submit >= self.config.qos.aging_s:
            return 0
        return q.tier_idx

    def _shed_victim(
        self, newcomer_tier_idx: int, now: float
    ) -> Optional[_Queued]:
        """The queued entry a full queue evicts to make room for a
        strictly-higher-tier newcomer: lowest effective tier, latest
        submission among ties (shedding the newest low-tier entry keeps
        the oldest closest to its aging promotion). None when nothing
        queued ranks strictly below the newcomer."""
        victim = None
        victim_key = None
        for q in self._queue:
            key = (self._effective_tier(q, now), q.t_submit)
            if victim_key is None or key > victim_key:
                victim, victim_key = q, key
        if victim is None or victim_key[0] <= newcomer_tier_idx:
            return None
        return victim

    # -- disaggregated prefill/decode (serving/fleet/) ------------------------
    def kv_geometry(self) -> dict:
        """The pool geometry a KV-transfer peer must match exactly — the
        handshake header both sides validate before any block row moves."""
        L, _, BS, Nkv, H = self._pool.values_shape
        return {
            "layers": int(L),
            "block_size": int(BS),
            "num_kv_heads": int(Nkv),
            "head_dim": int(H),
            "kv_cache_dtype": self.config.kv_cache_dtype,
        }

    def kv_frame_bytes_bound(self) -> int:
        """Upper bound on a legitimate KV-transfer frame into this pool —
        the WHOLE pool's bytes (k + v, scales included). The transfer
        listener refuses anything larger before allocating."""
        total = 0
        for side in (self._pool.k, self._pool.v):
            arrs = side if isinstance(side, tuple) else (side,)
            for a in arrs:
                total += int(np.prod(a.shape)) * a.dtype.itemsize
        return total

    def hot_prefixes(self) -> list[int]:
        """Cached chain heads advertised via /stats for the fleet router's
        prefix-affinity placement."""
        return self.pool.cached_chain_hashes(self.config.hot_prefix_advertise)

    # -- hierarchical KV cache (docs/serving.md "Hierarchical KV cache") ------
    def _spill_evicted(self, evicted: list) -> None:
        """BlockPool eviction hook: copy the evicted prefix blocks' rows
        device→host into the spill tier, keyed by chain hash. Runs inside
        ``allocate()`` — strictly before the caller can overwrite the
        blocks it was handed. ONE gather + device sync per eviction event
        (the host round trip, not the bytes, dominates on small pools),
        padded to a power-of-two block count so the arbitrary batch sizes
        churn cost at most log2(pool) compiled programs."""
        tier = self.pool.spill
        if tier is None:
            return
        bids = [bid for _, bid in evicted]
        pad = paged.bucket_blocks(len(bids))
        k, v = paged.extract_blocks(
            self._pool, bids + [bids[-1]] * (pad - len(bids))
        )
        payloads = paged.split_kv_blocks({"k": k, "v": v})[: len(bids)]
        for (h, _), payload in zip(evicted, payloads):
            if tier.put(h, payload, paged.kv_nbytes(payload)):
                self.pool.counters["spilled_blocks"] += 1

    def fetch_prefix_blocks(self, chain_hashes: Sequence[int]):
        """Serve a peer replica's ``/kv_fetch``: the longest leading run of
        ``chain_hashes`` this replica can source — resident prefix-cache
        blocks extract device→host, spilled blocks come straight from the
        host tier. → ``(n, kv dict | None)``. Caller holds the scheduler
        lock (the server front wraps this in ``loop.lock``)."""
        tier = self.pool.spill
        pieces: list[dict] = []
        for h in chain_hashes:
            bid = self.pool.cached_block(int(h))
            if bid is not None:
                k, v = paged.extract_blocks(self._pool, [bid])
                pieces.append({"k": k, "v": v})
                continue
            p = tier.get(int(h)) if tier is not None else None
            if p is None:
                break
            pieces.append(p)
        if not pieces:
            return 0, None
        return len(pieces), paged.concat_kv_blocks(pieces)

    # -- elastic fleet (docs/serving.md "Elastic fleet") ----------------------
    def export_hot_blocks(self, limit: Optional[int] = None):
        """A retiring replica's migration export: up to ``limit`` hot
        prefix blocks in EVICTION-DISTANCE order (pinned, then parked LRU
        MRU-first, then spill-tier MRU-first — exactly the
        ``cached_chain_hashes`` advertisement order, so the blocks most
        worth keeping warm ship first if the deadline cuts the transfer
        short). → ``(chain_hashes, kv | None)``. Caller holds the
        scheduler lock."""
        hashes = self.pool.cached_chain_hashes(
            self.config.hot_prefix_advertise if limit is None else int(limit)
        )
        tier = self.pool.spill
        out: list[int] = []
        pieces: list[dict] = []
        for h in hashes:
            bid = self.pool.cached_block(int(h))
            if bid is not None:
                k, v = paged.extract_blocks(self._pool, [bid])
                pieces.append({"k": k, "v": v})
                out.append(int(h))
                continue
            p = tier.get(int(h)) if tier is not None else None
            if p is not None:
                pieces.append(p)
                out.append(int(h))
        if not pieces:
            return [], None
        return out, paged.concat_kv_blocks(pieces)

    def receive_migrated_blocks(self, chain_hashes: Sequence[int], kv: dict) -> int:
        """A survivor's migration sink (the AKV1 ``kv_push`` handler):
        park the shipped block rows in the HOST SPILL TIER keyed by their
        chain hashes — the next admission sharing the prefix reloads them
        through the normal hierarchy seam, and ``cached_chain_hashes``
        re-advertises them so router affinity follows the heat. Blocks
        this replica already holds (resident or spilled) are skipped.
        → the number of blocks accepted. Requires ``kv_spill.enabled``
        (no tier → 0 accepted, a loud refusal upstream). Caller holds the
        scheduler lock."""
        tier = self.pool.spill
        if tier is None:
            return 0
        payloads = paged.split_kv_blocks(kv)
        accepted = 0
        for h, payload in zip(chain_hashes, payloads):
            h = int(h)
            if self.pool.cached_block(h) is not None or tier.get(h) is not None:
                continue
            if tier.put(h, payload, paged.kv_nbytes(payload)):
                # the spill ledger counts tier entries however they arrived
                # (eviction or migration) — check_invariants pins
                # spilled_blocks == spill_puts
                self.pool.counters["spilled_blocks"] += 1
                accepted += 1
        return accepted

    def _resolve_hierarchy(
        self, q: _Queued, hits: list, hit_tokens: int, fresh: list
    ) -> int:
        """Admission-time resolution of a prefix match that ends short of
        the prompt's full chain: reload spilled blocks from the host tier,
        then (router-hinted) fetch the remainder from the peer that
        advertises it, and scatter everything into the leading ``fresh``
        blocks through ``inject_blocks`` — the exact seam disagg handoff
        uses, so greedy output is bit-identical to recompute.
        Every failure degrades to recompute; nothing here can fail the
        request short of the injection itself. → the updated hit_tokens
        (prefill resumes past everything served from any tier)."""
        sp = self.config.kv_spill
        tier = self.pool.spill
        if not sp.enabled or tier is None or not fresh:
            return hit_tokens
        bs = self.config.block_size
        chain = prompt_chain(q.prompt, bs)
        k = len(hits)
        if k >= len(chain):
            return hit_tokens
        t0 = time.perf_counter()
        pieces: list[dict] = []
        reloaded = 0
        for h in chain[k:]:
            if reloaded >= len(fresh):
                break
            p = tier.get(h)
            if p is None:
                break
            pieces.append(p)
            reloaded += 1
        fetched = 0
        want = chain[k + reloaded :]
        if (
            want
            and sp.peer_fetch
            and q.kv_peer is not None
            and k + reloaded + len(want) <= k + len(fresh)
        ):
            timeout = sp.fetch_timeout_s
            if q.deadline_at is not None:
                timeout = min(timeout, q.deadline_at - time.perf_counter())
            if timeout > 0:
                tf0 = time.perf_counter()
                try:
                    from automodel_tpu.serving.fleet.kv_transfer import fetch_kv

                    n, kv = fetch_kv(
                        (str(q.kv_peer["host"]), int(q.kv_peer["port"])),
                        want, self.kv_geometry(), timeout_s=timeout,
                    )
                    if n and kv is not None:
                        pieces.append(kv)
                        fetched = n
                        self.pool.counters["peer_fetch_blocks"] += n
                    self.pool.counters["peer_fetches"] += 1
                    self._child_span(
                        q.trace, "kv_fetch", tf0,
                        request_id=q.rid, blocks=fetched,
                    )
                except Exception as e:
                    # the fallback ladder's last rung: any fetch failure —
                    # refused, timed out, died mid-stream — recomputes
                    # locally within the request's original deadline
                    self.pool.counters["peer_fetch_failures"] += 1
                    logger.warning(
                        "peer KV fetch from %s failed (%s: %s); "
                        "recomputing locally",
                        q.kv_peer, type(e).__name__, e,
                    )
                    self._child_span(
                        q.trace, "kv_fetch", tf0,
                        request_id=q.rid, blocks=0, error=type(e).__name__,
                    )
        total = reloaded + fetched
        if not total:
            return hit_tokens
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        if inj is not None:
            inj.maybe_trace_delay("kv_inject")
        # ONE scatter, padded to a power-of-two block count aimed at the
        # scratch block: reload/fetch run lengths are arbitrary, and an
        # exact-length inject compiles per distinct length (the handoff
        # path's documented compile churn) — bucketing bounds a reload's
        # worst-case TTFT to log2(pool) one-time compiles
        pad = paged.bucket_blocks(total)
        table = list(fresh[:total]) + [0] * (pad - total)
        self._pool = paged.inject_blocks(
            self._pool, np.asarray(table, np.int32),
            paged.pad_kv_blocks(paged.concat_kv_blocks(pieces), pad),
        )
        if reloaded:
            self.pool.counters["spill_reloads"] += 1
            self.pool.counters["spill_reloaded_blocks"] += reloaded
        self._child_span(
            q.trace, "kv_reload", t0, request_id=q.rid,
            blocks=total, reloaded=reloaded, fetched=fetched,
        )
        return hit_tokens + total * bs

    def pop_prefill_payload(self, request_id: str) -> dict:
        """Claim the extracted KV payload of a completed prefill-only
        request (the /prefill handler ships it to the decode replica)."""
        try:
            return self._prefill_payloads.pop(request_id)
        except KeyError:
            raise KeyError(
                f"no prefill payload for {request_id!r} — the request did "
                "not complete as 'prefilled', or the payload was evicted"
            )

    def _stash_prefill_payload(self, rid: str, payload: dict) -> None:
        self._prefill_payloads[rid] = payload
        # bounded: an abandoned payload (router died between /prefill and
        # pickup) must not pin host copies of prompt KV forever
        while len(self._prefill_payloads) > max(
            int(self.config.kv_transfer.max_pending), 1
        ):
            dropped, _ = self._prefill_payloads.popitem(last=False)
            logger.warning("evicting unclaimed prefill payload %s", dropped)

    def submit_prefilled(
        self,
        prompt_ids: Sequence[int],
        first_token: int,
        kv: dict,
        request_id: Optional[str] = None,
        max_new_tokens: Optional[int] = None,
        deadline_s: Optional[float] = None,
        max_queue_wait_s: Optional[float] = None,
        trace: Optional[SpanContext] = None,
    ) -> str:
        """Enqueue a request whose prompt KV was computed on a PREFILL
        replica: admission allocates the normal whole budget, scatters the
        shipped block rows into this pool through the ``paged_write_targets``
        seam, and the slot starts directly in decode with ``first_token``
        (sampled by the prefill replica from the prompt's last logits)
        already committed. ``kv`` is ``{"k": rows, "v": rows}`` with each
        side ``[L, nb, BS, Nkv, H]`` (or ``(int8 values, fp32 scales)``
        pairs for int8 pools), ``nb = ceil(len(prompt)/block_size)``."""
        if self._spec_enabled:
            raise GenerationUnsupported(
                "disaggregated KV handoff into a speculative engine is not "
                "supported: the draft model's parallel pool would miss the "
                "prompt KV and proposals would attend garbage"
            )
        prompt = [int(t) for t in prompt_ids]
        self._validate_kv_payload(prompt, kv)
        payload = {"first_token": int(first_token), "kv": kv}
        return self.submit(
            prompt, request_id=request_id, max_new_tokens=max_new_tokens,
            deadline_s=deadline_s, max_queue_wait_s=max_queue_wait_s,
            trace=trace, _payload=payload,
        )

    def _validate_kv_payload(self, prompt: list[int], kv: dict) -> None:
        geom = self.kv_geometry()
        nb = blocks_needed(len(prompt), self.config.block_size)
        want = (
            geom["layers"], nb, geom["block_size"], geom["num_kv_heads"],
            geom["head_dim"],
        )
        for side in ("k", "v"):
            rows = kv.get(side)
            if rows is None:
                raise ValueError(f"KV payload missing side {side!r}")
            quantized = isinstance(rows, tuple)
            if quantized != self._quantized:
                raise ValueError(
                    f"KV payload side {side!r} is "
                    f"{'int8' if quantized else 'raw'} but this pool is "
                    f"kv_cache_dtype={self.config.kv_cache_dtype}"
                )
            shape = tuple((rows[0] if quantized else rows).shape)
            if shape != want:
                raise ValueError(
                    f"KV payload side {side!r} shape {shape} != expected "
                    f"{want} (layers, ceil(prompt/block_size), block_size, "
                    "num_kv_heads, head_dim)"
                )
            if quantized and tuple(rows[1].shape) != want[:-1]:
                raise ValueError(
                    f"KV payload side {side!r} scales shape "
                    f"{tuple(rows[1].shape)} != expected {want[:-1]}"
                )

    def record_shed(
        self,
        request_id: Optional[str] = None,
        prompt_ids: Optional[Sequence[int]] = None,
        tenant: Optional[str] = None,
        tier: Optional[str] = None,
    ) -> dict:
        """Account an ACTUAL shed — the caller gave up on a ``QueueFull``
        and returned the overload signal to the client. Kept out of
        ``submit`` so a front that absorbs backpressure by retrying (the
        stdin batch mode) doesn't inflate ``requests_shed_total`` with
        retry attempts. ``tenant``/``tier`` label the record (and the
        per-tier /metrics families) with who was shed."""
        self.shed_total += 1
        qos = self.config.qos
        tenant = tenant if tenant is not None else qos.default_tenant
        tier = tier if tier is not None else qos.tier_for(tenant)
        q = _Queued(
            rid=request_id if request_id is not None else f"req-{next(self._ids)}",
            prompt=[int(t) for t in (prompt_ids or [])],
            max_new=0, t_submit=time.perf_counter(),
            tenant=tenant, tier=tier, tier_idx=tier_index(tier),
        )
        return self._rejection_record(q, "shed")

    def record_quota(
        self,
        request_id: Optional[str] = None,
        prompt_ids: Optional[Sequence[int]] = None,
        tenant: Optional[str] = None,
        tier: Optional[str] = None,
    ) -> dict:
        """Account a quota rejection the caller returned to the client —
        the ``record_shed`` seam's twin for ``QuotaExceeded``: ``submit``
        raises without a record so retrying fronts don't inflate the
        count; the front that actually answers the client calls this
        exactly once."""
        self.quota_total += 1
        qos = self.config.qos
        tenant = tenant if tenant is not None else qos.default_tenant
        tier = tier if tier is not None else qos.tier_for(tenant)
        q = _Queued(
            rid=request_id if request_id is not None else f"req-{next(self._ids)}",
            prompt=[int(t) for t in (prompt_ids or [])],
            max_new=0, t_submit=time.perf_counter(),
            tenant=tenant, tier=tier, tier_idx=tier_index(tier),
        )
        return self._rejection_record(q, "quota")

    # -- terminal records -----------------------------------------------------
    def _wall_ts(self) -> float:
        """Record timestamp: the process wall anchor + the monotonic clock.
        Never raw ``time.time()`` — a wall step mid-request would otherwise
        put a ``ts`` beside monotonic-difference durations it contradicts
        (the mixed-clock bug report --strict now lints for)."""
        return round(self._clock.wall(), 6)

    def _child_span(
        self,
        root: Optional[SpanContext],
        stage: str,
        t0: float,
        t1: Optional[float] = None,
        **attrs: Any,
    ) -> None:
        if self.tracer is not None and self.tracer.active(root):
            self.tracer.child(root, stage, t0, t1, **attrs)

    def _root_span(
        self,
        root: Optional[SpanContext],
        t0: float,
        t1: Optional[float] = None,
        **attrs: Any,
    ) -> None:
        if self.tracer is not None and self.tracer.active(root):
            self.tracer.record(root, "serve", t0, t1, **attrs)

    def _rejection_record(
        self, q: _Queued, reason: str, detail: Optional[str] = None
    ) -> dict:
        """Terminal record for a request that never reached a slot (shed /
        draining / queue timeout / admission failure)."""
        now = time.perf_counter()
        self.failed_total += 1
        if reason == "timeout":
            self.timeout_total += 1
        rec = {
            "event": "serve_request",
            "request_id": q.rid,
            "tokens": [],
            "n_generated": 0,
            "prompt_tokens": len(q.prompt),
            "completion_reason": reason,
            "retriable": reason in _RETRIABLE_REASONS,
            "tenant": q.tenant,
            "tier": q.tier,
            "queue_s": now - q.t_submit,
            "queue_depth": self.queue_depth,
            "ts": self._wall_ts(),
        }
        if detail:
            rec["detail"] = detail
        # drain/timeout/shed paths leave spans too: the whole life of this
        # request was the queue, and the root says why it ended
        self._child_span(
            q.trace, "queue", q.t_submit, now, request_id=q.rid
        )
        self._root_span(
            q.trace, q.t_submit, now,
            request_id=q.rid, completion_reason=reason,
        )
        self._emit(rec)
        return rec

    def _terminate(
        self, b: int, reason: str, detail: Optional[str] = None
    ) -> dict:
        """Free slot ``b`` and produce its one terminal record. ``reason``
        "stop"/"length" is a completion; anything else is a failure whose
        blocks must still come back (the leak-audit contract)."""
        slot = self._slots[b]
        now = time.perf_counter()
        gen = slot.generated or []
        self.pool.free(slot.blocks)
        self._slots[b] = None
        self._tables[b] = 0
        self._lengths[b] = 0
        self._active[b] = False
        self._cur[b] = self.gen_config.pad_token_id
        completed = reason in _COMPLETED_REASONS
        if completed:
            self.completed_total += 1
        else:
            self.failed_total += 1
            if reason == "timeout":
                self.timeout_total += 1
        rec = {
            "event": "serve_request",
            "request_id": slot.request_id,
            "tokens": list(gen),
            "n_generated": len(gen),
            "prompt_tokens": len(slot.prompt),
            "prefix_hit_tokens": slot.hit_tokens,
            "completion_reason": reason,
            "retriable": reason in _RETRIABLE_REASONS,
            "tenant": slot.tenant,
            "tier": slot.tier,
            "queue_s": slot.t_admit - slot.t_submit,
            "queue_depth": self.queue_depth,
            "block_occupancy": round(self.pool.occupancy(), 4),
            "ts": self._wall_ts(),
        }
        if slot.t_first is not None:
            decode_s = now - slot.t_first
            rec["ttft_s"] = slot.t_first - slot.t_submit
            # the first token is charged to ttft, like the single-wave engine
            rec["decode_tps"] = (
                (len(gen) - 1) / decode_s if decode_s > 0 and len(gen) > 1
                else 0.0
            )
        if slot.logprobs is not None:
            rec["logprobs"] = [round(lp, 6) for lp in slot.logprobs]
        if self._spec_enabled and slot.spec_proposed:
            rec["spec_proposed"] = slot.spec_proposed
            rec["spec_accepted"] = slot.spec_accepted
            rec["spec_accept_rate"] = round(
                slot.spec_accepted / slot.spec_proposed, 4
            )
        if detail:
            rec["detail"] = detail
        # tracing: the decode stage is the window from first token to
        # terminal (one span per request, attrs carry the volume); the root
        # span covers submit→terminal and names how it ended — including
        # the cancel/stall/drain paths, which land here like completions
        if slot.decoding and slot.t_first is not None:
            self._child_span(
                slot.trace, "decode", slot.t_first, now,
                request_id=slot.request_id, tokens=max(len(gen) - 1, 0),
            )
        self._root_span(
            slot.trace, slot.t_submit, now,
            request_id=slot.request_id, completion_reason=reason,
            n_generated=len(gen), prompt_tokens=len(slot.prompt),
        )
        self._emit(rec)
        return rec

    def _emit(self, rec: dict) -> None:
        try:
            if rec.get("completion_reason") in _COMPLETED_REASONS:
                self.metrics.observe_request(rec)
            else:
                self.metrics.observe_failure(rec.get("completion_reason", ""))
            self.metrics.observe_qos(rec)
            self._note_qos(rec)
        except Exception:  # telemetry must never break serving
            pass
        if self.on_record is not None:
            try:
                self.on_record(dict(rec))
            except Exception:  # telemetry must never break serving
                pass

    def _note_qos(self, rec: dict) -> None:
        """Fold one terminal record into the per-tier / per-tenant /stats
        rollups (the labeled /metrics families are observed beside this in
        ``ServingMetrics.observe_qos``)."""
        tier = rec.get("tier")
        tenant = rec.get("tenant")
        reason = rec.get("completion_reason")
        if tier is None or tenant is None or reason is None:
            return
        tc = self.tier_counters.get(tier)
        if tc is not None:
            if reason in _COMPLETED_REASONS:
                tc["completed"] += 1
            elif reason in tc:
                tc[reason] += 1
        nc = self.tenant_counters.setdefault(
            tenant,
            {"requests": 0, "completed": 0, "shed": 0, "quota": 0,
             "timeout": 0},
        )
        nc["requests"] += 1
        if reason in _COMPLETED_REASONS:
            nc["completed"] += 1
        elif reason in nc:
            nc[reason] += 1

    def qos_snapshot(self) -> dict:
        """The /stats ``qos`` block: live queue composition by tier and
        tenant plus the cumulative terminal rollups — the numbers
        fleet-status's TIER/TENANT summary and the noisy-neighbor tests
        read."""
        queued_by_tier: dict[str, int] = {t: 0 for t in TIERS}
        queued_by_tenant: dict[str, int] = {}
        for q in self._queue:
            queued_by_tier[q.tier] = queued_by_tier.get(q.tier, 0) + 1
            queued_by_tenant[q.tenant] = queued_by_tenant.get(q.tenant, 0) + 1
        return {
            "enabled": self.config.qos.enabled,
            "queued_by_tier": queued_by_tier,
            "queued_by_tenant": queued_by_tenant,
            "tiers": {t: dict(c) for t, c in self.tier_counters.items()},
            "tenants": {n: dict(c) for n, c in self.tenant_counters.items()},
        }

    def check_invariants(self) -> None:
        """Allocator + scheduler audit for the chaos suite: the pool's own
        invariants, queue entries unique by request id, and every queued
        entry carrying a valid tier. Raises on violation."""
        self.pool.check_invariants()
        rids = [q.rid for q in self._queue]
        if len(rids) != len(set(rids)):
            raise AssertionError(f"duplicate queued request ids: {rids}")
        for q in self._queue:
            tier_index(q.tier)
        for served in self._wfq_served.values():
            if served < 0:
                raise AssertionError(
                    f"negative WFQ service accumulator: {self._wfq_served}"
                )

    # -- scheduler ------------------------------------------------------------
    def _expire_tick(self) -> list[dict]:
        """Cancel every request whose deadline/queue-wait elapsed — queued,
        prefilling, or decoding — freeing its blocks."""
        now = time.perf_counter()
        done: list[dict] = []
        if self._queue and any(
            q.deadline_at is not None or q.queue_deadline_at is not None
            for q in self._queue
        ):
            keep: deque[_Queued] = deque()
            for q in self._queue:
                expired = (
                    (q.deadline_at is not None and now >= q.deadline_at)
                    or (
                        q.queue_deadline_at is not None
                        and now >= q.queue_deadline_at
                    )
                )
                if expired:
                    done.append(self._rejection_record(q, "timeout"))
                else:
                    keep.append(q)
            self._queue = keep
        for b, slot in enumerate(self._slots):
            if (
                slot is not None
                and slot.deadline_at is not None
                and now >= slot.deadline_at
            ):
                done.append(self._terminate(b, "timeout"))
        return done

    def _select_queued(self, now: float) -> int:
        """Index of the next queued request to admit. FIFO (index 0) when
        QoS is off — bit-identical to the engine before serving.qos
        existed. With QoS on the order is: effective tier (aging promotion
        counts) → weighted-fair service across tenants within the tier
        (least normalized service first) → EDF (earliest deadline) → FIFO.
        One O(queue) scan per free slot — max_queue bounds it."""
        if not self.config.qos.enabled or len(self._queue) <= 1:
            return 0
        best_i = 0
        best_key = None
        for i, q in enumerate(self._queue):
            key = (
                self._effective_tier(q, now),
                self._wfq_served.get((q.tier, q.tenant), 0.0)
                / self.config.qos.tenant(q.tenant).weight,
                q.deadline_at if q.deadline_at is not None else float("inf"),
                q.t_submit,
                i,
            )
            if best_key is None or key < best_key:
                best_key, best_i = key, i
        return best_i

    def _admit(self, done: list[dict]) -> None:
        for b in range(self.config.slots):
            if self._slots[b] is not None or not self._queue:
                continue
            idx = self._select_queued(time.perf_counter())
            q = self._queue[idx]
            t_adm0 = time.perf_counter()  # tracing: admission stage start
            if q.payload is not None:
                # KV handoff: the prompt's rows arrive pre-computed, so the
                # prefix cache is bypassed (shipped blocks are scattered
                # whole; the injected prefix registers below for FUTURE
                # requests to hit)
                hits, hit_tokens = [], 0
            else:
                hits, hit_tokens = self.pool.match_prefix(q.prompt)
            need = blocks_needed(
                len(q.prompt) if q.prefill_only else len(q.prompt) + q.max_new,
                self.config.block_size,
                0 if q.prefill_only else self.config.spec_overhang,
            )
            fresh = self.pool.allocate(need - len(hits))
            if fresh is None:
                # pool can't cover the selected head of the queue: undo the
                # hit refs and stop admitting this step (no overtaking past
                # the scheduling order's head — with QoS off that is plain
                # FIFO ttft fairness; with QoS on the head is the
                # tier/WFQ/EDF winner and overtaking it would invert the
                # priority order under exactly the pressure it exists for)
                if hits:
                    self.pool.free(hits)
                break
            del self._queue[idx]
            # WFQ accounting: charge the admitted request's whole token
            # budget to its (tier, tenant) lane — what "service" means here
            self._wfq_served[(q.tier, q.tenant)] = self._wfq_served.get(
                (q.tier, q.tenant), 0.0
            ) + float(len(q.prompt) + (0 if q.prefill_only else q.max_new))
            blocks = hits + fresh
            try:
                if q.payload is not None:
                    self._bind_injected_slot(b, q, blocks, done)
                else:
                    hit_tokens = self._resolve_hierarchy(
                        q, hits, hit_tokens, fresh
                    )
                    # token-weighted prefix accounting, stamped once per
                    # admission AFTER the hierarchy resolved: hit = matchable
                    # prompt tokens served from ANY tier, miss = matchable
                    # tokens about to recompute
                    bs = self.config.block_size
                    matchable = max(len(q.prompt) - 1, 0) // bs * bs
                    self.pool.note_prefix_tokens(
                        hit_tokens, max(matchable - hit_tokens, 0)
                    )
                    self._bind_slot(b, q, blocks, hit_tokens)
                # queue wait and admission (prefix match + whole-budget
                # block allocation + slot bind) as sibling stages under the
                # request root — the two ways a slow admission can hide
                self._child_span(
                    q.trace, "queue", q.t_submit, t_adm0, request_id=q.rid
                )
                self._child_span(
                    q.trace, "admission", t_adm0,
                    request_id=q.rid, blocks=len(blocks),
                    hit_tokens=hit_tokens if q.payload is None else 0,
                )
            except Exception as e:
                # leak audit: an exception between admit-time allocation and
                # slot binding must return EVERY block and fail only THIS
                # request — loudly — not the server
                self.pool.free(blocks)
                self.error_total += 1
                logger.exception("admission failed for %s", q.rid)
                done.append(
                    self._rejection_record(
                        q, "engine_error",
                        detail=f"admission: {type(e).__name__}: {e}",
                    )
                )

    def _bind_slot(
        self, b: int, q: _Queued, blocks: list[int], hit_tokens: int
    ) -> None:
        row = np.zeros((self.config.table_blocks,), np.int32)
        row[: len(blocks)] = blocks
        self._tables[b] = row
        self._lengths[b] = hit_tokens
        self._active[b] = False
        self._slots[b] = _Slot(
            request_id=q.rid, prompt=q.prompt, max_new=q.max_new,
            blocks=blocks, hit_tokens=hit_tokens,
            prefill_pos=hit_tokens, t_submit=q.t_submit,
            t_admit=time.perf_counter(), deadline_at=q.deadline_at,
            prefill_only=q.prefill_only, trace=q.trace,
            logprobs=[] if q.return_logprobs else None,
            tenant=q.tenant, tier=q.tier,
        )

    def _bind_injected_slot(
        self, b: int, q: _Queued, blocks: list[int], done: list[dict]
    ) -> None:
        """Admission for a KV-handoff request (``submit_prefilled``): the
        shipped prompt rows scatter into the allocated blocks and the slot
        starts directly in decode with the prefill replica's first token
        already committed — this replica never touches the prompt math."""
        p = len(q.prompt)
        nb = blocks_needed(p, self.config.block_size)
        row = np.zeros((self.config.table_blocks,), np.int32)
        row[: len(blocks)] = blocks
        t_inj0 = time.perf_counter()
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        if inj is not None:
            inj.maybe_trace_delay("kv_inject")
        self._pool = paged.inject_blocks(
            self._pool, np.asarray(blocks[:nb], np.int32), q.payload["kv"]
        )
        self._child_span(
            q.trace, "kv_inject", t_inj0,
            request_id=q.rid, blocks=nb, prompt_tokens=p,
        )
        first = int(q.payload["first_token"])
        now = time.perf_counter()
        self._tables[b] = row
        self._lengths[b] = p
        self._cur[b] = first
        self._active[b] = True
        self._slots[b] = _Slot(
            request_id=q.rid, prompt=q.prompt, max_new=q.max_new,
            blocks=blocks, hit_tokens=0, prefill_pos=p,
            t_submit=q.t_submit, t_admit=now, deadline_at=q.deadline_at,
            decoding=True, generated=[first], t_first=now, trace=q.trace,
            tenant=q.tenant, tier=q.tier,
        )
        # the injected prefix is as matchable as a locally-computed one —
        # future affinity-routed requests hit it without another transfer
        self.pool.register_prefix(q.prompt, blocks)
        self.kv_injected_total += 1
        if first in self._eos:
            done.append(self._terminate(b, "stop"))
        elif q.max_new <= 1:
            done.append(self._terminate(b, "length"))

    def _prefill_tick(self) -> list[dict]:
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        done: list[dict] = []
        chunk_len = self.config.prefill_chunk
        pad = self.gen_config.pad_token_id
        for b, slot in enumerate(self._slots):
            if slot is None or slot.decoding:
                continue
            p = len(slot.prompt)
            start = slot.prefill_pos
            real = min(chunk_len, p - start)
            ids = np.full((chunk_len,), pad, np.int32)
            ids[:real] = slot.prompt[start : start + real]
            t_chunk0 = time.perf_counter()
            if inj is not None:
                inj.maybe_trace_delay("prefill")
                inj.maybe_slo_breach("prefill", self._step_counter)
            if self.collect_program_costs and "chunk_prefill" not in self.program_costs:
                self._record_cost(
                    "chunk_prefill", self._chunk,
                    self.auto.params, self._pool,
                    jnp.asarray(self._tables[b]), jnp.asarray(ids),
                    jnp.int32(start), jnp.int32(real),
                )
            last, self._pool = self._chunk(
                self.auto.params, self._pool,
                jnp.asarray(self._tables[b]), jnp.asarray(ids),
                jnp.int32(start), jnp.int32(real),
            )
            if self._spec_enabled:
                # the draft model prefills the same chunk into its parallel
                # pool (same tables/offsets) so its proposals see the whole
                # prompt; its last-token logits are unused — the first
                # sampled token always comes from the TARGET
                _, self._draft_pool = self._draft_chunk(
                    self.draft_auto.params, self._draft_pool,
                    jnp.asarray(self._tables[b]), jnp.asarray(ids),
                    jnp.int32(start), jnp.int32(real),
                )
            # one span per chunk: a single long prompt's prefill shows as a
            # chunk train, and a stall inside one chunk names its offset
            self._child_span(
                slot.trace, "prefill", t_chunk0,
                request_id=slot.request_id, pos=start, tokens=real,
            )
            slot.prefill_pos = start + real
            self._lengths[b] = slot.prefill_pos
            if slot.prefill_pos < p:
                continue
            # prompt fully in: sample the first token (charged to ttft),
            # publish the prompt blocks to the prefix cache, flip to decode
            first = int(
                sample(
                    last[None, :],
                    jax.random.fold_in(self._base_key, self._step_counter),
                    self.gen_config.sampling,
                )[0]
            )
            if slot.logprobs is not None:
                # same raw-logits rule as the decode program (the chunk
                # already handed `last` to the host, so this is free)
                slot.logprobs.append(
                    float(jax.nn.log_softmax(last.astype(jnp.float32))[first])
                )
            self.pool.register_prefix(slot.prompt, slot.blocks)
            slot.t_first = time.perf_counter()
            slot.generated = [first]
            if slot.prefill_only:
                # disaggregated fleet: the prompt's block rows leave for a
                # decode replica — extract BEFORE _terminate decrefs the
                # blocks (contents survive until reuse, but extraction from
                # owned blocks is the contract the transfer relies on)
                k, v = paged.extract_blocks(self._pool, slot.blocks)
                self._stash_prefill_payload(slot.request_id, {
                    "first_token": first,
                    "prompt_len": p,
                    "kv": {"k": k, "v": v},
                    # host-side only: the /prefill handler parents its
                    # kv_send span under this request's root
                    "trace": slot.trace,
                })
                done.append(self._terminate(b, "prefilled"))
                continue
            slot.decoding = True
            self._cur[b] = first
            self._active[b] = True
            self._lengths[b] = p
            if first in self._eos:
                done.append(self._terminate(b, "stop"))
            elif slot.max_new <= 1:
                done.append(self._terminate(b, "length"))
        return done

    def _decode_tick(self) -> list[dict]:
        if not self._active.any():
            return []
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        if inj is not None:
            # lands inside every traced request's decode window (t_first →
            # terminal), so the delay attributes to the decode stage
            inj.maybe_trace_delay("decode")
            inj.maybe_slo_breach("decode", self._step_counter)
        if self._spec_enabled:
            return self._spec_decode_tick()
        params = self.auto.params
        if self.collect_program_costs and "paged_decode" not in self.program_costs:
            self._record_cost(
                "paged_decode", self._decode,
                params, self._pool,
                jnp.asarray(self._tables), jnp.asarray(self._lengths),
                jnp.asarray(self._cur), jnp.asarray(self._active),
                self._base_key, jnp.int32(self._step_counter),
            )
        tokens, logps, self._pool = self._decode(
            params, self._pool,
            jnp.asarray(self._tables), jnp.asarray(self._lengths),
            jnp.asarray(self._cur), jnp.asarray(self._active),
            self._base_key, jnp.int32(self._step_counter),
        )
        tokens = np.asarray(jax.device_get(tokens))
        logps = np.asarray(jax.device_get(logps))
        self.first_decode_done = True
        done: list[dict] = []
        for b, slot in enumerate(self._slots):
            if slot is None or not self._active[b]:
                continue
            tok = int(tokens[b])
            slot.generated.append(tok)
            if slot.logprobs is not None:
                slot.logprobs.append(float(logps[b]))
            self._lengths[b] += 1
            self._cur[b] = tok
            if tok in self._eos:
                done.append(self._terminate(b, "stop"))
            elif len(slot.generated) >= slot.max_new:
                done.append(self._terminate(b, "length"))
        return done

    def _spec_decode_tick(self) -> list[dict]:
        """One speculative round for the whole decode wave: the draft
        proposes ``spec_k`` tokens per slot (its own pool, shared tables),
        ONE batched verify forward through the target commits the accepted
        prefix + a correction/bonus token. Rollback of rejected drafts is
        pure bookkeeping — the host simply advances ``lengths`` by the
        committed count, leaving rejected K/V rows past the length where
        no future attend can see them and the next round overwrites."""
        k = self.config.speculative.k
        tables = jnp.asarray(self._tables)
        lengths = jnp.asarray(self._lengths)
        cur = jnp.asarray(self._cur)
        active = jnp.asarray(self._active)
        step = jnp.int32(self._step_counter)
        t_propose0 = time.perf_counter()
        drafts, draft_logits, self._draft_pool = self._propose(
            self.draft_auto.params, self._draft_pool,
            tables, lengths, cur, active, self._base_key, step,
        )
        t_verify0 = time.perf_counter()
        if self.collect_program_costs and "spec_verify" not in self.program_costs:
            self._record_cost(
                "spec_verify", self._verify,
                self.auto.params, self._pool, tables, lengths, cur,
                drafts, draft_logits, active, self._base_key, step,
            )
        tokens, n_commit, self._pool = self._verify(
            self.auto.params, self._pool, tables, lengths, cur,
            drafts, draft_logits, active, self._base_key, step,
        )
        tokens = np.asarray(jax.device_get(tokens))
        n_commit = np.asarray(jax.device_get(n_commit))
        t_wave_end = time.perf_counter()
        self.first_decode_done = True
        self.spec_rounds += 1  # one propose+verify round per WAVE, not per slot
        done: list[dict] = []
        for b, slot in enumerate(self._slots):
            if slot is None or not self._active[b]:
                continue
            # per-wave propose/verify spans on every traced slot the wave
            # served: the whole wave's wall time IS where this request's
            # time went (the calls are batched over the wave)
            self._child_span(
                slot.trace, "spec_propose", t_propose0, t_verify0,
                request_id=slot.request_id, k=k,
            )
            self._child_span(
                slot.trace, "spec_verify", t_verify0, t_wave_end,
                request_id=slot.request_id,
                accepted=int(n_commit[b]) - 1,
            )
            n = int(n_commit[b])
            accepted = n - 1
            slot.spec_proposed += k
            slot.spec_accepted += accepted
            self.spec_proposed_total += k
            self.spec_accepted_total += accepted
            reason = None
            used = 0
            for tok in (int(t) for t in tokens[b, :n]):
                slot.generated.append(tok)
                used += 1
                if tok in self._eos:
                    reason = "stop"
                    break
                if len(slot.generated) >= slot.max_new:
                    reason = "length"
                    break
            # committed length only ever moves FORWARD by what was kept:
            # the rejected tail needs no cache surgery (paged.py rollback
            # contract); a truncated commit only happens when terminating
            self._lengths[b] += used
            self._cur[b] = slot.generated[-1]
            if reason is not None:
                done.append(self._terminate(b, reason))
        return done

    def _rebuild(self, reason: str, detail: Optional[str] = None) -> list[dict]:
        """Recover from a stalled or failed program: fail the affected
        wave's requests, re-initialize the pool arrays (the donated buffers
        of a failed call are gone or garbage), clear the prefix cache
        (contents no longer trusted), audit the allocator, and keep the
        queue. Queued requests have no device state and ride through."""
        done: list[dict] = []
        affected = 0
        for b, slot in enumerate(self._slots):
            if slot is not None:
                done.append(self._terminate(b, reason, detail=detail))
                affected += 1
        self.pool.clear_prefix_cache()
        self.pool.check_invariants()
        self._init_pool_arrays()
        self._tables[:] = 0
        self._lengths[:] = 0
        self._active[:] = False
        self._cur[:] = self.gen_config.pad_token_id
        if reason == "engine_stall":
            self.stall_total += 1
        else:
            self.error_total += 1
        try:
            self.metrics.observe_engine_event(reason)
        except Exception:
            pass
        logger.error(
            "serving engine %s at step %d: failed %d in-flight request(s), "
            "pool rebuilt, queue (%d) kept — %s",
            reason, self._step_counter, affected, self.queue_depth,
            detail or "",
        )
        rec = {
            "event": "serve_engine_event",
            "reason": reason,
            "step": self._step_counter,
            "requests_failed": affected,
            "ts": self._wall_ts(),
        }
        if detail:
            rec["detail"] = detail
        if self.on_record is not None:
            try:
                self.on_record(rec)
            except Exception:
                pass
        return done

    def _injection_tick(self, inj: Any) -> None:
        """Serving fault hooks (resilience/fault_injection.py): allocator
        exhaustion, a slow/hung step, a mid-request engine exception, a
        noisy-neighbor tenant flood. Each is a cheap None-check when
        unarmed."""
        c = inj.config
        step = self._step_counter
        flood = inj.maybe_tenant_flood(step)
        if flood is not None:
            # noisy neighbor: one tenant slams the admission path with a
            # burst of real submissions — quotas, tiering, and shedding are
            # expected to contain it (tests/test_qos.py proves isolation).
            # Rejections are accounted through the same seams a front uses.
            tenant, n, tier = flood
            for i in range(n):
                rid = f"flood-{tenant}-{step}-{i}"
                try:
                    self.submit(
                        [1, 2, 3], request_id=rid, max_new_tokens=4,
                        tenant=tenant, tier=tier,
                    )
                except QuotaExceeded as e:
                    self.record_quota(
                        request_id=rid, tenant=e.tenant, tier=e.tier
                    )
                except QueueFull:
                    self.record_shed(request_id=rid, tenant=tenant, tier=tier)
                except EngineDraining:
                    break
        if self._exhaust_hold is not None and step >= self._exhaust_hold[1]:
            self.pool.free(self._exhaust_hold[0])
            self._exhaust_hold = None
            logger.error("fault injection: released the exhausted pool")
        if (
            c.serve_exhaust_blocks_at_step is not None
            and step == c.serve_exhaust_blocks_at_step
            and self._exhaust_hold is None
        ):
            grabbed = self.pool.allocate(self.pool.available()) or []
            self._exhaust_hold = (
                grabbed, step + max(int(c.serve_exhaust_hold_steps), 1)
            )
            logger.error(
                "fault injection: exhausted the block pool (%d blocks) "
                "until step %d", len(grabbed), self._exhaust_hold[1],
            )
        inj.maybe_serve_hang(step)
        inj.maybe_serve_exception(step)

    def step(self) -> list[dict]:
        """One scheduler iteration → the requests that reached a terminal
        state in it (every record carries a ``completion_reason``)."""
        if self._watchdog is not None:
            self._watchdog.pet(self._step_counter)
            if not self.first_decode_done:
                # the training watchdog's second-pet rule ends the compile
                # grace too early here: serving compiles TWO programs at
                # different steps (chunk prefill on the first prefill tick,
                # paged decode a few steps later) — hold the grace until
                # the decode program has actually run once
                self._watchdog.set_phase("compile")
        done: list[dict] = []
        try:
            from automodel_tpu.resilience.fault_injection import active_injector

            inj = active_injector()
            if inj is not None:
                self._injection_tick(inj)
            done += self._expire_tick()
            if self.draining:
                while self._queue:
                    done.append(
                        self._rejection_record(self._queue.popleft(), "draining")
                    )
                if (
                    self._drain_deadline is not None
                    and time.perf_counter() >= self._drain_deadline
                ):
                    for b, slot in enumerate(self._slots):
                        if slot is not None:
                            done.append(
                                self._terminate(
                                    b, "cancelled",
                                    detail="drain grace "
                                    f"{self.config.drain.grace_s}s expired",
                                )
                            )
            else:
                if self._pending_swap is None:
                    # a staged weight swap holds admissions (the queue keeps
                    # absorbing) so no request starts under weights that are
                    # about to be replaced mid-generation
                    self._admit(done)
            done += self._prefill_tick()
            done += self._decode_tick()
            rebuilt = False
        except Exception as e:
            rebuilt = True
            self._consecutive_rebuilds += 1
            if self._consecutive_rebuilds > self.MAX_CONSECUTIVE_REBUILDS:
                raise  # systemic — the serving front reports scheduler death
            done += self._rebuild(
                "engine_error", detail=f"{type(e).__name__}: {e}"
            )
        ev, self._stall_evidence = self._stall_evidence, None
        if ev is not None:
            # the wedged call returned after the watchdog fired: its wave is
            # suspect — fail it, rebuild, keep serving. Stall rebuilds draw
            # on the SAME consecutive budget as exception rebuilds: a step
            # that stalls every single time is just as systemic as one that
            # raises every time, and must not rebuild-loop forever.
            rebuilt = True
            self._consecutive_rebuilds += 1
            if self._consecutive_rebuilds > self.MAX_CONSECUTIVE_REBUILDS:
                raise RuntimeError(
                    f"serving engine stalled {self._consecutive_rebuilds} "
                    "consecutive scheduler iterations — systemic fault, "
                    "refusing to rebuild-loop"
                )
            done += self._rebuild(
                "engine_stall",
                detail=(
                    f"no step-boundary heartbeat for {ev.get('heartbeat_age_s')}s "
                    f"(deadline {ev.get('deadline_s')}s)"
                ),
            )
        if not rebuilt:
            self._consecutive_rebuilds = 0
        if self._pending_swap is not None and self.busy_slots == 0:
            # the step that terminated the last in-flight request is the
            # swap boundary: everything before this line ran (and finished)
            # under the old weights, everything admitted after runs under
            # the new — in-flight outputs are bit-untouched by the swap
            self._apply_pending_swap()
        self._step_counter += 1
        self.last_step_t = time.monotonic()
        if self.draining:
            self.drain_complete()  # stamps drain_duration_s when reached
        return done

    def run(self, max_iterations: Optional[int] = None) -> list[dict]:
        """Drain the queue and every running slot. ``max_iterations`` guards
        against scheduler bugs (default: a generous analytic bound)."""
        if max_iterations is None:
            n_req = len(self._queue) + self.busy_slots
            per_req = (
                -(-self.config.max_seq_len // self.config.prefill_chunk)
                + self.config.max_seq_len
            )
            max_iterations = 64 + (n_req + 1) * (per_req + 2)
        out: list[dict] = []
        for _ in range(max_iterations):
            if self.idle():
                return out
            out.extend(self.step())
        raise RuntimeError(
            f"serving engine failed to drain within {max_iterations} "
            f"iterations (queue={self.queue_depth}, busy={self.busy_slots})"
        )

    def _record_cost(self, name: str, jit_fn, *args) -> None:
        from automodel_tpu.telemetry.profiling import record_program_cost

        record_program_cost(self.program_costs, name, jit_fn, *args)

    # -- workload driver (bench leg + sustained-throughput tests) -------------
    def run_workload(
        self, arrivals: Sequence[tuple[float, Sequence[int], Optional[int]]]
    ) -> tuple[list[dict], dict]:
        """Drive a timed workload: ``arrivals`` is [(offset_s, prompt_ids,
        max_new_tokens|None)] sorted by offset. Requests are submitted when
        their offset elapses (wall clock); the engine steps continuously in
        between. → (completions, aggregate stats: sustained tokens/s, ttft
        p50/p99, peak occupancy/queue depth)."""
        arrivals = sorted(arrivals, key=lambda a: a[0])
        t0 = time.perf_counter()
        spec_proposed0 = self.spec_proposed_total
        spec_accepted0 = self.spec_accepted_total
        pending = deque(arrivals)
        out: list[dict] = []
        occ_peak, q_peak = 0.0, 0
        while pending or not self.idle():
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                _, prompt, max_new = pending.popleft()
                self.submit(prompt, max_new_tokens=max_new)
            if self.idle():
                if pending:
                    time.sleep(min(0.001, max(pending[0][0] - now, 0.0)))
                continue
            out.extend(self.step())
            occ_peak = max(occ_peak, self.pool.occupancy())
            q_peak = max(q_peak, self.queue_depth)
        dt = time.perf_counter() - t0
        completions = [
            r for r in out if r.get("completion_reason") in ("stop", "length")
        ]
        gen = sum(r["n_generated"] for r in completions)
        from automodel_tpu.telemetry.report import percentile

        ttfts = [
            r["ttft_s"] for r in completions if isinstance(r.get("ttft_s"), float)
        ]
        stats = {
            "requests": len(completions),
            "gen_tokens": gen,
            "wall_s": dt,
            "sustained_tokens_per_s": gen / dt if dt > 0 else 0.0,
            "ttft_p50_s": percentile(ttfts, 0.50),
            "ttft_p99_s": percentile(ttfts, 0.99),
            "block_occupancy_peak": round(occ_peak, 4),
            "queue_depth_peak": q_peak,
            "prefix_cache": dict(self.pool.counters),
        }
        if self._spec_enabled:
            proposed = self.spec_proposed_total - spec_proposed0
            accepted = self.spec_accepted_total - spec_accepted0
            stats["spec_proposed"] = proposed
            stats["spec_accepted"] = accepted
            stats["accept_rate"] = (
                round(accepted / proposed, 4) if proposed else None
            )
            stats["draft_tps"] = proposed / dt if dt > 0 else 0.0
        if len(completions) != len(out):
            stats["failed_requests"] = len(out) - len(completions)
        return out, stats
