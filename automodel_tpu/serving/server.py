"""`automodel_tpu serve` — a thin front on the continuous-batching engine.

Two modes, one engine:

- **stdin-JSONL** (default): one request object per line —
  ``{"prompt": "..."} | {"prompt_ids": [...]}`` plus optional ``id`` /
  ``max_new_tokens`` — all submitted into the admission queue, completions
  printed as JSON lines AS THEY FINISH (continuous batching means short
  requests return before long ones that arrived earlier).
- **local HTTP** (``serving.http.port``): POST /generate with the same
  request object blocks until that request completes; GET /stats returns
  queue depth / occupancy / allocator counters. A background thread runs
  the scheduler loop; handlers only enqueue and wait — stdlib
  ThreadingHTTPServer, no extra dependencies, explicitly a LOCAL/dev front
  (docs/serving.md covers what a production front needs on top).

Per-request telemetry (``ttft_s``, ``decode_tps``, ``queue_s``,
``queue_depth``, ``block_occupancy``) rides the PR 2 metrics JSONL via
``logging.metrics_path`` and is accepted by ``automodel_tpu report
--strict``.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)


def _encode_prompt(req: dict, tokenizer: Any) -> list[int]:
    if req.get("prompt_ids") is not None:
        return [int(t) for t in req["prompt_ids"]]
    prompt = req.get("prompt")
    if prompt is None:
        raise ValueError("request needs 'prompt' or 'prompt_ids'")
    if tokenizer is None:
        # token-id mode (tiny from-config models): same convention as the
        # generate CLI — whitespace/comma-separated ids
        toks = str(prompt).replace(",", " ").split()
        try:
            return [int(t) for t in toks]
        except ValueError:
            raise ValueError(
                "no tokenizer available: 'prompt' must be token ids "
                "(e.g. \"1 2 3\") or configure generation.tokenizer"
            )
    if callable(tokenizer):
        return tokenizer(str(prompt), add_special_tokens=True)["input_ids"]
    return tokenizer.encode(str(prompt))


def _decode_completion(tokens: list[int], tokenizer: Any) -> str:
    if tokenizer is None:
        return " ".join(map(str, tokens))
    return tokenizer.decode(tokens, skip_special_tokens=True)


class _EngineLoop:
    """Background scheduler thread for the HTTP mode: handlers submit under
    the lock and wait on a per-request event; the loop steps the engine
    whenever there is work."""

    def __init__(self, engine: Any):
        self.engine = engine
        self.lock = threading.Lock()
        self._events: dict[str, threading.Event] = {}
        self._results: dict[str, dict] = {}
        self._abandoned: set[str] = set()  # timed-out waiters: drop on finish
        self.error: Optional[str] = None  # scheduler-thread death, terminal
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def submit_blocking(
        self, prompt_ids: list[int], max_new_tokens: Optional[int],
        timeout_s: float,
    ) -> dict:
        ev = threading.Event()
        with self.lock:
            if self.error is not None:
                raise RuntimeError(f"serving engine is down: {self.error}")
            rid = self.engine.submit(prompt_ids, max_new_tokens=max_new_tokens)
            self._events[rid] = ev
        if not ev.wait(timeout=timeout_s):
            with self.lock:
                self._events.pop(rid, None)
                # the request can't be cancelled mid-flight: remember the
                # abandonment so its eventual completion is discarded
                # instead of accumulating in _results forever
                self._abandoned.add(rid)
            raise TimeoutError(f"request {rid} timed out after {timeout_s}s")
        with self.lock:
            if self.error is not None and rid not in self._results:
                raise RuntimeError(f"serving engine died: {self.error}")
            return self._results.pop(rid)

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self.lock:
                try:
                    idle = self.engine.idle()
                    done = [] if idle else self.engine.step()
                except Exception as e:  # scheduler death is TERMINAL, not silent
                    self.error = f"{type(e).__name__}: {e}"
                    logger.exception("serving scheduler thread died")
                    # wake every waiter so handlers return 503 immediately
                    # instead of blocking to their timeout
                    for ev in self._events.values():
                        ev.set()
                    self._events.clear()
                    return
                for rec in done:
                    rid = rec["request_id"]
                    ev = self._events.pop(rid, None)
                    if rid in self._abandoned:
                        self._abandoned.discard(rid)  # waiter gave up: drop
                        continue
                    self._results[rid] = rec
                    if ev is not None:
                        ev.set()
            if idle:
                time.sleep(0.005)


def serve_http(engine: Any, tokenizer: Any, port: int, host: str = "127.0.0.1"):
    """→ (ThreadingHTTPServer, _EngineLoop), both started. The caller calls
    ``server.serve_forever()`` (CLI) or drives requests itself (tests) and
    shuts both down."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    loop = _EngineLoop(engine)
    loop.start()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to logging, not stderr
            logger.debug("http: " + fmt, *args)

        def _json(self, code: int, obj: dict) -> None:
            body = (json.dumps(obj) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                # Prometheus text exposition (telemetry/prometheus.py):
                # histograms were observed per completion; gauges + pool
                # counters sync here, under the engine lock, so a scrape is
                # one consistent snapshot
                from automodel_tpu.telemetry.prometheus import CONTENT_TYPE

                with loop.lock:
                    engine.metrics.sync(engine)
                    body = engine.metrics.registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path != "/stats":
                return self._json(404, {"error": f"unknown path {self.path}"})
            with loop.lock:
                self._json(200, {
                    "queue_depth": engine.queue_depth,
                    "busy_slots": engine.busy_slots,
                    "completed_total": engine.completed_total,
                    "block_occupancy": engine.pool.occupancy(),
                    "allocator": dict(engine.pool.counters),
                })

        def do_POST(self):
            if self.path != "/generate":
                return self._json(404, {"error": f"unknown path {self.path}"})
            from automodel_tpu.serving.engine import QueueFull

            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                ids = _encode_prompt(req, tokenizer)
                rec = loop.submit_blocking(
                    ids, req.get("max_new_tokens"),
                    timeout_s=float(req.get("timeout_s", 300.0)),
                )
            except (ValueError, TypeError) as e:
                return self._json(400, {"error": str(e)})
            except QueueFull as e:
                # backpressure the client can act on — never a dropped
                # connection (the documented contract)
                return self._json(429, {"error": str(e)})
            except TimeoutError as e:
                return self._json(504, {"error": str(e)})
            except RuntimeError as e:  # scheduler thread died
                return self._json(503, {"error": str(e)})
            out = dict(rec)
            out["completion"] = _decode_completion(rec["tokens"], tokenizer)
            if req.get("id") is not None:
                out["id"] = req["id"]
            self._json(200, out)

    server = ThreadingHTTPServer((host, port), Handler)
    server._engine_loop = loop  # for the caller's shutdown path
    return server, loop


def main(cfg: Any) -> int:
    """`automodel_tpu serve -c cfg.yaml` (stdin-JSONL, or HTTP when
    serving.http.port is set)."""
    from automodel_tpu.generation.engine import (
        GenerationConfig,
        build_auto_from_cfg,
        resolve_tokenizer,
    )
    from automodel_tpu.loggers.log_utils import setup_logging
    from automodel_tpu.serving.engine import ServeConfig, ServingEngine

    setup_logging()
    serve_section = dict(cfg.get("serving", {}) or {})
    http_section = dict(serve_section.get("http") or {})
    serve_cfg = ServeConfig.from_dict(serve_section)
    gen_section = dict(cfg.get("generation", {}) or {})
    gen_cfg = GenerationConfig.from_dict(gen_section)
    tokenizer = resolve_tokenizer(
        gen_section.get("tokenizer"),
        cfg.model.get("pretrained_model_name_or_path"),
    )

    auto = build_auto_from_cfg(cfg)
    on_record = None
    metrics_path = (cfg.get("logging") or {}).get("metrics_path") if cfg.get("logging") else None
    metric_logger = None
    if metrics_path:
        from automodel_tpu.loggers.metric_logger import MetricLogger

        metric_logger = MetricLogger(metrics_path)

        def on_record(rec: dict) -> None:
            rec = dict(rec)
            rec.pop("tokens", None)  # completions don't belong in metrics
            metric_logger.log(rec)

    engine = ServingEngine(
        auto, serve_cfg, gen_cfg, on_record=on_record
    )

    if http_section.get("port") is not None:
        port = int(http_section["port"])
        host = str(http_section.get("host", "127.0.0.1"))
        server, loop = serve_http(engine, tokenizer, port, host=host)
        print(
            json.dumps({
                "event": "serve_listening",
                "host": host, "port": server.server_address[1],
                "slots": serve_cfg.slots, "num_blocks": serve_cfg.num_blocks,
            }),
            flush=True,
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            server.shutdown()
            loop.close()
            if metric_logger is not None:
                metric_logger.close()
        return 0

    # stdin-JSONL: submit every line, print completions as they finish. A
    # bad line is THAT client's error — it gets an error JSON line and the
    # batch continues; crashing here would destroy every other request's
    # in-flight work.
    from automodel_tpu.serving.engine import QueueFull

    n_submitted, n_bad = 0, 0
    for lineno, line in enumerate(sys.stdin, 1):
        line = line.strip()
        if not line:
            continue
        rid = None
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request line is not a JSON object")
            rid = req.get("id")
            ids = _encode_prompt(req, tokenizer)
            while True:
                try:
                    engine.submit(
                        ids,
                        request_id=str(rid) if rid is not None else None,
                        max_new_tokens=req.get("max_new_tokens"),
                    )
                    break
                except QueueFull:
                    # bounded queue + unbounded stdin: drain a step, retry
                    for rec in engine.step():
                        _emit(rec, tokenizer)
        except (ValueError, TypeError) as e:
            n_bad += 1
            err = {"error": f"line {lineno}: {e}"}
            if rid is not None:
                err["id"] = rid
            print(json.dumps(err), flush=True)
            continue
        n_submitted += 1
        # drain opportunistically so early completions stream out while
        # later lines are still being read
        for rec in engine.step():
            _emit(rec, tokenizer)
    if n_submitted == 0:
        print(
            "no requests: pipe JSONL lines like "
            '{"prompt": "1 2 3", "max_new_tokens": 8} into stdin',
            file=sys.stderr,
        )
        return 2
    for rec in engine.run():
        _emit(rec, tokenizer)
    if metric_logger is not None:
        metric_logger.close()
    return 0 if n_bad == 0 else 1


def _emit(rec: dict, tokenizer: Any) -> None:
    out = dict(rec)
    out["completion"] = _decode_completion(out.pop("tokens"), tokenizer)
    out.pop("event", None)
    print(json.dumps(out), flush=True)
