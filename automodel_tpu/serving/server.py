"""`automodel_tpu serve` — a thin front on the continuous-batching engine.

Two modes, one engine:

- **stdin-JSONL** (default): one request object per line —
  ``{"prompt": "..."} | {"prompt_ids": [...]}`` plus optional ``id`` /
  ``max_new_tokens`` / ``deadline_s`` / ``max_queue_wait_s`` — all
  submitted into the admission queue, completions printed as JSON lines AS
  THEY FINISH (continuous batching means short requests return before long
  ones that arrived earlier).
- **local HTTP** (``serving.http.port``): POST /generate with the same
  request object blocks until that request completes; GET /stats returns
  queue depth / occupancy / allocator counters; GET /metrics is the
  Prometheus exposition; GET /healthz (scheduler thread alive, last step
  age under the watchdog deadline) and GET /readyz (false while draining
  or before the first compiled decode) feed load balancers. A background
  thread runs the scheduler loop; handlers only enqueue and wait — stdlib
  ThreadingHTTPServer, no extra dependencies, explicitly a LOCAL/dev front
  (docs/serving.md covers what a production front needs on top).

Production hardening (docs/serving.md "Failure modes & operations"):

- **Graceful drain** — SIGTERM (chained through the PR 3
  ``PreemptionHandler``) flips both fronts to draining: new and queued
  requests are rejected retriable (HTTP 503 + ``Retry-After``, stdin-JSONL
  error/record lines), in-flight requests finish within
  ``serving.drain.grace_s`` (then are cancelled), the scheduler exits
  cleanly and the CLI exits 0 — or 75 (EX_TEMPFAIL, the launchers' requeue
  code) when running under slurm/k8s (``serving.drain.requeue_exit``).
- **Overload shedding** — a full admission queue is an explicit 503 +
  ``Retry-After`` (HTTP) / retriable error record (stdin), counted in
  ``requests_shed_total``; never a silent drop or unbounded ttft.
- **Engine stalls** — the scheduler-level ``EngineWatchdog``
  (``serving.watchdog:``) detects a wedged jitted step, dumps stacks + the
  flight recorder, and the engine fails only the affected wave and keeps
  serving.

Per-request telemetry (``ttft_s``, ``decode_tps``, ``queue_s``,
``queue_depth``, ``block_occupancy``, ``completion_reason``) rides the PR 2
metrics JSONL via ``logging.metrics_path`` and is accepted by
``automodel_tpu report --strict``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from pathlib import Path
from typing import Any, Optional

logger = logging.getLogger(__name__)

# Retry-After advice on retriable rejections (503s): long enough for a
# drain to finish or a queue burst to clear, short enough to keep clients
# live. A load balancer should prefer another replica immediately. With
# QoS enabled the advice is SCALED BY TIER (interactive 1×, batch 2×,
# best_effort 3×): lower tiers back off longer, so the first capacity
# that frees up goes to the tier the operator ranked higher.
RETRY_AFTER_S = 5


def _tier_retry_after(tier: Any) -> int:
    """Tier-scaled Retry-After seconds (falls back to the flat advice on
    an unknown/absent tier — a rejection must never raise over advice)."""
    from automodel_tpu.serving.engine import tier_index

    try:
        return RETRY_AFTER_S * (tier_index(str(tier)) + 1)
    except (TypeError, ValueError):
        return RETRY_AFTER_S


def _encode_prompt(req: dict, tokenizer: Any) -> list[int]:
    if req.get("prompt_ids") is not None:
        return [int(t) for t in req["prompt_ids"]]
    prompt = req.get("prompt")
    if prompt is None:
        raise ValueError("request needs 'prompt' or 'prompt_ids'")
    if tokenizer is None:
        # token-id mode (tiny from-config models): same convention as the
        # generate CLI — whitespace/comma-separated ids
        toks = str(prompt).replace(",", " ").split()
        try:
            return [int(t) for t in toks]
        except ValueError:
            raise ValueError(
                "no tokenizer available: 'prompt' must be token ids "
                "(e.g. \"1 2 3\") or configure generation.tokenizer"
            )
    if callable(tokenizer):
        return tokenizer(str(prompt), add_special_tokens=True)["input_ids"]
    return tokenizer.encode(str(prompt))


def _decode_completion(tokens: list[int], tokenizer: Any) -> str:
    if tokenizer is None:
        return " ".join(map(str, tokens))
    return tokenizer.decode(tokens, skip_special_tokens=True)


def _drain_exit_code(drain_cfg: Any) -> int:
    """0 after a clean drain — or the launchers' requeue code (75) so a
    drained replica under slurm/k8s is restarted instead of counted as
    done. ``auto`` sniffs the launcher env the PR 3/5 requeue rules key on."""
    from automodel_tpu.resilience import REQUEUE_EXIT_CODE

    if drain_cfg.requeue_exit == "always":
        return REQUEUE_EXIT_CODE
    if drain_cfg.requeue_exit == "never":
        return 0
    under_launcher = (
        "SLURM_JOB_ID" in os.environ or "KUBERNETES_SERVICE_HOST" in os.environ
    )
    return REQUEUE_EXIT_CODE if under_launcher else 0


# /stats key → the /metrics family carrying the same fact. The drift guard
# (tests/test_fleet_health.py) walks this table both ways: every /stats key
# must appear here, and every serve-family metric must be reachable from it
# or listed in STATS_METRICS_ONLY. None marks info keys with no numeric
# metric; a tuple means the stats value is the SUM of those families;
# "allocator" fans out to automodel_serve_block_<counter-key> per entry.
STATS_METRIC_EQUIV = {
    "queue_depth": "automodel_serve_queue_depth",
    "busy_slots": (
        "automodel_serve_running_slots",
        "automodel_serve_prefilling_slots",
    ),
    "completed_total": "automodel_serve_requests_completed",
    "failed_total": "automodel_serve_requests_failed",
    "shed_total": "automodel_serve_requests_shed",
    "timeout_total": "automodel_serve_requests_timeout",
    "stall_total": "automodel_serve_engine_stalls",
    "error_total": "automodel_serve_engine_errors",
    "draining": "automodel_serve_draining",
    "drain_duration_s": "automodel_serve_drain_duration_seconds",
    "block_occupancy": "automodel_serve_block_occupancy",
    "blocks_in_use": "automodel_serve_blocks_in_use",
    "allocator": "automodel_serve_block_*",
    "decode_backend": None,
    "kv_cache_dtype": None,
    "spec_proposed_total": (
        "automodel_serve_spec_accepted",
        "automodel_serve_spec_rejected",
    ),
    "spec_accepted_total": "automodel_serve_spec_accepted",
    "spec_accept_rate": "automodel_serve_spec_accept_rate",
    "role": None,
    "block_size": None,
    "kv_transfer_port": None,
    "kv_injected_total": "automodel_serve_kv_injected",
    "hot_prefixes": None,
    "spill_bytes": "automodel_serve_spill_bytes",
    "spill_entries": "automodel_serve_spill_entries",
    # elastic fleet: boot provenance (time_to_ready_s is null until the
    # first readiness; boot_source is an info string)
    "time_to_ready_s": "automodel_serve_time_to_ready_seconds",
    "boot_source": None,
    # multi-tenant QoS: over-quota rejections, plus the per-tier/per-tenant
    # queue/served breakdown (an info dict — the numeric facts ride the
    # labeled automodel_serve_tier_*/tenant_* families below)
    "quota_total": "automodel_serve_requests_quota",
    "qos": None,
    # live hot-swap (engine.swap_weights): monotonic weights generation —
    # the router reads per-replica version skew off this during a rolling
    # update
    "weights_version": "automodel_serve_weights_version",
}

# Families deliberately absent from /stats: per-request distributions have
# no single-number snapshot (histograms), and generated_tokens is observed
# per completion record rather than tracked on the engine.
STATS_METRICS_ONLY = (
    "automodel_serve_ttft_seconds",
    "automodel_serve_decode_tps",
    "automodel_serve_queue_seconds",
    "automodel_serve_stage_seconds",
    "automodel_serve_generated_tokens",
    # QoS labeled families: per-tier/per-tenant breakdowns whose /stats
    # shape is the "qos" info dict, not a single number
    "automodel_serve_tier_requests",
    "automodel_serve_tenant_requests",
    "automodel_serve_tier_ttft_seconds",
)


def stats_snapshot(engine: Any) -> dict:
    """The GET /stats body. Factored out of the handler so the drift guard
    can build it against a bare engine; call under the engine-loop lock
    when the scheduler is live."""
    return {
        "queue_depth": engine.queue_depth,
        "busy_slots": engine.busy_slots,
        "completed_total": engine.completed_total,
        "failed_total": engine.failed_total,
        "shed_total": engine.shed_total,
        "timeout_total": engine.timeout_total,
        "stall_total": engine.stall_total,
        "error_total": engine.error_total,
        "draining": engine.draining,
        "drain_duration_s": engine.drain_duration_s,
        "block_occupancy": engine.pool.occupancy(),
        "blocks_in_use": engine.pool.in_use(),
        "allocator": dict(engine.pool.counters),
        "decode_backend": engine.decode_backend,
        "kv_cache_dtype": engine.config.kv_cache_dtype,
        "spec_proposed_total": engine.spec_proposed_total,
        "spec_accepted_total": engine.spec_accepted_total,
        "spec_accept_rate": engine.spec_accept_rate,
        # fleet tier (serving/fleet/router.py probes these): role for pool
        # membership, block_size so the router can refuse affinity on a
        # geometry mismatch, hot_prefixes for prefix-affinity placement,
        # kv_transfer_port for the prefill→decode handoff
        "role": engine.config.role,
        "block_size": engine.config.block_size,
        "kv_transfer_port": engine.kv_transfer_port,
        "kv_injected_total": engine.kv_injected_total,
        "hot_prefixes": engine.hot_prefixes(),
        # hierarchical KV cache: host-tier occupancy (null when
        # serving.kv_spill is off; counters ride "allocator")
        "spill_bytes": (
            engine.pool.spill.bytes
            if engine.pool.spill is not None else None
        ),
        "spill_entries": (
            len(engine.pool.spill)
            if engine.pool.spill is not None else None
        ),
        # elastic fleet: which boot path this replica took and how long
        # startup→first-readiness took (the warm-vs-cold A/B number)
        "time_to_ready_s": engine.time_to_ready_s,
        "boot_source": engine.boot_source,
        # live hot-swap: which weights generation this replica serves
        "weights_version": engine.weights_version,
        # multi-tenant QoS: over-quota rejections + per-tier/per-tenant
        # queue and outcome breakdown (fleet-status renders these)
        "quota_total": engine.quota_total,
        "qos": engine.qos_snapshot(),
    }


_OK_REASONS = ("stop", "length")


def _reason_status(reason: str) -> int:
    """HTTP status for a terminal record that is not a completion."""
    if reason in _OK_REASONS:
        return 200
    if reason == "timeout":
        return 504  # the client's own budget expired — not retriable
    if reason == "quota":
        return 429  # over-quota: retriable AFTER Retry-After, not elsewhere
    return 503  # draining / cancelled / engine_stall / engine_error: retry


class _EngineLoop:
    """Background scheduler thread for the HTTP mode: handlers submit under
    the lock and wait on a per-request event; the loop steps the engine
    whenever there is work (and keeps stepping through a drain so in-flight
    requests finish and grace-expiry cancellations run)."""

    def __init__(self, engine: Any):
        self.engine = engine
        self.lock = threading.Lock()
        self._events: dict[str, threading.Event] = {}
        self._results: dict[str, dict] = {}
        self._abandoned: set[str] = set()  # timed-out waiters: drop on finish
        self.error: Optional[str] = None  # scheduler-thread death, terminal
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="serve-scheduler", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def alive(self) -> bool:
        return self._thread.is_alive() and self.error is None

    def submit_blocking(
        self,
        prompt_ids: list[int],
        req: dict,
        timeout_s: float,
        submit: Optional[Any] = None,
        trace: Optional[Any] = None,
    ) -> dict:
        """``submit`` (optional, called under the lock) replaces the plain
        ``engine.submit`` — the /prefill and KV-handoff paths enqueue
        through their own entry points but share this wait machinery.
        ``trace`` is the propagated traceparent context (the engine mints
        its root span as a child of it)."""
        from automodel_tpu.serving.engine import QueueFull, QuotaExceeded

        ev = threading.Event()
        with self.lock:
            if self.error is not None:
                raise RuntimeError(f"serving engine is down: {self.error}")
            try:
                if submit is not None:
                    rid = submit()
                else:
                    kvp = req.get("kv_peer")  # router prefix-fetch hint
                    rid = self.engine.submit(
                        prompt_ids,
                        max_new_tokens=req.get("max_new_tokens"),
                        deadline_s=req.get("deadline_s"),
                        max_queue_wait_s=req.get("max_queue_wait_s"),
                        trace=trace,
                        kv_peer=kvp if isinstance(kvp, dict) else None,
                        return_logprobs=bool(req.get("return_logprobs")),
                        tenant=req.get("tenant"),
                        tier=req.get("tier"),
                    )
            except QueueFull:
                # the HTTP front sheds immediately — a blocked handler
                # thread per queued-out client is exactly the unbounded
                # latency shedding exists to prevent. ONE tier-labeled
                # record per give-up, never per retry (tests/test_qos.py
                # pins this seam).
                self.engine.record_shed(
                    prompt_ids=prompt_ids,
                    tenant=req.get("tenant"), tier=req.get("tier"),
                )
                raise
            except QuotaExceeded as e:
                # same seam doctrine as record_shed: submit raised without
                # a record, the answering front counts exactly one
                self.engine.record_quota(
                    prompt_ids=prompt_ids, tenant=e.tenant, tier=e.tier
                )
                raise
            self._events[rid] = ev
        if not ev.wait(timeout=timeout_s):
            with self.lock:
                self._events.pop(rid, None)
                # the request can't be cancelled mid-flight: remember the
                # abandonment so its eventual completion is discarded
                # instead of accumulating in _results forever
                self._abandoned.add(rid)
            raise TimeoutError(f"request {rid} timed out after {timeout_s}s")
        with self.lock:
            if self.error is not None and rid not in self._results:
                raise RuntimeError(f"serving engine died: {self.error}")
            return self._results.pop(rid)

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self.lock:
                try:
                    idle = self.engine.idle()
                    done = [] if idle else self.engine.step()
                except Exception as e:  # scheduler death is TERMINAL, not silent
                    self.error = f"{type(e).__name__}: {e}"
                    logger.exception("serving scheduler thread died")
                    # wake every waiter so handlers return 503 immediately
                    # instead of blocking to their timeout
                    for ev in self._events.values():
                        ev.set()
                    self._events.clear()
                    return
                for rec in done:
                    rid = rec["request_id"]
                    ev = self._events.pop(rid, None)
                    if rid in self._abandoned:
                        self._abandoned.discard(rid)  # waiter gave up: drop
                        continue
                    self._results[rid] = rec
                    if ev is not None:
                        ev.set()
            if idle:
                # an idle server is healthy, not hung: keep the stall
                # watchdog's heartbeat fresh without counting a step
                self.engine.touch_watchdog()
                time.sleep(0.005)


def serve_http(
    engine: Any,
    tokenizer: Any,
    port: int,
    host: str = "127.0.0.1",
    kv_store: Any = None,
    on_retire: Any = None,
):
    """→ (ThreadingHTTPServer, _EngineLoop), both started. The caller calls
    ``server.serve_forever()`` (CLI) or drives requests itself (tests) and
    shuts both down. ``kv_store`` (a fleet ``HandoffStore``) arms the
    disaggregated paths: POST /generate with a ``handoff_id`` claims a
    transferred prefill payload from it. ``on_retire(migrate, deadline_s)``
    (optional, run on its own thread) arms POST /retire — the autoscaler's
    scale-down entry point: drain, optionally migrate hot prefix blocks to
    the survivor named in ``migrate``, then exit."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    loop = _EngineLoop(engine)
    loop.start()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to logging, not stderr
            logger.debug("http: " + fmt, *args)

        def _json(
            self, code: int, obj: dict, retry_after: Any = False
        ) -> None:
            # retry_after: False = no header, True = flat advice, a
            # number = that many seconds (the tier-scaled QoS advice)
            body = (json.dumps(obj) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after:
                secs = (
                    RETRY_AFTER_S if retry_after is True else int(retry_after)
                )
                self.send_header("Retry-After", str(secs))
            self.end_headers()
            self.wfile.write(body)

        def _retry_advice(self, req: dict) -> int:
            """Tier-scaled Retry-After for this request: explicit tier,
            else the tenant's configured default, else the global one."""
            qos = engine.config.qos
            tier = req.get("tier")
            if tier is None:
                tenant = req.get("tenant")
                tier = (
                    qos.tier_for(str(tenant))
                    if tenant is not None else qos.default_tier
                )
            return _tier_retry_after(tier)

        def _stash_qos_headers(self, req: dict) -> None:
            """The router forwards tenant/tier as X-Tenant-Id / X-Tier
            headers (same vehicle as traceparent); body fields from
            bare-bones clients win so a direct caller stays authoritative
            over a middlebox."""
            if req.get("tenant") is None:
                h = self.headers.get("X-Tenant-Id")
                if h is not None:
                    req["tenant"] = h
            if req.get("tier") is None:
                h = self.headers.get("X-Tier")
                if h is not None:
                    req["tier"] = h

        def do_GET(self):
            if self.path == "/metrics":
                # Prometheus text exposition (telemetry/prometheus.py):
                # histograms were observed per completion; gauges + pool
                # counters sync here, under the engine lock, so a scrape is
                # one consistent snapshot
                from automodel_tpu.telemetry.prometheus import CONTENT_TYPE

                with loop.lock:
                    engine.metrics.sync(engine)
                    body = engine.metrics.registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/healthz":
                # liveness: the scheduler thread exists and its last step
                # boundary is inside the stall watchdog's deadline. An IDLE
                # engine is healthy by definition — no steps run, so age is
                # meaningless there. Deliberately LOCK-FREE: during the
                # exact wedged-step stall this endpoint exists to report,
                # the scheduler thread holds loop.lock inside engine.step()
                # — taking it here would hang the kubelet's probe instead
                # of answering 503. Everything read is a GIL-atomic
                # attribute (the same contract the watchdog thread relies
                # on), at worst one step stale.
                alive = loop.alive()
                idle = engine.idle()
                age = engine.last_step_age_s
                wd = engine.watchdog
                deadline = wd.deadline_s if wd is not None else None
                ok = alive and (
                    idle or wd is None or age is None or age <= deadline
                )
                return self._json(200 if ok else 503, {
                    "ok": ok,
                    "scheduler_alive": alive,
                    "idle": idle,
                    "last_step_age_s": age,
                    "stall_deadline_s": deadline,
                    "error": loop.error,
                })
            if self.path == "/readyz":
                # readiness: drop out of the load balancer while draining,
                # and never advertise before the first decode compiled (the
                # warm-up request flips this at startup). Lock-free for the
                # same reason as /healthz — a stalled scheduler must not
                # make the probe hang.
                ready = (
                    loop.alive()
                    and not engine.draining
                    and engine.first_decode_done
                )
                if ready:
                    # idempotent time_to_ready_s stamp: warmup-disabled
                    # servers reach readiness on their first true probe
                    engine.note_ready()
                return self._json(200 if ready else 503, {
                    "ready": ready,
                    "draining": engine.draining,
                    "first_decode_done": engine.first_decode_done,
                })
            if self.path != "/stats":
                return self._json(404, {"error": f"unknown path {self.path}"})
            with loop.lock:
                self._json(200, stats_snapshot(engine))

        def _read_req(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request body is not a JSON object")
            return req

        def _trace_ctx(self, req: dict):
            """Propagated trace context: the W3C ``traceparent`` HTTP
            header (the router sets it), with a body field fallback for
            bare-bones clients. None = this engine roots a new trace."""
            tracer = getattr(engine, "tracer", None)
            if tracer is None:
                return None
            return tracer.parse(
                self.headers.get("traceparent") or req.get("traceparent")
            )

        def _prefill(self):
            """Disaggregated fleet: run chunked prefill ONLY, then stream
            the finished KV block rows to the decode replica named in
            ``transfer: {host, port, handoff_id}``. Responds after the
            receiver acked — the router's follow-up /generate can never
            race the transfer."""
            from automodel_tpu.serving.engine import EngineDraining, QueueFull
            from automodel_tpu.serving.fleet.kv_transfer import (
                KVTransferError,
                send_kv,
            )

            try:
                req = self._read_req()
                transfer = dict(req.get("transfer") or {})
                if not transfer.get("handoff_id") or not transfer.get("host") \
                        or transfer.get("port") is None:
                    return self._json(400, {
                        "error": "prefill needs transfer.{host,port,handoff_id}"
                    })
                ids = _encode_prompt(req, tokenizer)
                ctx = self._trace_ctx(req)
                rec = loop.submit_blocking(
                    ids, req, timeout_s=float(req.get("timeout_s", 300.0)),
                    submit=lambda: engine.submit(
                        ids, prefill_only=True,
                        deadline_s=req.get("deadline_s"),
                        max_queue_wait_s=req.get("max_queue_wait_s"),
                        trace=ctx,
                    ),
                )
            except (ValueError, TypeError) as e:
                return self._json(400, {"error": str(e)})
            except QueueFull as e:
                # submit_blocking already recorded the shed — mirroring
                # /generate, no second record here
                return self._json(
                    503, {"error": str(e), "retriable": True, "reason": "shed"},
                    retry_after=True,
                )
            except EngineDraining as e:
                return self._json(
                    503,
                    {"error": str(e), "retriable": True, "reason": "draining"},
                    retry_after=True,
                )
            except TimeoutError as e:
                return self._json(504, {"error": str(e)})
            except RuntimeError as e:
                return self._json(503, {"error": str(e), "retriable": True})
            reason = rec.get("completion_reason")
            if reason != "prefilled":
                code = _reason_status(reason)
                return self._json(code, {
                    "error": f"prefill ended as {reason}",
                    "completion_reason": reason,
                    "retriable": bool(rec.get("retriable")),
                }, retry_after=code == 503)
            try:
                with loop.lock:
                    payload = engine.pop_prefill_payload(rec["request_id"])
            except KeyError as e:
                # the bounded stash evicted this payload before pickup
                # (kv_transfer.max_pending prefills completed in between) —
                # a transient capacity condition, not a dead replica: answer
                # 503 retriable instead of dying without a response (which
                # the router would read as replica death)
                return self._json(
                    503, {"error": str(e), "retriable": True},
                    retry_after=True,
                )
            meta = {
                "handoff_id": str(transfer["handoff_id"]),
                "request_id": rec["request_id"],
                "prompt_len": payload["prompt_len"],
                "first_token": payload["first_token"],
                "geometry": engine.kv_geometry(),
            }
            # tracing: the KV handoff is its own stage — kv_send here,
            # parented under this request's prefill-side root; the context
            # rides the AKV1 header so the receiver's kv_receive span joins
            # the same trace
            tracer = getattr(engine, "tracer", None)
            root = payload.get("trace")
            send_ctx = None
            if tracer is not None and tracer.active(root):
                from automodel_tpu.telemetry.tracing import to_traceparent

                send_ctx = tracer.start(parent=root)
                meta["traceparent"] = to_traceparent(send_ctx)
            t_send0 = time.perf_counter()
            try:
                send_kv(
                    (str(transfer["host"]), int(transfer["port"])),
                    meta, payload["kv"],
                )
            except KVTransferError as e:
                if send_ctx is not None:
                    tracer.record(
                        send_ctx, "kv_send", t_send0,
                        request_id=rec["request_id"], error=str(e)[:200],
                    )
                return self._json(
                    502, {"ok": False, "error": str(e), "retriable": True}
                )
            if send_ctx is not None:
                tracer.record(
                    send_ctx, "kv_send", t_send0,
                    request_id=rec["request_id"],
                    handoff_id=meta["handoff_id"],
                    prompt_tokens=payload["prompt_len"],
                )
            return self._json(200, {
                "ok": True,
                "handoff_id": meta["handoff_id"],
                "first_token": payload["first_token"],
                "prompt_tokens": payload["prompt_len"],
                "prefix_hit_tokens": rec.get("prefix_hit_tokens", 0),
                "ttft_s": rec.get("ttft_s"),
            })

        def _swap_weights(self):
            """Live weight hot-swap: ``{"peer": {"host", "port"},
            "timeout_s": s}``. Fetches the replacement tree over the AKV1
            ``weights_fetch`` op from the peer (the post-training trainer
            runs the listener), validates it against the param-tree
            signature under the engine lock, stages the swap, then waits
            for it to land — in-flight requests finish under the old
            weights first. A signature mismatch answers 409 with the old
            params untouched."""
            from automodel_tpu.serving.fleet.kv_transfer import (
                KVTransferError,
                fetch_weights,
            )

            try:
                req = self._read_req()
            except (ValueError, TypeError) as e:
                return self._json(400, {"error": str(e)})
            peer = req.get("peer")
            if not (
                isinstance(peer, dict)
                and peer.get("host")
                and peer.get("port") is not None
            ):
                return self._json(400, {
                    "error": "swap_weights needs peer.{host, port}"
                })
            timeout_s = float(req.get("timeout_s", 120.0))
            t0 = time.perf_counter()
            try:
                _, arrays = fetch_weights(
                    (str(peer["host"]), int(peer["port"])),
                    timeout_s=timeout_s,
                )
            except (KVTransferError, OSError) as e:
                return self._json(502, {"ok": False, "error": str(e)})
            # the flat {leaf-name: array} dict IS a valid pytree whose
            # signature matches the nested tree (dict keys are the joined
            # path names) — swap_weights rebinds leaves by name anyway
            try:
                with loop.lock:
                    target = engine.swap_weights(arrays)
            except ValueError as e:
                return self._json(409, {
                    "ok": False, "error": str(e),
                    "weights_version": engine.weights_version,
                })
            # the staged swap applies at the scheduler's next idle step
            # boundary; weights_version is a GIL-atomic int, so this poll
            # is deliberately lock-free (mirror of /healthz)
            deadline = t0 + timeout_s
            while (
                engine.weights_version < target
                and time.perf_counter() < deadline
                and loop.alive()
            ):
                time.sleep(0.01)
            if engine.weights_version < target:
                return self._json(504, {
                    "ok": False, "staged": True,
                    "error": (
                        f"swap staged but in-flight requests did not clear "
                        f"within {timeout_s}s"
                    ),
                    "weights_version": engine.weights_version,
                })
            return self._json(200, {
                "ok": True,
                "weights_version": engine.weights_version,
                "swap_s": round(time.perf_counter() - t0, 6),
            })

        def do_POST(self):
            if self.path == "/prefill":
                return self._prefill()
            if self.path == "/swap_weights":
                return self._swap_weights()
            if self.path == "/retire":
                # elastic fleet scale-down: ``{"migrate": {"host", "port"}
                # | null, "deadline_s": s}``. Responds 200 IMMEDIATELY and
                # runs drain → migrate → exit on a background thread — the
                # autoscaler must not block a probe sweep on a drain, and
                # the retiring process, not the caller, owns the deadline.
                if on_retire is None:
                    return self._json(400, {
                        "error": "this server has no retire hook "
                        "(the serve CLI front arms it)"
                    })
                try:
                    req = self._read_req()
                except (ValueError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                migrate = req.get("migrate")
                if migrate is not None and not (
                    isinstance(migrate, dict)
                    and migrate.get("host")
                    and migrate.get("port") is not None
                ):
                    return self._json(400, {
                        "error": "migrate must be null or {host, port}"
                    })
                deadline_s = float(req.get("deadline_s", 30.0))
                threading.Thread(
                    target=on_retire, args=(migrate, deadline_s),
                    name="serve-retire", daemon=True,
                ).start()
                return self._json(200, {
                    "ok": True,
                    "draining": True,
                    "migrate": migrate is not None,
                    "deadline_s": deadline_s,
                })
            if self.path != "/generate":
                return self._json(404, {"error": f"unknown path {self.path}"})
            from automodel_tpu.serving.engine import (
                EngineDraining,
                QueueFull,
                QuotaExceeded,
            )

            req = {}
            try:
                req = self._read_req()
                self._stash_qos_headers(req)
                ids = _encode_prompt(req, tokenizer)
                ctx = self._trace_ctx(req)
                submit = None
                if req.get("handoff_id") is not None:
                    # disaggregated decode: claim the transferred prefill
                    # payload and start the request directly in decode
                    if kv_store is None:
                        return self._json(400, {
                            "error": "this replica runs no KV-transfer "
                            "listener (serving.role: decode, or "
                            "serving.kv_transfer.enabled: true)"
                        })
                    try:
                        entry = kv_store.pop(str(req["handoff_id"]))
                    except KeyError as e:
                        # never arrived / expired: the router retries the
                        # whole prefill→decode flow elsewhere
                        return self._json(
                            409, {"error": str(e), "retriable": True}
                        )
                    submit = lambda: engine.submit_prefilled(  # noqa: E731
                        ids, entry["meta"]["first_token"], entry["kv"],
                        max_new_tokens=req.get("max_new_tokens"),
                        deadline_s=req.get("deadline_s"),
                        max_queue_wait_s=req.get("max_queue_wait_s"),
                        trace=ctx,
                    )
                rec = loop.submit_blocking(
                    ids, req, timeout_s=float(req.get("timeout_s", 300.0)),
                    submit=submit, trace=ctx,
                )
            except (ValueError, TypeError) as e:
                return self._json(400, {"error": str(e)})
            except QueueFull as e:
                # overload SHED: an explicit retriable signal the client
                # (or its load balancer) can act on — never a dropped
                # connection, never an unbounded queue
                return self._json(
                    503, {"error": str(e), "retriable": True, "reason": "shed"},
                    retry_after=self._retry_advice(req),
                )
            except QuotaExceeded as e:
                # over-quota: retriable after the (tier-scaled) Retry-After
                # on THIS replica — a 429, not a 503, so load balancers
                # don't burn retry budget hopping replicas that share the
                # same per-tenant policy
                return self._json(
                    429,
                    {"error": str(e), "retriable": True, "reason": "quota",
                     "tenant": e.tenant, "tier": e.tier},
                    retry_after=_tier_retry_after(e.tier),
                )
            except EngineDraining as e:
                return self._json(
                    503,
                    {"error": str(e), "retriable": True, "reason": "draining"},
                    retry_after=self._retry_advice(req),
                )
            except TimeoutError as e:
                return self._json(504, {"error": str(e)})
            except RuntimeError as e:  # scheduler thread died
                return self._json(503, {"error": str(e)})
            out = dict(rec)
            out["completion"] = _decode_completion(rec["tokens"], tokenizer)
            if req.get("id") is not None:
                out["id"] = req["id"]
            reason = rec.get("completion_reason", "length")
            code = _reason_status(reason)
            self._json(
                code, out,
                retry_after=self._retry_advice(req) if code == 503 else False,
            )

    server = ThreadingHTTPServer((host, port), Handler)
    server._engine_loop = loop  # for the caller's shutdown path
    return server, loop


def _install_drain_handler(engine: Any, on_term=None):
    """Chain SIGTERM → drain through the PR 3 PreemptionHandler (prior
    handlers — libtpu, cluster agents — still run). → the installed
    handler, or None when serving.drain.install_signal_handler is off or
    this is not the main thread (signal.signal would raise)."""
    drain_cfg = engine.config.drain
    if not drain_cfg.install_signal_handler:
        return None
    if threading.current_thread() is not threading.main_thread():
        return None
    from automodel_tpu.resilience.preemption import PreemptionHandler

    handler = PreemptionHandler(
        signals=("SIGTERM",),
        on_preempt=on_term,
        log_message=(
            "serving drain: rejecting new requests retriable, finishing "
            f"in-flight within serving.drain.grace_s={drain_cfg.grace_s}"
        ),
    )
    try:
        handler.install()
    except ValueError:  # non-main-thread despite the check (exotic embeds)
        return None
    return handler


def _warmup(engine: Any) -> None:
    """One tiny request through the engine before the front opens: absorbs
    the prefill/decode compiles (ttft of the FIRST real request) and flips
    ``first_decode_done`` so /readyz can go true. Best-effort."""
    try:
        vocab = int(getattr(engine.model.config, "vocab_size", 2))
        # max_new_tokens=2, not 1: a 1-token request completes at the
        # prefill tick and never runs (or compiles) the decode program —
        # readiness requires one real decode step
        engine.submit([min(1, max(vocab - 1, 0))], request_id="__warmup__",
                      max_new_tokens=2)
        engine.run()
    except Exception as e:
        logger.warning("serve warm-up request failed: %r", e)


def _tree_path_name(path) -> str:
    """The param-tree leaf naming rule — MUST match
    ``checkpoint.checkpointer.param_tree_signature`` exactly, so signature
    entries and wire-transferred leaves line up one-to-one."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def _warm_start_params(auto: Any, ws: Any) -> bool:
    """Peer warm-start (docs/serving.md "Elastic fleet"): stream the whole
    param tree from the serving peer named in ``serving.warm_start`` and
    swap it under this replica's structurally built tree. The peer's
    param-tree signature must digest-match this replica's own (the PR 6
    checkpoint guard) BEFORE any leaf is swapped — a mismatch means the
    architectures differ and cold load is the only correct path. → True
    when the swap landed; False (after logging) on ANY failure, leaving
    the cold-built params untouched."""
    import jax

    from automodel_tpu.checkpoint.checkpointer import param_tree_signature
    from automodel_tpu.serving.fleet.kv_transfer import (
        KVTransferError,
        fetch_weights,
    )

    addr = (str(ws.peer_host), int(ws.peer_port))
    t0 = time.perf_counter()
    try:
        expected = param_tree_signature(auto.params)
        sig, arrays = fetch_weights(addr, timeout_s=ws.timeout_s)
        if sig.get("digest") != expected["digest"]:
            raise KVTransferError(
                f"peer param-tree signature {sig.get('digest')!r} != this "
                f"replica's {expected['digest']!r} — the peer serves a "
                "different architecture/shape/dtype tree"
            )
        leaves, treedef = jax.tree_util.tree_flatten_with_path(auto.params)
        new_leaves = []
        for path, leaf in leaves:
            name = _tree_path_name(path)
            arr = arrays.get(name)
            if arr is None:
                # digest match makes this unreachable short of a hostile
                # peer — still a loud fallback, never a KeyError
                raise KVTransferError(f"peer stream is missing leaf {name}")
            new_leaves.append(jax.device_put(arr, leaf.sharding))
        auto.params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        logger.info(
            "peer warm-start from %s:%d landed %d leaves in %.3fs",
            addr[0], addr[1], len(new_leaves), time.perf_counter() - t0,
        )
        return True
    except Exception as e:
        # the fallback ladder: ANY failure — refused, died mid-stream,
        # signature mismatch — keeps the cold-built params
        logger.warning(
            "peer warm-start from %s:%d failed (%s: %s); cold load",
            addr[0], addr[1], type(e).__name__, e,
        )
        return False


def main(cfg: Any) -> int:
    """`automodel_tpu serve -c cfg.yaml` (stdin-JSONL, or HTTP when
    serving.http.port is set)."""
    from automodel_tpu.generation.engine import (
        GenerationConfig,
        build_auto_from_cfg,
        resolve_tokenizer,
    )
    from automodel_tpu.loggers.log_utils import setup_logging
    from automodel_tpu.serving.engine import ServeConfig, ServingEngine

    setup_logging()
    # time_to_ready_s starts here — BEFORE the model build, because load
    # time is exactly what peer warm-start exists to cut
    t_boot = time.perf_counter()
    serve_section = dict(cfg.get("serving", {}) or {})
    http_section = dict(serve_section.get("http") or {})
    serve_cfg = ServeConfig.from_dict(serve_section)
    gen_section = dict(cfg.get("generation", {}) or {})
    gen_cfg = GenerationConfig.from_dict(gen_section)
    tokenizer = resolve_tokenizer(
        gen_section.get("tokenizer"),
        cfg.model.get("pretrained_model_name_or_path"),
    )

    auto = build_auto_from_cfg(cfg)
    # elastic-fleet boot ladder: peer warm-start when configured, cold HF
    # otherwise (and as the fallback when any part of the fetch fails).
    # The injected hf_load_delay_ms cold-load cost (fault_injection.py)
    # applies ONLY on the cold path — it stands in for the real HF
    # download/parse time a warm start skips, so the time_to_ready_s A/B
    # is measurable on tiny CPU models.
    boot_source = "cold_hf"
    if serve_cfg.warm_start.enabled:
        if _warm_start_params(auto, serve_cfg.warm_start):
            boot_source = "peer_warm_start"
    if boot_source == "cold_hf":
        from automodel_tpu.resilience.fault_injection import active_injector

        inj = active_injector()
        if inj is not None:
            inj.maybe_hf_load_delay()
    on_record = None
    metrics_path = (cfg.get("logging") or {}).get("metrics_path") if cfg.get("logging") else None
    metric_logger = None
    if metrics_path:
        from automodel_tpu.loggers.metric_logger import MetricLogger

        metric_logger = MetricLogger(metrics_path)

        def on_record(rec: dict) -> None:
            rec = dict(rec)
            rec.pop("tokens", None)  # completions don't belong in metrics
            metric_logger.log(rec)

    # request tracing (telemetry/tracing.py): spans ride the same metrics
    # JSONL as serve_request records — no metrics_path means no span sink,
    # so tracing silently has nowhere to write (documented)
    from automodel_tpu.telemetry.tracing import Tracer, TracingConfig

    tracing_cfg = TracingConfig.from_dict(dict(cfg.get("tracing", {}) or {}))
    tracer = Tracer.from_config(
        tracing_cfg,
        process=f"serve-{serve_cfg.role}-{os.getpid()}",
        emit=on_record,
    )

    engine = ServingEngine(
        auto, serve_cfg, gen_cfg, on_record=on_record, tracer=tracer
    )
    engine.boot_t = t_boot
    engine.boot_source = boot_source

    # fleet KV listener: a decode-role replica listens for prefill→decode
    # handoffs, and a spill-enabled replica listens for peer /kv_fetch
    # (serving.kv_transfer.enabled: null = auto-on for either role); the
    # bound port is advertised to the router via /stats
    kv_server = None
    ktc = serve_cfg.kv_transfer
    kv_on = (
        ktc.enabled
        if ktc.enabled is not None
        else (serve_cfg.role == "decode" or serve_cfg.kv_spill.enabled)
    )
    if kv_on:
        from automodel_tpu.serving.fleet.kv_transfer import KVTransferServer

        kv_server = KVTransferServer(
            engine.kv_geometry(), host=ktc.host, port=ktc.port,
            max_pending=ktc.max_pending, ttl_s=ktc.ttl_s,
            max_frame_bytes=engine.kv_frame_bytes_bound(),
            tracer=engine.tracer,
        ).start()
        engine.kv_transfer_port = kv_server.port
        logger.info("KV-transfer listener on port %d", kv_server.port)

        # warm-start source for joining replicas: serve this replica's
        # param tree over ``op: weights_fetch``. A hot-swap can replace the
        # whole tree mid-serve, but never mutates leaves in place — one
        # GIL-atomic snapshot of the attribute up front keeps the streamed
        # signature and leaves from one consistent generation, so no
        # scheduler lock is needed.
        def _serve_weights():
            import jax

            from automodel_tpu.checkpoint.checkpointer import (
                param_tree_signature,
            )

            params = engine.auto.params
            sig = param_tree_signature(params)
            leaves = jax.tree_util.tree_flatten_with_path(params)[0]
            return sig, [
                (_tree_path_name(path), leaf) for path, leaf in leaves
            ]

        kv_server.weights_handler = _serve_weights

    # stall-watchdog evidence routing: stacks + flight recorder land next
    # to the metrics JSONL when one is configured (same layout the training
    # guard uses)
    flight_recorder = None
    stacks_path = None
    if metrics_path:
        try:
            from automodel_tpu.telemetry.flight_recorder import (
                FlightRecorder,
                build_fingerprint,
            )

            parent = Path(metrics_path).parent
            stacks_path = str(parent / "watchdog_stacks.txt")
            flight_recorder = FlightRecorder(
                path=str(parent / "flight_recorder.json"),
                fingerprint=build_fingerprint(
                    config=cfg.to_dict() if hasattr(cfg, "to_dict") else None,
                    mesh_ctx=auto.mesh_ctx,
                ),
            )
        except Exception as e:  # evidence plumbing must not block serving
            logger.warning("flight recorder unavailable: %r", e)
    engine.start_watchdog(
        flight_recorder=flight_recorder, metric_logger=metric_logger,
        stacks_path=stacks_path,
    )

    try:
        if http_section.get("port") is not None:
            return _serve_http_forever(
                engine, tokenizer, http_section, serve_cfg,
                kv_store=kv_server.store if kv_server is not None else None,
                kv_server=kv_server,
            )
        return _serve_stdin(engine, tokenizer, serve_cfg)
    finally:
        engine.stop_watchdog()
        if kv_server is not None:
            kv_server.close()
        if metric_logger is not None:
            metric_logger.close()


def retire_sequence(engine, loop, migrate, deadline_s: float) -> str:
    """Drain, then ship hot prefix blocks to the survivor — in that order,
    all inside ``deadline_s``. Runs on the serve-retire thread; the caller
    shuts the HTTP front down afterwards. Migration failure degrades to
    plain drain; NOTHING here may block retirement past the deadline.

    Returns the outcome record name (``migration_complete`` /
    ``migration_failed`` / ``migration_skipped``) so callers and tests can
    branch without re-parsing the JSONL.
    """
    t0 = time.monotonic()
    deadline = t0 + max(float(deadline_s), 0.0)
    engine.begin_drain()
    # in-flight requests finish under the scheduler as usual; stop
    # waiting at drain-completion, scheduler death, or the deadline
    # (whichever is first) so migration still gets its window
    while time.monotonic() < deadline:
        if engine.drain_complete() or not loop.alive():
            break
        time.sleep(0.05)
    migrated = 0
    available = 0
    error = None
    if migrate is not None and loop.alive():
        from automodel_tpu.serving.fleet.kv_transfer import (
            KVTransferError,
            push_kv,
        )

        try:
            with loop.lock:
                hashes, kv = engine.export_hot_blocks()
            available = len(hashes)
            if hashes:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise KVTransferError(
                        "retire deadline expired before the prefix push"
                    )
                migrated = push_kv(
                    (str(migrate["host"]), int(migrate["port"])),
                    hashes, kv, engine.kv_geometry(),
                    timeout_s=remaining,
                )
        except Exception as e:
            error = f"{type(e).__name__}: {str(e)[:200]}"
            logger.warning(
                "scale-down prefix migration to %s failed (%s); "
                "degrading to plain drain", migrate, error,
            )
    if migrate is None:
        outcome = "migration_skipped"
    elif error is not None:
        outcome = "migration_failed"
    else:
        outcome = "migration_complete"
    if engine.on_record is not None:
        engine.on_record({
            "event": outcome,
            "ts": engine._wall_ts(),
            "migrated_blocks": migrated,
            "hot_blocks": available,
            "retire_s": round(time.monotonic() - t0, 6),
            **({"error": error} if error else {}),
        })
    return outcome


def _serve_http_forever(
    engine, tokenizer, http_section, serve_cfg, kv_store=None, kv_server=None
) -> int:
    port = int(http_section["port"])
    host = str(http_section.get("host", "127.0.0.1"))
    drain_cfg = serve_cfg.drain
    if http_section.get("warmup", True):
        _warmup(engine)
        engine.note_ready()  # warmup flipped first_decode_done: stamp now
    state = {"rc": 0}

    def _retire(migrate, deadline_s: float):
        retire_sequence(engine, loop, migrate, deadline_s)
        state["rc"] = _drain_exit_code(drain_cfg)
        server.shutdown()

    server, loop = serve_http(
        engine, tokenizer, port, host=host, kv_store=kv_store,
        on_retire=_retire,
    )
    if kv_server is not None and serve_cfg.kv_spill.enabled:
        # peer /kv_fetch answers from the engine's pools, so the handler
        # must serialize with the scheduler: wired here — after the loop
        # (and its lock) exist — rather than at listener construction
        def _serve_fetch(chain_hashes):
            with loop.lock:
                return engine.fetch_prefix_blocks(chain_hashes)

        kv_server.fetch_handler = _serve_fetch

        # migration sink: a retiring peer's ``kv_push`` parks blocks in
        # this replica's spill tier (same lock discipline as /kv_fetch)
        def _serve_push(chain_hashes, kv):
            with loop.lock:
                return engine.receive_migrated_blocks(chain_hashes, kv)

        kv_server.push_handler = _serve_push

    def _drain_then_stop():
        # begin_drain only flips flags (GIL-atomic stores the scheduler
        # reads at its next iteration) — deliberately NOT taken under
        # loop.lock: if SIGTERM lands while a step is wedged (the stall
        # scenario), the scheduler holds the lock and the grace countdown
        # would never even start
        engine.begin_drain()
        # the scheduler thread keeps stepping: in-flight requests finish,
        # grace expiry cancels stragglers INSIDE engine.step — this thread
        # only watches for completion, with margin for a slow final step
        deadline = time.monotonic() + drain_cfg.grace_s + 10.0
        while time.monotonic() < deadline:
            if engine.drain_complete() or not loop.alive():
                break
            time.sleep(0.05)
        state["rc"] = _drain_exit_code(drain_cfg)
        server.shutdown()

    def _on_term():
        threading.Thread(
            target=_drain_then_stop, name="serve-drain", daemon=True
        ).start()

    handler = _install_drain_handler(engine, on_term=_on_term)
    print(
        json.dumps({
            "event": "serve_listening",
            "host": host, "port": server.server_address[1],
            "slots": serve_cfg.slots, "num_blocks": serve_cfg.num_blocks,
        }),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        loop.close()
        if handler is not None:
            handler.restore()
    return state["rc"]


def _serve_stdin(engine, tokenizer, serve_cfg) -> int:
    """stdin-JSONL: submit every line, print terminal records as they
    happen. A bad line is THAT client's error — it gets an error JSON line
    and the batch continues; crashing here would destroy every other
    request's in-flight work. SIGTERM drains: remaining input is not read,
    queued requests are rejected retriable, in-flight requests finish
    within the grace."""
    import queue as queue_mod

    from automodel_tpu.serving.engine import (
        EngineDraining,
        QueueFull,
        QuotaExceeded,
    )

    drain_cfg = serve_cfg.drain
    handler = _install_drain_handler(engine)
    stdin = sys.stdin
    # a daemon reader thread feeds a queue: the scheduler loop never blocks
    # on stdin (completions stream out while input sits idle-open, SIGTERM
    # is observed between steps instead of inside a blocked read — PEP 475
    # would resume the read and swallow the drain), and select()'s
    # buffered-IO blind spot is avoided entirely
    lines_q: "queue_mod.Queue[str]" = queue_mod.Queue()

    def _reader():
        while True:
            line = stdin.readline()
            lines_q.put(line)  # "" = EOF sentinel
            if line == "":
                return

    threading.Thread(target=_reader, name="serve-stdin", daemon=True).start()

    counts = {"submitted": 0, "bad": 0}

    def handle_line(line: str, lineno: int) -> None:
        line = line.strip()
        if not line:
            return
        rid = None
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request line is not a JSON object")
            rid = req.get("id")
            ids = _encode_prompt(req, tokenizer)
            ctx = (
                engine.tracer.parse(req.get("traceparent"))
                if engine.tracer is not None else None
            )
            while True:
                try:
                    engine.submit(
                        ids,
                        request_id=str(rid) if rid is not None else None,
                        max_new_tokens=req.get("max_new_tokens"),
                        deadline_s=req.get("deadline_s"),
                        max_queue_wait_s=req.get("max_queue_wait_s"),
                        trace=ctx,
                        return_logprobs=bool(req.get("return_logprobs")),
                        tenant=req.get("tenant"),
                        tier=req.get("tier"),
                    )
                    break
                except QueueFull:
                    # bounded queue + unbounded stdin: absorb backpressure
                    # by draining a step — but if the step retired nothing
                    # and the queue is still full, SHED explicitly instead
                    # of spinning
                    before = engine.completed_total + engine.failed_total
                    for rec in engine.step():
                        _emit(rec, tokenizer)
                    if (
                        engine.completed_total + engine.failed_total == before
                        and engine.queue_depth >= engine.config.max_queue
                    ):
                        raise
        except QueueFull as e:
            # exactly ONE tier-labeled shed per given-up request, however
            # many backpressure retries the loop above absorbed
            engine.record_shed(
                request_id=str(rid) if rid is not None else None,
                tenant=req.get("tenant"), tier=req.get("tier"),
            )
            err = {
                "error": f"line {lineno}: {e}",
                "retriable": True, "reason": "shed",
            }
            if rid is not None:
                err["id"] = rid
            print(json.dumps(err), flush=True)
        except QuotaExceeded as e:
            engine.record_quota(
                request_id=str(rid) if rid is not None else None,
                tenant=e.tenant, tier=e.tier,
            )
            err = {
                "error": f"line {lineno}: {e}",
                "retriable": True, "reason": "quota",
                "tenant": e.tenant, "tier": e.tier,
            }
            if rid is not None:
                err["id"] = rid
            print(json.dumps(err), flush=True)
        except EngineDraining as e:
            err = {
                "error": f"line {lineno}: {e}",
                "retriable": True, "reason": "draining",
            }
            if rid is not None:
                err["id"] = rid
            print(json.dumps(err), flush=True)
        except (ValueError, TypeError) as e:
            counts["bad"] += 1
            err = {"error": f"line {lineno}: {e}"}
            if rid is not None:
                err["id"] = rid
            print(json.dumps(err), flush=True)
        else:
            counts["submitted"] += 1

    lineno = 0
    eof = False
    while not eof:
        if handler is not None and handler.preempted and not engine.draining:
            engine.begin_drain()
        if engine.draining:
            break
        got_line = False
        try:
            line = lines_q.get_nowait()
            if line == "":
                eof = True
            else:
                lineno += 1
                handle_line(line, lineno)
                got_line = True
        except queue_mod.Empty:
            pass
        # drain opportunistically so early completions stream out while
        # later lines are still being read (or while stdin sits idle-open)
        if not engine.idle():
            for rec in engine.step():
                _emit(rec, tokenizer)
        elif not got_line and not eof:
            engine.touch_watchdog()
            time.sleep(0.02)

    def _reject_buffered_lines() -> None:
        # lines the reader thread already pulled off the pipe are gone from
        # the client's side — dropping them silently on drain would break
        # the one-response-per-request contract, so each gets an explicit
        # retriable error line (they were never submitted, so there is no
        # engine record to emit)
        while True:
            try:
                line = lines_q.get_nowait()
            except queue_mod.Empty:
                return
            line = line.strip()
            if not line:
                continue
            err = {
                "error": "server is draining — retry against another replica",
                "retriable": True, "reason": "draining",
            }
            try:
                req = json.loads(line)
                if isinstance(req, dict) and req.get("id") is not None:
                    err["id"] = req["id"]
            except ValueError:
                pass
            print(json.dumps(err), flush=True)

    # EOF or drain: finish the remaining work. A SIGTERM landing in THIS
    # phase must still start the drain — the batch (pipe-then-close) case
    # spends almost its whole life here, after EOF. Iterations bounded by
    # the same analytic guard as ServingEngine.run.
    per_req = (
        -(-engine.config.max_seq_len // engine.config.prefill_chunk)
        + engine.config.max_seq_len
    )
    iter_bound = 64 + (engine.queue_depth + engine.busy_slots + 1) * (per_req + 2)
    drained_rc = None
    for _ in range(iter_bound):
        if handler is not None and handler.preempted and not engine.draining:
            engine.begin_drain()
        if engine.draining:
            _reject_buffered_lines()
            # engine.step rejects the queue retriable and cancels in-flight
            # requests once drain.grace_s expires
            if engine.drain_complete():
                drained_rc = _drain_exit_code(drain_cfg)
                break
        elif engine.idle():
            break
        for rec in engine.step():
            _emit(rec, tokenizer)
    else:
        raise RuntimeError(
            f"serving engine failed to drain within {iter_bound} iterations "
            f"(queue={engine.queue_depth}, busy={engine.busy_slots})"
        )
    if handler is not None:
        handler.restore()
    if drained_rc is not None:
        _reject_buffered_lines()  # lines that raced in during the drain
        return drained_rc
    if counts["submitted"] == 0:
        print(
            "no requests: pipe JSONL lines like "
            '{"prompt": "1 2 3", "max_new_tokens": 8} into stdin',
            file=sys.stderr,
        )
        return 2
    return 0 if counts["bad"] == 0 else 1


def _emit(rec: dict, tokenizer: Any) -> None:
    out = dict(rec)
    if out.get("event") == "serve_engine_event":
        # engine-level evidence (stall/rebuild) — pass through as-is
        print(json.dumps(out), flush=True)
        return
    out["completion"] = _decode_completion(out.pop("tokens", []), tokenizer)
    out.pop("event", None)
    print(json.dumps(out), flush=True)
