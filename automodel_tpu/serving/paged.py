"""Jitted paged-KV programs: block-table gather → existing cache attention.

Two programs, compiled once each per (chunk length, table width):

- **chunk prefill**: one prompt chunk (static padded length, traced offset)
  through the model's cached-attend path — queries attend the WHOLE gathered
  cache view under per-query position-tag masks (generation.kv_cache
  ``chunk_ctx`` + the 3D ``kv_mask`` in ops.attention.sdpa), so chunk N sees
  chunks 0..N-1 and any prefix-cache hit without recomputing them. This is
  what lets the scheduler interleave a long prompt with the running decode
  wave: each engine iteration spends at most one chunk of prefill compute.
- **paged decode**: one token per active slot. The per-slot block tables
  gather the pool into a contiguous ``[L, B, C_view, N_kv, H]`` view (an XLA
  gather — the TPU-native expression of paged attention; a bespoke
  Mosaic gather-attend kernel is the known next optimization, noted in
  docs/serving.md), the view feeds the UNCHANGED ``decode_ctx`` →
  ``sdpa_decode`` path, and the single written token scatters back to its
  (block, offset). Inactive slots write to scratch block 0.

Both programs donate the pool arrays, so the pool is updated in place
(no transient second copy of the whole cache).

View-position invariant: the serving engine uses the FULL layout only
(slot j of a sequence's view holds absolute position j), so a sequence's
view capacity must exceed its highest written position — the engine sizes
tables as ``ceil((max_seq_len + prefill_chunk) / block_size)`` blocks and
admission enforces ``prompt + max_new <= max_seq_len``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from automodel_tpu.generation import kv_cache
from automodel_tpu.generation.sampling import SamplingConfig, sample


def _logits_of(primary: Any) -> jnp.ndarray:
    return primary[0] if isinstance(primary, tuple) else primary


def init_pool(
    num_layers: int,
    num_blocks: int,
    block_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The HBM block pool: (k, v), each [L, NB, BS, N_kv, H]."""
    shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def place_pool(pool_k, pool_v, mesh_ctx):
    """Shard the pool: KV heads over the tensor axes (each TP shard owns its
    heads' blocks — the same no-cache-collective decode layout as
    generation.kv_cache.place_cache); blocks are NOT batch-sharded (every
    sequence's table may point anywhere in the pool). Non-divisible axes are
    dropped (replicated)."""
    if mesh_ctx is None:
        return pool_k, pool_v
    from jax.sharding import NamedSharding, PartitionSpec as P

    names = kv_cache.usable_axes(mesh_ctx, pool_k.shape[3], "tensor")
    sh = NamedSharding(mesh_ctx.mesh, P(None, None, None, names, None))
    return jax.device_put(pool_k, sh), jax.device_put(pool_v, sh)


def _gather_view(pool: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """pool [L, NB, BS, Nkv, H] + tables [B, NBseq] → view [L, B, Cv, Nkv, H]
    (Cv = NBseq * BS): each sequence's blocks, concatenated in table order —
    full layout, view position == absolute token position."""
    L, _, BS, Nkv, H = pool.shape
    B, NBseq = tables.shape
    return pool[:, tables].reshape(L, B, NBseq * BS, Nkv, H)


def build_chunk_prefill_fn(apply: Callable, chunk_len: int) -> Callable:
    """→ jitted ``chunk(params, pool_k, pool_v, table [NBseq], chunk_ids
    [chunk_len], start, real_len)`` → ``(last_logits [V] fp32, pool_k,
    pool_v)`` for ONE sequence. ``start`` is the absolute position of the
    chunk's first token (= prefix-cache hit length for the first chunk);
    ``real_len`` the unpadded chunk length; ``last_logits`` the logits of
    token ``start + real_len - 1`` (the first-token sample source once the
    whole prompt is in)."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def chunk(params, pool_k, pool_v, table, chunk_ids, start, real_len):
        L, _, BS, Nkv, H = pool_k.shape
        NBseq = table.shape[0]
        tables = table[None, :]
        view = kv_cache.KVCache(
            k=_gather_view(pool_k, tables),
            v=_gather_view(pool_v, tables),
            pos=jnp.full((1, NBseq * BS), -1, jnp.int32),
            lengths=jnp.zeros((1,), jnp.int32),
        )
        kvc, ctx = kv_cache.chunk_ctx(
            view, chunk_len, start[None].astype(jnp.int32),
            real_len[None].astype(jnp.int32),
        )
        positions = (
            start.astype(jnp.int32) + jnp.arange(chunk_len, dtype=jnp.int32)
        )[None, :]
        primary, new_view = apply(
            params, chunk_ids[None, :], position_ids=positions, cache=(kvc, ctx)
        )
        logits = _logits_of(primary)[0].astype(jnp.float32)  # [chunk_len, V]
        last = logits[real_len - 1]
        # scatter the whole view back: fresh blocks carry the chunk's new
        # K/V; shared prefix blocks rewrite their own gathered bytes
        # (identical values); padded table entries write to scratch block 0
        newk = new_view.k.reshape(L, NBseq, BS, Nkv, H)
        newv = new_view.v.reshape(L, NBseq, BS, Nkv, H)
        pool_k = pool_k.at[:, table].set(newk)
        pool_v = pool_v.at[:, table].set(newv)
        return last, pool_k, pool_v

    return chunk


def build_paged_decode_fn(
    apply: Callable,
    sampling: SamplingConfig,
    pad_id: int = 0,
) -> Callable:
    """→ jitted ``step(params, pool_k, pool_v, tables [B, NBseq], lengths
    [B], cur [B], active [B] bool, key, step_idx)`` → ``(next_tokens [B],
    pool_k, pool_v)``.

    One continuous-batching decode step: every ACTIVE slot advances one
    token (its K/V written at ``(table[len // BS], len % BS)``); inactive
    slots (free, or mid-prefill) compute junk that is masked from the
    sampled output and scattered into scratch block 0. Stop-token/length
    bookkeeping is the host scheduler's job — this program is stateless."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, pool_k, pool_v, tables, lengths, cur, active, key, step_idx):
        L, _, BS, Nkv, H = pool_k.shape
        B, NBseq = tables.shape
        Cv = NBseq * BS
        lengths = lengths.astype(jnp.int32)
        j = jnp.arange(Cv, dtype=jnp.int32)
        pos = jnp.where(j[None, :] < lengths[:, None], j[None, :], -1)
        view = kv_cache.KVCache(
            k=_gather_view(pool_k, tables),
            v=_gather_view(pool_v, tables),
            pos=pos.astype(jnp.int32),
            lengths=lengths,
        )
        kvc, ctx = kv_cache.decode_ctx(view)
        primary, new_view = apply(
            params, cur[:, None], position_ids=ctx.q_pos[:, None],
            cache=(kvc, ctx),
        )
        logits = _logits_of(primary)[:, -1].astype(jnp.float32)
        nxt = sample(logits, jax.random.fold_in(key, step_idx), sampling)
        nxt = jnp.where(active, nxt, jnp.int32(pad_id))
        # scatter exactly the written token back (full layout: the decode
        # write slot IS the absolute position lengths[b])
        b_idx = jnp.arange(B)
        tok_k = new_view.k[:, b_idx, lengths % Cv]  # [L, B, Nkv, H]
        tok_v = new_view.v[:, b_idx, lengths % Cv]
        blk = jnp.where(active, tables[b_idx, lengths // BS], 0)
        off = jnp.where(active, lengths % BS, 0)
        pool_k = pool_k.at[:, blk, off].set(tok_k)
        pool_v = pool_v.at[:, blk, off].set(tok_v)
        return nxt, pool_k, pool_v

    return step
