"""Jitted paged-KV programs: chunk prefill, paged decode (fused Pallas
kernel or XLA-gather fallback), and the speculative draft/verify pair.

The pool is a :class:`PagedKV`: per-layer k/v block arrays
``[L, NB, BS, N_kv, H]`` in the model's compute dtype, or int8 with
per-(token row, kv head) fp32 scales ``[L, NB, BS, N_kv]`` riding
alongside (``serving.kv_cache_dtype: int8`` — roughly half the bytes per
resident token, so ~2× the sequences per chip on the same HBM budget).

Programs, each compiled once per static shape and donating the pool:

- **chunk prefill** — one prompt chunk (static padded length, traced
  offset) for ONE sequence through the model's cached-attend path over the
  gathered (dequantized) view; the whole table scatters back
  quantize-on-write. Chunking is what lets a long prompt interleave with
  the running decode wave.
- **paged decode** — one token per active slot. Two backends, selected by
  ``serving.decode_kernel`` / ``AUTOMODEL_PAGED_DECODE`` / the autotune
  table (``autotune.paged_key``):

  * ``fused`` — the model's attention runs the Pallas paged kernel
    (ops/paged_attention.py) that indexes the pool IN PLACE through the
    per-slot block tables (scalar-prefetch DMA per block, int8 dequant
    in-kernel); the only pool write is the one token row's scatter. No
    gather → contiguous view → scatter-back round trip.
  * ``gather`` — the historical XLA path (block-table gather → the
    unchanged cached-attend → single-token scatter-back), kept as the
    fallback and the A/B baseline ``tools/kernel_bench.py`` races the
    kernel against.

- **draft propose / verify** — speculative decoding (Leviathan et al.
  2023): the draft model proposes ``spec_k`` tokens per slot (``spec_k``
  cheap decode steps over its OWN parallel pool, sharing the target's
  block tables so rollback is shared bookkeeping), then ONE batched
  verify forward pushes ``[cur, d_1..d_k]`` through the target —
  a chunk-shaped cached attend at per-slot offsets — and the rejection
  rule (generation.sampling.speculative_verify) commits the accepted
  prefix + one correction/bonus token. Rollback is a LENGTH DECREMENT:
  K/V of rejected tokens stays in the pool but sits past the committed
  length, which every attend masks out and the next round overwrites —
  no copies, no block churn.

View-position invariant (full layout only): slot j of a sequence's
view/table holds absolute position j, so admission sizes tables with
enough headroom for ``max(prefill_chunk, spec_k + 1)`` writes past
``max_seq_len`` (ServeConfig.table_blocks).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.generation import kv_cache
from automodel_tpu.generation.sampling import (
    SamplingConfig,
    sample,
    sample_with_logprobs,
    speculative_verify,
)
from automodel_tpu.ops.paged_attention import dequantize_kv, quantize_kv_rows


def _logits_of(primary: Any) -> jnp.ndarray:
    return primary[0] if isinstance(primary, tuple) else primary


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKV:
    """The HBM block pool. ``k``/``v`` are each either a raw array
    ``[L, NB, BS, N_kv, H]`` or, when quantized, a ``(values int8,
    scales fp32 [L, NB, BS, N_kv])`` pair — the same pytree shape the
    model's layer scan slices per layer."""

    k: Any
    v: Any

    @property
    def quantized(self) -> bool:
        return isinstance(self.k, tuple)

    @property
    def values_shape(self) -> tuple:
        return (self.k[0] if self.quantized else self.k).shape

    @property
    def nbytes(self) -> int:
        return int(sum(x.nbytes for x in jax.tree.leaves((self.k, self.v))))


def init_pool(
    num_layers: int,
    num_blocks: int,
    block_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantized: bool = False,
) -> PagedKV:
    """Zeroed pool; ``quantized`` stores int8 values + fp32 row scales."""
    shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
    if quantized:
        sshape = shape[:-1]

        def side():
            return (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32))

        return PagedKV(k=side(), v=side())
    return PagedKV(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def place_pool(pool: PagedKV, mesh_ctx) -> PagedKV:
    """Shard the pool: KV heads over the tensor axes (each TP shard owns its
    heads' blocks — the same no-cache-collective decode layout as
    generation.kv_cache.place_cache); blocks are NOT batch-sharded (every
    sequence's table may point anywhere in the pool). Non-divisible axes are
    dropped (replicated). Int8 scales shard on the same kv-head axis."""
    if mesh_ctx is None:
        return pool
    from jax.sharding import NamedSharding, PartitionSpec as P

    nkv = pool.values_shape[3]
    names = kv_cache.usable_axes(mesh_ctx, nkv, "tensor")
    val_s = NamedSharding(mesh_ctx.mesh, P(None, None, None, names, None))
    scale_s = NamedSharding(mesh_ctx.mesh, P(None, None, None, names))

    def place_side(side):
        if isinstance(side, tuple):
            return (
                jax.device_put(side[0], val_s),
                jax.device_put(side[1], scale_s),
            )
        return jax.device_put(side, val_s)

    return PagedKV(k=place_side(pool.k), v=place_side(pool.v))


# -- gather / scatter (the XLA fallback path + chunk prefill) ----------------


def _gather_side(side, tables: jnp.ndarray, dtype) -> jnp.ndarray:
    """One pool side + tables [B, NBseq] → contiguous view
    [L, B, Cv, Nkv, H] in ``dtype`` (int8 blocks dequantize here)."""
    if isinstance(side, tuple):
        vals, scales = side
        L, _, BS, Nkv, H = vals.shape
        B, NBseq = tables.shape
        g = dequantize_kv(vals[:, tables], scales[:, tables], dtype)
        return g.reshape(L, B, NBseq * BS, Nkv, H)
    L, _, BS, Nkv, H = side.shape
    B, NBseq = tables.shape
    return side[:, tables].reshape(L, B, NBseq * BS, Nkv, H)


def _scatter_rows(side, rows: jnp.ndarray, blk: jnp.ndarray, off: jnp.ndarray):
    """Scatter written token rows [L, B, S, Nkv, H] back into one pool side
    at (blk, off) [B, S] — quantize-on-write when the side is int8."""
    if isinstance(side, tuple):
        vals, scales = side
        q, s = quantize_kv_rows(rows)
        return (vals.at[:, blk, off].set(q), scales.at[:, blk, off].set(s))
    return side.at[:, blk, off].set(rows.astype(side.dtype))


def _scatter_table(side, new: jnp.ndarray, table: jnp.ndarray):
    """Scatter a whole single-sequence view [L, NBseq, BS, Nkv, H] back
    (chunk prefill): fresh blocks carry the chunk's new K/V; shared prefix
    blocks rewrite their own bytes (quantize∘dequantize is idempotent, so
    int8 prefix blocks are bit-identical); padded table entries write to
    scratch block 0."""
    if isinstance(side, tuple):
        vals, scales = side
        q, s = quantize_kv_rows(new)
        return (vals.at[:, table].set(q), scales.at[:, table].set(s))
    return side.at[:, table].set(new.astype(side.dtype))


# gather scatter-back targets resolve through the SAME helper the fused
# path's paged_ctx uses — the two backends can never write to different cells
_write_targets = kv_cache.paged_write_targets


# -- KV extraction / injection (disaggregated prefill→decode handoff) --------


def extract_blocks(pool: PagedKV, blocks) -> tuple:
    """Pull one request's block rows out of the pool to host memory —
    ``(k, v)``, each ``[L, nb, BS, Nkv, H]`` (or ``(int8 values, fp32
    scales)`` pairs for quantized pools). The prefill replica ships exactly
    these bytes; positions past the prompt inside the last block are junk
    the receiver's attend masks out (and the first decode write overwrites
    the next row before it is ever attended)."""
    import numpy as np

    idx = jnp.asarray(np.asarray(blocks, np.int32))

    def side(s):
        if isinstance(s, tuple):
            return (
                np.asarray(jax.device_get(s[0][:, idx])),
                np.asarray(jax.device_get(s[1][:, idx])),
            )
        return np.asarray(jax.device_get(s[:, idx]))

    return side(pool.k), side(pool.v)


@functools.lru_cache(maxsize=32)
def _inject_fn(nb: int, quantized: bool):
    """Jitted whole-block scatter for a KV handoff — donated pool, one
    compiled program per (block count, quantization). Block counts follow
    prompt lengths, so a production front should bucket prompts to bound
    compile churn (docs/serving.md); the handoff itself is correct at any
    nb."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def inject(pool: PagedKV, table, k_rows, v_rows):
        def side(s, rows):
            if isinstance(s, tuple):
                return (
                    s[0].at[:, table].set(jnp.asarray(rows[0], s[0].dtype)),
                    s[1].at[:, table].set(jnp.asarray(rows[1], s[1].dtype)),
                )
            return s.at[:, table].set(jnp.asarray(rows, s.dtype))

        return PagedKV(k=side(pool.k, k_rows), v=side(pool.v, v_rows))

    return inject


def inject_blocks(pool: PagedKV, blocks, kv: dict) -> PagedKV:
    """Scatter shipped block rows ``kv = {"k": rows, "v": rows}`` into the
    pool cells named by ``blocks`` — the receiving half of the prefill→
    decode handoff. Int8 payloads land their (values, scales) pairs as-is
    (no requantization: the round trip is bit-identical by construction);
    the scatter rides the same ``.at[:, table]`` cell addressing as chunk
    prefill's scatter-back, so sender and receiver land rows in the same
    cells for the same table."""
    import numpy as np

    table = jnp.asarray(np.asarray(blocks, np.int32))
    fn = _inject_fn(int(table.shape[0]), pool.quantized)
    return fn(pool, table, kv["k"], kv["v"])


# -- host-side KV payload surgery (spill tier + /kv_fetch) --------------------
# Numpy-only helpers over the ``{"k": rows|(values, scales), "v": ...}``
# payload shape ``extract_blocks``/``inject_blocks`` speak: the host spill
# tier parks ONE block per chain hash, and reload/peer-fetch re-assembles a
# consecutive run back into one inject — so payloads need slicing and
# concatenation along the block axis (axis 1) without touching a device.


def split_kv_blocks(kv: dict) -> list[dict]:
    """One payload per block: ``[L, nb, ...]`` arrays → nb ``[L, 1, ...]``
    payloads (copies, so a parked block never pins the whole extract)."""
    import numpy as np

    def slice_side(s, i):
        if isinstance(s, tuple):
            return tuple(np.ascontiguousarray(a[:, i : i + 1]) for a in s)
        return np.ascontiguousarray(s[:, i : i + 1])

    first = kv["k"][0] if isinstance(kv["k"], tuple) else kv["k"]
    return [
        {"k": slice_side(kv["k"], i), "v": slice_side(kv["v"], i)}
        for i in range(int(first.shape[1]))
    ]


def concat_kv_blocks(payloads: list[dict]) -> dict:
    """Inverse of :func:`split_kv_blocks`: re-assemble consecutive
    single-block payloads into one ``[L, nb, ...]`` inject payload."""
    import numpy as np

    if not payloads:
        raise ValueError("concat_kv_blocks: empty payload list")

    def cat_side(name):
        first = payloads[0][name]
        if isinstance(first, tuple):
            return tuple(
                np.concatenate([p[name][j] for p in payloads], axis=1)
                for j in range(len(first))
            )
        return np.concatenate([p[name] for p in payloads], axis=1)

    return {"k": cat_side("k"), "v": cat_side("v")}


def bucket_blocks(n: int) -> int:
    """Next power of two ≥ n — the block-count buckets the spill/reload
    paths pad to. Extract/inject compile one XLA program per distinct
    block count; eviction batches and reload runs have arbitrary sizes, so
    unbucketed calls would compile (and on a busy host, stall TTFT) per
    novel size. Buckets bound the program count to log2(pool)."""
    if n < 1:
        raise ValueError(f"bucket_blocks({n})")
    b = 1
    while b < n:
        b <<= 1
    return b


def pad_kv_blocks(kv: dict, nb: int) -> dict:
    """Pad a payload out to ``nb`` blocks by repeating its last block row.
    The caller aims the padding rows at the scratch block (id 0), whose
    contents are junk by contract — so a bucketed inject is bit-identical
    to an exact one everywhere that is ever attended."""
    import numpy as np

    def pad_side(s):
        if isinstance(s, tuple):
            return tuple(pad_side(a) for a in s)
        short = nb - int(s.shape[1])
        if short <= 0:
            return s
        reps = np.repeat(s[:, -1:], short, axis=1)
        return np.concatenate([s, reps], axis=1)

    return {"k": pad_side(kv["k"]), "v": pad_side(kv["v"])}


def kv_nbytes(kv: dict) -> int:
    """Host bytes a payload occupies (scales included) — the spill tier's
    budget currency."""
    total = 0
    for side in (kv["k"], kv["v"]):
        for arr in side if isinstance(side, tuple) else (side,):
            total += int(arr.nbytes)
    return total


# -- forward cores -----------------------------------------------------------


def _gather_forward(
    apply: Callable, params, pool: PagedKV, tables, lengths, tokens, active,
    compute_dtype, block_size: int,
):
    """tokens [B, S] at per-slot offsets through the GATHERED view (chunk
    cached-attend), scattering the S written rows back. → (logits [B,S,V]
    fp32, new pool). S = 1 is the classic paged decode step."""
    B, S = tokens.shape
    NBseq = tables.shape[1]
    BS = pool.values_shape[2]
    lengths = lengths.astype(jnp.int32)
    view = kv_cache.KVCache(
        k=_gather_side(pool.k, tables, compute_dtype),
        v=_gather_side(pool.v, tables, compute_dtype),
        pos=jnp.full((B, NBseq * BS), -1, jnp.int32),  # chunk_ctx retags
        lengths=jnp.zeros((B,), jnp.int32),
    )
    kvc, ctx = kv_cache.chunk_ctx(
        view, S, lengths, jnp.where(active, S, 0).astype(jnp.int32)
    )
    positions = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    primary, new_view = apply(
        params, tokens, position_ids=positions, cache=(kvc, ctx)
    )
    logits = _logits_of(primary).astype(jnp.float32)
    b_idx = jnp.arange(B)
    rows_k = new_view.k[:, b_idx[:, None], positions]  # [L, B, S, Nkv, H]
    rows_v = new_view.v[:, b_idx[:, None], positions]
    blk, off = _write_targets(tables, lengths, S, active, block_size)
    return logits, PagedKV(
        k=_scatter_rows(pool.k, rows_k, blk, off),
        v=_scatter_rows(pool.v, rows_v, blk, off),
    )


def _fused_forward(
    apply: Callable, params, pool: PagedKV, tables, lengths, tokens, active,
    block_size: int, interpret: bool,
):
    """tokens [B, S] through the paged-mode cache: per-layer writes scatter
    the S rows straight into the pool slices (quantize-on-write) and
    attention runs the fused Pallas kernel over the pool via the tables —
    no view is ever materialized. → (logits [B,S,V] fp32, new pool)."""
    B, S = tokens.shape
    lengths = lengths.astype(jnp.int32)
    kvc = kv_cache.KVCache(
        k=pool.k, v=pool.v,
        pos=jnp.zeros((B, 1), jnp.int32), lengths=lengths,
    )
    kvc, ctx = kv_cache.paged_ctx(
        kvc, tables, lengths, S, active, block_size, interpret=interpret
    )
    positions = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    primary, new_kvc = apply(
        params, tokens, position_ids=positions, cache=(kvc, ctx)
    )
    return _logits_of(primary).astype(jnp.float32), PagedKV(
        k=new_kvc.k, v=new_kvc.v
    )


def _make_forward(
    apply: Callable, backend: str, block_size: int, compute_dtype,
    interpret: bool,
) -> Callable:
    if backend == "fused":
        return functools.partial(
            _fused_forward, apply, block_size=block_size, interpret=interpret
        )
    return functools.partial(
        _gather_forward, apply,
        compute_dtype=compute_dtype, block_size=block_size,
    )


# -- programs ----------------------------------------------------------------


def build_chunk_prefill_fn(
    apply: Callable, chunk_len: int, compute_dtype=None
) -> Callable:
    """→ jitted ``chunk(params, pool, table [NBseq], chunk_ids [chunk_len],
    start, real_len)`` → ``(last_logits [V] fp32, pool)`` for ONE sequence.
    ``start`` is the absolute position of the chunk's first token (= the
    prefix-cache hit length for the first chunk); ``real_len`` the unpadded
    chunk length; ``last_logits`` the logits of token ``start + real_len -
    1`` (the first-token sample source once the whole prompt is in).
    Always the gathered-view path: prefill is compute-bound and one
    compiled program serves every offset."""

    @functools.partial(jax.jit, donate_argnums=(1,))
    def chunk(params, pool: PagedKV, table, chunk_ids, start, real_len):
        L, _, BS, Nkv, H = pool.values_shape
        NBseq = table.shape[0]
        cd = compute_dtype or (
            pool.k.dtype if not pool.quantized else jnp.bfloat16
        )
        tables = table[None, :]
        view = kv_cache.KVCache(
            k=_gather_side(pool.k, tables, cd),
            v=_gather_side(pool.v, tables, cd),
            pos=jnp.full((1, NBseq * BS), -1, jnp.int32),
            lengths=jnp.zeros((1,), jnp.int32),
        )
        kvc, ctx = kv_cache.chunk_ctx(
            view, chunk_len, start[None].astype(jnp.int32),
            real_len[None].astype(jnp.int32),
        )
        positions = (
            start.astype(jnp.int32) + jnp.arange(chunk_len, dtype=jnp.int32)
        )[None, :]
        primary, new_view = apply(
            params, chunk_ids[None, :], position_ids=positions, cache=(kvc, ctx)
        )
        logits = _logits_of(primary)[0].astype(jnp.float32)  # [chunk_len, V]
        last = logits[real_len - 1]
        newk = new_view.k.reshape(L, NBseq, BS, Nkv, H)
        newv = new_view.v.reshape(L, NBseq, BS, Nkv, H)
        return last, PagedKV(
            k=_scatter_table(pool.k, newk, table),
            v=_scatter_table(pool.v, newv, table),
        )

    return chunk


def build_paged_decode_fn(
    apply: Callable,
    sampling: SamplingConfig,
    pad_id: int = 0,
    *,
    backend: str = "gather",
    block_size: int = 16,
    compute_dtype=jnp.bfloat16,
    interpret: bool = False,
    with_logprobs: bool = False,
) -> Callable:
    """→ jitted ``step(params, pool, tables [B, NBseq], lengths [B], cur
    [B], active [B] bool, key, step_idx)`` → ``(next_tokens [B], pool)``,
    or ``(next_tokens [B], logprobs [B] fp32, pool)`` when
    ``with_logprobs`` — the sampled token's log-probability under the RAW
    distribution (see ``sample_with_logprobs``), masked to 0.0 on
    inactive slots.

    One continuous-batching decode step: every ACTIVE slot advances one
    token (its K/V written at ``(table[len // BS], len % BS)``); inactive
    slots (free, or mid-prefill) compute junk that is masked from the
    sampled output and scattered into scratch block 0. Stop-token/length
    bookkeeping is the host scheduler's job — this program is stateless."""
    forward = _make_forward(apply, backend, block_size, compute_dtype, interpret)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(params, pool, tables, lengths, cur, active, key, step_idx):
        logits, pool = forward(
            params, pool, tables, lengths, cur[:, None], active
        )
        skey = jax.random.fold_in(key, step_idx)
        if with_logprobs:
            nxt, logp = sample_with_logprobs(logits[:, -1], skey, sampling)
            nxt = jnp.where(active, nxt, jnp.int32(pad_id))
            logp = jnp.where(active, logp, jnp.float32(0.0))
            return nxt, logp, pool
        nxt = sample(logits[:, -1], skey, sampling)
        nxt = jnp.where(active, nxt, jnp.int32(pad_id))
        return nxt, pool

    return step


def build_draft_propose_fn(
    draft_apply: Callable,
    sampling: SamplingConfig,
    spec_k: int,
    pad_id: int = 0,
    *,
    backend: str = "gather",
    block_size: int = 16,
    compute_dtype=jnp.bfloat16,
    interpret: bool = False,
) -> Callable:
    """→ jitted ``propose(draft_params, draft_pool, tables, lengths, cur,
    active, key, step_idx)`` → ``(draft_tokens [B, k], draft_logits
    [B, k, V] fp32, draft_pool)``: ``spec_k`` sequential draft decode
    steps inside one program, each writing the draft's K/V at the shared
    block-table positions. Draft keys fold ``(step, 1 + i)`` so proposal
    streams never collide with the verify correction stream."""
    forward = _make_forward(
        draft_apply, backend, block_size, compute_dtype, interpret
    )

    @functools.partial(jax.jit, donate_argnums=(1,))
    def propose(params, pool, tables, lengths, cur, active, key, step_idx):
        kstep = jax.random.fold_in(key, step_idx)
        toks, logs = [], []
        length, c = lengths.astype(jnp.int32), cur
        for i in range(spec_k):
            logits, pool = forward(
                params, pool, tables, length, c[:, None], active
            )
            lg = logits[:, -1]
            nxt = sample(lg, jax.random.fold_in(kstep, 1 + i), sampling)
            nxt = jnp.where(active, nxt, jnp.int32(pad_id))
            toks.append(nxt)
            logs.append(lg)
            length = length + 1
            c = nxt
        return jnp.stack(toks, axis=1), jnp.stack(logs, axis=1), pool

    return propose


def build_verify_fn(
    apply: Callable,
    sampling: SamplingConfig,
    spec_k: int,
    pad_id: int = 0,
    *,
    backend: str = "gather",
    block_size: int = 16,
    compute_dtype=jnp.bfloat16,
    interpret: bool = False,
) -> Callable:
    """→ jitted ``verify(params, pool, tables, lengths, cur, drafts
    [B, k], draft_logits [B, k, V], active, key, step_idx)`` →
    ``(tokens [B, k+1], n_commit [B], pool)``: ONE batched forward over
    the fed chunk ``[cur, d_1..d_k]`` at per-slot offsets (the verify
    attend is chunk-shaped — per-query causal masks over the paged
    cache), then the rejection rule. The pool keeps the K/V of every fed
    token; rejected tails sit past the committed length the host keeps,
    masked out of all future attends — rollback without copies."""
    forward = _make_forward(apply, backend, block_size, compute_dtype, interpret)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def verify(
        params, pool, tables, lengths, cur, drafts, draft_logits, active,
        key, step_idx,
    ):
        fed = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
        logits, pool = forward(params, pool, tables, lengths, fed, active)
        kstep = jax.random.fold_in(jax.random.fold_in(key, step_idx), 0)
        toks, n = speculative_verify(logits, draft_logits, drafts, kstep, sampling)
        n = jnp.where(active, n, 0).astype(jnp.int32)
        toks = jnp.where(active[:, None], toks, jnp.int32(pad_id))
        return toks, n, pool

    return verify
