"""Slurm submission for multi-host TPU jobs.

Parity: reference launcher (components/launcher/slurm/ — SlurmConfig
config.py:43, sbatch template template.py:91, submit utils.py:65). On TPU
pods each host runs the SAME single-controller program; `srun` starts one
task per host and JAX discovers peers through `jax.distributed.initialize`
(coordinator = task 0), replacing the reference's torchrun rendezvous.
"""

from __future__ import annotations

import dataclasses
import subprocess
from pathlib import Path
from typing import Optional, Sequence

SBATCH_TEMPLATE = """#!/bin/bash
#SBATCH --job-name={job_name}
#SBATCH --nodes={nodes}
#SBATCH --ntasks-per-node=1
#SBATCH --time={time_limit}
{extra_directives}

export JAX_COORDINATOR_ADDRESS=$(scontrol show hostnames $SLURM_JOB_NODELIST | head -n1):{coordinator_port}
export JAX_NUM_PROCESSES=$SLURM_NTASKS
{env_exports}

srun --kill-on-bad-exit=1 bash -c '
export JAX_PROCESS_ID=$SLURM_PROCID
{container_prefix}python -m automodel_tpu.cli.app {command} {domain} -c {config_path} {overrides}
rc=$?
{marker_line}exit $rc
'
rc=$?
{requeue_block}exit $rc
"""

# exit {requeue_exit_code} (REQUEUE_EXIT_CODE, resilience/preemption.py)
# means "preempted; emergency checkpoint committed — run me again": requeue
# this job instead of failing it. Auto-resume picks up the newest
# manifest-verified checkpoint on restart. The hang watchdog
# (resilience/watchdog.py) exits with the SAME code when a host wedges and
# a committed checkpoint exists, so a hung job gets recycled through this
# exact path instead of burning its reservation to the time limit.
#
# Multi-node wrinkle: with --kill-on-bad-exit=1, srun reports the HIGHEST
# task exit code — the first task to exit 75 triggers a SIGKILL of its
# peers (exit 137), which masks the 75. Each task therefore drops a marker
# file on the (shared) submit directory when it exits 75, and the epilogue
# requeues on rc==75 OR the marker.
MARKER_LINE = (
    'if [ $rc -eq {requeue_exit_code} ]; '
    'then touch ".preempted_$SLURM_JOB_ID"; fi\n'
)
REQUEUE_BLOCK = """if [ $rc -eq {requeue_exit_code} ] || [ -f ".preempted_$SLURM_JOB_ID" ]; then
  echo "preempted: requeueing $SLURM_JOB_ID"
  rm -f ".preempted_$SLURM_JOB_ID"
  scontrol requeue $SLURM_JOB_ID
fi
"""


@dataclasses.dataclass
class VolumeMapping:
    source: str
    dest: str

    def __str__(self) -> str:
        return f"{self.source}:{self.dest}"


@dataclasses.dataclass
class SlurmConfig:
    job_name: str = "automodel-tpu"
    nodes: int = 1
    time_limit: str = "04:00:00"
    account: Optional[str] = None
    partition: Optional[str] = None
    container_image: Optional[str] = None
    container_mounts: Sequence[VolumeMapping] = ()
    coordinator_port: int = 8476
    env: dict = dataclasses.field(default_factory=dict)
    extra_directives: Sequence[str] = ()
    job_dir: str = "slurm_jobs"
    # preemption-aware requeue (resilience/): a task exiting with
    # REQUEUE_EXIT_CODE gets `scontrol requeue`d; requires the job to be
    # requeueable, so the --requeue directive is emitted alongside. The
    # code itself is NOT configurable here — the trainer always exits
    # resilience.REQUEUE_EXIT_CODE, and a knob that only changed the
    # launcher side would silently break every requeue.
    requeue_on_preemption: bool = True
    # `--signal=TERM@N`: slurm delivers SIGTERM to the JOB STEP's tasks
    # (the python trainers — NOT `B:`, which would signal only the batch
    # shell, where no trap forwards it) N seconds before the time limit,
    # so hitting the wall clock becomes a normal preemption (emergency
    # checkpoint → exit 75 → requeue) instead of a SIGKILL that loses
    # everything since the last cadence save. 0 disables the directive.
    # `automodel_tpu serve` rides the same signal: SIGTERM starts a
    # graceful drain (in-flight requests finish within
    # serving.drain.grace_s — keep term_grace_s above it) and the server
    # exits REQUEUE_EXIT_CODE under slurm (serving.drain.requeue_exit:
    # auto), so a drained replica requeues via the same rc-75 rules.
    term_grace_s: int = 90


def render_sbatch(
    cfg: SlurmConfig, command: str, domain: str, config_path: str, overrides: Sequence[str] = ()
) -> str:
    directives = list(cfg.extra_directives)
    if cfg.account:
        directives.append(f"#SBATCH --account={cfg.account}")
    if cfg.partition:
        directives.append(f"#SBATCH --partition={cfg.partition}")
    from automodel_tpu.resilience.preemption import REQUEUE_EXIT_CODE

    requeue_block = marker_line = ""
    if cfg.requeue_on_preemption:
        directives.append("#SBATCH --requeue")
        directives.append("#SBATCH --open-mode=append")
        if cfg.term_grace_s > 0:
            directives.append(f"#SBATCH --signal=TERM@{cfg.term_grace_s}")
        requeue_block = REQUEUE_BLOCK.format(requeue_exit_code=REQUEUE_EXIT_CODE)
        marker_line = MARKER_LINE.format(requeue_exit_code=REQUEUE_EXIT_CODE)
    container_prefix = ""
    if cfg.container_image:
        mounts = ",".join(str(m) for m in cfg.container_mounts)
        mount_arg = f" --container-mounts={mounts}" if mounts else ""
        container_prefix = (
            f"srun --container-image={cfg.container_image}{mount_arg} "
        )
    env_exports = "\n".join(f"export {k}={v}" for k, v in cfg.env.items())
    return SBATCH_TEMPLATE.format(
        job_name=cfg.job_name,
        nodes=cfg.nodes,
        time_limit=cfg.time_limit,
        extra_directives="\n".join(directives),
        coordinator_port=cfg.coordinator_port,
        env_exports=env_exports,
        container_prefix=container_prefix,
        command=command,
        domain=domain,
        config_path=config_path,
        overrides=" ".join(overrides),
        requeue_block=requeue_block,
        marker_line=marker_line,
    )


def submit(
    cfg: SlurmConfig,
    command: str,
    domain: str,
    config_path: str,
    overrides: Sequence[str] = (),
    dry_run: bool = False,
) -> str:
    """Write the sbatch script and submit it; returns the script path (and
    prints the job id on submission)."""
    job_dir = Path(cfg.job_dir)
    job_dir.mkdir(parents=True, exist_ok=True)
    script = job_dir / f"{cfg.job_name}.sbatch"
    script.write_text(render_sbatch(cfg, command, domain, config_path, overrides))
    if not dry_run:
        out = subprocess.run(
            ["sbatch", str(script)], check=True, capture_output=True, text=True
        )
        print(out.stdout.strip())
    return str(script)
