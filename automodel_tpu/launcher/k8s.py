"""Kubernetes (GKE/JobSet) launcher.

Parity: the reference's CLI k8s path is a stub (_cli/app.py:333); here the
launcher renders a complete multi-host TPU JobSet-style manifest and
optionally submits via kubectl — multi-host JAX picks up coordination from
the TPU pod environment (jax.distributed.initialize with no args).
"""

from __future__ import annotations

import dataclasses
import subprocess
from pathlib import Path
from typing import Optional

MANIFEST_TEMPLATE = """\
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  backoffLimit: 0
  completions: {num_hosts}
  parallelism: {num_hosts}
  completionMode: Indexed
  template:
    spec:
      restartPolicy: Never
      subdomain: {name}
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {accelerator}
        cloud.google.com/gke-tpu-topology: {topology}
      containers:
        - name: train
          image: {image}
          command: ["python", "-m", "automodel_tpu.cli.app", "{command}", "{domain}", "-c", "{config_path}"{overrides}]
          resources:
            requests:
              google.com/tpu: "{chips_per_host}"
            limits:
              google.com/tpu: "{chips_per_host}"
          env:
            - name: JAX_PLATFORMS
              value: "tpu"
{extra_env}
"""


@dataclasses.dataclass
class K8sConfig:
    name: str = "automodel-train"
    image: str = "python:3.12"
    accelerator: str = "tpu-v5p-slice"
    topology: str = "2x2x1"
    num_hosts: int = 1
    chips_per_host: int = 4
    env: Optional[dict] = None
    manifest_dir: str = "k8s"


def render_manifest(
    cfg: K8sConfig,
    command: str,
    domain: str,
    config_path: str,
    overrides: Optional[list] = None,
) -> str:
    """NOTE: ``config_path`` must exist INSIDE the container image (or be
    provided via a ConfigMap/volume patch on the rendered manifest) — the
    manifest does not ship local files."""
    extra_env = ""
    for k, v in (cfg.env or {}).items():
        extra_env += f'            - name: {k}\n              value: "{v}"\n'
    ov = "".join(f', "{o}"' for o in (overrides or []))
    return MANIFEST_TEMPLATE.format(
        overrides=ov,
        name=cfg.name,
        image=cfg.image,
        accelerator=cfg.accelerator,
        topology=cfg.topology,
        num_hosts=cfg.num_hosts,
        chips_per_host=cfg.chips_per_host,
        command=command,
        domain=domain,
        config_path=config_path,
        extra_env=extra_env.rstrip("\n"),
    )


def submit(
    cfg: K8sConfig,
    command: str,
    domain: str,
    config_path: str,
    apply: bool = True,
    overrides: Optional[list] = None,
) -> Path:
    """Write the manifest; `kubectl apply` it when requested and available."""
    out = Path(cfg.manifest_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{cfg.name}.yaml"
    path.write_text(render_manifest(cfg, command, domain, config_path, overrides))
    if apply:
        subprocess.run(["kubectl", "apply", "-f", str(path)], check=True)
    return path
