"""Kubernetes (GKE/JobSet) launcher.

Parity: the reference's CLI k8s path is a stub (_cli/app.py:333); here the
launcher renders a complete multi-host TPU JobSet-style manifest and
optionally submits via kubectl — multi-host JAX picks up coordination from
the TPU pod environment (jax.distributed.initialize with no args).
"""

from __future__ import annotations

import dataclasses
import subprocess
from pathlib import Path
from typing import Optional

MANIFEST_TEMPLATE = """\
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  backoffLimit: {backoff_limit}
{pod_failure_policy}  completions: {num_hosts}
  parallelism: {num_hosts}
  completionMode: Indexed
  template:
    spec:
      restartPolicy: Never
      terminationGracePeriodSeconds: {termination_grace_s}
      subdomain: {name}
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {accelerator}
        cloud.google.com/gke-tpu-topology: {topology}
      containers:
        - name: train
          image: {image}
          command: ["python", "-m", "automodel_tpu.cli.app", "{command}", "{domain}", "-c", "{config_path}"{overrides}]
          resources:
            requests:
              google.com/tpu: "{chips_per_host}"
            limits:
              google.com/tpu: "{chips_per_host}"
          env:
            - name: JAX_PLATFORMS
              value: "tpu"
{extra_env}
"""

# requeue wiring (resilience/preemption.py): a container exiting with
# {requeue_exit_code} means "preempted; emergency checkpoint committed" —
# Ignore recreates the pod WITHOUT consuming backoffLimit, so spot
# preemptions requeue forever while any real crash still FailJobs
# immediately (restartPolicy must stay Never for podFailurePolicy).
# Rules match in order; the DisruptionTarget rule comes FIRST so a
# preemption/eviction kill that never reaches the trainer's exit-75 path —
# an emergency save outliving the grace window ends in SIGKILL (137), and a
# node-level eviction may record no container exit at all — still requeues
# instead of tripping the catch-all FailJob.
POD_FAILURE_POLICY = """\
  podFailurePolicy:
    rules:
      - action: Ignore
        onPodConditions:
          - type: DisruptionTarget
            status: "True"
      - action: Ignore
        onExitCodes:
          containerName: train
          operator: In
          values: [{requeue_exit_code}]
      - action: FailJob
        onExitCodes:
          containerName: train
          operator: NotIn
          values: [{requeue_exit_code}]
"""

# Multi-host: when one host is preempted (exits 75, Ignored above) its
# peers die from broken collectives with ORDINARY non-zero exits and no
# DisruptionTarget condition — indistinguishable, by exit code, from a
# real crash. Two layers disarm that: (1) the preempted trainer drops a
# marker into the SHARED checkpoint root at SIGTERM time, and a peer
# whose run then crashes while the marker is fresh exits 75 itself
# (cli/app.py _crash_is_preemption_collateral) — Ignored above; (2) the
# marker is best-effort (an object-store checkpoint root can't host it),
# so the catch-all FailJob is still dropped and residual peer deaths
# Count against a backoffLimit sized to absorb several preemption events
# per host. A genuinely crashing job still exhausts that budget quickly;
# podFailurePolicy itself has no cross-pod state to do better with.
POD_FAILURE_POLICY_MULTIHOST = """\
  podFailurePolicy:
    rules:
      - action: Ignore
        onPodConditions:
          - type: DisruptionTarget
            status: "True"
      - action: Ignore
        onExitCodes:
          containerName: train
          operator: In
          values: [{requeue_exit_code}]
"""

# preemption-collateral retry budget per host (multi-host requeue only)
BACKOFF_PER_HOST = 4


@dataclasses.dataclass
class K8sConfig:
    name: str = "automodel-train"
    image: str = "python:3.12"
    accelerator: str = "tpu-v5p-slice"
    topology: str = "2x2x1"
    num_hosts: int = 1
    chips_per_host: int = 4
    env: Optional[dict] = None
    manifest_dir: str = "k8s"
    # the exit code itself is deliberately not configurable: the trainer
    # always exits resilience.REQUEUE_EXIT_CODE on preemption
    requeue_on_preemption: bool = True
    # how long the kubelet waits between SIGTERM and SIGKILL on pod
    # deletion/eviction: the emergency-checkpoint window. The hang
    # watchdog's exit-75 (a wedged host detected mid-run) rides the same
    # Ignore rules as preemption, so a hung pod recycles without burning
    # the backoff budget. A serving pod (`automodel_tpu serve`) uses the
    # same window for its graceful drain — keep this above
    # serving.drain.grace_s so in-flight requests finish before SIGKILL;
    # the drained server exits REQUEUE_EXIT_CODE in-cluster
    # (serving.drain.requeue_exit: auto), riding the same Ignore rules.
    termination_grace_s: int = 90


def render_manifest(
    cfg: K8sConfig,
    command: str,
    domain: str,
    config_path: str,
    overrides: Optional[list] = None,
) -> str:
    """NOTE: ``config_path`` must exist INSIDE the container image (or be
    provided via a ConfigMap/volume patch on the rendered manifest) — the
    manifest does not ship local files."""
    extra_env = ""
    for k, v in (cfg.env or {}).items():
        extra_env += f'            - name: {k}\n              value: "{v}"\n'
    from automodel_tpu.resilience.preemption import REQUEUE_EXIT_CODE

    ov = "".join(f', "{o}"' for o in (overrides or []))
    backoff_limit = 0  # no requeue, or single host: any real crash fails fast
    pod_failure_policy = ""
    if cfg.requeue_on_preemption:
        if cfg.num_hosts > 1:
            pod_failure_policy = POD_FAILURE_POLICY_MULTIHOST.format(
                requeue_exit_code=REQUEUE_EXIT_CODE
            )
            backoff_limit = BACKOFF_PER_HOST * cfg.num_hosts
        else:
            pod_failure_policy = POD_FAILURE_POLICY.format(
                requeue_exit_code=REQUEUE_EXIT_CODE
            )
    return MANIFEST_TEMPLATE.format(
        overrides=ov,
        pod_failure_policy=pod_failure_policy,
        backoff_limit=backoff_limit,
        termination_grace_s=cfg.termination_grace_s,
        name=cfg.name,
        image=cfg.image,
        accelerator=cfg.accelerator,
        topology=cfg.topology,
        num_hosts=cfg.num_hosts,
        chips_per_host=cfg.chips_per_host,
        command=command,
        domain=domain,
        config_path=config_path,
        extra_env=extra_env.rstrip("\n"),
    )


def submit(
    cfg: K8sConfig,
    command: str,
    domain: str,
    config_path: str,
    apply: bool = True,
    overrides: Optional[list] = None,
) -> Path:
    """Write the manifest; `kubectl apply` it when requested and available."""
    out = Path(cfg.manifest_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{cfg.name}.yaml"
    path.write_text(render_manifest(cfg, command, domain, config_path, overrides))
    if apply:
        subprocess.run(["kubectl", "apply", "-f", str(path)], check=True)
    return path


# -- serving fleet (docs/serving.md "Fleet") ---------------------------------
#
# Topology: one router Deployment (no TPU — placement is pure python) in
# front of role-labelled replica StatefulSets behind a headless Service.
# The router discovers replica pods by resolving the Service name each
# probe cycle (fleet.dns), so scale-ups join and deleted pods leave without
# a router restart. Probes are the PR 9 endpoints every replica (and the
# router itself) serves: /readyz gates load-balancer membership (false
# while draining / before the first compiled decode), /healthz restarts a
# wedged pod. terminationGracePeriodSeconds must stay above
# serving.drain.grace_s so SIGTERM drains finish before SIGKILL.

FLEET_SERVICE_TEMPLATE = """\
apiVersion: v1
kind: Service
metadata:
  name: {name}-replicas
spec:
  clusterIP: None  # headless: one A record per replica pod (fleet.dns)
  selector:
    app: {name}
  ports:
    - name: http
      port: {replica_port}
"""

FLEET_REPLICA_TEMPLATE = """\
apiVersion: apps/v1
kind: StatefulSet
metadata:
  name: {name}-{role}
spec:
  serviceName: {name}-replicas
  replicas: {replicas}
  selector:
    matchLabels:
      app: {name}
      role: {role}
  template:
    metadata:
      labels:
        app: {name}
        role: {role}
    spec:
      terminationGracePeriodSeconds: {termination_grace_s}
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {accelerator}
        cloud.google.com/gke-tpu-topology: {topology}
      containers:
        - name: serve
          image: {image}
          command: ["python", "-m", "automodel_tpu.cli.app", "serve", "-c", "{config_path}", "--serving.role={role}", "--serving.http.port={replica_port}", "--serving.http.host=0.0.0.0", "--serving.kv_transfer.port={kv_port}", "--serving.kv_transfer.host=0.0.0.0"]
          ports:
            - containerPort: {replica_port}
            - containerPort: {kv_port}
          readinessProbe:
            httpGet: {{path: /readyz, port: {replica_port}}}
            periodSeconds: 5
          livenessProbe:
            httpGet: {{path: /healthz, port: {replica_port}}}
            periodSeconds: 10
            failureThreshold: 6
          resources:
            requests:
              google.com/tpu: "{chips_per_host}"
            limits:
              google.com/tpu: "{chips_per_host}"
          env:
            - name: JAX_PLATFORMS
              value: "tpu"
{extra_env}
"""

FLEET_ROUTER_TEMPLATE = """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}-router
spec:
  replicas: 1
  selector:
    matchLabels:
      app: {name}-router
  template:
    metadata:
      labels:
        app: {name}-router
    spec:
      terminationGracePeriodSeconds: {termination_grace_s}
      containers:
        - name: route
          image: {image}
          command: ["python", "-m", "automodel_tpu.cli.app", "route", "-c", "{config_path}", "--fleet.dns={name}-replicas", "--fleet.dns_port={replica_port}", "--fleet.port={router_port}", "--fleet.host=0.0.0.0"]
          ports:
            - containerPort: {router_port}
          readinessProbe:
            httpGet: {{path: /readyz, port: {router_port}}}
            periodSeconds: 5
          livenessProbe:
            httpGet: {{path: /healthz, port: {router_port}}}
            periodSeconds: 10
            failureThreshold: 6
---
apiVersion: v1
kind: Service
metadata:
  name: {name}-router
spec:
  selector:
    app: {name}-router
  ports:
    - name: http
      port: {router_port}
"""


@dataclasses.dataclass
class K8sFleetConfig:
    """The ``k8s_fleet:`` section — router + role-labelled replica sets.
    Roles with count 0 render no StatefulSet; a prefill/decode split plus
    ``mixed: 0`` is the disaggregated topology, ``mixed: N`` alone is the
    affinity-routed homogeneous fleet."""

    name: str = "automodel-serve"
    image: str = "python:3.12"
    accelerator: str = "tpu-v5e-slice"
    topology: str = "2x2"
    chips_per_host: int = 4
    router_port: int = 8000
    replica_port: int = 8100
    kv_port: int = 8200  # decode replicas' KV-transfer listener
    mixed: int = 2
    prefill: int = 0
    decode: int = 0
    env: Optional[dict] = None
    manifest_dir: str = "k8s"
    # must exceed serving.drain.grace_s (replica) / fleet.drain_grace_s
    # (router) — same rule as the single-engine notes above
    termination_grace_s: int = 90


def render_fleet_manifest(cfg: K8sFleetConfig, config_path: str) -> str:
    """One multi-document YAML: headless Service + one StatefulSet per
    non-empty role + the router Deployment/Service. ``config_path`` must
    exist inside the image (same contract as render_manifest)."""
    if cfg.mixed + cfg.prefill + cfg.decode < 1:
        raise ValueError("k8s_fleet: needs at least one replica in some role")
    if cfg.prefill > 0 and cfg.decode < 1:
        # mixed pods do NOT run the KV-transfer listener (server.py
        # auto-enables it only for role decode), so prefill+mixed would
        # render a fleet whose prefill chips can never hand KV off —
        # idle TPU pods with no error anywhere. Refuse at render time.
        raise ValueError(
            "k8s_fleet: prefill replicas need a decode pool to stream KV "
            "to (mixed replicas run no KV-transfer listener)"
        )
    extra_env = ""
    for k, v in (cfg.env or {}).items():
        extra_env += f'            - name: {k}\n              value: "{v}"\n'
    docs = [
        FLEET_SERVICE_TEMPLATE.format(
            name=cfg.name, replica_port=cfg.replica_port
        )
    ]
    for role, count in (
        ("mixed", cfg.mixed), ("prefill", cfg.prefill), ("decode", cfg.decode)
    ):
        if count < 1:
            continue
        docs.append(
            FLEET_REPLICA_TEMPLATE.format(
                name=cfg.name, role=role, replicas=count, image=cfg.image,
                accelerator=cfg.accelerator, topology=cfg.topology,
                chips_per_host=cfg.chips_per_host,
                replica_port=cfg.replica_port, kv_port=cfg.kv_port,
                termination_grace_s=cfg.termination_grace_s,
                config_path=config_path,
                extra_env=extra_env.rstrip("\n"),
            )
        )
    docs.append(
        FLEET_ROUTER_TEMPLATE.format(
            name=cfg.name, image=cfg.image, router_port=cfg.router_port,
            replica_port=cfg.replica_port,
            termination_grace_s=cfg.termination_grace_s,
            config_path=config_path,
        )
    )
    return "---\n".join(docs)


def submit_fleet(
    cfg: K8sFleetConfig, config_path: str, apply: bool = True
) -> Path:
    """Write the fleet manifest; `kubectl apply` when requested."""
    out = Path(cfg.manifest_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"{cfg.name}-fleet.yaml"
    path.write_text(render_fleet_manifest(cfg, config_path))
    if apply:
        subprocess.run(["kubectl", "apply", "-f", str(path)], check=True)
    return path


def scale_fleet_role(
    cfg: K8sFleetConfig, role: str, replicas: int, apply: bool = True
) -> list[str]:
    """Resize one role's StatefulSet (the autoscaler's k8s backend).

    A scale-down removes the highest ordinal pod; its preStop/SIGTERM
    path runs the serve front's drain, so the same retire semantics the
    local backend gets from POST /retire arrive here via pod lifecycle.
    Returns the kubectl argv (tests assert it without a cluster)."""
    if role not in ("mixed", "prefill", "decode"):
        raise ValueError(f"k8s_fleet: unknown role {role!r}")
    if replicas < 0:
        raise ValueError(f"k8s_fleet: replicas={replicas}")
    argv = [
        "kubectl", "scale", "statefulset", f"{cfg.name}-{role}",
        f"--replicas={replicas}",
    ]
    if apply:
        subprocess.run(argv, check=True)
    return argv
