"""Checkpointable RNG state.

Parity: the reference's StatefulRNG / ScopedRNG (training/rng.py:83,115)
capture python/numpy/torch generator states. Here device-side randomness is a
jax PRNG key threaded through TrainState (functional, already checkpointable);
this class covers the HOST side (python/numpy used by data pipelines).
"""

from __future__ import annotations

import contextlib
import random
from typing import Any

import numpy as np


class StatefulRNG:
    def __init__(self, seed: int = 0, ranked: bool = False, rank: int = 0):
        seed = seed + (rank if ranked else 0)
        self.python = random.Random(seed)
        self.numpy = np.random.default_rng(seed)

    def state_dict(self) -> dict[str, Any]:
        return {
            "python": self.python.getstate(),
            "numpy": self.numpy.bit_generator.state,
        }

    def load_state_dict(self, state: dict[str, Any]) -> None:
        pystate = state["python"]
        # JSON round-trips tuples as lists; random.setstate needs tuples.
        if isinstance(pystate, list):
            pystate = tuple(
                tuple(p) if isinstance(p, list) else p for p in pystate
            )
        self.python.setstate(pystate)
        self.numpy.bit_generator.state = state["numpy"]


def sampling_key(seed, step=None, host_index: int | None = None):
    """Per-host deterministic sampling stream (generation subsystem).

    Folds the HOST index into the base key so multi-host generation never
    samples identical streams (each host sampling the same tokens for its
    own slots would correlate every host's output), then optionally the
    decode step. The decode while_loop folds its traced step index itself
    (``jax.random.fold_in(key, i)``), so callers there pass ``step=None``;
    ``step`` accepts a traced value too (fold_in is jit-safe).

    ``seed``: int or an existing PRNG key. ``host_index`` defaults to
    ``jax.process_index()``."""
    import jax

    key = seed if isinstance(seed, jax.Array) else jax.random.key(int(seed))
    if host_index is None:
        host_index = jax.process_index()
    key = jax.random.fold_in(key, host_index)
    if step is not None:
        key = jax.random.fold_in(key, step)
    return key


@contextlib.contextmanager
def scoped_rng(seed: int):
    """Temporarily seed global python/numpy RNGs (reference ScopedRNG)."""
    py_state = random.getstate()
    np_state = np.random.get_state()
    random.seed(seed)
    np.random.seed(seed)
    try:
        yield
    finally:
        random.setstate(py_state)
        np.random.set_state(np_state)
