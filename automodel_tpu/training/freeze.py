"""Parameter freezing by path pattern.

Parity: the reference freezes modules by config before sharding
(infrastructure.py:441 parameter freezing; recipes/vlm/finetune.py freeze
config for vision towers / language model). TPU-native: freezing is two
complementary pieces. (1) `optax.multi_transform` routes frozen leaves to
`set_to_zero`, so no optimizer state is allocated for them and weight decay
cannot touch them. (2) the train step zeroes frozen leaves' gradients right
after value_and_grad (build_train_step(grad_mask=...)) — that makes the
backward ops producing them dead code XLA eliminates, and keeps grad_norm
a metric over trainable params only.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Sequence

import jax
import optax

from automodel_tpu.parallel.plans import path_str


def freeze_mask(params: Any, freeze_patterns: Sequence[str]) -> Any:
    """Pytree of bools matching `params`: True = trainable, False = frozen.
    Patterns are fnmatch-style over "a/b/c" paths (e.g. "vision/*")."""

    def label(path, _leaf):
        p = path_str(path)
        return not any(fnmatch.fnmatch(p, pat) for pat in freeze_patterns)

    return jax.tree_util.tree_map_with_path(label, params)


def apply_freeze(
    optimizer: optax.GradientTransformation, mask: Any
) -> optax.GradientTransformation:
    """Wrap `optimizer` so frozen leaves receive zero updates and hold no
    optimizer state."""
    labels = jax.tree.map(lambda t: "train" if t else "freeze", mask)
    return optax.multi_transform(
        {"train": optimizer, "freeze": optax.set_to_zero()}, labels
    )


def trainable_count(mask: Any, params: Any) -> tuple[int, int]:
    """(trainable param count, total param count) for logging."""
    counts = jax.tree.map(
        lambda t, p: (int(p.size) if t else 0, int(p.size)), mask, params
    )
    leaves = jax.tree.leaves(counts, is_leaf=lambda x: isinstance(x, tuple))
    return sum(a for a, _ in leaves), sum(b for _, b in leaves)
