"""Train state pytree."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar

    @classmethod
    def create(cls, params: Any, opt_state: Any) -> "TrainState":
        return cls(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))
