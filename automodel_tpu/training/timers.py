"""Named timers for benchmarking.

Parity: Megatron-style `Timers` (reference: components/training/timers.py:
257-346 — barriered start/stop with min/max across ranks). Single-controller
JAX needs no cross-rank reduction: one process observes the whole step. The
device sync happens by blocking on a data transfer (`jax.device_get`), which
is the only true barrier on tunneled/remote backends.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax


class Timer:
    def __init__(self, name: str):
        self.name = name
        self._start: Optional[float] = None
        self.elapsed_history: list[float] = []

    def start(self, barrier_on: Any = None) -> None:
        if barrier_on is not None:
            jax.block_until_ready(barrier_on)
        self._start = time.perf_counter()

    def stop(self, barrier_on: Any = None) -> float:
        if barrier_on is not None:
            jax.block_until_ready(barrier_on)
        assert self._start is not None, f"timer {self.name} not started"
        dt = time.perf_counter() - self._start
        self.elapsed_history.append(dt)
        self._start = None
        return dt

    def mean(self, skip_first: int = 0) -> float:
        h = self.elapsed_history[skip_first:]
        return sum(h) / max(len(h), 1)

    def min(self, skip_first: int = 0) -> float:
        h = self.elapsed_history[skip_first:]
        return min(h) if h else 0.0

    def max(self, skip_first: int = 0) -> float:
        h = self.elapsed_history[skip_first:]
        return max(h) if h else 0.0


def measured_bubble_fraction(step_s: float, work_s: float) -> float:
    """Measured pipeline bubble: the fraction of a step spent idle given
    the schedule-free work time ``work_s`` (e.g. the T_work intercept of a
    microbatch sweep fit, tools/profile_pp.py, or a pp=1 run of the same
    per-rank compute). Compare against the analytic laws in
    utils/flops_utils.{gpipe,zero}_bubble_fraction per schedule."""
    if step_s <= 0:
        return 0.0
    return max(0.0, 1.0 - work_s / step_s)


class Timers:
    def __init__(self):
        self._timers: dict[str, Timer] = {}

    def __call__(self, name: str) -> Timer:
        if name not in self._timers:
            self._timers[name] = Timer(name)
        return self._timers[name]

    def summary(self, skip_first: int = 0) -> dict[str, dict[str, float]]:
        return {
            n: {
                "mean_s": t.mean(skip_first),
                "min_s": t.min(skip_first),
                "max_s": t.max(skip_first),
                "count": len(t.elapsed_history),
            }
            for n, t in self._timers.items()
        }
