"""Named timers for benchmarking.

Parity: Megatron-style `Timers` (reference: components/training/timers.py:
257-346 — barriered start/stop with min/max across ranks). Single-controller
JAX needs no cross-rank reduction: one process observes the whole step. The
device sync happens by blocking on a data transfer (`jax.device_get`), which
is the only true barrier on tunneled/remote backends.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional

import jax

# retained raw entries per timer; aggregates (count/mean/min/max) stay exact
# for the whole run regardless — the cap only bounds host memory on
# million-step runs where the train loop times every step
_MAX_HISTORY = 4096


class Timer:
    def __init__(self, name: str):
        self.name = name
        self._start: Optional[float] = None
        self.elapsed_history: deque[float] = deque(maxlen=_MAX_HISTORY)
        self._pending: deque[float] = deque(maxlen=_MAX_HISTORY)
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = 0.0

    def start(self, barrier_on: Any = None) -> None:
        if barrier_on is not None:
            jax.block_until_ready(barrier_on)
        self._start = time.perf_counter()

    def stop(self, barrier_on: Any = None) -> float:
        if barrier_on is not None:
            jax.block_until_ready(barrier_on)
        assert self._start is not None, f"timer {self.name} not started"
        dt = time.perf_counter() - self._start
        self.elapsed_history.append(dt)
        self._pending.append(dt)
        self._count += 1
        self._sum += dt
        self._min = dt if dt < self._min else self._min
        self._max = dt if dt > self._max else self._max
        self._start = None
        return dt

    @property
    def count(self) -> int:
        return self._count

    def mean(self, skip_first: int = 0) -> float:
        if skip_first:  # over the retained window only
            h = list(self.elapsed_history)[skip_first:]
            return sum(h) / max(len(h), 1)
        return self._sum / max(self._count, 1)

    def min(self, skip_first: int = 0) -> float:
        if skip_first:
            h = list(self.elapsed_history)[skip_first:]
            return min(h) if h else 0.0
        return self._min if self._count else 0.0

    def max(self, skip_first: int = 0) -> float:
        if skip_first:
            h = list(self.elapsed_history)[skip_first:]
            return max(h) if h else 0.0
        return self._max

    def drain(self) -> list[float]:
        """Entries recorded since the previous drain. Lets a periodic
        consumer (per-log-window step-time decomposition) report window
        means while `summary()` keeps the whole-run view."""
        new = list(self._pending)
        self._pending.clear()
        return new


def measured_bubble_fraction(step_s: float, work_s: float) -> float:
    """Measured pipeline bubble: the fraction of a step spent idle given
    the schedule-free work time ``work_s`` (e.g. the T_work intercept of a
    microbatch sweep fit, tools/profile_pp.py, or a pp=1 run of the same
    per-rank compute). Compare against the analytic laws in
    utils/flops_utils.{gpipe,zero}_bubble_fraction per schedule."""
    if step_s <= 0:
        return 0.0
    return max(0.0, 1.0 - work_s / step_s)


class Timers:
    def __init__(self):
        self._timers: dict[str, Timer] = {}

    def __call__(self, name: str) -> Timer:
        if name not in self._timers:
            self._timers[name] = Timer(name)
        return self._timers[name]

    def drain_means(self) -> dict[str, float]:
        """Per-timer mean over the entries recorded since the last drain;
        timers with no new entries are omitted."""
        out: dict[str, float] = {}
        for n, t in self._timers.items():
            new = t.drain()
            if new:
                out[n] = sum(new) / len(new)
        return out

    def summary(self, skip_first: int = 0) -> dict[str, dict[str, float]]:
        return {
            n: {
                "mean_s": t.mean(skip_first),
                "min_s": t.min(skip_first),
                "max_s": t.max(skip_first),
                "count": t.count,
            }
            for n, t in self._timers.items()
        }
