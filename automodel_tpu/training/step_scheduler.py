"""Step scheduling: epochs, grad accumulation, checkpoint/val cadence.

Parity: reference StepScheduler (components/training/step_scheduler.py:48) —
iterates (epoch, grad-acc batch group) pairs, exposes ckpt/val/log cadence
predicates, is checkpointable, and stops cleanly on a shutdown signal
(DistributedSignalHandler, training/signal_handler.py:91; single-controller
JAX needs only a host-side SIGTERM hook).
"""

from __future__ import annotations

import signal
from typing import Any, Iterator, Optional


class StepScheduler:
    def __init__(
        self,
        grad_acc_steps: int = 1,
        ckpt_every_steps: int = 0,
        val_every_steps: int = 0,
        log_every_steps: int = 1,
        num_epochs: int = 1,
        max_steps: Optional[int] = None,
        dataloader: Any = None,
    ):
        self.grad_acc_steps = grad_acc_steps
        self.ckpt_every_steps = ckpt_every_steps
        self.val_every_steps = val_every_steps
        self.log_every_steps = log_every_steps
        self.num_epochs = num_epochs
        self.max_steps = max_steps
        self.dataloader = dataloader
        self.step = 0  # optimizer steps taken
        self.epoch = 0
        self._shutdown = False
        self._handler = None

    # -- graceful shutdown --------------------------------------------------
    def install_signal_handler(self, signals: tuple = (signal.SIGTERM,)) -> None:
        """Install the stop-at-step-boundary handler, CHAINING any handler
        already installed (cluster agents and libtpu hook the same signals;
        overwriting them silently disabled their cleanup). The caller owns
        restoration via ``restore_signal_handlers()`` — the recipe runs it
        AFTER the end-of-run checkpoint save, because restoring at loop
        exit would expose that save to a second (now default-disposition)
        signal. The chaining machinery itself is
        resilience.PreemptionHandler — one implementation of
        capture/chain/restore, two consumers."""
        from automodel_tpu.resilience.preemption import PreemptionHandler

        if self._handler is None:
            self._handler = PreemptionHandler(
                signals=signals, on_preempt=self.request_shutdown,
                log_message="stopping at the next step boundary (graceful shutdown)",
            )
        self._handler.install()

    def restore_signal_handlers(self) -> None:
        if self._handler is not None:
            self._handler.restore()

    def request_shutdown(self) -> None:
        """Programmatic stop at the next step boundary (the preemption
        handler calls this so SIGTERM drains the loop cleanly)."""
        self._shutdown = True

    @property
    def shutdown_requested(self) -> bool:
        return self._shutdown

    # -- iteration ----------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        """Yield one item per optimizer step: a list of `grad_acc_steps`
        microbatches — or, when the dataloader is a prefetch pipeline
        (``yields_groups``, data/prefetch.py), the already-grouped
        ``PreparedBatch`` it yields (the pipeline does the grad-acc grouping
        and tail-discard in its producer thread; step/epoch budget, max
        steps, and shutdown draining stay HERE on both paths)."""
        from automodel_tpu.data.collators import stack_microbatches  # noqa: F401

        grouped = bool(getattr(self.dataloader, "yields_groups", False))
        while self.epoch < self.num_epochs:
            group: list = []
            for batch in self.dataloader:
                if not grouped:
                    group.append(batch)
                    if len(group) < self.grad_acc_steps:
                        continue
                if self.max_steps is not None and self.step >= self.max_steps:
                    return
                # increment BEFORE yielding so the consumer's loop body
                # (cadence predicates, checkpoint naming) sees the step
                # number of the optimizer step it is currently taking,
                # matching TrainState.step after train_step.
                self.step += 1
                yield batch if grouped else group
                group = []
                if self._shutdown:
                    return
            self.epoch += 1
            # a signal landing in the epoch tail (after the last full
            # group yielded) must stop HERE, not a full epoch later
            if self._shutdown:
                return

    # -- cadence ------------------------------------------------------------
    @property
    def is_ckpt_step(self) -> bool:
        return self.ckpt_every_steps > 0 and self.step % self.ckpt_every_steps == 0

    @property
    def is_val_step(self) -> bool:
        return self.val_every_steps > 0 and self.step % self.val_every_steps == 0

    @property
    def is_log_step(self) -> bool:
        return self.log_every_steps > 0 and self.step % self.log_every_steps == 0

    # -- state --------------------------------------------------------------
    def state_dict(self) -> dict:
        return {"step": self.step, "epoch": self.epoch}

    def load_state_dict(self, state: dict) -> None:
        self.step = state["step"]
        self.epoch = state["epoch"]
