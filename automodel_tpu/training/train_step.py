"""The jitted training step.

Parity with the reference hot path (recipes/llm/train_ft.py:1284
_run_train_optim_step): microbatch grad accumulation, GLOBAL label-token
normalization across the dp_cp group and all microbatches
(train_ft.py:1292-1303), grad clip, optimizer step, loss/grad-norm metrics.

TPU-native structure: ONE `jax.jit` covers the whole optimizer step —
the microbatch loop is a `lax.scan` over a leading accumulation axis, so
FSDP all-gathers, loss collectives, and the optimizer update are all
scheduled by XLA inside a single program (the reference needs
MoEFSDPSyncMixin + no_sync contexts to get this right; here it falls out
of functional grads). Collectives are implicit: batches arrive sharded over
(dp, cp); `jnp.sum` of loss/token-count is a global reduction XLA lowers to
psum over the data axes.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from automodel_tpu.training.train_state import TrainState


def build_train_step(
    loss_fn: Callable[[Any, dict], tuple],
    optimizer: optax.GradientTransformation,
    lr_schedule: Optional[Callable] = None,
    donate: bool = True,
    post_step_fn: Optional[Callable[[Any, dict], Any]] = None,
    grad_mask: Any = None,
    anomaly_flags: bool = True,
    on_nonfinite: str = "raise",
    nan_grads_at_step: Optional[int] = None,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Build the jitted (state, batch) → (state, metrics) step.

    ``loss_fn(params, microbatch) -> (loss_sum, n_valid_tokens[, extras])``
    where loss_sum is the UN-normalized token-loss sum (normalization happens
    here, globally) and `extras` is an optional pytree of per-microbatch
    auxiliaries (MoE expert counts, aux losses) summed across microbatches.
    ``batch`` leaves carry a leading microbatch axis [A, ...]; A=1 for no
    accumulation.

    ``post_step_fn(new_params, extras_sum) -> new_params`` runs AFTER the
    optimizer update, outside the gradient — the reference's
    update_moe_gate_bias slot (train_ft.py:1341, aux-free load balancing).

    ``anomaly_flags`` (default on): fold `telemetry.anomaly` reductions into
    the metrics dict INSIDE the jit — a boolean ``nonfinite`` (loss or any
    grad), the grad non-finite element count, and per-param-group grad norms
    (``grad_norm/<group>``). A few scalar reductions XLA fuses into the
    existing grad traversal; no extra device round-trips (the metrics dict
    is only fetched at log steps), so a NaN/Inf is caught in the step it
    occurs with the group that produced it.

    ``on_nonfinite`` (resilience/, fault_tolerance.on_nonfinite): with
    ``"skip"``, a step whose loss or gradient goes non-finite DISCARDS the
    update inside the jit — params and opt-state are carried through
    bit-identical (``jnp.where`` on the already-computed new trees, so
    there is no control-flow divergence and no recompile) and the metrics
    gain a ``skipped`` flag the recipe counts. ``"raise"``/``"rollback"``
    are host-side policies (recipes/train_ft.py). The non-default policies
    (skip/rollback) force the bare ``nonfinite`` flag even when
    ``anomaly_flags`` is off; the default ``raise`` policy respects the
    anomaly_flags opt-out — disabling anomaly flags under ``raise``
    disables non-finite detection entirely (the recipe warns loudly at
    setup). The step counter still advances on a skipped step (the LR
    schedule and cadence predicates stay aligned with the data stream).

    ``nan_grads_at_step`` (fault injection): poison every gradient leaf at
    the optimizer step with that 1-based number (``state.step + 1``, the
    number the scheduler and metrics report). Keyed on the TRACED step, so
    arming it costs one fused select per leaf and no recompile.

    ``grad_mask`` (bool pytree, True = trainable): frozen leaves' gradients
    are replaced by zeros immediately after value_and_grad — XLA dead-code-
    eliminates the backward compute that only produced them, and grad_norm
    reflects trainable params only (see training/freeze.py).

    Pipeline-parallel loss_fns (parallel/pp.py wrappers): under
    pp_schedule='zero_bubble' the per-stage VJP is split into B/W passes and
    weight-grad (W) chunks land OUT of microbatch order, summed in fp32
    inside the pipeline's custom_vjp (parallel/zero_bubble.py) — the
    gradient value_and_grad returns here is only materialized once every W
    chunk has landed, so the fp32 global-norm clip below never sees a
    partial gradient. A loss_fn built over a pipelined model carries
    ``pipeline_info`` and the metrics gain the analytic
    ``pp_bubble_fraction`` for the active schedule.
    """

    # a loss_fn may carry frozen params (LoRA base) to pass as a REAL jit
    # argument — closures over device trees become captured constants baked
    # into every lowering (GBs for large bases)
    bound_params = getattr(loss_fn, "bound_params", None)
    # a loss_fn may also want the optimizer step (QAT delayed fake-quant
    # enablement, quantization/qat.py) — passed as a traced kwarg. LoRA
    # dropout additionally folds the microbatch index so accumulation
    # microbatches draw independent masks.
    needs_step = getattr(loss_fn, "needs_step", False)
    needs_mb_index = getattr(loss_fn, "needs_mb_index", False)

    def call_loss(params, mb, bound, step, mb_index=None):
        kw = {"step": step} if needs_step else {}
        if needs_mb_index:
            kw["mb_index"] = mb_index
        out = (
            loss_fn(params, mb, bound, **kw)
            if bound is not None
            else loss_fn(params, mb, **kw)
        )
        if len(out) == 3:
            return out
        loss_sum, n = out
        return loss_sum, n, {}

    def mb_value_and_grad(params, mb, bound, step, mb_index=None):
        def wrapped(p):
            loss_sum, n, extras = call_loss(p, mb, bound, step, mb_index)
            return loss_sum.astype(jnp.float32), (n, extras)
        val, grads = jax.value_and_grad(wrapped, has_aux=True)(params)
        if grad_mask is not None:
            grads = jax.tree.map(
                lambda g, m: g if m else jnp.zeros_like(g), grads, grad_mask
            )
        return val, grads

    def step_fn(state: TrainState, batch: dict, bound=None) -> tuple[TrainState, dict]:
        n_mb = jax.tree.leaves(batch)[0].shape[0]
        if n_mb == 1:
            # no-accumulation fast path: the fp32 zeros+add accumulator would
            # DOUBLE every grad buffer (bf16→fp32) and drag ~3 full-size
            # layout copies through global-norm/scale (measured 2.5GB each on
            # the MoE bench fingerprint's stacked expert grads). Grads stay in
            # param dtype; moment fp32-ness is the OPTIMIZER's contract
            # (optim/builders.scale_by_adam_fp32_moments — optax's own adam
            # would inherit bf16 from these grads and freeze nu).
            mb = jax.tree.map(lambda x: x[0], batch)
            (loss_sum, (n_tokens, extras)), grads = mb_value_and_grad(
                state.params, mb, bound, state.step,
                jnp.int32(0),
            )
            extras_sum = extras
        else:
            grads0 = jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), state.params
            )
            carry0 = (grads0, jnp.float32(0.0), jnp.int32(0))

            def body(carry, mb_and_i):
                mb, mb_i = mb_and_i
                g_acc, l_acc, n_acc = carry
                (loss_sum, (n, extras)), grads = mb_value_and_grad(
                    state.params, mb, bound, state.step, mb_i
                )
                g_acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), g_acc, grads
                )
                return (g_acc, l_acc + loss_sum, n_acc + n), extras

            (grads, loss_sum, n_tokens), extras_stacked = jax.lax.scan(
                body, carry0, (batch, jnp.arange(n_mb, dtype=jnp.int32))
            )
            extras_sum = jax.tree.map(lambda x: x.sum(axis=0), extras_stacked)
        denom = jnp.maximum(n_tokens, 1).astype(jnp.float32)
        # divide in fp32 even for bf16 grads (a bf16-rounded token count is
        # off by up to 0.4%); the convert/divide/convert fuses — no
        # materialized fp32 copy
        grads = jax.tree.map(
            lambda g: (g.astype(jnp.float32) / denom).astype(g.dtype), grads
        )
        if nan_grads_at_step is not None:
            poison = jnp.where(
                state.step + 1 == nan_grads_at_step, jnp.float32(jnp.nan), 0.0
            )
            grads = jax.tree.map(lambda g: g + poison.astype(g.dtype), grads)
        from automodel_tpu.optim.builders import global_norm_fp32

        grad_norm = global_norm_fp32(grads)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        # keep params in their original dtype (apply_updates may upcast)
        new_params = jax.tree.map(
            lambda new, old: new.astype(old.dtype), new_params, state.params
        )
        if post_step_fn is not None:
            new_params = post_step_fn(new_params, extras_sum)
        metrics = {
            "loss": loss_sum / denom,
            "grad_norm": grad_norm,
            "num_label_tokens": n_tokens,
            "step": state.step + 1,
        }
        if anomaly_flags:
            from automodel_tpu.telemetry.anomaly import anomaly_metrics

            metrics.update(anomaly_metrics(loss_sum, grads))
        elif on_nonfinite != "raise" or nan_grads_at_step is not None:
            # the host-side policies need the flag even with the full
            # anomaly reductions disabled
            from automodel_tpu.telemetry.anomaly import nonfinite_count

            metrics["nonfinite"] = ~jnp.isfinite(loss_sum) | (
                nonfinite_count(grads) > 0
            )
        if on_nonfinite == "skip":
            bad = metrics["nonfinite"]
            # carry params AND opt-state through unchanged (bit-identical:
            # jnp.where with a scalar pred selects whole buffers) — the NaN
            # never reaches the weights or the Adam moments
            new_params = jax.tree.map(
                lambda new, old: jnp.where(bad, old, new), new_params, state.params
            )
            new_opt_state = jax.tree.map(
                lambda new, old: jnp.where(bad, old, new),
                new_opt_state,
                state.opt_state,
            )
            metrics["skipped"] = bad
        if "moe_aux_loss" in extras_sum:
            metrics["moe_aux_loss"] = extras_sum["moe_aux_loss"] / batch_size(batch)
        pinfo = getattr(loss_fn, "pipeline_info", None)
        if pinfo:
            from automodel_tpu.utils.flops_utils import pipeline_bubble_fraction

            metrics["pp_bubble_fraction"] = pipeline_bubble_fraction(
                pinfo["pp"], pinfo["n_microbatches"],
                pinfo.get("schedule", "gpipe"), pinfo.get("zb_queue"),
                pinfo.get("w_deferred_fraction", 1.0),
            )
        # a loss_fn may derive its own scalar metrics from the summed extras
        # (posttrain/: dpo_loss, accept_margin, kl_to_ref) — the callable
        # runs in-jit over the microbatch-summed tree, so token-weighted
        # means normalize by the SAME global denominator as the loss
        metric_extras = getattr(loss_fn, "metric_extras", None)
        if metric_extras is not None:
            metrics.update(metric_extras(extras_sum, denom))
        if "expert_counts" in extras_sum:
            c = extras_sum["expert_counts"].astype(jnp.float32)  # [L, E]
            per_layer = c.max(axis=-1) / jnp.maximum(c.mean(axis=-1), 1.0)
            metrics["expert_load_imbalance"] = per_layer.mean()
            # per-layer detail for the JSONL (reference:
            # moe/load_balance_metrics.py detailed metrics)
            metrics["expert_load_imbalance_per_layer"] = per_layer
        if lr_schedule is not None:
            metrics["lr"] = lr_schedule(state.step)
        new_state = TrainState(
            params=new_params, opt_state=new_opt_state, step=state.step + 1
        )
        return new_state, metrics

    def batch_size(batch) -> jnp.ndarray:
        leaf = jax.tree.leaves(batch)[0]
        return jnp.float32(leaf.shape[0])

    jitted = jax.jit(step_fn, donate_argnums=(0,) if donate else ())
    if bound_params is None:
        return jitted
    return lambda state, batch: jitted(state, batch, bound_params)


def build_eval_step(
    loss_fn: Callable[[Any, dict], tuple[jnp.ndarray, jnp.ndarray]],
) -> Callable[[TrainState, dict], dict]:
    """Validation step: microbatch-scanned loss sum + token count."""
    bound_params = getattr(loss_fn, "bound_params", None)
    needs_step = getattr(loss_fn, "needs_step", False)

    def step_fn(state: TrainState, batch: dict, bound=None) -> dict:
        def body(carry, mb):
            l_acc, n_acc = carry
            kw = {"step": state.step} if needs_step else {}
            out = (
                loss_fn(state.params, mb, bound, **kw)
                if bound is not None
                else loss_fn(state.params, mb, **kw)
            )
            loss_sum, n = out[:2]
            return (l_acc + loss_sum.astype(jnp.float32), n_acc + n), None

        (loss_sum, n), _ = jax.lax.scan(body, (jnp.float32(0.0), jnp.int32(0)), batch)
        return {"loss_sum": loss_sum, "num_label_tokens": n}

    jitted = jax.jit(step_fn)
    if bound_params is None:
        return jitted
    return lambda state, batch: jitted(state, batch, bound_params)


def make_causal_lm_loss(
    model: Any,
    loss: str = "masked_ce",
    constrain: Callable = lambda x, s: x,
    **loss_kwargs: Any,
) -> Callable[[Any, dict], tuple[jnp.ndarray, jnp.ndarray]]:
    """Standard next-token-prediction loss over a causal LM.

    Labels follow the HF convention (already shifted by the collator:
    labels[t] is the target for position t, ignore_index=-100 padding).
    ``loss='fused_linear_ce'`` skips logits materialization (reference:
    FusedLinearCrossEntropy, loss/linear_ce.py:119).
    """
    from automodel_tpu.ops import losses as L

    def loss_fn(params, mb):
        kw = {
            k: mb[k]
            for k in (
                "position_ids", "segment_ids", "pixel_values",
                "mrope_position_ids",
            )
            if k in mb and mb[k] is not None
        }
        if loss in ("fused_linear_ce", "vocab_parallel_ce"):
            out = model.hidden(params, mb["input_ids"], constrain=constrain, **kw)
            hidden, maux = out if isinstance(out, tuple) else (out, None)
            kernel = model.lm_head(params).astype(hidden.dtype)
            mesh_ctx = getattr(constrain, "mesh_ctx", None)
            if loss == "vocab_parallel_ce" and mesh_ctx is not None:
                loss_sum, n = L.vocab_parallel_cross_entropy(
                    hidden, kernel, mb["labels"], mesh_ctx,
                    logits_soft_cap=model.config.logits_soft_cap, **loss_kwargs,
                )
            else:
                loss_sum, n = L.fused_linear_cross_entropy(
                    hidden, kernel, mb["labels"],
                    logits_soft_cap=model.config.logits_soft_cap, **loss_kwargs,
                )
        else:
            out = model(params, mb["input_ids"], constrain=constrain, **kw)
            logits, maux = out if isinstance(out, tuple) else (out, None)
            loss_sum, n = L.build_loss(loss, **loss_kwargs)(logits, mb["labels"])
        if maux is None:
            return loss_sum, n
        # MoE models return (output, aux). The aux loss is a per-batch mean;
        # weighting by this microbatch's token count makes the global
        # normalization (divide by total tokens) produce the correct
        # token-weighted average across microbatches and the dp_cp group.
        loss_sum = loss_sum + maux.aux_loss * n.astype(jnp.float32)
        extras = {
            "moe_aux_loss": maux.aux_loss,
            "expert_counts": maux.expert_counts,
        }
        return loss_sum, n, extras

    # pipelined models advertise their schedule so the step metrics (and the
    # benchmark recipe) can report bubble fraction per schedule
    info = getattr(model, "pipeline_info", None)
    if info:
        loss_fn.pipeline_info = info

    return loss_fn
