from automodel_tpu.parallel.mesh import (
    LOGICAL_AXIS_RULES,
    MeshAxisName,
    MeshConfig,
    MeshContext,
    build_mesh,
    initialize_distributed,
)

__all__ = [
    "LOGICAL_AXIS_RULES",
    "MeshAxisName",
    "MeshConfig",
    "MeshContext",
    "build_mesh",
    "initialize_distributed",
]
