"""Pipeline parallelism: SPMD microbatch pipeline over the ``pp`` mesh axis.

Parity: the reference's AutoPipeline (distributed/pipelining/autopipeline.py:
46, functional.py:289-560) — FQN-based stage splitting + torch.distributed
pipelining schedules (gpipe/1f1b/interleaved). TPU-native design (SURVEY.md
§7): the decoder stack's stacked layer axis IS the stage structure — slice it
across pp, and run a GPipe wavefront as a `lax.scan` over ticks inside a
`shard_map` that is MANUAL over pp only (`axis_names={'pp'}`): activations
hop stages via `lax.ppermute` while dp/tp/fsdp sharding inside each stage
stays compiler-managed (GSPMD auto axes). `jax.grad` differentiates through
the whole pipeline (transpose of ppermute reverses the ring), so the backward
wavefront needs no hand-written schedule, and XLA overlaps the ppermute with
stage compute.

Outputs leave the pipeline SHARDED on pp (out_specs lead with "pp"); the
caller slices the last stage's entry, which lowers to a broadcast from one
rank instead of the full-activation psum an earlier revision paid per step
(reference keeps loss on the last stage the same way, train_ft.py:1365).

MoE stacks pipeline too: the stage function may return (y, stage_aux) and
per-stage aux (expert counts, aux losses) accumulates across microbatch
ticks under a validity mask, coming back [pp, L/pp, ...] for reassembly —
the composition the reference reaches via PP+EP parallelize_fn per stage
(moe/parallelizer.py:300).

Bubble: (pp-1)/(M+pp-1) with M microbatches — choose M >= 4·pp.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from automodel_tpu.parallel.mesh import MeshContext


def spmd_pipeline(
    stage_fn: Callable,  # (stage_params, x [mb,...], aux) -> y | (y, stage_aux)
    stage_params: Any,  # pytree, leaves [L, ...] with L divisible by pp
    inputs: jnp.ndarray,  # [M, mb, ...] microbatched activations
    aux: Any,  # pytree of [M, ...] per-microbatch side inputs (cos/sin/seg)
    mesh_ctx: MeshContext,
    has_stage_aux: bool = False,
) -> Any:
    """Run the stacked-layer decoder as a pp-stage pipeline.

    Returns [M, mb, ...] outputs, or (outputs, global_aux) when
    ``has_stage_aux`` — global_aux leaves lead with the pp axis
    ([pp, L/pp, ...]) for the caller to reassemble into [L, ...].
    """
    mesh = mesh_ctx.mesh
    pp = mesh.shape["pp"]
    if pp == 1:
        if has_stage_aux:
            ys, auxs = jax.lax.map(
                lambda args: stage_fn(stage_params, args[0], args[1]), (inputs, aux)
            )
            # sum microbatch contributions; prepend the pp=1 stage axis
            auxs = jax.tree.map(lambda a: a.sum(0)[None].astype(jnp.float32), auxs)
            return ys, auxs
        return jax.lax.map(
            lambda args: stage_fn(stage_params, args[0], args[1]), (inputs, aux)
        )
    M = inputs.shape[0]
    compute_dtype = inputs.dtype

    param_specs = jax.tree.map(lambda _: P("pp"), stage_params)
    # the input buffer crosses the shard_map boundary replicated over pp; its
    # transpose is a psum of cotangents, which must be f32 (bf16 all-reduce
    # also trips XLA-CPU's AllReducePromotion). Inside the region activations
    # are cast back, so ppermute traffic stays in compute dtype.
    inputs = inputs.astype(jnp.float32)

    def pp_fn(sp, inp, auxb):
        # local views: sp leaves [L/pp, ...]; inp/auxb full [M, ...]
        p = jax.lax.axis_index("pp")
        n_ticks = M + pp - 1
        state0 = jnp.zeros(inp.shape[1:], compute_dtype)

        a0 = jax.tree.map(lambda b: b[0], auxb)
        if has_stage_aux:
            _, aux_shape = jax.eval_shape(stage_fn, sp, state0, a0)
            acc0 = jax.tree.map(lambda s: jnp.zeros(s.shape, jnp.float32), aux_shape)
        else:
            acc0 = None

        def tick(carry, t):
            state, acc = carry
            in_idx = jnp.clip(t, 0, M - 1)
            mb_idx = jnp.clip(t - p, 0, M - 1)
            x_in = jnp.where(p == 0, inp[in_idx].astype(compute_dtype), state)
            a = jax.tree.map(lambda b: b[mb_idx], auxb)
            if has_stage_aux:
                y, saux = stage_fn(sp, x_in, a)
                # rank p holds a real microbatch only for ticks [p, p+M)
                valid = jnp.logical_and(t >= p, t < p + M)
                acc = jax.tree.map(
                    lambda A, s: A + jnp.where(valid, s.astype(jnp.float32), 0.0),
                    acc,
                    saux,
                )
            else:
                y = stage_fn(sp, x_in, a)
            state_next = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)]
            )
            return (state_next, acc), y

        (_, acc), ys = jax.lax.scan(tick, (state0, acc0), jnp.arange(n_ticks))
        # each rank returns its own tick outputs, sharded on a leading pp
        # axis; only rank pp-1's row holds final-stage activations and the
        # caller's slice of that row lowers to a broadcast from one rank —
        # no full-activation psum.
        ys = ys[pp - 1 :][None]
        if has_stage_aux:
            return ys, jax.tree.map(lambda A: A[None], acc)
        return ys

    out_specs = (P("pp"), P("pp")) if has_stage_aux else P("pp")
    mapped = shard_map(
        pp_fn,
        mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=out_specs,
        axis_names={"pp"},
        check_vma=False,
    )
    if has_stage_aux:
        ys, acc = mapped(stage_params, inputs, aux)
        return ys[pp - 1], acc
    return mapped(stage_params, inputs, aux)[pp - 1]


_logged_a2a_pp = False


def _log_a2a_pp_fallback():
    global _logged_a2a_pp
    if not _logged_a2a_pp:
        _logged_a2a_pp = True
        import logging

        logging.getLogger(__name__).info(
            "experts='a2a' inside pipeline stages runs as the dropless ragged "
            "path with GSPMD-chosen ep collectives (nested shard_map over ep "
            "is not possible inside the pp-manual region); no tokens drop."
        )


def _maybe_remat(fn, backend):
    if backend.remat in ("full", "selective"):
        pol = (
            jax.checkpoint_policies.nothing_saveable
            if backend.remat == "full"
            else jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
        return jax.checkpoint(fn, policy=pol)
    return fn


def _microbatch_plumbing(model, params, input_ids, position_ids, M):
    """Shared embed/rope/split prep for the pipelined forwards."""
    from automodel_tpu.ops.rope import rope_table

    cfg, backend = model.config, model.backend
    cd = backend.compute_jnp_dtype
    B, S = input_ids.shape
    assert B % M == 0, f"batch {B} not divisible by n_microbatches {M}"
    if position_ids is None:
        position_ids = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
        )
    h = params["embed"]["embedding"].astype(cd)[input_ids]
    rope_dim = getattr(model, "pp_rope_dim", None) or cfg.head_dim
    cos, sin = rope_table(position_ids, rope_dim, cfg.rope)

    def split(x):
        return None if x is None else x.reshape(M, B // M, *x.shape[1:])

    return h, cos, sin, split


@dataclasses.dataclass
class PipelinedCausalLM:
    """Wrap a dense stacked-layer causal LM (llama family) for PP execution.

    Embedding and lm_head run GSPMD outside the pipeline (they live on the
    reference's first/last stages; here every rank holds them sharded —
    simpler, and XLA fuses their collectives with the pipeline edges).
    Exposes the same model API (call/hidden/lm_head/sharding_rules) so
    make_causal_lm_loss and recipes need no PP-specific code.
    """

    model: Any  # LlamaForCausalLM
    mesh_ctx: MeshContext
    n_microbatches: int = 4

    @property
    def config(self):
        return self.model.config

    @property
    def backend(self):
        return self.model.backend

    def init(self, key: jax.Array) -> dict:
        return self.model.init(key)

    def lm_head(self, params: dict) -> jnp.ndarray:
        return self.model.lm_head(params)

    @property
    def sharding_rules(self) -> list[tuple[str, tuple]]:
        """Layer-stacked leaves get their leading dim sharded on `stage`."""
        rules = []
        for pat, spec in self.model.sharding_rules:
            if "layers/" in pat:
                # the family rules already spell the stacked layer dim as a
                # leading None — PP shards that dim on `stage`
                rules.append((pat, ("stage", *tuple(spec)[1:])))
            else:
                rules.append((pat, spec))
        return rules

    # -- forward -------------------------------------------------------------
    def hidden(self, params, input_ids, position_ids=None, segment_ids=None,
               constrain=None):
        from automodel_tpu.models.llama.model import decoder_layer
        from automodel_tpu.ops.norms import rms_norm

        cfg, backend = self.model.config, self.model.backend
        constrain = constrain or (lambda x, s: x)
        M = self.n_microbatches
        B, S = input_ids.shape
        h, cos, sin, split = _microbatch_plumbing(
            self.model, params, input_ids, position_ids, M
        )
        h = constrain(h, ("batch", "seq", None))
        aux = {"cos": split(cos), "sin": split(sin)}
        if segment_ids is not None:
            aux["seg"] = split(segment_ids)

        def stage_fn(sp, x, a):
            def layer(carry, lp):
                out = decoder_layer(
                    cfg, backend, carry, lp, a["cos"], a["sin"], a.get("seg"),
                    lambda t, s: t,  # constraints referencing pp are invalid
                )                     # inside the manual region; GSPMD infers
                return out, None

            out, _ = jax.lax.scan(_maybe_remat(layer, backend), x, sp)
            return out

        hm = spmd_pipeline(
            stage_fn, params["layers"], split(h), aux, self.mesh_ctx
        )
        h = hm.reshape(B, S, -1)
        h = constrain(h, ("batch", "seq", None))
        return rms_norm(h, params["final_norm"]["scale"], cfg.rms_eps)

    def __call__(self, params, input_ids, **kw):
        h = self.hidden(params, input_ids, **kw)
        logits = h @ self.model.lm_head(params).astype(h.dtype)
        cfg = self.model.config
        if cfg.logits_soft_cap is not None:
            logits = cfg.logits_soft_cap * jnp.tanh(logits / cfg.logits_soft_cap)
        return logits


@dataclasses.dataclass
class PipelinedMoECausalLM:
    """PP for the MoE families (Qwen3-MoE shaped, incl. DeepSeek-V3 MLA).

    The routed-MoE stack pipelines over pp (EP/TP/FSDP stay GSPMD-managed
    inside each stage); the short dense prefix (DeepSeek
    first_k_dense_replace) runs GSPMD outside the pipeline on every rank,
    like embed/lm_head. Per-layer gate aux (expert counts, aux loss) rides
    the tick scan under a validity mask and reassembles to the same
    MoEModelAux the unpipelined forward returns — so aux-free bias updates
    and load-balance metrics work unchanged under PP (reference:
    PP+EP composition via per-stage parallelize_fn, moe/parallelizer.py:300).
    """

    model: Any  # MoEForCausalLM | DeepseekV3ForCausalLM
    mesh_ctx: MeshContext
    n_microbatches: int = 4

    @property
    def config(self):
        return self.model.config

    @property
    def backend(self):
        return self.model.backend

    def init(self, key: jax.Array) -> dict:
        return self.model.init(key)

    def lm_head(self, params: dict) -> jnp.ndarray:
        return self.model.lm_head(params)

    def post_step_fn(self, params: dict, extras: dict) -> dict:
        return self.model.post_step_fn(params, extras)

    _NONSTACK = ("embed/", "lm_head/", "final_norm/")

    @property
    def sharding_rules(self) -> list[tuple[str, tuple]]:
        """dense_layers stay replicated over pp (they run outside the
        pipeline); moe_layers leaves get their stack dim sharded on
        `stage`. Family patterns are normalized so both prefixed variants
        match ('layers/attn/...' → 'attn/...')."""
        rules: list[tuple[str, tuple]] = []
        for pat, spec in self.model.sharding_rules:
            if any(s in pat for s in self._NONSTACK):
                rules.append((pat, spec))
                continue
            core = pat[len("layers/"):] if pat.startswith("layers/") else pat
            rules.append((f"^dense_layers/.*{core}", spec))
            rules.append((f"^moe_layers/.*{core}", ("stage", *tuple(spec)[1:])))
        return rules

    def hidden(self, params, input_ids, **kw):
        # same contract as the wrapped MoE models: (hidden, MoEModelAux) —
        # the fused_linear_ce loss path consumes the aux from hidden()
        return self._forward(params, input_ids, **kw)

    def _forward(self, params, input_ids, position_ids=None, segment_ids=None,
                 constrain=None):
        from automodel_tpu.models.llama.model import ACT_FNS
        from automodel_tpu.moe.layer import moe_block
        from automodel_tpu.ops.norms import rms_norm

        cfg, backend = self.model.config, self.model.backend
        moe = cfg.moe
        constrain = constrain or (lambda x, s: x)
        attn_block = self.model.pp_attn_block
        M = self.n_microbatches
        B, S = input_ids.shape
        h, cos, sin, split = _microbatch_plumbing(
            self.model, params, input_ids, position_ids, M
        )
        h = constrain(h, ("batch", "seq", None))

        # dense prefix outside the pipeline (GSPMD on every rank)
        if "dense_layers" in params:
            def dense_fn(carry, lp):
                hh = attn_block(cfg, backend, carry, lp, cos, sin, segment_ids, constrain)
                x = rms_norm(hh, lp["post_attn_norm"]["scale"], cfg.rms_eps)
                act = ACT_FNS[cfg.act]
                mlp = (
                    act(x @ lp["mlp"]["gate_proj"]["kernel"].astype(x.dtype))
                    * (x @ lp["mlp"]["up_proj"]["kernel"].astype(x.dtype))
                ) @ lp["mlp"]["down_proj"]["kernel"].astype(x.dtype)
                return constrain(hh + mlp, ("batch", "seq", None)), None

            h, _ = jax.lax.scan(_maybe_remat(dense_fn, backend), h, params["dense_layers"])

        aux_in = {"cos": split(cos), "sin": split(sin)}
        if segment_ids is not None:
            aux_in["seg"] = split(segment_ids)

        # the a2a token-exchange dispatcher is itself a shard_map over ep/tp,
        # and jax only allows nested shard_map over axes ALREADY manual — so
        # inside the pp-manual region it cannot run. Use the dropless ragged
        # path instead: XLA partitions its grouped GEMMs over the auto ep
        # axis (no token drops; explicit a2a-in-PP needs nested manual axes)
        experts_backend = backend.experts
        if experts_backend == "a2a":
            _log_a2a_pp_fallback()
            experts_backend = "ragged"

        def stage_fn(sp, x, a):
            def layer(carry, lp):
                hh = attn_block(
                    cfg, backend, carry, lp, a["cos"], a["sin"], a.get("seg"),
                    lambda t, s: t,
                )
                xx = rms_norm(hh, lp["post_attn_norm"]["scale"], cfg.rms_eps)
                out, aux = moe_block(
                    xx,
                    lp["moe"],
                    moe,
                    ACT_FNS[cfg.act],
                    experts_backend=experts_backend,
                    fake_gate=backend.fake_balanced_gate,
                    constrain=lambda t, s: t,
                )
                return hh + out, aux

            out, auxs = jax.lax.scan(_maybe_remat(layer, backend), x, sp)
            return out, auxs  # auxs leaves [L/pp, ...]

        hm, acc = spmd_pipeline(
            stage_fn, params["moe_layers"], split(h), aux_in, self.mesh_ctx,
            has_stage_aux=True,
        )
        h = hm.reshape(B, S, -1)
        h = constrain(h, ("batch", "seq", None))
        h = rms_norm(h, params["final_norm"]["scale"], cfg.rms_eps)

        # acc leaves [pp, L/pp, ...] summed over microbatches → [L_moe, ...];
        # aux losses were per-microbatch means, so average over M
        from automodel_tpu.models.qwen3_moe.model import MoEModelAux

        counts = acc.expert_counts.reshape(-1, *acc.expert_counts.shape[2:])
        aux_loss = acc.aux_loss.reshape(-1).sum() / self.n_microbatches
        return h, MoEModelAux(counts, aux_loss)

    def __call__(self, params, input_ids, **kw):
        h, aux = self._forward(params, input_ids, **kw)
        logits = h @ self.model.lm_head(params).astype(h.dtype)
        cfg = self.model.config
        if cfg.logits_soft_cap is not None:
            logits = cfg.logits_soft_cap * jnp.tanh(logits / cfg.logits_soft_cap)
        return logits, aux


def maybe_pipeline(model: Any, mesh_ctx: Optional[MeshContext], n_microbatches: int = 4):
    """Wrap `model` for PP when the mesh has pp > 1. Dense llama-family and
    MoE (qwen3-moe / deepseek-v3) stacks are supported; mixed-window stacks
    (gemma/gpt-oss) still raise."""
    if mesh_ctx is None or mesh_ctx.pp_size == 1:
        return model
    if not hasattr(model, "config"):
        raise NotImplementedError("PP needs a stacked-layer causal LM")
    cfg = model.config
    if getattr(cfg, "moe", None) is not None:
        if not hasattr(model, "pp_attn_block"):
            raise NotImplementedError(
                f"PP for {type(model).__name__} not supported yet (per-layer "
                "static attention windows don't slice across pp ranks)"
            )
        n_moe = cfg.num_layers - cfg.moe.num_dense_layers
        if n_moe % mesh_ctx.pp_size != 0:
            raise ValueError(
                f"moe layer count {n_moe} must divide pp={mesh_ctx.pp_size}"
            )
        return PipelinedMoECausalLM(model, mesh_ctx, n_microbatches)
    from automodel_tpu.models.llama.model import LlamaForCausalLM

    if not isinstance(model, LlamaForCausalLM):
        # e.g. gemma: homogeneous llama layers is what the dense stage runs
        raise NotImplementedError(
            f"PP for {type(model).__name__} not supported yet (the dense "
            "pipeline stage runs llama-family decoder layers)"
        )
    if cfg.num_layers % mesh_ctx.pp_size != 0:
        raise ValueError(
            f"num_layers {cfg.num_layers} must divide pp={mesh_ctx.pp_size}"
        )
    return PipelinedCausalLM(model, mesh_ctx, n_microbatches)
