"""Pipeline parallelism: SPMD microbatch pipeline over the ``pp`` mesh axis.

Parity: the reference's AutoPipeline (distributed/pipelining/autopipeline.py:
46, functional.py:289-560) — FQN-based stage splitting + torch.distributed
pipelining schedules (gpipe/1f1b/interleaved). TPU-native design (SURVEY.md
§7): the decoder stack's stacked layer axis IS the stage structure — slice it
across pp, and run a GPipe wavefront as a `lax.scan` over ticks inside a
`shard_map` that is MANUAL over pp only (`axis_names={'pp'}`): activations
hop stages via `lax.ppermute` while dp/tp/fsdp sharding inside each stage
stays compiler-managed (GSPMD auto axes). `jax.grad` differentiates through
the whole pipeline (transpose of ppermute reverses the ring), so the backward
wavefront needs no hand-written schedule, and XLA overlaps the ppermute with
stage compute.

Bubble: (pp-1)/(M+pp-1) with M microbatches — choose M >= 4·pp. The
interleaved/zero-bubble schedules of the reference map to circular stage
assignment here (planned: num_repeats > 1 slicing the layer axis round-robin).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from automodel_tpu.parallel.mesh import MeshContext


def spmd_pipeline(
    stage_fn: Callable,  # (stage_params, x [mb,...], aux pytree) -> y [mb,...]
    stage_params: Any,  # pytree, leaves [L, ...] with L divisible by pp
    inputs: jnp.ndarray,  # [M, mb, ...] microbatched activations
    aux: Any,  # pytree of [M, ...] per-microbatch side inputs (cos/sin/seg)
    mesh_ctx: MeshContext,
) -> jnp.ndarray:
    """Run the stacked-layer decoder as a pp-stage pipeline; returns [M, mb, ...]."""
    mesh = mesh_ctx.mesh
    pp = mesh.shape["pp"]
    if pp == 1:
        ys = jax.lax.map(lambda args: stage_fn(stage_params, args[0], args[1]), (inputs, aux))
        return ys
    M = inputs.shape[0]
    compute_dtype = inputs.dtype

    param_specs = jax.tree.map(lambda _: P("pp"), stage_params)
    # the input buffer crosses the shard_map boundary replicated over pp; its
    # transpose is a psum of cotangents, which must be f32 (bf16 all-reduce
    # also trips XLA-CPU's AllReducePromotion). Inside the region activations
    # are cast back, so ppermute traffic stays in compute dtype.
    inputs = inputs.astype(jnp.float32)

    def pp_fn(sp, inp, auxb):
        # local views: sp leaves [L/pp, ...]; inp/auxb full [M, ...]
        sp = jax.tree.map(lambda x: x, sp)
        p = jax.lax.axis_index("pp")
        n_ticks = M + pp - 1
        state0 = jnp.zeros(inp.shape[1:], compute_dtype)

        def tick(state, t):
            in_idx = jnp.clip(t, 0, M - 1)
            mb_idx = jnp.clip(t - p, 0, M - 1)
            x_in = jnp.where(p == 0, inp[in_idx].astype(compute_dtype), state)
            a = jax.tree.map(lambda b: b[mb_idx], auxb)
            y = stage_fn(sp, x_in, a)
            y_out = jnp.where(
                jnp.logical_and(p == pp - 1, t >= pp - 1), y, jnp.zeros_like(y)
            )
            state_next = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)]
            )
            return state_next, y_out

        _, ys = jax.lax.scan(tick, state0, jnp.arange(n_ticks))
        # only the last stage produced real outputs; make them global.
        # (psum over pp = one activation all-reduce per step; the planned
        # refinement keeps loss computation on the last stage instead.)
        # f32 ring: XLA CPU's AllReducePromotion crashes on bf16 psum, and on
        # TPU f32 reduction of bf16 zeros+values is exact anyway.
        ys = jax.lax.psum(ys.astype(jnp.float32), "pp").astype(ys.dtype)
        return ys[pp - 1 :]

    mapped = shard_map(
        pp_fn,
        mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=P(),
        axis_names={"pp"},
        check_vma=False,
    )
    return mapped(stage_params, inputs, aux)


@dataclasses.dataclass
class PipelinedCausalLM:
    """Wrap a dense stacked-layer causal LM (llama family) for PP execution.

    Embedding and lm_head run GSPMD outside the pipeline (they live on the
    reference's first/last stages; here every rank holds them sharded —
    simpler, and XLA fuses their collectives with the pipeline edges).
    Exposes the same model API (call/hidden/lm_head/sharding_rules) so
    make_causal_lm_loss and recipes need no PP-specific code.
    """

    model: Any  # LlamaForCausalLM
    mesh_ctx: MeshContext
    n_microbatches: int = 4

    @property
    def config(self):
        return self.model.config

    @property
    def backend(self):
        return self.model.backend

    def init(self, key: jax.Array) -> dict:
        return self.model.init(key)

    def lm_head(self, params: dict) -> jnp.ndarray:
        return self.model.lm_head(params)

    @property
    def sharding_rules(self) -> list[tuple[str, tuple]]:
        """Layer-stacked leaves get their leading dim sharded on `stage`."""
        rules = []
        for pat, spec in self.model.sharding_rules:
            if "layers/" in pat:
                # the family rules already spell the stacked layer dim as a
                # leading None — PP shards that dim on `stage`
                rules.append((pat, ("stage", *tuple(spec)[1:])))
            else:
                rules.append((pat, spec))
        return rules

    # -- forward -------------------------------------------------------------
    def hidden(self, params, input_ids, position_ids=None, segment_ids=None,
               constrain=None):
        from automodel_tpu.models.llama.model import decoder_layer
        from automodel_tpu.ops.norms import rms_norm
        from automodel_tpu.ops.rope import rope_table

        cfg, backend = self.model.config, self.model.backend
        constrain = constrain or (lambda x, s: x)
        cd = backend.compute_jnp_dtype
        B, S = input_ids.shape
        M = self.n_microbatches
        assert B % M == 0, f"batch {B} not divisible by n_microbatches {M}"
        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
            )

        h = params["embed"]["embedding"].astype(cd)[input_ids]
        h = constrain(h, ("batch", "seq", None))
        cos, sin = rope_table(position_ids, cfg.head_dim, cfg.rope)

        def split(x):
            return None if x is None else x.reshape(M, B // M, *x.shape[1:])

        aux = {"cos": split(cos), "sin": split(sin)}
        if segment_ids is not None:
            aux["seg"] = split(segment_ids)

        def stage_fn(sp, x, a):
            def layer(carry, lp):
                out = decoder_layer(
                    cfg, backend, carry, lp, a["cos"], a["sin"], a.get("seg"),
                    lambda t, s: t,  # constraints referencing pp are invalid
                )                     # inside the manual region; GSPMD infers
                return out, None

            fn = layer
            if backend.remat in ("full", "selective"):
                pol = (
                    jax.checkpoint_policies.nothing_saveable
                    if backend.remat == "full"
                    else jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                )
                fn = jax.checkpoint(layer, policy=pol)
            out, _ = jax.lax.scan(fn, x, sp)
            return out

        hm = spmd_pipeline(
            stage_fn, params["layers"], split(h), aux, self.mesh_ctx
        )
        h = hm.reshape(B, S, -1)
        h = constrain(h, ("batch", "seq", None))
        return rms_norm(h, params["final_norm"]["scale"], cfg.rms_eps)

    def __call__(self, params, input_ids, **kw):
        h = self.hidden(params, input_ids, **kw)
        logits = h @ self.model.lm_head(params).astype(h.dtype)
        cfg = self.model.config
        if cfg.logits_soft_cap is not None:
            logits = cfg.logits_soft_cap * jnp.tanh(logits / cfg.logits_soft_cap)
        return logits


def maybe_pipeline(model: Any, mesh_ctx: Optional[MeshContext], n_microbatches: int = 4):
    """Wrap `model` for PP when the mesh has pp > 1 (dense families only for
    now; MoE+PP composition is tracked work)."""
    if mesh_ctx is None or mesh_ctx.pp_size == 1:
        return model
    if not hasattr(model, "config") or getattr(model.config, "moe", None) is not None:
        raise NotImplementedError("PP currently supports dense stacked-layer models")
    if model.config.num_layers % mesh_ctx.pp_size != 0:
        raise ValueError(
            f"num_layers {model.config.num_layers} must divide pp={mesh_ctx.pp_size}"
        )
    return PipelinedCausalLM(model, mesh_ctx, n_microbatches)
