"""Zero-bubble pipeline schedule: B/W-split backward with deferred weight-grads.

Reference blueprint: the ZBVZeroBubble schedule family (functional.py:490-560;
"Zero Bubble Pipeline Parallelism", Qi et al.) splits each stage's backward
into B — activation gradients, on the critical inter-stage path — and W —
weight gradients, computable from saved (input, output-cotangent) pairs at any
later tick. In this repo's synchronous-tick SPMD formulation (parallel/pp.py:
one lax.scan over global ticks, stages hop via ppermute) per-rank asynchronous
slots don't exist, so the schedule takes the synchronous-tick form:

  fwd wave   (M+pp-1 ticks, cost F each)   — unchanged GPipe wavefront
  B wave     (M+pp-1 ticks, cost ~2F each) — hand-written reverse wavefront:
             per tick, recompute the stage forward and propagate ONLY the
             activation cotangent dx through ppermute; the per-matmul
             (x, dy) pairs needed for weight grads are exported into a
             deferral buffer instead of being contracted on the tick
  W flush    (M slots of flat work, cost ~F each) — all ranks contract their
             own stage's deferred dW chunks with NO pipeline dependency,
             i.e. zero bubble for the W third of the backward

Per-rank idle drops from 3(pp-1) tick-equivalents (GPipe: fwd + AD backward
at 3F/tick under remat) to 3(pp-1) out of a larger denominator with the W
work bubble-free:   bubble = 3(pp-1) / (4M + 3(pp-1))  <  (pp-1)/(M+pp-1)
for every M — strictly below the GPipe law (analytic model in
utils/flops_utils.pipeline_bubble_fraction; measured in PROFILE_PP_r06.md).

Mechanism for the B/W split without hand-writing the transformer backward:
``split_dot`` is a custom_vjp matmul whose backward returns dx immediately,
a symbolically-zero weight cotangent, and EXPORTS (x, dy) as the cotangents
of two zero-valued "tap" primal inputs grafted into the layer param tree
(``zb_tap`` keys, consumed by models/llama/model._proj). jax.vjp over the
tapped stage therefore computes exactly B (the heavy dW contractions are
dead and DCE'd) while the tap cotangents deliver the stash the deferred W
contraction needs — no recompute in the W phase.

Deferral-queue bound: the stash for one microbatch is ~the no-remat
activation footprint of one stage. ``zb_queue`` bounds how many microbatches
may be in flight: a full queue consumes its oldest entry ON the B tick
(degrading that tick toward the combined GPipe cost but capping memory at
queue_size stashes); zb_queue=None defers everything to the flat flush.

Grad-accumulation contract (training/train_step.py): W contributions land
out of microbatch order inside this file's backward — summed here in fp32 —
and the COMPLETE gradient (B-computed small params + W-computed kernels)
is what leaves the custom_vjp, so the train step's fp32 global-norm clip
only ever sees gradients with all W chunks landed.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from automodel_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

# -- B/W split matmul ---------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def split_dot(export_x: bool, x, w, xtap, ytap):
    """``x @ w`` whose backward computes ONLY dx; dw is deferred.

    ``xtap``/``ytap`` are zero-valued primal inputs shaped like x and y (or
    shape [0] for a shared-x site, see SITE specs): their cotangents are
    DEFINED to be (x, dy) — the pair the deferred weight-grad contraction
    dW = x^T dy needs. Taking jax.vjp w.r.t. the taps exports the stash
    from inside an AD-generated backward without any side channel.
    """
    del xtap, ytap
    return x @ w.astype(x.dtype)


def _split_dot_fwd(export_x, x, w, xtap, ytap):
    del xtap, ytap
    return x @ w.astype(x.dtype), (x, w)


def _split_dot_bwd(export_x, res, dy):
    x, w = res
    dy = dy.astype(x.dtype)
    dx = dy @ w.astype(dy.dtype).T
    dw = jnp.zeros_like(w)  # deferred to the W phase; dead → DCE'd
    dxtap = x if export_x else jnp.zeros((0,), x.dtype)
    return dx, dw, dxtap, dy


split_dot.defvjp(_split_dot_fwd, _split_dot_bwd)


# -- site specs ---------------------------------------------------------------
# Site path (inside one layer's param tree) → site to borrow the input-side
# tap from (q/k/v and gate/up consume the same normed activation — one
# export serves all three), or None to export its own.

DENSE_SITES: dict[tuple, Optional[tuple]] = {
    ("attn", "q_proj"): None,
    ("attn", "k_proj"): ("attn", "q_proj"),
    ("attn", "v_proj"): ("attn", "q_proj"),
    ("attn", "o_proj"): None,
    ("mlp", "gate_proj"): None,
    ("mlp", "up_proj"): ("mlp", "gate_proj"),
    ("mlp", "down_proj"): None,
}

# MoE stages defer the attention projections only: expert/router weight
# grads stay on the B tick (the grouped-matmul backends carry their own
# custom_vjp; threading taps through them is future work) — correctness is
# unaffected, the bubble win is proportional to the attention share.
ATTN_SITES: dict[tuple, Optional[tuple]] = {
    k: v for k, v in DENSE_SITES.items() if k[0] == "attn"
}


# -- tree surgery -------------------------------------------------------------


def _copy_tree(d):
    if isinstance(d, dict):
        return {k: _copy_tree(v) for k, v in d.items()}
    return d


def _node(tree: Any, path: tuple) -> Optional[dict]:
    node = tree
    for k in path:
        if not isinstance(node, dict) or k not in node:
            return None
        node = node[k]
    return node if isinstance(node, dict) else None


def resolve_sites(stage_params: Any, sites: dict) -> dict:
    """Filter the site spec to sites actually deferrable in this tree:
    present, a plain stacked [Lp, Din, Dout] kernel (not NF4-packed), and
    no activation-side LoRA riding the projection. A site whose x-source
    got filtered exports its own input instead."""
    elig = {}
    for site, share in sites.items():
        node = _node(stage_params, site)
        if node is None:
            continue
        k = node.get("kernel")
        if not hasattr(k, "ndim") or k.ndim != 3:
            continue
        if "lora_A" in node or "lora_drop_seed" in node:
            continue
        elig[site] = share
    return {
        s: (sh if sh in elig and elig[sh] is None else None)
        for s, sh in elig.items()
    }


def graft_taps(stage_params: Any, resolved: dict, mb: int, S: int, dtype):
    """→ (tapped, heavy): ``tapped`` is the stage tree with each deferred
    site's kernel REMOVED (so the B-pass vjp never accumulates its zero
    cotangent over the layer scan) and a ``zb_tap`` zeros pair inserted;
    ``heavy`` holds the removed stacked kernels, closed over by the stage
    body and re-inserted per layer."""
    tapped = _copy_tree(stage_params)
    heavy = {}
    for site, share in resolved.items():
        node = _node(tapped, site)
        kern = node.pop("kernel")
        heavy[site] = kern
        Lp, Din, Dout = kern.shape
        xtap = (
            jnp.zeros((Lp, mb, S, Din), dtype)
            if share is None
            else jnp.zeros((Lp, 0), dtype)
        )
        node["zb_tap"] = (xtap, jnp.zeros((Lp, mb, S, Dout), dtype))
    return tapped, heavy


def insert_heavy(lp: dict, heavy: dict, i) -> dict:
    """Per-layer: put layer i's slice of each removed kernel back so the
    layer body (which reads p["kernel"]) runs unchanged."""
    lp = _copy_tree(lp)
    for site, kern in heavy.items():
        _node(lp, site)["kernel"] = jax.lax.dynamic_index_in_dim(
            kern, i, 0, keepdims=False
        )
    return lp


def split_taps(d_tapped: Any, resolved: dict):
    """Cotangent tree of the tapped stage → (stash {site: (x, dy)}, rest)."""
    rest = _copy_tree(d_tapped)
    stash = {}
    for site in resolved:
        stash[site] = _node(rest, site).pop("zb_tap")
    return stash, rest


def insert_kernel_grads(d_rest: Any, dW: dict) -> Any:
    out = _copy_tree(d_rest)
    for site, g in dW.items():
        _node(out, site)["kernel"] = g
    return out


class FloatPartition:
    """Static float/int split of a pytree (vjp can only differentiate float
    leaves; int leaves — segment ids, LoRA seed data — are closed over and
    get float0 cotangents)."""

    def __init__(self, tree: Any):
        leaves, self.treedef = jax.tree.flatten(tree)
        self.is_f = [jnp.issubdtype(l.dtype, jnp.floating) for l in leaves]
        self.shapes = [jnp.shape(l) for l in leaves]

    def floats(self, tree: Any) -> list:
        ls = jax.tree.leaves(tree)
        return [l for l, m in zip(ls, self.is_f) if m]

    def ints(self, tree: Any) -> list:
        ls = jax.tree.leaves(tree)
        return [l for l, m in zip(ls, self.is_f) if not m]

    def join(self, floats: list, ints: list) -> Any:
        fi, ii, out = iter(floats), iter(ints), []
        for m in self.is_f:
            out.append(next(fi) if m else next(ii))
        return jax.tree.unflatten(self.treedef, out)

    def cotangent(self, float_cts: list) -> Any:
        """Full cotangent tree: float leaves from ``float_cts``, float0
        zeros for int leaves (the custom_vjp contract for int primals)."""
        from jax import dtypes as jdt

        fi, out = iter(float_cts), []
        for m, shp in zip(self.is_f, self.shapes):
            out.append(next(fi) if m else np.zeros(shp, jdt.float0))
        return jax.tree.unflatten(self.treedef, out)


# -- deferred-W contraction ---------------------------------------------------


def _dw_contract(x, dy):
    """(x [..., Lp, mb, S, Din], dy [..., Lp, mb, S, Dout]) → [Lp, Din, Dout]
    in fp32 — the deferred weight-grad chunk. Leading axes beyond the layer
    axis (queue slots) are contracted too."""
    eq = "lbsi,lbso->lio" if x.ndim == 4 else "qlbsi,qlbso->lio"
    return jnp.einsum(eq, x, dy, preferred_element_type=jnp.float32)


def accumulate_dw(dW_acc: dict, stash: dict, resolved: dict) -> dict:
    out = dict(dW_acc)
    for site, share in resolved.items():
        xv = stash[share or site][0]
        dyv = stash[site][1]
        out[site] = out[site] + _dw_contract(xv, dyv)
    return out


# -- the pipeline -------------------------------------------------------------


def zb_spmd_pipeline(
    layer_fn: Callable,  # (h, lp, aux_slice) -> (h, stage_aux_leaf | None)
    stage_params: Any,   # pytree, leaves [L, ...] with L divisible by pp
    inputs: jnp.ndarray,  # [M, mb, S, D] microbatched activations
    aux: Any,            # pytree of [M, ...] per-microbatch side inputs
    mesh_ctx: Any,
    *,
    sites: dict,
    has_stage_aux: bool = False,
    zb_queue: Optional[int] = None,
    remat: str = "none",
) -> Any:
    """Zero-bubble drop-in for ``pp.spmd_pipeline`` (pp > 1, ep-auto only).

    Same contract: returns the last stage's outputs [M, mb, S, D] (plus the
    microbatch-summed stage aux, leaves [pp, L/pp, ...], when
    ``has_stage_aux``). Forward is the identical GPipe wavefront; the whole
    backward is hand-scheduled inside a custom_vjp (module docstring).
    """
    from automodel_tpu.models.common.stacking import remat_wrap

    mesh = mesh_ctx.mesh
    pp = mesh.shape["pp"]
    M, mb, S = inputs.shape[0], inputs.shape[1], inputs.shape[2]
    cd = inputs.dtype
    n_ticks = M + pp - 1
    Q = M if zb_queue is None else max(1, min(int(zb_queue), M))
    bounded = Q < M

    param_specs = jax.tree.map(lambda _: P("pp"), stage_params)
    data_spec = P()
    aux_part = FloatPartition(aux)
    sp_part = FloatPartition(stage_params)

    def stage_fwd(sp, x, a):
        def body(h, lp):
            return layer_fn(h, lp, a)

        return jax.lax.scan(body, x, sp)

    # ---- forward wavefront (custom_vjp primal; also saves per-tick stage
    # inputs — the 1F1B-equivalent stage-boundary residuals) ----------------
    def fwd_fn(sp, inp, auxb):
        p = jax.lax.axis_index("pp")
        state0 = jnp.zeros(inp.shape[1:], cd)
        if has_stage_aux:
            a0 = jax.tree.map(lambda b: b[0], auxb)
            _, aux_shape = jax.eval_shape(stage_fwd, sp, state0, a0)
            acc0 = jax.tree.map(lambda s: jnp.zeros(s.shape, jnp.float32), aux_shape)
        else:
            acc0 = None

        def tick(carry, t):
            state, acc = carry
            in_idx = jnp.clip(t, 0, M - 1)
            mb_idx = jnp.clip(t - p, 0, M - 1)
            x_in = jnp.where(p == 0, inp[in_idx].astype(cd), state)
            a = jax.tree.map(lambda b: b[mb_idx], auxb)
            y, saux = stage_fwd(sp, x_in, a)
            if has_stage_aux:
                valid = jnp.logical_and(t >= p, t < p + M)
                acc = jax.tree.map(
                    lambda A, s_: A + jnp.where(valid, s_.astype(jnp.float32), 0.0),
                    acc,
                    saux,
                )
            state_next = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)]
            )
            return (state_next, acc), (y, x_in)

        (_, acc), (ys, xs) = jax.lax.scan(tick, (state0, acc0), jnp.arange(n_ticks))
        ys = ys[pp - 1 :][None]
        xs = xs[None]
        if has_stage_aux:
            return ys, xs, jax.tree.map(lambda A: A[None], acc)
        return ys, xs

    def run_fwd(sp, inp, auxb):
        out_specs = (P("pp"), P("pp"), P("pp")) if has_stage_aux else (P("pp"), P("pp"))
        return shard_map(
            fwd_fn,
            mesh=mesh,
            in_specs=(param_specs, data_spec, data_spec),
            out_specs=out_specs,
            axis_names={"pp"},
            check_vma=False,
        )(sp, inp, auxb)

    # ---- hand-scheduled backward: B wave + bounded deferral + W flush -----
    def bwd_fn(sp, inp, auxb, xs, d_ys, d_acc):
        p = jax.lax.axis_index("pp")
        off = (pp - 1) - p
        xs = xs[0]  # [n_ticks, mb, S, D] — this rank's saved stage inputs
        d_acc_l = (
            jax.tree.map(lambda a: a[0], d_acc) if has_stage_aux else None
        )
        resolved = resolve_sites(sp, sites)
        tapped, heavy = graft_taps(sp, resolved, mb, S, cd)
        tp_part = FloatPartition(tapped)
        # int leaves (e.g. LoRA seed data) are closed over for the primal
        # and get ZERO fillers on the cotangent side
        tp_ints = tp_part.ints(tapped)
        tp_int_zeros = [jnp.zeros_like(l) for l in tp_ints]
        stripped = split_taps(tapped, resolved)[1]  # structure/dtype reference

        def btick(carry, s):
            dstate, small_acc, dW_acc, buf = carry
            j = s - off
            jc = jnp.clip(j, 0, M - 1)
            valid = jnp.logical_and(j >= 0, j < M)
            # my stage-output cotangent for microbatch jc: the loss feeds
            # the last rank directly; earlier ranks receive the next
            # stage's dx from the reverse ppermute (timing: rank p+1
            # computed mb jc's dx exactly one tick ago)
            dy = jnp.where(p == pp - 1, d_ys[jc].astype(cd), dstate)
            x_in = xs[jnp.clip(jc + p, 0, n_ticks - 1)]
            a_sl = jax.tree.map(lambda b: b[jc], auxb)
            a_ints = aux_part.ints(a_sl)

            def f(tp_floats, x, a_floats):
                tp = tp_part.join(tp_floats, tp_ints)
                a_full = aux_part.join(a_floats, a_ints)

                def body(carry2, lp):
                    h, i = carry2
                    h2, yaux = layer_fn(h, insert_heavy(lp, heavy, i), a_full)
                    return (h2, i + 1), yaux

                (h_out, _), yauxs = jax.lax.scan(
                    remat_wrap(body, remat), (x, jnp.int32(0)), tp
                )
                return (h_out, yauxs) if has_stage_aux else h_out

            _, vjp_fn = jax.vjp(
                f, tp_part.floats(tapped), x_in, aux_part.floats(a_sl)
            )
            if has_stage_aux:
                seed_aux = jax.tree.map(
                    lambda g: jnp.where(valid, g, 0.0), d_acc_l
                )
                d_tpf, dx, d_af = vjp_fn((dy, seed_aux))
            else:
                d_tpf, dx, d_af = vjp_fn(dy)
            d_tapped = tp_part.join(d_tpf, tp_int_zeros)
            stash, d_rest = split_taps(d_tapped, resolved)
            small_acc = jax.tree.map(
                lambda A, g: A + jnp.where(valid, g, 0).astype(jnp.float32),
                small_acc,
                d_rest,
            )
            # deferral buffer: ring slot jc % Q. A full (bounded) queue
            # consumes its oldest entry on this tick — that W contraction
            # rides the B tick, trading bubble for the memory cap. Invalid
            # ticks neither consume nor overwrite (keep the old slot).
            slot = jc % Q
            popped = jax.tree.map(
                lambda b: jax.lax.dynamic_index_in_dim(b, slot, 0, keepdims=False),
                buf,
            )
            if bounded:
                dW_acc = accumulate_dw(
                    dW_acc,
                    jax.tree.map(lambda g: jnp.where(valid, g, 0), popped),
                    resolved,
                )
            new_slot = jax.tree.map(
                lambda new, old: jnp.where(valid, new.astype(old.dtype), old),
                stash,
                popped,
            )
            buf = jax.tree.map(
                lambda b, v: jax.lax.dynamic_update_index_in_dim(b, v, slot, 0),
                buf,
                new_slot,
            )
            dstate_next = jax.lax.ppermute(
                dx, "pp", [(i, (i - 1) % pp) for i in range(pp)]
            )
            return (dstate_next, small_acc, dW_acc, buf), (dx, d_af)

        small0 = jax.tree.map(
            lambda l: jnp.zeros(l.shape, jnp.float32), stripped
        )
        dW0 = {
            site: jnp.zeros(heavy[site].shape, jnp.float32) for site in heavy
        }
        buf0 = jax.tree.map(
            lambda l: jnp.zeros((Q, *l.shape), l.dtype),
            split_taps(tapped, resolved)[0],
        )
        carry0 = (jnp.zeros(inputs.shape[1:], cd), small0, dW0, buf0)
        (_, small_acc, dW_acc, buf), (dxs, d_afs) = jax.lax.scan(
            btick, carry0, jnp.arange(n_ticks)
        )
        # ---- W flush: flat, bubble-free — every rank contracts its own
        # stage's remaining deferred chunks, no inter-stage dependency ----
        dW_acc = accumulate_dw(dW_acc, buf, resolved)
        d_small = jax.tree.map(
            lambda A, ref: A.astype(ref.dtype), small_acc, stripped
        )
        d_sp = insert_kernel_grads(
            d_small,
            {s: dW_acc[s].astype(heavy[s].dtype) for s in dW_acc},
        )
        # only float leaves leave the region; int leaves (if any) get
        # float0 cotangents assembled at the custom_vjp boundary
        d_sp = sp_part.floats(d_sp)
        # per-microbatch rows of this rank's dx / aux cotangents live at
        # ticks j + off; rank 0's dx rows ARE the input cotangent. The
        # replicated-input transpose is a psum — same f32 collective the
        # AD path pays (pp.py:111-115).
        idx = off + jnp.arange(M)
        d_inp = jax.lax.psum(
            jnp.where(p == 0, dxs[idx], 0).astype(jnp.float32), "pp"
        )
        d_aux_f = [
            jax.lax.psum(t[idx].astype(jnp.float32), "pp") for t in d_afs
        ]
        return d_sp, d_inp, d_aux_f

    def run_bwd(sp, inp, auxb, xs, d_ys, d_acc):
        n_aux_f = sum(aux_part.is_f)
        sp_f_specs = [
            s for s, m in zip(jax.tree.leaves(param_specs), sp_part.is_f) if m
        ]
        d_sp_f, d_inp, d_aux_f = shard_map(
            bwd_fn,
            mesh=mesh,
            in_specs=(
                param_specs, data_spec, data_spec, P("pp"), data_spec,
                (P("pp") if has_stage_aux else data_spec),
            ),
            out_specs=(sp_f_specs, P(), [P()] * n_aux_f),
            axis_names={"pp"},
            check_vma=False,
        )(sp, inp, auxb, xs, d_ys, d_acc)
        return d_sp_f, d_inp, d_aux_f

    @jax.custom_vjp
    def pipe(sp, inp, auxb):
        out = run_fwd(sp, inp, auxb)
        if has_stage_aux:
            ys, _, acc = out
            return ys[pp - 1], acc
        ys, _ = out
        return ys[pp - 1]

    def pipe_fwd(sp, inp, auxb):
        out = run_fwd(sp, inp, auxb)
        if has_stage_aux:
            ys, xs, acc = out
            return (ys[pp - 1], acc), (sp, inp, auxb, xs)
        ys, xs = out
        return ys[pp - 1], (sp, inp, auxb, xs)

    def pipe_bwd(res, ct):
        sp, inp, auxb, xs = res
        if has_stage_aux:
            d_ys, d_acc = ct
        else:
            d_ys, d_acc = ct, jnp.zeros((), jnp.float32)
        d_sp_f, d_inp, d_aux_f = run_bwd(sp, inp, auxb, xs, d_ys, d_acc)
        # cotangent dtypes: float leaves cast back to primal dtype; int
        # leaves (segment ids, seed data) get float0 per the vjp contract
        aux_templates = [
            l for l, m in zip(jax.tree.leaves(auxb), aux_part.is_f) if m
        ]
        d_auxb = aux_part.cotangent(
            [g.astype(t.dtype) for g, t in zip(d_aux_f, aux_templates)]
        )
        d_sp = sp_part.cotangent(d_sp_f)
        return d_sp, d_inp, d_auxb

    pipe.defvjp(pipe_fwd, pipe_bwd)

    out = pipe(stage_params, inputs.astype(jnp.float32), aux)
    return out
