"""Sharding-plan application: param-path regex → logical spec → NamedSharding.

This is the reference's TP-plan machinery (distributed/parallelizer.py:864-947,
optimized_tp_plans.py) re-expressed for GSPMD: instead of swapping nn.Module
forwards, a plan is a list of ``(path_regex, logical_dims)`` rules matched
against pytree paths; resolution to physical axes goes through
MeshContext.resolve so one plan serves every mesh shape (FSDP-only, TP, HSDP,
EP...). FSDP is "just" the `fsdp` logical axis appearing in the rules — there
is no wrapper layer (SURVEY.md §7 idiomatic mapping).
"""

from __future__ import annotations

import logging
import re
from typing import Any, Callable, Sequence

import jax
from jax.sharding import NamedSharding

from automodel_tpu.parallel.mesh import MeshContext

logger = logging.getLogger(__name__)

Rules = Sequence[tuple[str, tuple]]


def path_str(path: tuple) -> str:
    """KeyPath → "a/b/c" string for regex matching."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_rule(path: str, rules: Rules) -> tuple | None:
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return None


def make_param_shardings(ctx: MeshContext, params: Any, rules: Rules) -> Any:
    """Pytree of NamedSharding matching `params` structure. Unmatched leaves
    are fully replicated (and logged once — silent replication of a large
    param is the classic GSPMD perf bug)."""
    unmatched: list[str] = []

    def resolve(path, leaf):
        p = path_str(path)
        spec = match_rule(p, rules)
        if spec is None:
            if getattr(leaf, "size", 0) > 1 << 16:
                unmatched.append(p)
            return ctx.replicated()
        return ctx.sharding(*spec)

    out = jax.tree_util.tree_map_with_path(resolve, params)
    if unmatched:
        logger.warning("Sharding rules matched nothing for large params: %s", unmatched)
    return out


def shard_params(ctx: MeshContext, params: Any, rules: Rules) -> Any:
    """device_put the whole param tree with its plan shardings."""
    shardings = make_param_shardings(ctx, params, rules)
    return jax.device_put(params, shardings)


def make_constrain(ctx: MeshContext | None) -> Callable:
    """Activation-constraint callback handed into model forwards."""
    if ctx is None:
        return lambda x, spec: x

    def constrain(x, spec):
        return jax.lax.with_sharding_constraint(x, ctx.sharding(*spec))

    # mesh-aware ops (e.g. the MoE a2a dispatcher's shard_map) fetch the
    # context from the callback rather than widening every model signature
    constrain.mesh_ctx = ctx
    return constrain


def abstract_params(init_fn: Callable, *args: Any) -> Any:
    """Shapes-only param tree (reference meta-device init,
    auto_model.py:234-241 → here jax.eval_shape: no memory touched)."""
    return jax.eval_shape(init_fn, *args)
