"""Context parallelism: ring attention over the ``cp`` mesh axis.

Parity: the reference's CP paths (distributed/cp_utils.py:68-184 — torch
experimental `context_parallel` ring SDPA with allgather KV rotation; and the
TE `cp_comm_type="p2p"` ring, moe/parallelizer.py:279-297). TPU-native
design (SURVEY.md §7): `shard_map` over the cp axis with `lax.ppermute` KV
rotation and online-softmax (flash-style) merging of per-block partial
results, so each device only ever holds ``S/cp`` keys/values — the
long-context mechanism.

Two layers:

- :func:`ring_attention_shard` — per-device ring loop; runs INSIDE a
  shard_map region (or any context where ``axis_name`` is bound).
- :func:`make_ring_attention` — wraps it in `shard_map` with specs resolved
  from the MeshContext and registers it as the ``"ring"`` backend in
  `ops.attention.ATTENTION_BACKENDS` via :func:`install_ring_backend`.

Two seq layouts:

- CONTIGUOUS (default): rank r holds positions [r·S/cp, (r+1)·S/cp). Causal
  masking makes this load-imbalanced (later ranks do more real work).
- ZIGZAG (``zigzag=True``): the sequence splits into 2·cp chunks and rank r
  holds chunks (r, 2cp-1-r) — every rank sees the same causal work, the
  standard ring-attention balancing (the reference balances via THD
  round-robin partitioning, cp_utils.py:296-337). The DATA must be permuted
  into zigzag order first (:func:`zigzag_indices` / :func:`apply_zigzag` on
  input_ids/labels/position_ids/segment_ids); rope stays correct because
  position_ids carry true positions, and the loss is layout-invariant
  because labels were shifted before the permutation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from automodel_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from automodel_tpu.ops.attention import repeat_kv

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def zigzag_indices(seq_len: int, cp: int):
    """Permutation putting global positions into zigzag-layout order: chunk
    list (0, 2cp-1), (1, 2cp-2), ... concatenated rank-major."""
    import numpy as np

    if seq_len % (2 * cp):
        raise ValueError(f"seq_len {seq_len} must divide 2*cp={2 * cp}")
    half = seq_len // (2 * cp)
    chunks = np.arange(seq_len).reshape(2 * cp, half)
    order = []
    for r in range(cp):
        order.append(chunks[r])
        order.append(chunks[2 * cp - 1 - r])
    return np.concatenate(order)


def apply_zigzag(x, cp: int, axis: int = 1):
    """Reorder the seq axis into zigzag layout (host or device arrays)."""
    import numpy as np

    idx = zigzag_indices(x.shape[axis], cp)
    return jnp.take(x, idx, axis=axis) if isinstance(x, jnp.ndarray) else np.take(
        x, idx, axis=axis
    )


def undo_zigzag(x, cp: int, axis: int = 1):
    import numpy as np

    idx = zigzag_indices(x.shape[axis], cp)
    inv = np.empty_like(idx)
    inv[idx] = np.arange(len(idx))
    return jnp.take(x, inv, axis=axis) if isinstance(x, jnp.ndarray) else np.take(
        x, inv, axis=axis
    )


def _zigzag_positions(rank, s_loc: int, cp: int):
    """Global positions of a rank's local tokens in zigzag layout."""
    half = s_loc // 2
    a = jnp.arange(half)
    return jnp.concatenate(
        [rank * half + a, (2 * cp - 1 - rank) * half + a]
    )


def _ring_interpret_requested() -> bool:
    import os

    return os.environ.get("AUTOMODEL_RING_INTERPRET", "0") == "1"


def ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "cp",
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    sinks: Optional[jnp.ndarray] = None,
    zigzag: bool = False,
    platform: Optional[str] = None,
) -> jnp.ndarray:
    """Ring attention on per-device shards. q/k/v: [B, S_loc, N(,kv), H],
    segment_ids: [B, S_loc]. Requires `axis_name` bound (shard_map).

    On TPU (or under AUTOMODEL_RING_INTERPRET=1) each ring step runs the
    Pallas blockwise kernels from ops.ring_flash — O(S_loc·block) memory;
    otherwise (and for logits_soft_cap, which the kernel path doesn't carry)
    the XLA formulation below materializes per-step S_loc² logits.

    ``sinks`` (gpt-oss, [N] per-head logits): a sink is one extra virtual
    key with value 0, so it never needs to ride the ring — the merged
    (out, lse) pair absorbs it AFTER the last step: lse' = logaddexp(lse,
    sink) and out' = out·exp(lse − lse'). The saved lse' makes the existing
    blockwise backward exact (p = exp(s − lse') are the extended-softmax
    probabilities), with d_sink = −Σ p_sink·Δ falling out of the same
    flash identity the kernels use."""
    from automodel_tpu.ops.platform_check import is_tpu_platform

    interpret = _ring_interpret_requested()
    if logits_soft_cap is None and (interpret or is_tpu_platform(platform)):
        return _ring_flash_shard(
            q, k, v,
            axis_name=axis_name, causal=causal, scale=scale,
            segment_ids=segment_ids, sliding_window=sliding_window,
            sinks=sinks, zigzag=zigzag, interpret=interpret,
        )
    return _ring_attention_shard_xla(
        q, k, v,
        axis_name=axis_name, causal=causal, scale=scale,
        segment_ids=segment_ids, logits_soft_cap=logits_soft_cap,
        sliding_window=sliding_window, sinks=sinks, zigzag=zigzag,
    )


def _ring_flash_shard(
    q, k, v, *, axis_name, causal, scale, segment_ids, sliding_window,
    zigzag, interpret, sinks=None,
):
    from automodel_tpu.ops.ring_flash import (
        NEG_INF,
        flash_block_bwd,
        flash_block_fwd,
        merge_partials,
    )

    b, s_loc, n, h = q.shape
    scale = scale if scale is not None else 1.0 / (h**0.5)
    cp = jax.lax.psum(1, axis_name)  # python int inside shard_map
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def pos_of(rank):
        if zigzag:
            return _zigzag_positions(rank, s_loc, int(cp))
        return rank * s_loc + jnp.arange(s_loc)

    if segment_ids is None:
        seg0 = jnp.zeros((b, s_loc), jnp.int32)
    else:
        seg0 = segment_ids.astype(jnp.int32)

    def rotate(*xs):
        # one ppermute over the tuple → one fused collective on ICI
        return jax.lax.ppermute(xs, axis_name, perm)

    # NOTE: the custom_vjp fwd/bwd must not close over tracers (axis_index);
    # rank/positions are recomputed inside each impl.
    def _fwd_impl(q, k, v, seg, sk):
        my_rank = jax.lax.axis_index(axis_name)
        q_pos = pos_of(my_rank)
        out = jnp.zeros((b, s_loc, n, h), jnp.float32)
        lse = jnp.full((b, n, s_loc), NEG_INF, jnp.float32)

        # python loop: cp is a static int here, and unrolling lets the last
        # step skip its (result-discarding) kv rotation — ring attention is
        # ICI-bound, so a dead full-KV ppermute per layer is real wall-clock
        k_blk, v_blk, seg_blk = k, v, seg
        for step in range(cp):
            kv_pos = pos_of((my_rank - step) % cp)
            o_t, lse_t = flash_block_fwd(
                q, k_blk, v_blk, q_pos, kv_pos, seg, seg_blk,
                causal=causal, window=sliding_window, scale=scale,
                interpret=interpret,
            )
            out, lse = merge_partials(out, lse, o_t.astype(jnp.float32), lse_t)
            if step < cp - 1:
                k_blk, v_blk, seg_blk = rotate(k_blk, v_blk, seg_blk)
        if sk is not None:
            # fold the sink in post-merge: one zero-value virtual key
            s_b = sk.astype(jnp.float32)[None, :, None]  # [1, n, 1]
            lse_ext = jnp.logaddexp(lse, s_b)  # extended lse (dead rows → s)
            out = out * jnp.exp(lse - lse_ext).transpose(0, 2, 1)[..., None]
            lse = lse_ext
        return out.astype(q.dtype), lse

    @jax.custom_vjp
    def ring(q, k, v, seg, sk):
        return _fwd_impl(q, k, v, seg, sk)[0]

    def ring_fwd(q, k, v, seg, sk):
        out, lse = _fwd_impl(q, k, v, seg, sk)
        return out, (q, k, v, seg, sk, out, lse)

    def ring_bwd(res, dout):
        q, k, v, seg, sk, out, lse = res
        my_rank = jax.lax.axis_index(axis_name)
        q_pos = pos_of(my_rank)
        do32 = dout.astype(jnp.float32)
        # delta = rowsum(dO ∘ O) per (b, n, s) — the flash backward constant
        delta = (do32 * out.astype(jnp.float32)).sum(-1).transpose(0, 2, 1)

        dq = jnp.zeros(q.shape, jnp.float32)
        dk = jnp.zeros(k.shape, jnp.float32)
        dv = jnp.zeros(v.shape, jnp.float32)
        k_blk, v_blk, seg_blk = k, v, seg
        for step in range(cp):
            kv_pos = pos_of((my_rank - step) % cp)
            dq_t, dk_t, dv_t = flash_block_bwd(
                q, k_blk, v_blk, dout, lse, delta, q_pos, kv_pos, seg, seg_blk,
                causal=causal, window=sliding_window, scale=scale,
                interpret=interpret,
            )
            dq = dq + dq_t
            # dk/dv ride the ring WITH their kv block; after cp total
            # rotations they are back on the owning device with every
            # contribution (the k/v/seg blocks themselves stop one step
            # early — the last compute doesn't need the next block)
            dk, dv = dk + dk_t, dv + dv_t
            if step < cp - 1:
                k_blk, v_blk, seg_blk, dk, dv = rotate(
                    k_blk, v_blk, seg_blk, dk, dv
                )
            else:  # k/v/seg are done; dk/dv still need the final hop home
                dk, dv = rotate(dk, dv)
        import numpy as np

        ct_seg = np.zeros(seg.shape, jax.dtypes.float0)
        ct_sk = None
        if sk is not None:
            # sink column of the flash backward: dp_sink = dO·v_sink = 0, so
            # ds_sink = p_sink·(0 − Δ); summed over its (b, s) broadcast
            p_sink = jnp.exp(sk.astype(jnp.float32)[None, :, None] - lse)
            ct_sk = (-(p_sink * delta).sum(axis=(0, 2))).astype(sk.dtype)
        return (
            dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            ct_seg, ct_sk,
        )

    ring.defvjp(ring_fwd, ring_bwd)
    return ring(q, k, v, seg0, sinks)


def _ring_attention_shard_xla(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "cp",
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    sinks: Optional[jnp.ndarray] = None,
    zigzag: bool = False,
) -> jnp.ndarray:
    """Reference XLA ring (materializes per-step S_loc² logits)."""
    b, s_loc, n, h = q.shape
    n_kv = k.shape[2]
    scale = scale if scale is not None else 1.0 / (h**0.5)
    cp = jax.lax.psum(1, axis_name)
    my_rank = jax.lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32)

    def pos_of(rank):  # global positions of rank's local tokens
        if zigzag:
            # cp is a traced axis size only under vmap-style tracing; in
            # shard_map it is a python int via psum(1) — static here
            return _zigzag_positions(rank, s_loc, int(cp))
        return rank * s_loc + jnp.arange(s_loc)

    q_pos = pos_of(my_rank)

    # online-softmax accumulators
    o = jnp.zeros((b, s_loc, n, h), jnp.float32)
    m = jnp.full((b, n, s_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, n, s_loc), jnp.float32)

    if segment_ids is None:
        seg = jnp.zeros((b, s_loc), jnp.int32)
    else:
        seg = segment_ids.astype(jnp.int32)

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(step, carry):
        o, m, l, k_blk, v_blk, seg_blk = carry
        src_rank = (my_rank - step) % cp
        kv_pos = pos_of(src_rank)

        k_exp = repeat_kv(k_blk, n // n_kv).astype(jnp.float32)
        v_exp = repeat_kv(v_blk, n // n_kv).astype(jnp.float32)
        logits = jnp.einsum("bqnh,bknh->bnqk", q32, k_exp) * scale
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)

        mask = jnp.ones((s_loc, s_loc), bool)
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
        if sliding_window is not None:
            mask = mask & (q_pos[:, None] - kv_pos[None, :] < sliding_window)
        mask = mask[None, None]  # [1,1,sq,sk]
        if segment_ids is not None:
            mask = mask & (seg[:, None, :, None] == seg_blk[:, None, None, :])
        logits = jnp.where(mask, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bnqk,bknh->bqnh", p, v_exp
        )

        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_nxt = jax.lax.ppermute(seg_blk, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt, seg_nxt

    o, m, l, *_ = jax.lax.fori_loop(0, cp, body, (o, m, l, k, v, seg))
    if sinks is not None:
        # the sink is one zero-value virtual key: it only grows the softmax
        # denominator, so fold it into l post-hoc (this path is plain
        # differentiable XLA — autodiff carries d_sinks)
        l = l + jnp.exp(sinks.astype(jnp.float32)[None, :, None] - m)
    l_t = l.transpose(0, 2, 1)[..., None]  # [b,s,n,1]
    out = jnp.where(l_t > 0, o / jnp.maximum(l_t, 1e-30), 0.0)
    return out.astype(q.dtype)


def make_ring_attention(mesh_ctx, zigzag: bool = False):
    """Drop-in attention over GLOBAL arrays: shard_map'd ring over cp, with
    batch sharded on the data axes and heads on tp (the GSPMD layout the rest
    of the model uses)."""
    mesh = mesh_ctx.mesh
    bspec = mesh_ctx.resolve(("batch",))  # P over batch axes
    batch_axes = bspec[0] if len(bspec) else None
    cp_ax = "cp" if mesh.shape["cp"] > 1 else None
    tp_ax = "tp" if mesh.shape["tp"] > 1 else None
    qkv_spec = P(batch_axes, cp_ax, tp_ax, None)
    seg_spec = P(batch_axes, cp_ax)

    def ring(
        q,
        k,
        v,
        *,
        causal: bool = True,
        scale: Optional[float] = None,
        segment_ids: Optional[jnp.ndarray] = None,
        logits_soft_cap: Optional[float] = None,
        sliding_window: Optional[int] = None,
        sinks: Optional[jnp.ndarray] = None,
        **_ignored,
    ):
        has_seg = segment_ids is not None
        has_sinks = sinks is not None
        in_specs = (qkv_spec, qkv_spec, qkv_spec)
        if has_seg:
            in_specs += (seg_spec,)
        if has_sinks:
            in_specs += (P(tp_ax),)  # per-head logits follow the head shard
        inner = functools.partial(
            ring_attention_shard,
            axis_name="cp",
            causal=causal,
            scale=scale,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
            zigzag=zigzag and mesh.shape["cp"] > 1,
            platform=mesh_ctx.platform,
        )

        def fn(*args):
            q_, k_, v_, *rest = args
            rest = list(rest)
            kw = {}
            if has_seg:
                kw["segment_ids"] = rest.pop(0)
            if has_sinks:
                kw["sinks"] = rest.pop(0)
            return inner(q_, k_, v_, **kw)

        mapped = shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec, check_vma=False
        )
        args = (q, k, v)
        if has_seg:
            args += (segment_ids,)
        if has_sinks:
            args += (sinks,)
        return mapped(*args)

    return ring


def install_ring_backend(mesh_ctx, zigzag: bool = False) -> None:
    """Register ``"ring"`` in the attention-backend registry, bound to this
    mesh. One mesh at a time (module-global registry) — matches the
    one-mesh-per-process training model."""
    from automodel_tpu.ops.attention import ATTENTION_BACKENDS

    ATTENTION_BACKENDS["ring"] = make_ring_attention(mesh_ctx, zigzag=zigzag)
