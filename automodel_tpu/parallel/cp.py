"""Context parallelism: ring attention over the ``cp`` mesh axis.

Parity: the reference's CP paths (distributed/cp_utils.py:68-184 — torch
experimental `context_parallel` ring SDPA with allgather KV rotation; and the
TE `cp_comm_type="p2p"` ring, moe/parallelizer.py:279-297). TPU-native
design (SURVEY.md §7): `shard_map` over the cp axis with `lax.ppermute` KV
rotation and online-softmax (flash-style) merging of per-block partial
results, so each device only ever holds ``S/cp`` keys/values — the
long-context mechanism.

Two layers:

- :func:`ring_attention_shard` — per-device ring loop; runs INSIDE a
  shard_map region (or any context where ``axis_name`` is bound).
- :func:`make_ring_attention` — wraps it in `shard_map` with specs resolved
  from the MeshContext and registers it as the ``"ring"`` backend in
  `ops.attention.ATTENTION_BACKENDS` via :func:`install_ring_backend`.

Sharding is CONTIGUOUS on the seq dim (rank r holds positions
[r·S/cp, (r+1)·S/cp)). With causal masking this is load-imbalanced (later
ranks do more real work; every rank computes every block and masks) — the
reference balances via THD round-robin partitioning (cp_utils.py:296-337).
A zigzag layout is a planned perf upgrade; correctness and O(S/cp) memory
hold either way.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from automodel_tpu.ops.attention import repeat_kv

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "cp",
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Ring attention on per-device shards. q/k/v: [B, S_loc, N(,kv), H],
    segment_ids: [B, S_loc]. Requires `axis_name` bound (shard_map)."""
    b, s_loc, n, h = q.shape
    n_kv = k.shape[2]
    scale = scale if scale is not None else 1.0 / (h**0.5)
    cp = jax.lax.psum(1, axis_name)
    my_rank = jax.lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32)
    q_pos = my_rank * s_loc + jnp.arange(s_loc)  # global q positions

    # online-softmax accumulators
    o = jnp.zeros((b, s_loc, n, h), jnp.float32)
    m = jnp.full((b, n, s_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, n, s_loc), jnp.float32)

    if segment_ids is None:
        seg = jnp.zeros((b, s_loc), jnp.int32)
    else:
        seg = segment_ids.astype(jnp.int32)

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(step, carry):
        o, m, l, k_blk, v_blk, seg_blk = carry
        src_rank = (my_rank - step) % cp
        kv_pos = src_rank * s_loc + jnp.arange(s_loc)

        k_exp = repeat_kv(k_blk, n // n_kv).astype(jnp.float32)
        v_exp = repeat_kv(v_blk, n // n_kv).astype(jnp.float32)
        logits = jnp.einsum("bqnh,bknh->bnqk", q32, k_exp) * scale
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)

        mask = jnp.ones((s_loc, s_loc), bool)
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
        if sliding_window is not None:
            mask = mask & (q_pos[:, None] - kv_pos[None, :] < sliding_window)
        mask = mask[None, None]  # [1,1,sq,sk]
        if segment_ids is not None:
            mask = mask & (seg[:, None, :, None] == seg_blk[:, None, None, :])
        logits = jnp.where(mask, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bnqk,bknh->bqnh", p, v_exp
        )

        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_nxt = jax.lax.ppermute(seg_blk, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt, seg_nxt

    o, m, l, *_ = jax.lax.fori_loop(0, cp, body, (o, m, l, k, v, seg))
    l_t = l.transpose(0, 2, 1)[..., None]  # [b,s,n,1]
    out = jnp.where(l_t > 0, o / jnp.maximum(l_t, 1e-30), 0.0)
    return out.astype(q.dtype)


def make_ring_attention(mesh_ctx):
    """Drop-in attention over GLOBAL arrays: shard_map'd ring over cp, with
    batch sharded on the data axes and heads on tp (the GSPMD layout the rest
    of the model uses)."""
    mesh = mesh_ctx.mesh
    bspec = mesh_ctx.resolve(("batch",))  # P over batch axes
    batch_axes = bspec[0] if len(bspec) else None
    cp_ax = "cp" if mesh.shape["cp"] > 1 else None
    tp_ax = "tp" if mesh.shape["tp"] > 1 else None
    qkv_spec = P(batch_axes, cp_ax, tp_ax, None)
    seg_spec = P(batch_axes, cp_ax)

    def ring(
        q,
        k,
        v,
        *,
        causal: bool = True,
        scale: Optional[float] = None,
        segment_ids: Optional[jnp.ndarray] = None,
        logits_soft_cap: Optional[float] = None,
        sliding_window: Optional[int] = None,
        **_ignored,
    ):
        has_seg = segment_ids is not None
        in_specs = (qkv_spec, qkv_spec, qkv_spec) + ((seg_spec,) if has_seg else ())
        inner = functools.partial(
            ring_attention_shard,
            axis_name="cp",
            causal=causal,
            scale=scale,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )

        def fn(*args):
            if has_seg:
                q_, k_, v_, s_ = args
                return inner(q_, k_, v_, segment_ids=s_)
            q_, k_, v_ = args
            return inner(q_, k_, v_)

        mapped = shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec, check_vma=False
        )
        args = (q, k, v) + ((segment_ids,) if has_seg else ())
        return mapped(*args)

    return ring


def install_ring_backend(mesh_ctx) -> None:
    """Register ``"ring"`` in the attention-backend registry, bound to this
    mesh. One mesh at a time (module-global registry) — matches the
    one-mesh-per-process training model."""
    from automodel_tpu.ops.attention import ATTENTION_BACKENDS

    ATTENTION_BACKENDS["ring"] = make_ring_attention(mesh_ctx)
