"""Device mesh construction and distributed context.

Capability parity with the reference mesh layer
(components/distributed/mesh.py:55-72, mesh_utils.py:46,190-228,302-334):
canonical axis names, dp inference from world size, flattened axis groupings
for param/loss sharding, and a MoE expert axis — but expressed TPU-natively.

TPU-first design (NOT a port):

* ONE `jax.sharding.Mesh` instead of the reference's separate 5-D dense mesh +
  3-D MoE mesh.  Axis order (outer→inner) = ``(pp, dp_replicate, dp_shard,
  ep, cp, tp)`` so that the most communication-intensive axes (tp, cp) map to
  the innermost / fastest ICI dimensions. The reference's derived submeshes
  (``dp``, ``dp_shard_cp``, ``dp_cp``, ``ep_shard``) become *logical axis
  groupings* — tuples of mesh axes inside a PartitionSpec — because GSPMD
  shards an array dim over the product of listed axes. No submesh objects,
  no DTensor placements.

* Expert parallelism is a factor of the data-shard product
  (``dp_shard_total = dp_shard * ep``), mirroring the reference invariant
  ``ep_shard = dp*cp/ep`` (mesh_utils.py:179-187): expert weights shard their
  expert dim on ``ep`` and their FSDP dim on ``(dp_shard, cp)``; dense params
  shard on ``(dp_shard, ep, cp)``; batches shard on
  ``(dp_replicate, dp_shard, ep)``.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


class MeshAxisName:
    """Canonical mesh axis names (reference: distributed/mesh.py:55-72)."""

    PP = "pp"
    DP_REPLICATE = "dp_replicate"
    DP_SHARD = "dp_shard"
    EP = "ep"
    CP = "cp"
    TP = "tp"

    ALL = (PP, DP_REPLICATE, DP_SHARD, EP, CP, TP)


# Logical axis → physical mesh axes. These are the reference's flattened
# submeshes (mesh_utils.py:210-228) re-expressed as PartitionSpec groupings.
LOGICAL_AXIS_RULES: dict[str, tuple[str, ...]] = {
    "batch": (MeshAxisName.DP_REPLICATE, MeshAxisName.DP_SHARD, MeshAxisName.EP),
    # param sharding dim ("fsdp"): the reference's dp_shard_cp submesh.
    "fsdp": (MeshAxisName.DP_SHARD, MeshAxisName.EP, MeshAxisName.CP),
    # loss all-reduce group: the reference's dp_cp submesh.
    "loss_dp": (
        MeshAxisName.DP_REPLICATE,
        MeshAxisName.DP_SHARD,
        MeshAxisName.EP,
        MeshAxisName.CP,
    ),
    "seq": (MeshAxisName.CP,),
    "tensor": (MeshAxisName.TP,),
    "expert": (MeshAxisName.EP,),
    # the reference's ep_shard: FSDP dim for expert weights.
    "expert_fsdp": (MeshAxisName.DP_SHARD, MeshAxisName.CP),
    # batch dim INSIDE the expert-parallel region: ep has moved to the expert
    # dim (the dispatch all-to-all), so tokens shard over the remaining data
    # axes only.
    "expert_batch": (MeshAxisName.DP_REPLICATE, MeshAxisName.DP_SHARD),
    "stage": (MeshAxisName.PP,),
    "vocab": (MeshAxisName.TP,),
    None: (),
}


@dataclasses.dataclass
class MeshConfig:
    """Parallelism degrees. -1 for dp_shard means 'infer from world size'
    (reference: mesh_utils.py:160-168).

    ``dcn`` (multi-slice only): per-axis degrees laid across the DATA-CENTER
    NETWORK (between ICI slices) instead of ICI; the per-axis ICI degree is
    axis_total / dcn[axis]. Default (empty) lays pp/dp_replicate/dp_shard
    across slices automatically; ep/tp/cp never default over DCN (latency-
    bound collectives) and require an explicit entry here (reference hybrid
    topology note, init_utils.py:90-163; jax
    mesh_utils.create_hybrid_device_mesh)."""

    dp_replicate: int = 1
    dp_shard: int = -1  # total data-shard degree INCLUDING ep (dp_shard_total)
    tp: int = 1
    cp: int = 1
    pp: int = 1
    ep: int = 1
    dcn: Optional[dict] = None
    # pipeline schedule (pp > 1): 'gpipe' = AD-transposed wavefront;
    # 'zero_bubble' = B/W-split backward with deferred weight-grads
    # (parallel/zero_bubble.py) — bubble 3(pp-1)/(4M+3(pp-1)) vs GPipe's
    # (pp-1)/(M+pp-1). pp_zb_queue bounds the weight-grad deferral queue
    # (microbatches of stash held live; None = defer all — max speedup,
    # ~no-remat activation memory for one stage × M microbatches).
    pp_schedule: str = "gpipe"
    pp_zb_queue: Optional[int] = None

    def validate(self, world_size: int) -> "MeshConfig":
        cfg = dataclasses.replace(self)
        known = cfg.dp_replicate * cfg.tp * cfg.cp * cfg.pp
        if cfg.dp_shard == -1:
            if world_size % known != 0:
                raise ValueError(
                    f"world_size {world_size} not divisible by dp_replicate*tp*cp*pp={known}"
                )
            cfg.dp_shard = world_size // known
        total = known * cfg.dp_shard
        if total != world_size:
            raise ValueError(
                f"Mesh degrees {cfg} product {total} != world size {world_size}"
            )
        if cfg.ep < 1 or cfg.dp_shard % cfg.ep != 0:
            raise ValueError(
                f"ep={cfg.ep} must divide dp_shard_total={cfg.dp_shard} "
                f"(reference invariant ep_shard = dp*cp/ep, mesh_utils.py:179-187)"
            )
        if cfg.pp_schedule not in ("gpipe", "zero_bubble"):
            raise ValueError(
                f"pp_schedule={cfg.pp_schedule!r} must be gpipe|zero_bubble"
            )
        if cfg.pp_zb_queue is not None and cfg.pp_zb_queue < 1:
            raise ValueError(f"pp_zb_queue={cfg.pp_zb_queue} must be >= 1")
        return cfg


class MeshContext:
    """Single source of truth for distributed state (reference: mesh.py:79).

    Wraps the jax Mesh plus the logical-axis mapping; all sharding rules in
    the framework go through :meth:`resolve` / :meth:`sharding` so that a
    logical spec like ``("fsdp", "tensor")`` is portable across mesh shapes.
    """

    def __init__(self, mesh: Mesh, config: MeshConfig):
        self.mesh = mesh
        self.config = config
        self.rules = dict(LOGICAL_AXIS_RULES)

    # -- sizes --------------------------------------------------------------
    @property
    def world_size(self) -> int:
        return self.mesh.size

    def size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    @property
    def dp_size(self) -> int:
        return (
            self.size(MeshAxisName.DP_REPLICATE)
            * self.size(MeshAxisName.DP_SHARD)
            * self.size(MeshAxisName.EP)
        )

    @property
    def dp_cp_size(self) -> int:
        return self.dp_size * self.size(MeshAxisName.CP)

    @property
    def tp_size(self) -> int:
        return self.size(MeshAxisName.TP)

    @property
    def cp_size(self) -> int:
        return self.size(MeshAxisName.CP)

    @property
    def pp_size(self) -> int:
        return self.size(MeshAxisName.PP)

    @property
    def ep_size(self) -> int:
        return self.size(MeshAxisName.EP)

    @property
    def platform(self) -> str:
        """Platform of the devices computation actually runs on ('tpu',
        'cpu', ...). Kernel eligibility must key off THIS, not the process
        default device — a CPU mesh can coexist with a visible TPU backend."""
        return self.mesh.devices.flat[0].platform

    # -- sharding -----------------------------------------------------------
    def resolve(self, logical: Sequence[Any] | None) -> P:
        """Map a logical spec (tuple of logical axis names / None / tuples of
        logical names) to a physical PartitionSpec, dropping size-1 axes."""
        if logical is None:
            return P()
        phys: list[Any] = []
        for dim in logical:
            names: list[str] = []
            for lg in (dim if isinstance(dim, (tuple, list)) else (dim,)):
                if lg is None:
                    continue
                for ax in self.rules[lg]:
                    if self.mesh.shape[ax] > 1:
                        names.append(ax)
            if not names:
                phys.append(None)
            elif len(names) == 1:
                phys.append(names[0])
            else:
                phys.append(tuple(names))
        while phys and phys[-1] is None:
            phys.pop()
        return P(*phys)

    def sharding(self, *logical: Any) -> NamedSharding:
        return NamedSharding(self.mesh, self.resolve(logical))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def __repr__(self) -> str:
        return f"MeshContext(shape={dict(self.mesh.shape)})"


def hybrid_mesh_shapes(
    config: MeshConfig, world_size: int, n_slices: int
) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Split the mesh shape into (ici_shape, dcn_shape) for
    `mesh_utils.create_hybrid_device_mesh` on a multi-host DCN×ICI topology.

    ``config.dcn`` gives per-axis DCN degrees; their product must equal the
    DCN granule count (number of ICI slices) and each must divide its axis.
    Default: greedily lay the OUTER axes (pp, dp_replicate, dp_shard) across
    slices in order — the axes whose collectives amortize over DCN — and
    refuse to split tp/cp/ep implicitly (latency-bound collectives: tp/cp
    all-reduces and the MoE token all-to-all; declare MeshConfig.dcn
    explicitly to override)."""
    cfg = config.validate(world_size)
    axes = {
        "pp": cfg.pp,
        "dp_replicate": cfg.dp_replicate,
        "dp_shard": cfg.dp_shard // cfg.ep,
        "ep": cfg.ep,
        "cp": cfg.cp,
        "tp": cfg.tp,
    }
    dcn = dict(cfg.dcn or {})
    if dcn:
        unknown = set(dcn) - set(axes)
        if unknown:
            raise ValueError(f"dcn axes {sorted(unknown)} not mesh axes {list(axes)}")
        prod = int(np.prod(list(dcn.values())))
        if prod != n_slices:
            raise ValueError(
                f"dcn degrees {dcn} product {prod} != DCN granule (slice) count "
                f"{n_slices}"
            )
        for a, d in dcn.items():
            if d < 1 or axes[a] % d:
                raise ValueError(f"dcn[{a}]={d} must divide axis degree {axes[a]}")
    else:
        rem = n_slices
        for a in ("pp", "dp_replicate", "dp_shard"):
            g = math.gcd(axes[a], rem)
            if g > 1:
                dcn[a] = g
                rem //= g
        if rem != 1:
            raise ValueError(
                f"cannot lay {n_slices} DCN granules across "
                f"{ {a: axes[a] for a in ('pp', 'dp_replicate', 'dp_shard')} } "
                "without splitting ep/tp/cp over DCN (latency-bound "
                "collectives); set MeshConfig.dcn explicitly to opt in"
            )
    dcn_shape = tuple(dcn.get(a, 1) for a in axes)
    ici_shape = tuple(axes[a] // dcn.get(a, 1) for a in axes)
    return ici_shape, dcn_shape


def build_mesh(
    config: MeshConfig | None = None,
    devices: Sequence[jax.Device] | None = None,
    **degrees: int,
) -> MeshContext:
    """Build the device mesh (reference: create_device_mesh, mesh_utils.py:46).

    The mesh axis ``dp_shard`` holds ``dp_shard_total // ep`` so the flat
    product over ``(dp_shard, ep)`` equals the configured data-shard degree.
    Multi-host (jax.process_count() > 1 over the given devices) goes through
    `create_hybrid_device_mesh` so DCN-crossing axes are the ones declared
    (or defaulted) by :func:`hybrid_mesh_shapes`.
    """
    if config is None:
        config = MeshConfig(**degrees)
    devices = list(devices if devices is not None else jax.devices())
    config = config.validate(len(devices))
    shape = (
        config.pp,
        config.dp_replicate,
        config.dp_shard // config.ep,
        config.ep,
        config.cp,
        config.tp,
    )
    # DCN granules are ICI SLICES, not processes: a multi-host single-slice
    # pod (e.g. v4-32, ICI spans hosts) builds a plain device mesh; only
    # genuinely DCN-connected multi-slice topologies go hybrid. Devices
    # without slice_index (CPU multi-process) count as one slice.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    n_slices = 1 if None in slice_ids else len(slice_ids)
    from jax.experimental import mesh_utils as jmu

    if n_slices > 1:
        ici_shape, dcn_shape = hybrid_mesh_shapes(config, len(devices), n_slices)
        dev_array = jmu.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices
        )
        logger.info("Hybrid DCN×ICI mesh: ici=%s dcn=%s", ici_shape, dcn_shape)
    else:
        try:
            dev_array = jmu.create_device_mesh(shape, devices=devices)
        except (ValueError, NotImplementedError, AssertionError) as e:
            # CPU/host platforms without torus assignment. On real TPU this
            # fallback loses topology-aware placement — make it loud.
            logger.warning(
                "create_device_mesh failed (%s); falling back to flat device "
                "order. On TPU hardware this loses ICI-aware placement.", e
            )
            dev_array = np.array(devices).reshape(shape)
    mesh = Mesh(dev_array.reshape(shape), MeshAxisName.ALL)
    logger.info("Built mesh %s", dict(mesh.shape))
    return MeshContext(mesh, config)


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs: Any,
) -> None:
    """Multi-host init (reference: init_utils.py:90 NCCL init → here
    `jax.distributed.initialize` over the TPU runtime; single-process is a
    no-op because JAX is single-controller).

    Args fall back to the env the launchers render (launcher/slurm.py:24-29,
    launcher/k8s.py): JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID. On TPU pods with none of these set,
    `jax.distributed.initialize()` discovers the topology itself — we only
    call it when a multi-host env is actually declared. Validated before
    dialing so a bad rendezvous fails fast with a config error instead of a
    hang at the coordinator timeout."""
    import os

    env = os.environ
    coordinator_address = coordinator_address or env.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and env.get("JAX_NUM_PROCESSES"):
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and env.get("JAX_PROCESS_ID"):
        process_id = int(env["JAX_PROCESS_ID"])
    if not coordinator_address:
        return  # single process / TPU-pod auto-discovery happens lazily
    if num_processes is None or process_id is None:
        raise ValueError(
            "JAX_COORDINATOR_ADDRESS is set but JAX_NUM_PROCESSES / "
            "JAX_PROCESS_ID are not — the launchers export all three "
            "(launcher/slurm.py, launcher/k8s.py)"
        )
    if num_processes < 1 or not (0 <= process_id < num_processes):
        raise ValueError(
            f"invalid process topology: process_id={process_id} "
            f"num_processes={num_processes}"
        )
    if ":" not in coordinator_address:
        raise ValueError(
            f"coordinator_address {coordinator_address!r} must be host:port"
        )
    logger.info(
        "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
        coordinator_address, num_processes, process_id,
    )
    # timed init (resilience/timed_sync.py): a host that never shows up at
    # the rendezvous — bad DNS, a pod that crashed before python started —
    # must surface as a diagnosed SyncTimeout naming the sync point, not an
    # indefinite block inside the coordinator handshake.
    # AUTOMODEL_INIT_TIMEOUT_S bounds the wait (default 600s, generous for
    # slow pod scheduling).
    from automodel_tpu.resilience.timed_sync import timed_call

    timeout_s = float(env.get("AUTOMODEL_INIT_TIMEOUT_S", "600"))
    timed_call(
        lambda: jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        ),
        name="distributed_init",
        timeout_s=timeout_s,
    )
