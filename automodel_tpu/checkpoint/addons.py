"""Consolidated-HF export addons.

Parity: reference checkpoint/addons.py — ``PeftAddon`` (adapter artifacts,
see peft/lora.py export) and ``ConsolidatedHFAddon``: the consolidated
``hf/`` directory must be loadable by ``transformers.from_pretrained``,
which needs config.json / generation_config.json / tokenizer files next to
the safetensors weights.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path
from typing import Any, Optional

TOKENIZER_FILES = (
    "tokenizer.json",
    "tokenizer_config.json",
    "special_tokens_map.json",
    "tokenizer.model",
    "vocab.json",
    "vocab.txt",
    "merges.txt",
    "generation_config.json",
    "preprocessor_config.json",  # VLM processors
    "chat_template.json",
)


def write_hf_addons(
    out_dir: str | Path,
    hf_config: Optional[dict] = None,
    source_dir: Optional[str | Path] = None,
) -> list[str]:
    """Make ``out_dir`` a self-sufficient HF model dir: write config.json
    (from the ingested config) and copy tokenizer/generation artifacts from
    the source checkpoint when available. Returns the file names written."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    written: list[str] = []
    if hf_config is not None:
        (out / "config.json").write_text(json.dumps(hf_config, indent=2, default=str))
        written.append("config.json")
    if source_dir is not None:
        src = Path(source_dir)
        for name in TOKENIZER_FILES:
            f = src / name
            if f.exists() and not (out / name).exists():
                shutil.copy2(f, out / name)
                written.append(name)
        if hf_config is None and (src / "config.json").exists():
            shutil.copy2(src / "config.json", out / "config.json")
            written.append("config.json")
    return written
