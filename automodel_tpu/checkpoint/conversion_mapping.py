"""HF checkpoint key-conversion mappings.

Parity: reference checkpoint/conversion_mapping.py (228 LoC) — some hub
checkpoints store keys under older/newer HF conventions than the adapters
expect (renames, and FUSED tensors like ``qkv_proj``/``gate_up_proj`` that
must split into the canonical per-projection keys). A ``RemappedReader``
wraps HFCheckpointReader and presents the canonical view, so state-dict
adapters never see the variant layout.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class Rename:
    """Key regex rename: canonical key ``sub`` of ``pattern``."""

    pattern: str
    sub: str


@dataclasses.dataclass(frozen=True)
class Split:
    """A fused on-disk tensor serving several canonical keys.

    ``pattern``: regex over the fused on-disk key, with groups usable in the
    target templates. ``targets``: canonical key template → slicer taking
    (fused array, sizes dict) → split array. ``sizes`` names are resolved
    from the model's HF config by the caller.
    """

    pattern: str
    targets: dict[str, Callable[[np.ndarray, dict], np.ndarray]]


# phi3 / fused-qkv style checkpoints: qkv_proj.weight = [q; k; v] rows,
# gate_up_proj.weight = [gate; up] rows (torch Linear [out, in])
FUSED_QKV = Split(
    pattern=r"^(?P<p>.*\.self_attn\.)qkv_proj\.weight$",
    targets={
        r"\g<p>q_proj.weight": lambda a, s: a[: s["q"]],
        r"\g<p>k_proj.weight": lambda a, s: a[s["q"] : s["q"] + s["kv"]],
        r"\g<p>v_proj.weight": lambda a, s: a[s["q"] + s["kv"] :],
    },
)
FUSED_GATE_UP = Split(
    pattern=r"^(?P<p>.*\.mlp\.)gate_up_proj\.weight$",
    targets={
        r"\g<p>gate_proj.weight": lambda a, s: a[: a.shape[0] // 2],
        r"\g<p>up_proj.weight": lambda a, s: a[a.shape[0] // 2 :],
    },
)


class RemappedReader:
    """Reader wrapper presenting canonical keys over a variant checkpoint."""

    def __init__(
        self,
        reader: Any,
        renames: Sequence[Rename] = (),
        splits: Sequence[Split] = (),
        sizes: Optional[dict] = None,
    ):
        self.reader = reader
        self.sizes = sizes or {}
        self._rename_to_raw: dict[str, str] = {}
        self._split_sources: dict[str, tuple[str, Callable]] = {}
        raw_keys = list(reader.keys())
        consumed: set[str] = set()
        for raw in raw_keys:
            for r in renames:
                if re.match(r.pattern, raw):
                    self._rename_to_raw[re.sub(r.pattern, r.sub, raw)] = raw
                    consumed.add(raw)
                    break
            for sp in splits:
                m = re.match(sp.pattern, raw)
                if m:
                    for tmpl, slicer in sp.targets.items():
                        self._split_sources[m.expand(tmpl)] = (raw, slicer)
                    consumed.add(raw)
        self._passthrough = [k for k in raw_keys if k not in consumed]

    def keys(self) -> list[str]:
        return (
            self._passthrough
            + list(self._rename_to_raw)
            + list(self._split_sources)
        )

    def get_tensor(self, key: str) -> np.ndarray:
        if key in self._split_sources:
            raw, slicer = self._split_sources[key]
            return np.ascontiguousarray(slicer(self.reader.get_tensor(raw), self.sizes))
        raw = self._rename_to_raw.get(key, key)
        return self.reader.get_tensor(raw)

    def info(self, key: str):
        if key in self._split_sources:
            return "BF16", tuple(self.get_tensor(key).shape)
        return self.reader.info(self._rename_to_raw.get(key, key))

    def close(self) -> None:
        self.reader.close()


# mixtral stores the MoE block as block_sparse_moe with w1/w3/w2 experts —
# rename to the canonical qwen3-moe-style keys the MoE adapter reads
MIXTRAL_RENAMES = (
    Rename(r"^(.*\.)block_sparse_moe\.gate\.weight$", r"\1mlp.gate.weight"),
    # MiniMax-M2 keeps the mixtral block layout and adds the DeepSeek-style
    # aux-free correction bias on the router
    Rename(
        r"^(.*\.)block_sparse_moe\.gate\.e_score_correction_bias$",
        r"\1mlp.gate.e_score_correction_bias",
    ),
    Rename(
        r"^(.*\.)block_sparse_moe\.experts\.(\d+)\.w1\.weight$",
        r"\1mlp.experts.\2.gate_proj.weight",
    ),
    Rename(
        r"^(.*\.)block_sparse_moe\.experts\.(\d+)\.w3\.weight$",
        r"\1mlp.experts.\2.up_proj.weight",
    ),
    Rename(
        r"^(.*\.)block_sparse_moe\.experts\.(\d+)\.w2\.weight$",
        r"\1mlp.experts.\2.down_proj.weight",
    ),
)

# qwen2-moe: singular shared_expert → the adapter's shared_experts keys
QWEN2_MOE_RENAMES = (
    Rename(r"^(.*\.mlp\.)shared_expert\.(.*)$", r"\1shared_experts.\2"),
)


def detect_remaps(reader: Any, hf_config: Optional[dict] = None) -> Optional[RemappedReader]:
    """Wrap `reader` when a known variant layout is detected (fused qkv /
    gate_up, mixtral block_sparse_moe, qwen2-moe shared_expert); None when
    the checkpoint is already canonical."""
    keys = reader.keys()
    get = lambda k, d=None: (hf_config or {}).get(k, d)
    renames: tuple = ()
    if any(".block_sparse_moe." in k for k in keys):
        renames += MIXTRAL_RENAMES
    if any(".mlp.shared_expert." in k for k in keys):
        renames += QWEN2_MOE_RENAMES
    has_fused = any(k.endswith(".self_attn.qkv_proj.weight") for k in keys) or any(
        k.endswith(".mlp.gate_up_proj.weight") for k in keys
    )
    if not has_fused and not renames:
        return None
    heads = get("num_attention_heads") or 1
    head_dim = get("head_dim") or (get("hidden_size", 0) // heads)
    sizes = {
        "q": heads * head_dim,
        "kv": (get("num_key_value_heads") or heads) * head_dim,
    }
    splits = (FUSED_QKV, FUSED_GATE_UP) if has_fused else ()
    return RemappedReader(reader, renames=renames, splits=splits, sizes=sizes)
