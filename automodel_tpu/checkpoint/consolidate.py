"""Offline orbax→consolidated-HF conversion tool.

Parity: reference tools/offline_hf_consolidation.py — turn an existing
training run's sharded checkpoint into a transformers-loadable HF dir
without re-running the recipe.

Usage:
    python -m automodel_tpu.checkpoint.consolidate <step_dir> <out_dir>

``step_dir`` is an epoch_X_step_Y directory containing ``state/`` (orbax)
and ``config.json`` (the recipe config snapshot, written at save time).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np


def consolidate(step_dir: str | Path, out_dir: str | Path) -> Path:
    import orbax.checkpoint as ocp

    from automodel_tpu.checkpoint.addons import write_hf_addons
    from automodel_tpu.checkpoint.hf_io import save_hf_checkpoint
    from automodel_tpu.models.common.config import BackendConfig
    from automodel_tpu.models.registry import resolve_architecture

    step_dir = Path(step_dir)
    snap = json.loads((step_dir / "config.json").read_text())
    mcfg = snap.get("model", {})
    hf_config = mcfg.get("hf_config")
    source_dir = None
    if hf_config is None:
        # from_pretrained runs: read the source checkpoint's config
        source_dir = mcfg.get("pretrained_model_name_or_path")
        cfg_file = Path(source_dir or "") / "config.json"
        if not cfg_file.exists():
            raise FileNotFoundError(
                "config snapshot has no model.hf_config and the source dir "
                f"config is unavailable ({cfg_file})"
            )
        hf_config = json.loads(cfg_file.read_text())

    backend = BackendConfig(**{
        k: v for k, v in dict(mcfg.get("backend", {}) or {}).items() if k != "_target_"
    })
    model, adapter = resolve_architecture(hf_config)(hf_config, backend)

    # restore on host: rebuild the full TrainState abstract tree (orbax
    # restores by pytree structure) from the recipe's config snapshot
    import jax

    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.training.train_state import TrainState

    ocfg = dict(snap.get("optimizer", {}) or {"name": "adamw"})
    ocfg.pop("_target_", None)
    optimizer = build_optimizer(**ocfg)
    abstract_params = jax.eval_shape(model.init, jax.random.key(0))
    abstract = jax.eval_shape(
        lambda p: TrainState.create(p, optimizer.init(p)), abstract_params
    )
    # restore everything onto one local device (host consolidation)
    dev = jax.local_devices()[0]
    one = jax.sharding.SingleDeviceSharding(dev)
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=one), abstract
    )
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore((step_dir / "state").absolute(), abstract)
    params = jax.tree.map(np.asarray, state.params)

    out = Path(out_dir)
    save_hf_checkpoint(out, adapter.to_hf(params))
    write_hf_addons(out, hf_config=hf_config, source_dir=source_dir)
    return out


def main(argv: list[str]) -> int:
    if len(argv) != 2:
        print(__doc__)
        return 2
    out = consolidate(argv[0], argv[1])
    print(f"consolidated HF checkpoint written to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
