"""Offline checkpoint auditor — the engine behind `automodel_tpu verify-ckpt <dir>`.

Verifies MANIFEST.json integrity (file list, sizes, streamed checksums) and
the layout-marker stamp for a single step dir or a whole checkpoint root —
WITHOUT deserializing any array, so a multi-TB tree audits at disk
bandwidth before an operator commits a big run to resuming from it.

Exit codes: 0 = the tree is resumable as the Checkpointer sees it — every
committed checkpoint verifies, uncommitted crash leftovers beside them are
tolerated (resume skips them, _prune GCs them), and a tree with no
manifests at all but completed ``state/`` dirs audits as LEGACY
(pre-manifest era, resumed via the Checkpointer's fallback); 1 = a
committed dir is corrupt/truncated, or nothing in the tree is resumable;
2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from automodel_tpu.resilience.manifest import (
    MANIFEST_NAME,
    classify_step_dirs,
    has_manifest,
    step_dir_key,
    verify_manifest,
)


def _is_step_dir(p: Path) -> bool:
    return step_dir_key(p) is not None


def audit_dir(step_dir: Path, check_checksums: bool = True) -> dict:
    """→ {dir, committed, ok, problems, n_files, bytes, layout_markers}."""
    rec: dict = {"dir": str(step_dir), "committed": has_manifest(step_dir)}
    if not rec["committed"]:
        # a completed state/ with no manifest is what the Checkpointer's
        # legacy fallback resumes from (pre-manifest era save) — recorded
        # so the exit-code logic can audit such trees as resumable
        rec["legacy_state"] = (step_dir / "state").exists()
        rec.update(
            ok=False,
            problems=[f"{MANIFEST_NAME} missing (uncommitted or pre-manifest save)"],
        )
        return rec
    ok, problems = verify_manifest(step_dir, check_checksums=check_checksums)
    rec.update(ok=ok, problems=problems)
    try:
        manifest = json.loads((step_dir / MANIFEST_NAME).read_text())
        rec["n_files"] = len(manifest.get("files", {}))
        rec["bytes"] = sum(m.get("bytes", 0) for m in manifest.get("files", {}).values())
        markers = manifest.get("fingerprint", {}).get("layout_markers")
        if markers:
            rec["layout_markers"] = markers
    except (ValueError, OSError):
        pass
    return rec


def audit_tree(root: Path, check_checksums: bool = True) -> list[dict]:
    """A step dir audits itself; a root audits every epoch_*_step_* child."""
    if _is_step_dir(root) or has_manifest(root):
        return [audit_dir(root, check_checksums)]
    # same committed/legacy/unfinished classification the Checkpointer's
    # resume uses (manifest.classify_step_dirs) — the audit and the resume
    # path can never disagree about what a dir is
    _, classified = classify_step_dirs(root)
    children = sorted((p for p, _ in classified), key=step_dir_key)
    if not children:
        return [audit_dir(root, check_checksums)]  # report the miss
    return [audit_dir(p, check_checksums) for p in children]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="automodel_tpu verify-ckpt",
        description="Verify checkpoint manifests without loading arrays.",
    )
    ap.add_argument("path", help="a step dir (epoch_E_step_S) or a checkpoint root")
    ap.add_argument(
        "--no-checksums", action="store_true",
        help="existence+size pass only (fast triage of a huge tree)",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    root = Path(args.path)
    if not root.exists():
        print(f"verify-ckpt: {root} does not exist", file=sys.stderr)
        return 2
    recs = audit_tree(root, check_checksums=not args.no_checksums)
    # a tree with no manifest ANYWHERE but completed state/ dirs is a
    # pre-manifest-era run, which the Checkpointer's legacy fallback
    # resumes (with a warning) — audit it the same way. One manifest in
    # the tree makes it manifest-era: bare dirs are then crash leftovers.
    manifest_era = any(r["committed"] for r in recs)
    for r in recs:
        r["legacy"] = not manifest_era and r.pop("legacy_state", False)
    if args.json:
        print(json.dumps(recs, indent=2))
    else:
        for r in recs:
            status = (
                "OK " if r["ok"]
                else "CORRUPT" if r["committed"]
                else "LEGACY" if r["legacy"]
                else "UNCOMMITTED"
            )
            size = f" {r['bytes'] / 1e6:.1f}MB/{r['n_files']}f" if "bytes" in r else ""
            print(f"{status:11s} {r['dir']}{size}")
            for p in r.get("problems", []):
                print(f"            - {p}")
    n_ok = sum(r["ok"] for r in recs)
    n_legacy = sum(r["legacy"] for r in recs)
    print(
        f"{n_ok}/{len(recs)} checkpoint dir(s) verify"
        + (f" ({n_legacy} legacy pre-manifest, resumable unverified)" if n_legacy else ""),
        file=sys.stderr,
    )
    # exit contract: an uncommitted leftover (kill mid-async-save) next to
    # verified checkpoints is a state the Checkpointer tolerates — resume
    # skips it and _prune GCs it — so it must not fail an operator's audit;
    # only a corrupt COMMITTED dir, or a tree with nothing resumable, does
    n_corrupt = sum(r["committed"] and not r["ok"] for r in recs)
    return 1 if n_corrupt or not (n_ok or n_legacy) else 0


if __name__ == "__main__":
    raise SystemExit(main())
