"""Quantized-checkpoint ingest: FP8-blockwise and MXFP4 → bf16 numpy.

Parity: the reference dequantizes quantized hub checkpoints while loading —
DeepSeek-V3 FP8-blockwise (128x128 ``*_scale_inv`` tiles, reference
models/deepseek_v3/state_dict_adapter.py:375 ``dequantize_from_fp8``) and
GPT-OSS MXFP4 (``*_blocks``/``*_scales`` nibble packing, reference
models/gpt_oss/state_dict_adapter.py:117 ``_convert_moe_packed_tensors``).

TPU-native: dequant happens on the host, tensor-by-tensor, inside the
checkpoint reader — so state-dict adapters only ever see logical bf16
tensors and each dequantized leaf can be ``device_put`` to its target
sharding immediately (no CUDA/Triton kernel needed; the hot path is a
one-time load). Quantizer counterparts exist for round-trip tests and for
emitting quantized checkpoints on save.
"""

from __future__ import annotations

import numpy as np
import ml_dtypes

FP8_BLOCK_SIZE = 128

# MXFP4 e2m1 code points, low nibble first (index == 4-bit code).
FP4_VALUES = np.array(
    [0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0,
     -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0],
    dtype=np.float32,
)

_MXFP4_GROUP = 32  # fp4 values per shared e8m0 scale


def dequantize_fp8_blockwise(
    weight: np.ndarray,
    scale_inv: np.ndarray,
    dtype=ml_dtypes.bfloat16,
    block_size: int = FP8_BLOCK_SIZE,
) -> np.ndarray:
    """``weight`` fp8 [M, N] x ``scale_inv`` fp32 [ceil(M/B), ceil(N/B)]
    per-128x128-block scales → dense [M, N] in ``dtype``."""
    if weight.ndim != 2:
        raise ValueError(f"fp8 blockwise weight must be 2-D, got {weight.shape}")
    m, n = weight.shape
    br = -(-m // block_size)
    bc = -(-n // block_size)
    if scale_inv.shape != (br, bc):
        raise ValueError(
            f"scale_inv shape {scale_inv.shape} != expected {(br, bc)} "
            f"for weight {weight.shape} at block {block_size}"
        )
    # row-block loop keeps the fp32 temp at [block_size, N] instead of
    # materializing a full [M, N] fp32 weight + scale matrix on the host
    out = np.empty((m, n), dtype)
    col_scale = np.repeat(scale_inv.astype(np.float32), block_size, axis=1)[:, :n]
    for i in range(br):
        r0, r1 = i * block_size, min((i + 1) * block_size, m)
        out[r0:r1] = (weight[r0:r1].astype(np.float32) * col_scale[i][None, :]).astype(
            dtype
        )
    return out


def quantize_fp8_blockwise(
    weight: np.ndarray, block_size: int = FP8_BLOCK_SIZE
) -> tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`dequantize_fp8_blockwise` (test/export helper):
    per-block absmax scaling into float8_e4m3fn + fp32 ``scale_inv``."""
    m, n = weight.shape
    br = -(-m // block_size)
    bc = -(-n // block_size)
    w = weight.astype(np.float32)
    padded = np.zeros((br * block_size, bc * block_size), np.float32)
    padded[:m, :n] = w
    blocks = padded.reshape(br, block_size, bc, block_size)
    absmax = np.abs(blocks).max(axis=(1, 3))
    fp8_max = 448.0  # e4m3fn
    scale_inv = np.where(absmax > 0, absmax / fp8_max, 1.0).astype(np.float32)
    inv = np.repeat(np.repeat(scale_inv, block_size, 0), block_size, 1)[:m, :n]
    q = (w / inv).astype(ml_dtypes.float8_e4m3fn)
    return q, scale_inv


def dequantize_mxfp4(
    blocks: np.ndarray,
    scales: np.ndarray,
    dtype=ml_dtypes.bfloat16,
    rows_per_chunk: int = 1 << 20,
) -> np.ndarray:
    """MXFP4 ``*_blocks`` uint8 [..., R, G, B] + ``*_scales`` uint8
    [..., R, G] → bf16 in the HF logical layout [..., G*B*2, R].

    Each byte packs two e2m1 values (low nibble first); each group of
    ``B*2 = 32`` values shares one e8m0 scale (exponent = scales - 127).
    The final swapaxes matches transformers' mxfp4 integration (and the
    reference's ``out.transpose(1, 2)``): on disk the quantized tensor is
    stored transposed relative to the bf16 checkpoint layout.
    """
    if blocks.shape[:-1] != scales.shape:
        raise ValueError(f"blocks {blocks.shape} / scales {scales.shape} mismatch")
    *prefix, g, b = blocks.shape
    exp = scales.astype(np.int32).reshape(-1, 1) - 127
    flat = blocks.reshape(-1, b)
    rows_total = flat.shape[0]
    out = np.empty((rows_total, b * 2), dtype=dtype)
    for r0 in range(0, rows_total, rows_per_chunk):
        r1 = min(r0 + rows_per_chunk, rows_total)
        blk = flat[r0:r1]
        sub = np.empty((r1 - r0, b * 2), np.float32)
        sub[:, 0::2] = FP4_VALUES[blk & 0x0F]
        sub[:, 1::2] = FP4_VALUES[blk >> 4]
        np.ldexp(sub, exp[r0:r1], out=sub)
        out[r0:r1] = sub.astype(dtype)
    out = out.reshape(*prefix, g * b * 2)
    return np.swapaxes(out, -1, -2)


def pack_mxfp4(weight: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`dequantize_mxfp4` (test/export helper): HF-layout
    [..., C, R] bf16 → (blocks uint8 [..., R, C//32, 16], scales uint8
    [..., R, C//32]) with per-group absmax e8m0 scales."""
    w = np.swapaxes(np.asarray(weight, np.float32), -1, -2)  # [..., R, C]
    *prefix, r, c = w.shape
    if c % _MXFP4_GROUP:
        raise ValueError(f"last dim {c} not a multiple of {_MXFP4_GROUP}")
    g = c // _MXFP4_GROUP
    grp = w.reshape(*prefix, r, g, _MXFP4_GROUP)
    absmax = np.abs(grp).max(axis=-1)
    # e8m0 scale: power of two s.t. absmax/2^e <= 6 (max e2m1 magnitude)
    e = np.where(absmax > 0, np.ceil(np.log2(np.maximum(absmax, 1e-30) / 6.0)), 0.0)
    e = np.clip(e, -127, 128).astype(np.int32)
    scales = (e + 127).astype(np.uint8)
    scaled = grp / np.exp2(e)[..., None]
    # nearest e2m1 code per value
    dist = np.abs(scaled[..., None] - FP4_VALUES)  # [..., 32, 16]
    codes = dist.argmin(axis=-1).astype(np.uint8)
    lo = codes[..., 0::2]
    hi = codes[..., 1::2]
    blocks = (lo | (hi << 4)).reshape(*prefix, r, g, _MXFP4_GROUP // 2)
    return blocks, scales


