"""Distributed training checkpointer.

Parity: reference Checkpointer/CheckpointingConfig
(components/checkpoint/checkpointing.py:142,100) + BaseRecipe save/load
(recipes/base_recipe.py:241-545): epoch/step dirs, latest symlink, model in
either native sharded or consolidated-HF format, optimizer state, per-run
extra Statefuls (dataloader, RNG, step scheduler), config snapshot.

TPU-native: orbax handles sharded async array IO (the DCP equivalent);
consolidated HF safetensors goes through checkpoint/hf_io.py. Restoring
reshards automatically to the current mesh — orbax restores to the target
shardings we pass, so elastic re-layout (reference: DCP resharding) is free.

Resilience contract (resilience/manifest.py): every save COMMITS by writing
``MANIFEST.json`` last (for async saves, when the upload drains at the next
``wait()``/``close()``), listing every file with size + checksum. Only
committed dirs count for auto-resume and pruning; ``load()`` verifies and
walks back past corrupt dirs (bounded by ``max_restore_fallbacks``) instead
of crashing a restarted run on a damaged newest checkpoint. Orbax calls ride
the retrying-I/O decorator so transient storage errors back off instead of
killing the run.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import time
from pathlib import Path
from typing import Any, Callable, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from automodel_tpu.resilience.fault_injection import active_injector
from automodel_tpu.resilience.manifest import (
    classify_step_dirs,
    has_manifest,
    step_dir_key as _dir_key,
    verify_manifest,
    write_manifest,
)
from automodel_tpu.resilience.retry import retry_io

logger = logging.getLogger(__name__)


class CheckpointIntegrityError(Exception):
    """No loadable checkpoint: every candidate (within the walk-back bound)
    failed manifest verification, or an explicitly named dir is damaged."""


@dataclasses.dataclass
class CheckpointingConfig:
    enabled: bool = True
    checkpoint_dir: str = "checkpoints"
    model_save_format: str = "sharded"  # sharded | safetensors (consolidated HF)
    save_consolidated: bool = False
    keep_last_k: int = 0  # 0 = keep all
    restore_from: Optional[str] = None
    # async staged save: the orbax save returns immediately and uploads in
    # the background; the next save (or close()) waits for it — reference
    # async staging, checkpointing.py:84-97,519-540
    is_async: bool = False
    # auto-resume walk-back bound: how many older committed checkpoints
    # load() may fall back to when newer ones fail verification
    max_restore_fallbacks: int = 3
    # False = size-only manifests: keeps the commit marker + truncation
    # detection but skips the commit-time checksum read-back of the whole
    # tree (a full disk-bandwidth pass — material at multi-TB scale)
    manifest_checksums: bool = True
    # param-tree signature guard (production resume, reference
    # base_recipe.py:768-850): every save records the state tree's
    # (path, shape, dtype) signature; load() refuses a checkpoint whose
    # signature mismatches the BUILT model instead of letting orbax restore
    # garbage into a differently-shaped tree (or half-succeed). False only
    # for deliberate surgery (manual partial restores).
    check_param_signature: bool = True


@retry_io(op="orbax_save", max_attempts=3)
def _orbax_save_sync(path: Path, state: Any) -> None:
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state)


@retry_io(op="orbax_restore", max_attempts=3)
def _orbax_restore(path: Path, abstract_state: Any) -> Any:
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract_state)


def param_tree_signature(tree: Any) -> dict:
    """Structural signature of a state pytree: sorted ``path:shape:dtype``
    entries + a digest. Works on concrete arrays and ShapeDtypeStructs alike
    (load-side comparison uses the abstract target tree)."""
    import zlib

    entries = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        entries.append(f"{name}:{shape}:{dtype}")
    entries.sort()
    digest = zlib.crc32("\n".join(entries).encode())
    return {"n_leaves": len(entries), "digest": f"{digest:08x}", "entries": entries}


def verify_param_signature(
    found: Optional[dict], expected: dict, ckpt_dir: Path, max_diffs: int = 12
) -> None:
    """Loudly refuse resuming a checkpoint whose param-tree structure/shapes
    mismatch the built model. A checkpoint with no recorded signature
    (pre-guard save) loads unchanged — orbax's own restore still type-checks
    leaf-by-leaf there."""
    if not found:
        return
    if found.get("digest") == expected["digest"]:
        return
    f_set, e_set = set(found.get("entries") or ()), set(expected["entries"])
    missing = sorted(e_set - f_set)  # model expects, checkpoint lacks
    extra = sorted(f_set - e_set)  # checkpoint has, model doesn't
    lines = [f"model expects but checkpoint lacks: {p}" for p in missing[:max_diffs]]
    lines += [f"checkpoint has but model lacks:    {p}" for p in extra[:max_diffs]]
    more = len(missing) + len(extra) - len(lines)
    if more > 0:
        lines.append(f"... and {more} more")
    if not lines:  # same entries, different digest (should not happen)
        lines = [f"digest {found.get('digest')} != expected {expected['digest']}"]
    raise ValueError(
        f"checkpoint {ckpt_dir} param-tree signature mismatches the built "
        f"model ({found.get('n_leaves')} vs {expected['n_leaves']} leaves) — "
        "refusing to resume. Rebuild the model with the config the "
        "checkpoint was saved under (its config.json records it), or set "
        "checkpoint.check_param_signature: false for deliberate surgery:\n  "
        + "\n  ".join(lines)
    )


class Checkpointer:
    def __init__(self, config: CheckpointingConfig):
        self.config = config
        self.root = Path(config.checkpoint_dir)
        self._async: Optional[ocp.AsyncCheckpointer] = None
        # (dir, epoch, step, layout_markers) whose manifest commits when the
        # in-flight async save drains
        self._pending_commit: Optional[tuple[Path, int, int, Optional[dict]]] = None
        # best-val marker deferred until its dir's async save COMMITS —
        # BEST.json must never point at an uncommitted (unrestorable) tree
        self._pending_best: Optional[tuple[Path, str, float]] = None
        # recipes point this at telemetry.record_step so integrity events
        # (fallbacks, failed verifications) land in the flight recorder
        self.event_hook: Optional[Callable[[dict], None]] = None
        # recipes point this at the goodput ledger: (kind, seconds, step)
        # per operation — "ckpt_save" (sync write / async staging),
        # "ckpt_drain" (async drain + commit), "ckpt_restore" (load)
        self.timing_hook: Optional[Callable[..., None]] = None
        # drain seconds spent INSIDE the current save() call (its internal
        # wait() for the previous async save) — subtracted so one wall-clock
        # second is never reported as both save and drain
        self._inner_drain_s = 0.0
        # multi-host commit discipline: the recipe points this at the
        # distributed guard's timed barrier so NO host writes the manifest
        # until EVERY host's save drained — a straggling or dead peer
        # otherwise leaves a committed manifest vouching for a tree whose
        # shards from that host never landed. A timeout here raises
        # (SyncTimeout): the dir stays uncommitted and resume skips it.
        self.commit_barrier: Optional[Callable[[str], None]] = None
        if config.is_async:
            self._async = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())

    def _event(self, rec: dict) -> None:
        if self.event_hook is not None:
            try:
                self.event_hook(rec)
            except Exception:  # telemetry must never break checkpointing
                pass

    def _timing(self, kind: str, seconds: float, step: Optional[int] = None) -> None:
        if self.timing_hook is not None:
            try:
                self.timing_hook(kind, seconds, step=step)
            except Exception:  # telemetry must never break checkpointing
                pass

    def wait(self) -> None:
        """Block until any in-flight async save finishes (the reference gates
        the next optimizer step on staging, train_ft.py:1336), then COMMIT it
        by writing its manifest — a crash before this point leaves the dir
        uncommitted and auto-resume ignores it. A drain that RAISES discards
        the pending commit: a later close() (the recipe's finally) must not
        write a manifest over a partial upload — its checksums would match
        the partial bytes and verification could never catch it. The failure
        does NOT propagate when a save was pending: the dir stays
        uncommitted (resume skips it), the event lands in the flight
        recorder, and the next cadence save tries again — a flaky remote
        store costs one checkpoint, not the whole run."""
        pending, self._pending_commit = self._pending_commit, None
        t0 = time.perf_counter()
        try:
            if self._async is not None:
                try:
                    self._async.wait_until_finished()
                except Exception as e:
                    if pending is None:
                        raise  # no save in flight: this is not a drain failure
                    logger.error(
                        "async checkpoint save to %s FAILED (%r); dir left "
                        "uncommitted — resume will skip it, next cadence save "
                        "retries", pending[0], e,
                    )
                    self._event({
                        "event": "async_save_failed", "dir": str(pending[0]),
                        "error": repr(e), "ts": time.time(),
                    })
                    # the dir never committed: a best-mark waiting on it must
                    # die with it, or BEST.json would name an unrestorable tree
                    if self._pending_best is not None and self._pending_best[0] == pending[0]:
                        self._pending_best = None
                    return
            if pending is not None:
                self._commit(*pending)
        finally:
            if pending is not None:
                # only a drain that had a commit to finish gets a timing
                # stamp — an idle wait() is a no-op, not a segment
                dt = time.perf_counter() - t0
                self._inner_drain_s += dt
                self._timing("ckpt_drain", dt, step=pending[2])

    def _commit(
        self, out: Path, epoch: int, step: int, layout_markers: Optional[dict]
    ) -> None:
        if self.commit_barrier is not None:
            self.commit_barrier("checkpoint_commit")
        # the commit marker is the last storage touchpoint on the save path;
        # retried like every other one (write_manifest is tmp+rename, so a
        # retry after a transient EIO mid-checksum-read-back is idempotent)
        retry_io(op="manifest_commit", max_attempts=3)(write_manifest)(
            out, epoch=epoch, step=step, layout_markers=layout_markers,
            checksums=self.config.manifest_checksums,
        )
        if self._pending_best is not None and self._pending_best[0] == out:
            _, metric, value = self._pending_best
            self._pending_best = None
            self._write_best(out, metric, value)
        inj = active_injector()
        if inj is not None:
            inj.after_checkpoint_save(out)

    def close(self) -> None:
        self.wait()
        if self._async is not None:
            self._async.close()
            self._async = None

    # -- paths --------------------------------------------------------------
    def step_dir(self, epoch: int, step: int) -> Path:
        return self.root / f"epoch_{epoch}_step_{step}"

    def _candidate_dirs(self, include_legacy_tail: bool = False) -> list[Path]:
        """Committed checkpoint dirs, newest first by (epoch, step).

        Committed = manifest present — a single stat per dir, because this
        runs on every save (via _prune) and a per-file size sweep over
        thousands of orbax array files on a FUSE mount would stall the step
        boundary. Contents are verified (sizes AND checksums) at load time
        by _verify_for_load, which walks back past any dir that fails. The
        committed/legacy/unfinished classification (and the manifest-era
        rule deciding which a bare completed-``state/`` dir is) lives in
        ``manifest.classify_step_dirs``, shared with ``verify-ckpt``.

        ``include_legacy_tail`` (walk-back only): in a manifest-era tree,
        append the completed-``state/`` no-manifest dirs AFTER every
        manifest dir — a valid legacy checkpoint is a better last resort
        than crashing when every manifest-era dir fails verification."""
        manifest_era, classified = classify_step_dirs(self.root)
        cands = [
            p for p, kind in classified
            if kind == "committed" or (kind == "legacy_state" and not manifest_era)
        ]
        legacy_tail = [
            p for p, kind in classified
            if kind == "legacy_state" and manifest_era
        ]
        cands.sort(key=_dir_key, reverse=True)
        if include_legacy_tail:
            cands.extend(sorted(legacy_tail, key=_dir_key, reverse=True))
        return cands

    def latest_committed_dir(self) -> Path | None:
        """Newest checkpoint committed into THIS run's tree — no
        ``restore_from`` bootstrap fallback. The preemption path uses this
        to decide requeue-eligibility: a run that committed nothing must
        exit as a real failure, or the launcher would requeue it to
        re-bootstrap and be preempted again at zero net progress."""
        cands = self._candidate_dirs()
        return cands[0] if cands else None

    def latest_dir(self) -> Path | None:
        """Newest committed run-local checkpoint; ``restore_from`` is only
        the BOOTSTRAP source, used when the run's own tree is empty. (If it
        pinned every resume, a preempted-and-requeued run would restart
        from the original base checkpoint forever — zero net progress under
        recurring preemption.)"""
        cands = self._candidate_dirs()
        if cands:
            return cands[0]
        if self.config.restore_from:
            return Path(self.config.restore_from)
        return None

    # -- save ---------------------------------------------------------------
    def save(
        self,
        state: Any,
        epoch: int,
        step: int,
        extra_state: dict[str, dict] | None = None,
        hf_export: Any = None,  # (adapter, params) for consolidated HF save
        config_snapshot: dict | None = None,
        hf_meta: dict | None = None,  # {"hf_config": dict, "source_dir": str}
        layout_markers: dict[str, str] | None = None,
    ) -> Path:
        t_save = time.perf_counter()
        self._inner_drain_s = 0.0
        out = self.step_dir(epoch, step)
        out.mkdir(parents=True, exist_ok=True)
        if layout_markers:
            extra_state = {
                **(extra_state or {}), "_layout_markers": dict(layout_markers)
            }
        if self.config.check_param_signature:
            extra_state = {
                **(extra_state or {}),
                "_param_signature": param_tree_signature(state),
            }
        # saving the same step twice (cadence save + end-of-loop save) is
        # idempotent: replace the previous state dir
        self.wait()  # at most one async save in flight
        # UNCOMMIT first: a stale manifest must not vouch for the dir while
        # its contents are being rewritten underneath it
        manifest = out / "MANIFEST.json"
        if manifest.exists():
            manifest.unlink()
        if (out / "state").exists():
            shutil.rmtree(out / "state")
        # a kill mid-async-save strands `state.orbax-checkpoint-tmp-*`;
        # reclaim it here so the re-save doesn't carry dead bytes (the
        # manifest writer independently refuses to list such dirs)
        for stale in out.glob("*.orbax-checkpoint-tmp-*"):
            shutil.rmtree(stale, ignore_errors=True)
        if extra_state:
            (out / "extra_state.json").write_text(json.dumps(extra_state, default=_json_default))
        if config_snapshot:
            (out / "config.json").write_text(json.dumps(config_snapshot, indent=2, default=str))
        if hf_export is not None and (
            self.config.save_consolidated or self.config.model_save_format == "safetensors"
        ):
            from automodel_tpu.checkpoint.addons import write_hf_addons
            from automodel_tpu.checkpoint.hf_io import save_hf_checkpoint

            adapter, params = hf_export
            # adapter.to_hf is a generator that np.asarray's one leaf at a
            # time — device→host transfer streams per leaf, and
            # save_hf_checkpoint flushes shard files as they fill.
            save_hf_checkpoint(out / "hf", adapter.to_hf(params))
            write_hf_addons(out / "hf", **(hf_meta or {}))
        if self._async is not None:
            # dispatch (blocking device→host staging) retried like the sync
            # path; the OSError-typed filter never retries orbax state
            # errors, only transient storage failures
            retry_io(op="orbax_async_dispatch", max_attempts=3)(self._async.save)(
                (out / "state").absolute(), args=ocp.args.StandardSave(state)
            )
            self._pending_commit = (out, epoch, step, layout_markers)
        else:
            _orbax_save_sync((out / "state").absolute(), state)
            self._commit(out, epoch, step, layout_markers)
        self._prune(protect={out.resolve()})
        # the internal wait() above already reported the PREVIOUS save's
        # drain as ckpt_drain — subtract it so save/drain never double-bill
        # the same wall-clock second
        self._timing(
            "ckpt_save",
            max(time.perf_counter() - t_save - self._inner_drain_s, 0.0),
            step=step,
        )
        return out

    def _prune(self, protect: set[Path] | None = None) -> None:
        """Delete committed checkpoints beyond ``keep_last_k`` (by (epoch,
        step), oldest first). Only COMMITTED dirs count toward k — an
        uncommitted crash leftover must not silently consume a keep slot —
        and neither the dir named by ``restore_from`` (the resume source of
        a running job) nor the in-flight save target is ever deleted.

        Uncommitted leftovers strictly OLDER than the newest committed
        checkpoint are garbage (a kill mid-save can leave a multi-GB
        partial tree per incident — on spot capacity that fills the volume)
        and are deleted too — but ONLY dirs without a completed ``state/``
        (a kill mid-upload leaves ``state.orbax-checkpoint-tmp-*``, never
        ``state/``). A dir WITH ``state/`` and no manifest is
        indistinguishable from a valid legacy (pre-manifest) checkpoint,
        and sweeping those would destroy every legacy restore point the
        moment the first manifest-era save lands. A newer-or-equal
        uncommitted dir is left alone (it may be the save currently in
        flight)."""
        k = self.config.keep_last_k
        if k <= 0 or not self.root.exists():
            return
        protect = set(protect or ())
        if self.config.restore_from:
            protect.add(Path(self.config.restore_from).resolve())
        if self._pending_commit is not None:
            protect.add(self._pending_commit[0].resolve())
        best = self.best_info()
        if best is not None:
            # the best-val checkpoint outlives keep_last_k: production
            # resume/export points at it long after the cadence window moved
            protect.add((self.root / best["dir"]).resolve())
        committed = self._candidate_dirs()  # newest first
        for p in committed[k:]:
            if p.resolve() in protect:
                continue
            shutil.rmtree(p)
        if not committed:
            return
        newest_key = _dir_key(committed[0])
        keep = {p.resolve() for p in committed}
        for p in self.root.iterdir():
            key = _dir_key(p)
            if key is None or not p.is_dir() or p.resolve() in keep | protect:
                continue
            if key < newest_key and not (p / "state").exists():
                logger.warning("pruning stale uncommitted checkpoint dir %s", p)
                shutil.rmtree(p)

    # -- load ---------------------------------------------------------------
    def load(
        self,
        abstract_state: Any,
        path: str | os.PathLike | None = None,
        expected_layout_markers: dict[str, str] | None = None,
        before_step: int | None = None,
    ) -> tuple[Any, dict]:
        """Restore (state, extra_state). `abstract_state` is a pytree of
        jax.ShapeDtypeStruct with shardings (from eval_shape + plan) so orbax
        reshards onto the current mesh.

        ``expected_layout_markers``: the model's native-layout contract
        (e.g. GptOssForCausalLM.native_layout_markers). Checked BEFORE the
        array restore so a pre-flip checkpoint (interleaved gpt-oss gate_up)
        fails loudly instead of loading params that silently mis-compute.

        An explicitly named dir (``path`` arg, or ``restore_from`` when the
        run-local tree is empty — the bootstrap case) is fully verified and
        FAILS on damage — the user asked for that checkpoint, silently
        substituting another would be worse. Auto-resume walks back through
        committed run-local dirs (newest first, at most
        ``max_restore_fallbacks`` extra candidates), loudly logging each
        rejected dir into the flight recorder.

        ``before_step`` (auto-resume only) restricts candidates to
        checkpoints saved STRICTLY BEFORE that optimizer step — the
        non-finite rollback policy uses it because a cadence save at (or
        after) the diverged step already contains the poisoned params."""
        t_load = time.perf_counter()
        if path is not None:
            d = self._verify_for_load(Path(path))
        else:
            try:
                d = self._pick_verified_latest(before_step=before_step)
            except FileNotFoundError:
                # no run-local committed checkpoint at all → bootstrap
                # (restore_from is by definition older than any run step,
                # so it also satisfies before_step)
                if not self.config.restore_from:
                    raise
                d = self._verify_for_load(Path(self.config.restore_from))
        extra_file = d / "extra_state.json"
        extra = json.loads(extra_file.read_text()) if extra_file.exists() else {}
        check_layout_markers(
            extra.get("_layout_markers"), expected_layout_markers, d
        )
        # structure/shape guard BEFORE the array restore: a mismatched tree
        # must refuse loudly, not crash mid-restore (or worse, half-load)
        if self.config.check_param_signature:
            verify_param_signature(
                extra.get("_param_signature"),
                param_tree_signature(abstract_state),
                d,
            )
        state = _orbax_restore((d / "state").absolute(), abstract_state)
        key = _dir_key(d)
        self._timing(
            "ckpt_restore",
            time.perf_counter() - t_load,
            step=key[1] if key else None,
        )
        return state, extra

    def _verify_for_load(self, d: Path) -> Path:
        if not d.exists():
            raise FileNotFoundError(f"No checkpoint found at {d}")
        if not has_manifest(d):
            # pre-manifest tree: nothing to verify against — load with a
            # warning rather than stranding older runs
            logger.warning(
                "checkpoint %s has no MANIFEST.json (pre-manifest save) — "
                "loading unverified", d,
            )
            return d
        ok, problems = verify_manifest(d, check_checksums=True)
        if ok:
            return d
        raise CheckpointIntegrityError(
            f"checkpoint {d} fails integrity verification:\n  "
            + "\n  ".join(problems)
        )

    def _pick_verified_latest(self, before_step: int | None = None) -> Path:
        cands = self._candidate_dirs(include_legacy_tail=True)
        if before_step is not None:
            cands = [p for p in cands if _dir_key(p)[1] < before_step]
        if not cands:
            raise FileNotFoundError(
                f"No checkpoint found under {self.root}"
                + (f" before step {before_step}" if before_step is not None else "")
            )
        budget = 1 + max(self.config.max_restore_fallbacks, 0)
        rejected: list[str] = []
        for i, d in enumerate(cands[:budget]):
            try:
                chosen = self._verify_for_load(d)
            except CheckpointIntegrityError as e:
                quarantined = self._quarantine(d)
                logger.error(
                    "checkpoint %s FAILED verification — quarantined as %s, "
                    "falling back to the previous committed checkpoint (%s)",
                    d, quarantined, e,
                )
                self._event(
                    {
                        "event": "checkpoint_fallback",
                        "rejected": str(d),
                        "quarantined_as": str(quarantined),
                        "problems": str(e),
                    }
                )
                rejected.append(f"{d}: {e}")
                continue
            if i > 0:
                logger.warning(
                    "resuming from OLDER checkpoint %s after %d newer dir(s) "
                    "failed verification — some steps will be retrained",
                    chosen, i,
                )
            return chosen
        raise CheckpointIntegrityError(
            f"no loadable checkpoint under {self.root} within "
            f"{budget} candidate(s):\n  " + "\n  ".join(rejected)
        )

    def _quarantine(self, d: Path) -> Path:
        """Rename a committed-but-corrupt dir out of the ``epoch_E_step_S``
        namespace (data kept for forensics). Without this, corrupt dirs
        would keep occupying ``keep_last_k`` slots FOREVER — pruning counts
        them as committed and would delete the newer GOOD post-resume saves
        instead, until every restore candidate is corrupt."""
        target = d.with_name(d.name + ".corrupt")
        n = 0
        while target.exists():
            n += 1
            target = d.with_name(f"{d.name}.corrupt{n}")
        try:
            d.rename(target)
        except OSError:  # quarantine is best-effort; the walk-back proceeds
            return d
        return target

    def has_checkpoint(self) -> bool:
        return self.latest_dir() is not None

    # -- best-val marker ------------------------------------------------------
    def best_info(self) -> Optional[dict]:
        """The BEST.json record ({dir, metric, value, epoch, step, ts}), or
        None. The named dir may have been pruned away externally — callers
        treat a dangling record as 'no best yet'."""
        f = self.root / "BEST.json"
        if not f.exists():
            return None
        try:
            info = json.loads(f.read_text())
        except (OSError, ValueError):
            return None
        d = self.root / str(info.get("dir", ""))
        return info if info.get("dir") and d.exists() else None

    def mark_best(self, step_dir: Path, metric: str, value: float) -> None:
        """Stamp ``step_dir`` as the best-val checkpoint: BEST.json at the
        tree root (tmp+rename — crash-safe) plus a ``best`` symlink for
        humans and tooling (skipped on filesystems without symlink support;
        BEST.json is the source of truth — production resume points at it
        without parsing the metrics JSONL). The marked dir is protected from
        keep_last_k pruning for as long as it holds the marker.

        With an async save in flight for ``step_dir`` the marker is
        DEFERRED until that save commits (and discarded if the drain
        fails): BEST.json must never name a dir auto-resume would skip."""
        if self._pending_commit is not None and self._pending_commit[0] == step_dir:
            self._pending_best = (step_dir, metric, float(value))
            return
        self._write_best(step_dir, metric, value)

    def _write_best(self, step_dir: Path, metric: str, value: float) -> None:
        info = {
            "dir": step_dir.name,
            "metric": metric,
            "value": float(value),
            "ts": time.time(),
        }
        key = _dir_key(step_dir)
        if key is not None:
            info["epoch"], info["step"] = key
        tmp = self.root / "BEST.json.tmp"
        tmp.write_text(json.dumps(info, indent=2))
        tmp.replace(self.root / "BEST.json")
        link = self.root / "best"
        try:
            if link.is_symlink() or link.exists():
                link.unlink()
            link.symlink_to(step_dir.name)
        except OSError:  # symlink-less FS (some object-store FUSE mounts)
            pass
        logger.info(
            "best checkpoint: %s (%s=%.6g)", step_dir.name, metric, value
        )


def check_layout_markers(
    found: dict | None, expected: dict[str, str] | None, ckpt_dir: Path
) -> None:
    """Fail loudly when a native checkpoint's on-disk param layout predates
    the model's current contract. A checkpoint with NO marker for an
    expected key is treated as pre-versioning (e.g. gpt-oss gate_up saved
    interleaved before the contiguous flip) — loading it would not error
    anywhere, just silently mis-compute."""
    if not expected:
        return
    found = found or {}
    problems = []
    for key, want in expected.items():
        got = found.get(key)
        if got is None:
            problems.append(
                f"{key}: checkpoint has no layout marker (pre-versioning "
                f"save); current code expects {want!r}"
            )
        elif got != want:
            problems.append(f"{key}: checkpoint has {got!r}, code expects {want!r}")
    if problems:
        raise ValueError(
            f"native checkpoint {ckpt_dir} was saved under an incompatible "
            "param layout — re-export it through the HF path (to_hf/from_hf "
            "applies the layout transforms) instead of loading it natively:\n  "
            + "\n  ".join(problems)
        )


def _json_default(o: Any):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, tuple):
        return list(o)
    raise TypeError(f"not JSON serializable: {type(o)}")
