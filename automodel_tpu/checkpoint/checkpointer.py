"""Distributed training checkpointer.

Parity: reference Checkpointer/CheckpointingConfig
(components/checkpoint/checkpointing.py:142,100) + BaseRecipe save/load
(recipes/base_recipe.py:241-545): epoch/step dirs, latest symlink, model in
either native sharded or consolidated-HF format, optimizer state, per-run
extra Statefuls (dataloader, RNG, step scheduler), config snapshot.

TPU-native: orbax handles sharded async array IO (the DCP equivalent);
consolidated HF safetensors goes through checkpoint/hf_io.py. Restoring
reshards automatically to the current mesh — orbax restores to the target
shardings we pass, so elastic re-layout (reference: DCP resharding) is free.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


@dataclasses.dataclass
class CheckpointingConfig:
    enabled: bool = True
    checkpoint_dir: str = "checkpoints"
    model_save_format: str = "sharded"  # sharded | safetensors (consolidated HF)
    save_consolidated: bool = False
    keep_last_k: int = 0  # 0 = keep all
    restore_from: Optional[str] = None
    # async staged save: the orbax save returns immediately and uploads in
    # the background; the next save (or close()) waits for it — reference
    # async staging, checkpointing.py:84-97,519-540
    is_async: bool = False


class Checkpointer:
    def __init__(self, config: CheckpointingConfig):
        self.config = config
        self.root = Path(config.checkpoint_dir)
        self._async: Optional[ocp.AsyncCheckpointer] = None
        if config.is_async:
            self._async = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())

    def wait(self) -> None:
        """Block until any in-flight async save finishes (the reference gates
        the next optimizer step on staging, train_ft.py:1336)."""
        if self._async is not None:
            self._async.wait_until_finished()

    def close(self) -> None:
        if self._async is not None:
            self._async.wait_until_finished()
            self._async.close()
            self._async = None

    # -- paths --------------------------------------------------------------
    def step_dir(self, epoch: int, step: int) -> Path:
        return self.root / f"epoch_{epoch}_step_{step}"

    def latest_dir(self) -> Path | None:
        if self.config.restore_from:
            return Path(self.config.restore_from)
        if not self.root.exists():
            return None
        # only COMMITTED checkpoints count: orbax writes to a tmp-suffixed
        # dir and renames to `state` on completion, so a crash mid-async-save
        # leaves no `state/` and auto-resume falls back to the previous step
        cands = [
            p
            for p in self.root.iterdir()
            if p.is_dir() and p.name.startswith("epoch_") and (p / "state").exists()
        ]
        if not cands:
            return None
        return max(cands, key=lambda p: int(p.name.rsplit("_", 1)[1]))

    # -- save ---------------------------------------------------------------
    def save(
        self,
        state: Any,
        epoch: int,
        step: int,
        extra_state: dict[str, dict] | None = None,
        hf_export: Any = None,  # (adapter, params) for consolidated HF save
        config_snapshot: dict | None = None,
        hf_meta: dict | None = None,  # {"hf_config": dict, "source_dir": str}
        layout_markers: dict[str, str] | None = None,
    ) -> Path:
        out = self.step_dir(epoch, step)
        out.mkdir(parents=True, exist_ok=True)
        if layout_markers:
            extra_state = {
                **(extra_state or {}), "_layout_markers": dict(layout_markers)
            }
        # saving the same step twice (cadence save + end-of-loop save) is
        # idempotent: replace the previous state dir
        self.wait()  # at most one async save in flight
        if (out / "state").exists():
            shutil.rmtree(out / "state")
        if self._async is not None:
            self._async.save(
                (out / "state").absolute(), args=ocp.args.StandardSave(state)
            )
        else:
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save((out / "state").absolute(), state)
        if extra_state:
            (out / "extra_state.json").write_text(json.dumps(extra_state, default=_json_default))
        if config_snapshot:
            (out / "config.json").write_text(json.dumps(config_snapshot, indent=2, default=str))
        if hf_export is not None and (
            self.config.save_consolidated or self.config.model_save_format == "safetensors"
        ):
            from automodel_tpu.checkpoint.addons import write_hf_addons
            from automodel_tpu.checkpoint.hf_io import save_hf_checkpoint

            adapter, params = hf_export
            # adapter.to_hf is a generator that np.asarray's one leaf at a
            # time — device→host transfer streams per leaf, and
            # save_hf_checkpoint flushes shard files as they fill.
            save_hf_checkpoint(out / "hf", adapter.to_hf(params))
            write_hf_addons(out / "hf", **(hf_meta or {}))
        self._prune()
        return out

    def _prune(self) -> None:
        k = self.config.keep_last_k
        if k <= 0 or not self.root.exists():
            return
        cands = sorted(
            (p for p in self.root.iterdir() if p.is_dir() and p.name.startswith("epoch_")),
            key=lambda p: int(p.name.rsplit("_", 1)[1]),
        )
        for p in cands[:-k]:
            shutil.rmtree(p)

    # -- load ---------------------------------------------------------------
    def load(
        self,
        abstract_state: Any,
        path: str | os.PathLike | None = None,
        expected_layout_markers: dict[str, str] | None = None,
    ) -> tuple[Any, dict]:
        """Restore (state, extra_state). `abstract_state` is a pytree of
        jax.ShapeDtypeStruct with shardings (from eval_shape + plan) so orbax
        reshards onto the current mesh.

        ``expected_layout_markers``: the model's native-layout contract
        (e.g. GptOssForCausalLM.native_layout_markers). Checked BEFORE the
        array restore so a pre-flip checkpoint (interleaved gpt-oss gate_up)
        fails loudly instead of loading params that silently mis-compute."""
        d = Path(path) if path else self.latest_dir()
        if d is None:
            raise FileNotFoundError(f"No checkpoint found under {self.root}")
        extra_file = d / "extra_state.json"
        extra = json.loads(extra_file.read_text()) if extra_file.exists() else {}
        check_layout_markers(
            extra.get("_layout_markers"), expected_layout_markers, d
        )
        with ocp.StandardCheckpointer() as ckptr:
            state = ckptr.restore((d / "state").absolute(), abstract_state)
        return state, extra

    def has_checkpoint(self) -> bool:
        return self.latest_dir() is not None


def check_layout_markers(
    found: dict | None, expected: dict[str, str] | None, ckpt_dir: Path
) -> None:
    """Fail loudly when a native checkpoint's on-disk param layout predates
    the model's current contract. A checkpoint with NO marker for an
    expected key is treated as pre-versioning (e.g. gpt-oss gate_up saved
    interleaved before the contiguous flip) — loading it would not error
    anywhere, just silently mis-compute."""
    if not expected:
        return
    found = found or {}
    problems = []
    for key, want in expected.items():
        got = found.get(key)
        if got is None:
            problems.append(
                f"{key}: checkpoint has no layout marker (pre-versioning "
                f"save); current code expects {want!r}"
            )
        elif got != want:
            problems.append(f"{key}: checkpoint has {got!r}, code expects {want!r}")
    if problems:
        raise ValueError(
            f"native checkpoint {ckpt_dir} was saved under an incompatible "
            "param layout — re-export it through the HF path (to_hf/from_hf "
            "applies the layout transforms) instead of loading it natively:\n  "
            + "\n  ".join(problems)
        )


def _json_default(o: Any):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, tuple):
        return list(o)
    raise TypeError(f"not JSON serializable: {type(o)}")
