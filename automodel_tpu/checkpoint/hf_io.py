"""HF safetensors checkpoint IO — self-contained, torch-free.

Parity: the reference's HF-storage layer (components/checkpoint/_backports/
hf_storage.py, consolidate_hf_safetensors.py) reads/writes sharded
``model-0000x-of-0000y.safetensors`` + ``model.safetensors.index.json``.
TPU-native: single-controller JAX needs no multi-rank consolidation dance —
tensors stream shard-file by shard-file on the host and each leaf is
device_put directly to its target sharding.

The safetensors container format is parsed/emitted directly ([8-byte LE u64
header length][JSON header][raw data]) because the `safetensors` numpy
front-end cannot represent bf16 — `ml_dtypes.bfloat16` (bundled with jax)
can, so bf16 checkpoints round-trip without a torch dependency or an f32
upcast.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
from pathlib import Path
from typing import Any, Iterable, Iterator

import ml_dtypes
import numpy as np

from automodel_tpu.resilience.retry import retry_io

SAFETENSORS_INDEX = "model.safetensors.index.json"
MAX_SHARD_BYTES = 5 * 1024**3

_ST_TO_NP = {
    "F64": np.dtype(np.float64),
    "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16),
    "BF16": np.dtype(ml_dtypes.bfloat16),
    "F8_E4M3": np.dtype(ml_dtypes.float8_e4m3fn),
    "F8_E5M2": np.dtype(ml_dtypes.float8_e5m2),
    "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32),
    "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8),
    "U64": np.dtype(np.uint64),
    "U32": np.dtype(np.uint32),
    "U16": np.dtype(np.uint16),
    "U8": np.dtype(np.uint8),
    "BOOL": np.dtype(np.bool_),
}
_NP_TO_ST = {v: k for k, v in _ST_TO_NP.items()}


@retry_io(op="safetensors_read_header", max_attempts=3)
def _read_header(path: Path) -> tuple[dict, int]:
    """(header dict, data section offset). Retried: remote mounts (GCS
    fuse, NFS) surface transient EIO/ESTALE here; a malformed header is a
    ValueError and propagates immediately."""
    with open(path, "rb") as f:
        (n,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(n))
    return header, 8 + n


class HFCheckpointReader:
    """Lazy mmap reader over an HF checkpoint dir (single file or
    sharded+index). Tensors are copied out of the mmap on access, so each
    `get_tensor` touches only that tensor's bytes.

    Quantized hub checkpoints dequantize transparently (reference:
    models/deepseek_v3/state_dict_adapter.py:375 FP8-blockwise,
    models/gpt_oss/state_dict_adapter.py:117 MXFP4): ``get_tensor`` on an
    fp8 weight with a companion ``_scale_inv`` returns the bf16 dequant,
    and on an absent key whose ``_blocks``/``_scales`` pair exists returns
    the MXFP4 unpack — so state-dict adapters only ever see logical bf16
    tensors. Pass ``dequantize=False`` to read raw quantized payloads."""

    def __init__(self, path: str | os.PathLike, dequantize: bool = True):
        self.dequantize = dequantize
        self.path = Path(path)
        index_file = self.path / SAFETENSORS_INDEX
        self.weight_map: dict[str, str] = {}
        if index_file.exists():
            index = json.loads(index_file.read_text())
            self.weight_map = dict(index["weight_map"])
        else:
            cands = sorted(self.path.glob("*.safetensors"))
            if not cands:
                raise FileNotFoundError(f"No safetensors checkpoint under {self.path}")
            for c in cands:
                header, _ = _read_header(c)
                for k in header:
                    if k != "__metadata__":
                        self.weight_map[k] = c.name
        # per shard file: (header, data_offset, mmap)
        self._files: dict[str, tuple[dict, int, Any]] = {}

    def _is_fp8_blockwise(self, key: str) -> bool:
        """Shared predicate between keys()/info()/get_tensor(): an fp8 weight
        with a companion ``_scale_inv`` dequantizes transparently."""
        return (
            key in self.weight_map
            and f"{key}_scale_inv" in self.weight_map
            and self._raw_info(key)[0] in ("F8_E4M3", "F8_E5M2")
        )

    def _is_mxfp4(self, key: str) -> bool:
        return (
            key not in self.weight_map
            and f"{key}_blocks" in self.weight_map
            and f"{key}_scales" in self.weight_map
        )

    def keys(self) -> list[str]:
        """Logical tensor keys: quantization side-car keys (``_scale_inv``,
        ``_blocks``/``_scales``) collapse into the tensor they decode to."""
        if not self.dequantize:
            return list(self.weight_map)
        out = []
        for k in self.weight_map:
            if k.endswith("_scale_inv") and self._is_fp8_blockwise(
                k[: -len("_scale_inv")]
            ):
                continue
            if k.endswith("_blocks") and self._is_mxfp4(k[: -len("_blocks")]):
                out.append(k[: -len("_blocks")])
                continue
            if k.endswith("_scales") and self._is_mxfp4(k[: -len("_scales")]):
                continue
            out.append(k)
        return out

    def _file(self, name: str) -> tuple[dict, int, Any]:
        if name not in self._files:
            p = self.path / name
            header, data_off = _read_header(p)
            f = open(p, "rb")
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            f.close()
            self._files[name] = (header, data_off, mm)
        return self._files[name]

    def _raw_info(self, key: str) -> tuple[str, tuple[int, ...]]:
        header, _, _ = self._file(self.weight_map[key])
        meta = header[key]
        return meta["dtype"], tuple(meta["shape"])

    def info(self, key: str) -> tuple[str, tuple[int, ...]]:
        """(safetensors dtype string, shape) without reading data — the
        logical post-dequant view for quantized entries (same predicates as
        get_tensor, so the two can never disagree)."""
        if self.dequantize:
            if self._is_fp8_blockwise(key):
                return "BF16", self._raw_info(key)[1]
            if self._is_mxfp4(key):
                *prefix, r, g, b = self._raw_info(f"{key}_blocks")[1]
                return "BF16", (*prefix, g * b * 2, r)
        return self._raw_info(key)

    def _raw_tensor(self, key: str) -> np.ndarray:
        header, data_off, mm = self._file(self.weight_map[key])
        meta = header[key]
        dtype = _ST_TO_NP[meta["dtype"]]
        start, end = meta["data_offsets"]
        buf = mm[data_off + start : data_off + end]
        return np.frombuffer(buf, dtype=dtype).reshape(meta["shape"])

    def get_tensor(self, key: str) -> np.ndarray:
        if self.dequantize:
            from automodel_tpu.checkpoint import quant_io

            if self._is_fp8_blockwise(key):
                return quant_io.dequantize_fp8_blockwise(
                    self._raw_tensor(key), self._raw_tensor(f"{key}_scale_inv")
                )
            if self._is_mxfp4(key):
                return quant_io.dequantize_mxfp4(
                    self._raw_tensor(f"{key}_blocks"),
                    self._raw_tensor(f"{key}_scales"),
                )
        return self._raw_tensor(key)

    def close(self) -> None:
        for _, _, mm in self._files.values():
            mm.close()
        self._files.clear()


@retry_io(op="safetensors_write", max_attempts=3)
def _write_safetensors(path: Path, tensors: dict[str, np.ndarray]) -> None:
    header: dict[str, Any] = {}
    offset = 0
    for k, arr in tensors.items():
        st_dtype = _NP_TO_ST.get(arr.dtype)
        if st_dtype is None:
            raise TypeError(f"{k}: dtype {arr.dtype} has no safetensors encoding")
        header[k] = {
            "dtype": st_dtype,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + arr.nbytes],
        }
        offset += arr.nbytes
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    # safetensors spec: pad header with spaces to 8-byte alignment
    pad = (8 - (len(hbytes) % 8)) % 8
    hbytes += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hbytes)))
        f.write(hbytes)
        for k, arr in tensors.items():
            f.write(np.ascontiguousarray(arr).tobytes())


def save_hf_checkpoint(
    path: str | os.PathLike,
    tensors: Iterable[tuple[str, np.ndarray]],
    metadata: dict | None = None,
    max_shard_bytes: int = MAX_SHARD_BYTES,
    dtype: Any = None,
) -> None:
    """Write sharded safetensors + index (consolidated-HF layout the
    reference produces via _HuggingFaceStorageWriter, checkpointing.py:733).

    Streams: each shard file is written and released as soon as it reaches
    `max_shard_bytes`, so peak host memory is one shard, not the model.
    Shards get temporary names until the total count is known, then are
    renamed to ``model-0000x-of-0000y.safetensors``.
    """
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    shard: dict[str, np.ndarray] = {}
    shard_size = 0
    shard_keys: list[list[str]] = []
    total = 0

    def flush():
        nonlocal shard, shard_size
        if not shard:
            return
        _write_safetensors(path / f"shard-{len(shard_keys):05d}.tmp", shard)
        shard_keys.append(list(shard))
        shard = {}
        shard_size = 0

    for key, arr in tensors:
        arr = np.asarray(arr)
        if dtype is not None:
            arr = arr.astype(dtype)
        if shard_size + arr.nbytes > max_shard_bytes and shard:
            flush()
        shard[key] = arr
        shard_size += arr.nbytes
        total += arr.nbytes
    flush()

    n = len(shard_keys)
    weight_map: dict[str, str] = {}
    for i, keys in enumerate(shard_keys):
        fname = (
            "model.safetensors" if n == 1 else f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        )
        (path / f"shard-{i:05d}.tmp").rename(path / fname)
        weight_map.update({k: fname for k in keys})
    if n != 1:
        index = {
            "metadata": {"total_size": total, **(metadata or {})},
            "weight_map": weight_map,
        }
        (path / SAFETENSORS_INDEX).write_text(json.dumps(index, indent=2))


class LazyStacked:
    """A native leaf with a leading stack axis whose rows are fetched on
    demand (one HF tensor group per row). Lets the loader build the sharded
    device array shard-by-shard via ``jax.make_array_from_callback`` without
    ever materializing the stacked leaf on host — the 100B-class ingest
    story (reference: load_base_model streams per-rank shards,
    checkpointing.py:429; SURVEY hard-part 3)."""

    def __init__(self, row_fns):
        self.row_fns = list(row_fns)
        self._cache: tuple[int, np.ndarray] | None = None  # (idx, row)

    def row(self, i: int) -> np.ndarray:
        if self._cache is None or self._cache[0] != i:
            self._cache = (i, np.asarray(self.row_fns[i]()))
        return self._cache[1]

    @property
    def shape(self) -> tuple[int, ...]:
        return (len(self.row_fns), *self.row(0).shape)

    @property
    def dtype(self):
        return self.row(0).dtype

    def materialize(self) -> np.ndarray:
        return np.stack([self.row_fns[i]() for i in range(len(self.row_fns))], 0)


def _place_lazy(leaf: "LazyStacked", sharding) -> Any:
    """Build a sharded jax.Array from a LazyStacked leaf.

    Each row is fetched from the checkpoint EXACTLY ONCE (no per-device
    refetch when the stack axis is unsharded — the common FSDP/TP layout);
    row slices go straight to their target device, and per-device shards
    are stacked ON DEVICE, so host transient memory stays O(one row)."""
    import jax

    shape = leaf.shape
    idx_map = sharding.addressable_devices_indices_map(shape)
    row_ranges = {d: range(*idx[0].indices(shape[0])) for d, idx in idx_map.items()}
    bufs: dict = {d: [] for d in idx_map}
    for i in range(shape[0]):
        row = None
        for d, idx in idx_map.items():
            if i in row_ranges[d]:
                if row is None:
                    row = leaf.row(i)
                bufs[d].append(jax.device_put(row[tuple(idx[1:])], d))
    shards = []
    for d in idx_map:
        with jax.default_device(d):
            shards.append(jax.numpy.stack(bufs[d], 0))
        bufs[d] = None
    return jax.make_array_from_single_device_arrays(shape, sharding, shards)


def _tree_get(tree: Any, path: tuple) -> Any:
    for k in path:
        tree = tree[k]
    return tree


def _tree_set(tree: dict, path: tuple, value: Any) -> None:
    for k in path[:-1]:
        tree = tree.setdefault(k, {})
    tree[path[-1]] = value


def assemble_tree(leaves: Iterable[tuple[tuple[str, ...], Any]]) -> dict:
    """(path, leaf) pairs → nested dict tree (LazyStacked leaves realize)."""
    out: dict = {}
    for path, leaf in leaves:
        if hasattr(leaf, "materialize"):
            leaf = leaf.materialize()
        _tree_set(out, path, leaf)
    return out


def load_params_from_hf(
    adapter: Any,
    reader: HFCheckpointReader | str | os.PathLike,
    shardings: Any = None,
    dtype: Any = None,
) -> Any:
    """Assemble a native param tree from an HF checkpoint, placing each leaf
    on device with its target sharding as it is built (reference:
    load_base_model, checkpointing.py:429 — but with no per-rank dance).

    When the adapter exposes ``iter_from_hf`` (all in-tree adapters do),
    leaves stream: each is device_put as soon as it is assembled, and
    LazyStacked leaves never materialize on host at all — peak host memory
    is O(largest row), not O(model)."""
    import jax

    if not hasattr(reader, "get_tensor"):  # path-like → open; readers
        reader = HFCheckpointReader(reader)  # (incl. RemappedReader) pass through

    def get(key: str) -> np.ndarray:
        arr = reader.get_tensor(key)
        return arr.astype(dtype) if dtype is not None else arr

    if hasattr(adapter, "iter_from_hf"):
        out: dict = {}
        for path, leaf in adapter.iter_from_hf(get):
            sh = _tree_get(shardings, path) if shardings is not None else None
            if isinstance(leaf, LazyStacked):
                placed = (
                    _place_lazy(leaf, sh)
                    if sh is not None
                    else jax.numpy.asarray(leaf.materialize())
                )
            else:
                placed = (
                    jax.device_put(leaf, sh)
                    if sh is not None
                    else jax.numpy.asarray(leaf)
                )
            _tree_set(out, path, placed)
        reader.close()
        return out

    params = adapter.from_hf(get)
    if shardings is not None:
        params = jax.tree.map(
            lambda leaf, sh: jax.device_put(leaf, sh), params, shardings
        )
    else:
        params = jax.tree.map(jax.numpy.asarray, params)
    reader.close()
    return params
