"""HF safetensors checkpoint IO.

Parity: the reference's HF-storage layer (components/checkpoint/_backports/
hf_storage.py, consolidate_hf_safetensors.py) reads/writes sharded
``model-0000x-of-0000y.safetensors`` + ``model.safetensors.index.json``.
TPU-native: single-controller JAX needs no multi-rank consolidation dance —
we stream tensors shard-file by shard-file on the host and device_put each
leaf directly to its target sharding (SURVEY.md §7: "single-controller makes
this simpler than the reference's rank dance").
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Callable, Iterable, Iterator

import numpy as np

SAFETENSORS_INDEX = "model.safetensors.index.json"
MAX_SHARD_BYTES = 5 * 1024**3

# torch-free dtype mapping for reading HF checkpoints via numpy
_ST_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def _bf16_to_f32(raw: np.ndarray) -> np.ndarray:
    """View uint16 bf16 payload as float32 (shift into high mantissa bits)."""
    u32 = raw.astype(np.uint32) << 16
    return u32.view(np.float32)


class HFCheckpointReader:
    """Lazy reader over a HF checkpoint dir (single file or sharded+index)."""

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        index_file = self.path / SAFETENSORS_INDEX
        self.weight_map: dict[str, str] = {}
        if index_file.exists():
            index = json.loads(index_file.read_text())
            self.weight_map = dict(index["weight_map"])
        else:
            single = self.path / "model.safetensors"
            if not single.exists():
                cands = sorted(self.path.glob("*.safetensors"))
                if not cands:
                    raise FileNotFoundError(f"No safetensors checkpoint under {self.path}")
                single = cands[0]
            from safetensors import safe_open

            with safe_open(str(single), framework="numpy") as f:
                for k in f.keys():
                    self.weight_map[k] = single.name
        self._open_files: dict[str, Any] = {}

    def keys(self) -> list[str]:
        return list(self.weight_map)

    def _file(self, name: str):
        if name not in self._open_files:
            from safetensors import safe_open

            self._open_files[name] = safe_open(str(self.path / name), framework="numpy")
        return self._open_files[name]

    def get_tensor(self, key: str) -> np.ndarray:
        f = self._file(self.weight_map[key])
        try:
            return f.get_tensor(key)
        except Exception:
            # numpy framework can't decode bf16; read the slice raw and widen.
            sl = f.get_slice(key)
            dtype = sl.get_dtype()
            if str(dtype).upper() in ("BF16", "BFLOAT16"):
                import torch

                with_safe = self.path / self.weight_map[key]
                from safetensors import safe_open as so

                with so(str(with_safe), framework="pt") as tf:
                    t = tf.get_tensor(key)
                return t.float().numpy()
            raise

    def close(self) -> None:
        self._open_files.clear()


def save_hf_checkpoint(
    path: str | os.PathLike,
    tensors: Iterable[tuple[str, np.ndarray]],
    metadata: dict | None = None,
    max_shard_bytes: int = MAX_SHARD_BYTES,
    dtype: Any = None,
) -> None:
    """Write sharded safetensors + index (consolidated-HF layout the
    reference produces via _HuggingFaceStorageWriter, checkpointing.py:733).

    `tensors` is an iterator so callers can stream device shards → host
    without holding the full model in RAM.
    """
    from safetensors.numpy import save_file

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    shards: list[dict[str, np.ndarray]] = [{}]
    sizes = [0]
    weight_map: dict[str, str] = {}
    total = 0
    for key, arr in tensors:
        arr = np.asarray(arr)
        if dtype is not None:
            arr = arr.astype(dtype)
        nbytes = arr.nbytes
        if sizes[-1] + nbytes > max_shard_bytes and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][key] = arr
        sizes[-1] += nbytes
        total += nbytes
    n = len(shards)
    if n == 1:
        fname = "model.safetensors"
        save_file(shards[0], str(path / fname))
        weight_map = {k: fname for k in shards[0]}
    else:
        for i, shard in enumerate(shards):
            fname = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
            save_file(shard, str(path / fname))
            weight_map.update({k: fname for k in shard})
    index = {"metadata": {"total_size": total, **(metadata or {})}, "weight_map": weight_map}
    (path / SAFETENSORS_INDEX).write_text(json.dumps(index, indent=2))


def load_params_from_hf(
    adapter: Any,
    reader: HFCheckpointReader | str | os.PathLike,
    shardings: Any = None,
    dtype: Any = None,
) -> Any:
    """Assemble a native param tree from an HF checkpoint, placing each leaf
    on device with its target sharding as it is built (reference:
    load_base_model, checkpointing.py:429 — but with no per-rank dance)."""
    import jax

    if not isinstance(reader, HFCheckpointReader):
        reader = HFCheckpointReader(reader)

    def get(key: str) -> np.ndarray:
        arr = reader.get_tensor(key)
        return arr.astype(dtype) if dtype is not None else arr

    params = adapter.from_hf(get)
    if shardings is not None:
        params = jax.tree.map(
            lambda leaf, sh: jax.device_put(leaf, sh), params, shardings
        )
    else:
        params = jax.tree.map(jax.numpy.asarray, params)
    reader.close()
    return params
