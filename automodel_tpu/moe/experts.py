"""Expert computation backends.

Parity: reference `GroupedExperts*` (components/moe/experts.py:158,478,763,
946) — four CUDA-era backends (loop/grouped_mm, FP8, DeepEP, TE). TPU-native
backends:

- ``dense``  — every expert processes every token, combine by routing weight
  (einsum). O(E/K) extra FLOPs; numerics reference + tiny-model tests.
- ``gspmd``  — capacity-based dispatch/combine einsums (the GSPMD MoE
  formulation proven on TPU pods: Switch/GLaM). Expert dim sharded on the
  ``ep`` mesh axis; XLA inserts the all-to-all that DeepEP hand-codes on
  GPUs (reference fused_a2a.py → here compiler-scheduled ICI collectives).
  Tokens over capacity are dropped (capacity_factor; the aux-free bias and
  aux loss keep loads balanced so drops stay rare).
- ``ragged`` — dropless sort + `jax.lax.ragged_dot` grouped matmul
  (megablocks-style). Best single-slice path; EP via shard_map a2a is the
  planned extension.

All backends take fused gate_up weights [E, D, 2I] and down [E, I, D];
SwiGLU-family activation.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.gate import GateOutput

Act = Callable[[jnp.ndarray], jnp.ndarray]


def _split_gate_up(gu: jnp.ndarray, interleaved: bool) -> tuple[jnp.ndarray, jnp.ndarray]:
    if interleaved:  # gpt-oss checkpoints interleave gate/up on the last dim
        return gu[..., ::2], gu[..., 1::2]
    return jnp.split(gu, 2, axis=-1)


def _ffn(
    h: jnp.ndarray,
    w: dict,
    act2: Act,
    interleaved: bool = False,
) -> jnp.ndarray:
    """h: [..., D] → [..., D] through one expert's weights dict
    {gate_up [D,2I], down [I,D], (gate_up_bias [2I], down_bias [D])}.
    `act2(gate, up)` is the two-argument gated activation."""
    gu = h @ w["gate_up"].astype(h.dtype)
    if "gate_up_bias" in w:
        gu = gu + w["gate_up_bias"].astype(h.dtype)
    g, u = _split_gate_up(gu, interleaved)
    out = act2(g, u) @ w["down"].astype(h.dtype)
    if "down_bias" in w:
        out = out + w["down_bias"].astype(h.dtype)
    return out


def dense_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    weights: dict,  # leaves with leading expert dim E
    cfg: MoEConfig,
    act2: Act,
) -> jnp.ndarray:
    E = cfg.num_experts
    # combine weights [T, E]
    cw = jnp.zeros((x.shape[0], E), x.dtype)
    cw = cw.at[
        jnp.arange(x.shape[0])[:, None], gate_out.topk_idx
    ].add(gate_out.topk_weights)
    ys = jax.vmap(
        lambda w: _ffn(x, w, act2, cfg.interleaved_gate_up), in_axes=0, out_axes=0
    )(weights)  # [E, T, D]
    return jnp.einsum("etd,te->td", ys, cw)


def gspmd_experts(
    x: jnp.ndarray,  # [B, S, D] — batch groups kept for sharded dispatch
    gate_out: GateOutput,  # computed over T = B*S flattened tokens
    weights: dict,
    cfg: MoEConfig,
    act2: Act,
    constrain: Callable = lambda a, spec: a,
) -> jnp.ndarray:
    """Capacity-based dispatch/combine (GSPMD MoE). Returns [B, S, D]."""
    B, S, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    cap = max(K, int(math.ceil(S * K / E * cfg.capacity_factor)))

    idx = gate_out.topk_idx.reshape(B, S, K)
    w = gate_out.topk_weights.reshape(B, S, K).astype(jnp.float32)

    # position of each (token, k) pick inside its expert's buffer, in
    # token-major priority order (reference dispatch order)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B,S,K,E]
    flat = onehot.reshape(B, S * K, E)
    pos = (jnp.cumsum(flat, axis=1) - flat).reshape(B, S, K, E)  # [B,S,K,E]
    pos = jnp.einsum("bske,bske->bsk", pos, onehot).astype(jnp.int32)
    keep = pos < cap
    pos_oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32) * keep[..., None]
    # dispatch/combine tensors [B, S, E, C]
    disp = jnp.einsum("bske,bskc->bsec", onehot, pos_oh)
    comb = jnp.einsum("bsk,bske,bskc->bsec", w, onehot, pos_oh)

    expert_in = jnp.einsum("bsec,bsd->ebcd", disp, x.astype(jnp.float32)).astype(
        x.dtype
    )
    expert_in = constrain(expert_in, ("expert", "expert_batch", None, None))
    expert_out = jax.vmap(
        lambda h, w: _ffn(h, w, act2, cfg.interleaved_gate_up)
    )(expert_in, weights)  # [E, B, C, D]
    expert_out = constrain(expert_out, ("expert", "expert_batch", None, None))
    out = jnp.einsum(
        "bsec,ebcd->bsd", comb, expert_out.astype(jnp.float32)
    )
    return out.astype(x.dtype)


def ragged_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    weights: dict,
    cfg: MoEConfig,
    act2: Act,
) -> jnp.ndarray:
    """Dropless sort + ragged_dot grouped matmul (single-slice hot path)."""
    T, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    flat_expert = gate_out.topk_idx.reshape(-1)  # [T*K]
    order = jnp.argsort(flat_expert)  # stable
    token_of = order // K
    xs = x[token_of]  # [T*K, D] sorted by expert
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)
    sorted_expert = flat_expert[order]

    gu = jax.lax.ragged_dot(xs, weights["gate_up"].astype(xs.dtype), group_sizes)
    if "gate_up_bias" in weights:
        gu = gu + weights["gate_up_bias"].astype(xs.dtype)[sorted_expert]
    g, u = _split_gate_up(gu, cfg.interleaved_gate_up)
    ys = jax.lax.ragged_dot(act2(g, u), weights["down"].astype(xs.dtype), group_sizes)
    if "down_bias" in weights:
        ys = ys + weights["down_bias"].astype(xs.dtype)[sorted_expert]

    wflat = gate_out.topk_weights.reshape(-1)[order]  # aligned with ys
    out = jnp.zeros((T, D), jnp.float32)
    out = out.at[token_of].add(ys.astype(jnp.float32) * wflat[:, None].astype(jnp.float32))
    return out.astype(x.dtype)


EXPERT_BACKENDS = {
    "dense": dense_experts,
    "gspmd": gspmd_experts,
    "ragged": ragged_experts,
}
