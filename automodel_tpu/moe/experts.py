"""Expert computation backends.

Parity: reference `GroupedExperts*` (components/moe/experts.py:158,478,763,
946) — four CUDA-era backends (loop/grouped_mm, FP8, DeepEP, TE). TPU-native
backends:

- ``dense``  — every expert processes every token, combine by routing weight
  (einsum). O(E/K) extra FLOPs; numerics reference + tiny-model tests.
- ``gspmd``  — capacity-based dispatch/combine einsums (the GSPMD MoE
  formulation proven on TPU pods: Switch/GLaM). Expert dim sharded on the
  ``ep`` mesh axis; XLA inserts the all-to-all that DeepEP hand-codes on
  GPUs (reference fused_a2a.py → here compiler-scheduled ICI collectives).
  Tokens over capacity are dropped (capacity_factor; the aux-free bias and
  aux loss keep loads balanced so drops stay rare).
- ``ragged`` — dropless sort + `jax.lax.ragged_dot` grouped matmul
  (megablocks-style). Best single-slice path.
- ``a2a``    — the DeepEP-equivalent token-exchange dispatcher (reference
  token_dispatcher.py:339, fused_a2a.py:102,201): explicit shard_map over the
  ``ep`` mesh axis with `lax.all_to_all` dispatch/combine around a local
  `ragged_dot` grouped matmul. Dropless by construction at the default
  capacity (per-peer worst case); `a2a_capacity_factor` bounds buffers for
  perf runs (over-capacity picks contribute zero, like the reference's
  bounded dispatch buffers). TP is handled inside the manual region: gate/up
  are pre-split so their tp shards align, down-proj partial sums ride the
  combine all_to_all and a single psum("tp") happens at [T, D].

All backends take fused gate_up weights [E, D, 2I] and down [E, I, D];
SwiGLU-family activation.
"""

from __future__ import annotations

import functools
import math
from typing import Callable

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.gate import GateOutput
from automodel_tpu.ops.fp8 import fp8_qdq_blockwise, fp8_qdq_tensor
from automodel_tpu.ops.grouped_matmul import ragged_dot

Act = Callable[[jnp.ndarray], jnp.ndarray]


def _split_gate_up(gu: jnp.ndarray, interleaved: bool) -> tuple[jnp.ndarray, jnp.ndarray]:
    if interleaved:  # gpt-oss checkpoints interleave gate/up on the last dim
        return gu[..., ::2], gu[..., 1::2]
    return jnp.split(gu, 2, axis=-1)


def _ffn(
    h: jnp.ndarray,
    w: dict,
    act2: Act,
    interleaved: bool = False,
    gated: bool = True,
) -> jnp.ndarray:
    """h: [..., D] → [..., D] through one expert's weights dict
    {gate_up [D,2I] (or [D,I] non-gated), down [I,D], (…biases)}.
    `act2(gate, up)` is the two-argument gated activation; non-gated experts
    (nemotron relu2) skip the split and act2 ignores its second operand."""
    gu = h @ w["gate_up"].astype(h.dtype)
    if "gate_up_bias" in w:
        gu = gu + w["gate_up_bias"].astype(h.dtype)
    g, u = _split_gate_up(gu, interleaved) if gated else (gu, gu)
    out = act2(g, u) @ w["down"].astype(h.dtype)
    if "down_bias" in w:
        out = out + w["down_bias"].astype(h.dtype)
    return out


def dense_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    weights: dict,  # leaves with leading expert dim E
    cfg: MoEConfig,
    act2: Act,
) -> jnp.ndarray:
    E = cfg.num_experts
    # combine weights [T, E]
    cw = jnp.zeros((x.shape[0], E), x.dtype)
    cw = cw.at[
        jnp.arange(x.shape[0])[:, None], gate_out.topk_idx
    ].add(gate_out.topk_weights)
    ys = jax.vmap(
        lambda w: _ffn(x, w, act2, cfg.interleaved_gate_up, cfg.gated), in_axes=0, out_axes=0
    )(weights)  # [E, T, D]
    return jnp.einsum("etd,te->td", ys, cw)


def gspmd_experts(
    x: jnp.ndarray,  # [B, S, D] — batch groups kept for sharded dispatch
    gate_out: GateOutput,  # computed over T = B*S flattened tokens
    weights: dict,
    cfg: MoEConfig,
    act2: Act,
    constrain: Callable = lambda a, spec: a,
) -> jnp.ndarray:
    """Capacity-based dispatch/combine (GSPMD MoE). Returns [B, S, D]."""
    B, S, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    cap = max(K, int(math.ceil(S * K / E * cfg.capacity_factor)))

    idx = gate_out.topk_idx.reshape(B, S, K)
    w = gate_out.topk_weights.reshape(B, S, K).astype(jnp.float32)

    # position of each (token, k) pick inside its expert's buffer, in
    # token-major priority order (reference dispatch order)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B,S,K,E]
    flat = onehot.reshape(B, S * K, E)
    pos = (jnp.cumsum(flat, axis=1) - flat).reshape(B, S, K, E)  # [B,S,K,E]
    pos = jnp.einsum("bske,bske->bsk", pos, onehot).astype(jnp.int32)
    keep = pos < cap
    pos_oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32) * keep[..., None]
    # dispatch/combine tensors [B, S, E, C]
    disp = jnp.einsum("bske,bskc->bsec", onehot, pos_oh)
    comb = jnp.einsum("bsk,bske,bskc->bsec", w, onehot, pos_oh)

    expert_in = jnp.einsum("bsec,bsd->ebcd", disp, x.astype(jnp.float32)).astype(
        x.dtype
    )
    expert_in = constrain(expert_in, ("expert", "expert_batch", None, None))
    expert_out = jax.vmap(
        lambda h, w: _ffn(h, w, act2, cfg.interleaved_gate_up, cfg.gated)
    )(expert_in, weights)  # [E, B, C, D]
    expert_out = constrain(expert_out, ("expert", "expert_batch", None, None))
    out = jnp.einsum(
        "bsec,ebcd->bsd", comb, expert_out.astype(jnp.float32)
    )
    return out.astype(x.dtype)


def _name_ckpt(x: jnp.ndarray, name: str) -> jnp.ndarray:
    """checkpoint_name tag: under remat='full_save_dispatch' these values
    are SAVED across the remat boundary (policy save_only_these_names), so
    the recompute pass skips re-argsorting the T·K picks."""
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(x, name)


def _float0_zero(a: jnp.ndarray):
    import numpy as np

    return np.zeros(a.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _dispatch_take(x, order, inv, K):
    """xs[p] = x[order[p] // K] with a gather-only VJP.

    Autodiff's VJP of this gather is a scatter-add onto [T, D] — the single
    most expensive op in the old MoE step (XLA scatter runs ~4x slower than
    a gather at bench shape, PROFILE_MOE_r04.md). Because ``order`` is a
    bijection over the T·K picks, dx[t] = Σ_k dxs[inv[t·K+k]] is a pure
    gather + K-fold dense sum instead. order/inv are explicit args (not a
    closure) so the function stays remat/checkpoint-safe."""
    return jnp.take(x, order // K, axis=0)


def _dispatch_take_fwd(x, order, inv, K):
    return _dispatch_take(x, order, inv, K), (order, inv, x.shape[0])


def _dispatch_take_bwd(K, res, dxs):
    order, inv, T = res
    dx = jnp.take(dxs, inv, axis=0).reshape(T, K, dxs.shape[-1]).sum(axis=1)
    return dx, _float0_zero(order), _float0_zero(inv)


_dispatch_take.defvjp(_dispatch_take_fwd, _dispatch_take_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _sorted_combine(ys, w, order, inv, K):
    """out[t] = Σ_k w[t,k] · ys[inv[t·K+k]] → [T, D] fp32.

    Replaces the fp32 ``.at[token_of].add`` scatter combine (~5ms/layer at
    bench shape) with an unsort GATHER + dense weighted K-fold sum (~1.5ms);
    the hand-written VJP keeps the backward scatter-free too (d_ys is a
    gather of dout rows scaled by the pick weight)."""
    T, D = w.shape[0], ys.shape[-1]
    yu = jnp.take(ys, inv, axis=0).reshape(T, K, D)
    return jnp.einsum(
        "tkd,tk->td", yu, w.astype(yu.dtype),
        preferred_element_type=jnp.float32,
    )


def _sorted_combine_fwd(ys, w, order, inv, K):
    return _sorted_combine(ys, w, order, inv, K), (ys, w, order, inv)


def _sorted_combine_bwd(K, res, dout):
    ys, w, order, inv = res
    T, D = w.shape[0], ys.shape[-1]
    # pick p came from token order[p]//K with weight wflat[order[p]]
    dys = (
        jnp.take(dout, order // K, axis=0)
        * jnp.take(w.reshape(-1), order)[:, None].astype(dout.dtype)
    ).astype(ys.dtype)
    yu = jnp.take(ys, inv, axis=0).reshape(T, K, D)
    dw = jnp.einsum("td,tkd->tk", dout, yu.astype(dout.dtype)).astype(w.dtype)
    return dys, dw, _float0_zero(order), _float0_zero(inv)


_sorted_combine.defvjp(_sorted_combine_fwd, _sorted_combine_bwd)


@jax.custom_vjp
def _perm_take(x, perm, inv):
    """y[i] = x[perm[i]] for a bijection ``perm`` with precomputed inverse
    ``inv`` — the VJP is the INVERSE gather (autodiff's transpose of a
    gather is an XLA scatter, ~4x slower at bench shape)."""
    return jnp.take(x, perm, axis=0)


def _perm_take_fwd(x, perm, inv):
    return jnp.take(x, perm, axis=0), (perm, inv)


def _perm_take_bwd(res, dy):
    perm, inv = res
    return jnp.take(dy, inv, axis=0), _float0_zero(perm), _float0_zero(inv)


_perm_take.defvjp(_perm_take_fwd, _perm_take_bwd)


@jax.custom_vjp
def _slot_pack(xs, src, dst, valid):
    """Peer-chunk send buffer as a GATHER: out[r] = xs[src[r]] where slot r
    is valid, else 0. Because picks arrive sorted by expert (hence
    peer-contiguous), slot r of peer p reads pick peer_off[p] + r%C — no
    ``.at[dst].set`` scatter in the forward. The VJP gathers by ``dst``
    (dropped picks map to the appended zero row): scatter-free both ways."""
    return jnp.where(valid[:, None], jnp.take(xs, src, axis=0), 0)


def _slot_pack_fwd(xs, src, dst, valid):
    return _slot_pack(xs, src, dst, valid), (src, dst, valid)


def _slot_pack_bwd(res, dy):
    src, dst, valid = res
    dxs = jnp.concatenate([dy, jnp.zeros((1, dy.shape[-1]), dy.dtype)])[dst]
    return dxs, _float0_zero(src), _float0_zero(dst), _float0_zero(valid)


_slot_pack.defvjp(_slot_pack_fwd, _slot_pack_bwd)


@jax.custom_vjp
def _slot_unpack(y, dst, src, valid):
    """Slots → picks: out[p] = y[dst[p]], with the sentinel dst (= num rows)
    reading an appended zero row (dropped picks contribute zero). VJP is the
    valid-masked gather by ``src`` — the exact transpose, scatter-free."""
    return jnp.concatenate([y, jnp.zeros((1, y.shape[-1]), y.dtype)])[dst]


def _slot_unpack_fwd(y, dst, src, valid):
    return _slot_unpack(y, dst, src, valid), (dst, src, valid)


def _slot_unpack_bwd(res, dp):
    dst, src, valid = res
    dy = jnp.where(valid[:, None], jnp.take(dp, src, axis=0), 0)
    return dy, _float0_zero(dst), _float0_zero(src), _float0_zero(valid)


_slot_unpack.defvjp(_slot_unpack_fwd, _slot_unpack_bwd)


def ragged_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    weights: dict,
    cfg: MoEConfig,
    act2: Act,
    platform: str | None = None,
    fp8: bool = False,
) -> jnp.ndarray:
    """Dropless sort + ragged_dot grouped matmul (single-slice hot path).

    Dispatch and combine are expressed as permutation GATHERS with custom
    VJPs (no XLA scatter anywhere in fwd or bwd — see PROFILE_MOE_r04.md for
    why); group sizes reuse the gate's expert_counts (an exact bincount of
    topk_idx, moe/gate.py).

    ``fp8``: e4m3 QDQ on both grouped-matmul operands — 128×128 blockwise
    scales on the expert weights, per-tensor dynamic on activations, STE
    grads (reference GroupedExpertsFP8, components/moe/experts.py:478)."""
    T, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    flat_expert = gate_out.topk_idx.reshape(-1)  # [T*K]
    order = _name_ckpt(jnp.argsort(flat_expert), "moe_sort_order")  # stable
    inv = _name_ckpt(jnp.argsort(order), "moe_sort_inv")
    group_sizes = gate_out.expert_counts.astype(jnp.int32)
    sorted_expert = flat_expert[order]
    xs = _dispatch_take(x, order, inv, K)  # [T*K, D] sorted by expert

    w_gu = weights["gate_up"].astype(xs.dtype)
    w_dn = weights["down"].astype(xs.dtype)
    if fp8:
        xs = fp8_qdq_tensor(xs)
        w_gu = fp8_qdq_blockwise(w_gu)
        w_dn = fp8_qdq_blockwise(w_dn)
    gu = ragged_dot(xs, w_gu, group_sizes, platform=platform)
    if "gate_up_bias" in weights:
        gu = gu + weights["gate_up_bias"].astype(xs.dtype)[sorted_expert]
    g, u = _split_gate_up(gu, cfg.interleaved_gate_up) if cfg.gated else (gu, gu)
    h_mid = act2(g, u)
    if fp8:
        h_mid = fp8_qdq_tensor(h_mid)
    ys = ragged_dot(h_mid, w_dn, group_sizes, platform=platform)
    if "down_bias" in weights:
        ys = ys + weights["down_bias"].astype(xs.dtype)[sorted_expert]

    out = _sorted_combine(ys, gate_out.topk_weights, order, inv, K)
    return out.astype(x.dtype)


def _fused_act_of(cfg: MoEConfig, act_name: str, fp8: bool):
    """(act_kind, limit) for the fused expert-MLP kernel, or a loud error
    when the config is outside what the kernel implements (same envelope as
    ragged_fused: silu-gated swiglu / swiglu_oai, no fp8 QDQ in-kernel)."""
    if fp8:
        raise NotImplementedError(
            "fused expert MLP does not implement the fp8 QDQ path — drop "
            "fp8_experts or use the unfused backend"
        )
    if not cfg.gated:
        raise NotImplementedError(
            "fused expert MLP supports gated swiglu experts only"
        )
    if cfg.activation not in ("swiglu", "swiglu_oai") or (
        cfg.activation == "swiglu" and act_name != "silu"
    ):
        raise NotImplementedError(
            f"fused expert MLP implements silu-gated swiglu and swiglu_oai, "
            f"not activation={cfg.activation!r} with base act {act_name!r}"
        )
    return (
        "swiglu_oai" if cfg.activation == "swiglu_oai" else "swiglu",
        cfg.activation_limit,
    )


def a2a_experts(
    x: jnp.ndarray,  # [B, S, D]
    gate_out: GateOutput,
    weights: dict,
    cfg: MoEConfig,
    act2: Act,
    ctx,  # parallel.mesh.MeshContext | None
    platform: str | None = None,
    fp8: bool = False,
    fused_act=None,
) -> jnp.ndarray:
    """Dropless token-exchange EP dispatch (reference DeepEP dispatcher,
    token_dispatcher.py:339 + fused_a2a.py:102 → shard_map + lax.all_to_all).

    Per device block: sort (token, k) picks by expert id, all_to_all the
    per-peer chunks (static capacity C per peer), locally re-sort by expert
    and run `ragged_dot` grouped matmuls, then reverse the exchange and
    scatter-combine. `ragged_all_to_all` would avoid chunk padding but is not
    implemented by XLA:CPU (where the multichip tests run); the padded
    formulation is numerically identical and XLA lowers the all_to_all onto
    ICI either way.
    """
    B, S, D = x.shape
    if ctx is not None:
        platform = ctx.platform
    if ctx is None or ctx.ep_size == 1:
        # single-slice: the ragged path is already dropless
        if fused_act is not None:
            return ragged_fused_experts(
                x.reshape(-1, D), gate_out, weights, cfg, act2,
                platform=platform,
            ).reshape(B, S, D)
        return ragged_experts(
            x.reshape(-1, D), gate_out, weights, cfg, act2, platform=platform,
            fp8=fp8,
        ).reshape(B, S, D)

    from automodel_tpu.parallel.mesh import MeshAxisName as A
    from jax.sharding import PartitionSpec as P

    mesh = ctx.mesh
    ep = ctx.ep_size
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    if E % ep:
        raise ValueError(f"num_experts={E} must be divisible by ep={ep}")
    E_loc = E // ep
    b_div = mesh.shape[A.DP_REPLICATE] * mesh.shape[A.DP_SHARD] * mesh.shape[A.EP]
    s_div = mesh.shape[A.CP]
    if B % b_div or S % s_div:
        raise ValueError(
            f"batch {B}x{S} not divisible by data axes {b_div}x{s_div} for a2a dispatch"
        )
    Tl = (B // b_div) * (S // s_div)  # tokens per device block
    cap = Tl * min(K, E_loc)  # strict per-peer worst case → dropless
    if cfg.a2a_capacity_factor is not None:
        cap = min(cap, int(math.ceil(cfg.a2a_capacity_factor * Tl * K / ep)))
    C = -(-cap // 8) * 8  # chunk rows per peer, padded for TPU layouts

    wd = _a2a_weights(weights, cfg)

    batch_axes = (A.DP_REPLICATE, A.DP_SHARD, A.EP)
    tok_spec = P(batch_axes, A.CP, None)
    w_specs = {
        "gw": P(A.EP, None, A.TP),
        "uw": P(A.EP, None, A.TP),
        "dw": P(A.EP, A.TP, None),
        "gb": P(A.EP, A.TP),
        "ub": P(A.EP, A.TP),
        "db": P(A.EP, None),
    }

    body = functools.partial(
        _a2a_body,
        ep=ep, ep_axis=A.EP, E=E, E_loc=E_loc, C=C, D=D, K=K,
        act2=act2, gated=cfg.gated, tp_axis=A.TP, platform=platform, fp8=fp8,
        fused_act=fused_act,
    )
    idx = gate_out.topk_idx.reshape(B, S, K)
    cw = gate_out.topk_weights.reshape(B, S, K)
    # check_vma=False (same stance as the ring in parallel/cp.py): the
    # region runs Pallas kernels whose interpret-mode discharge cannot
    # carry mixed vma (jax limitation), and custom-VJP cotangent psums are
    # then placed by the spec-based shard_map transpose. The in-kernel
    # _match_vma/_out_sds plumbing stays for vma-checked callers (pp).
    from automodel_tpu.utils.compat import shard_map

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, {k: w_specs[k] for k in wd}),
        out_specs=tok_spec,
        check_vma=False,
    )(x, idx, cw, wd)


def _a2a_weights(weights: dict, cfg: MoEConfig) -> dict:
    """Per-shard weight dict for the a2a body. Gated experts pre-split
    gate/up so their tp shards align; non-gated (nemotron relu2) experts
    carry the single up projection as 'gw' and act2 ignores its second
    operand (same convention as _ffn)."""
    if cfg.gated:
        gw, uw = _split_gate_up(weights["gate_up"], cfg.interleaved_gate_up)
        wd = {"gw": gw, "uw": uw, "dw": weights["down"]}
        if "gate_up_bias" in weights:
            wd["gb"], wd["ub"] = _split_gate_up(
                weights["gate_up_bias"], cfg.interleaved_gate_up
            )
    else:
        wd = {"gw": weights["gate_up"], "dw": weights["down"]}
        if "gate_up_bias" in weights:
            wd["gb"] = weights["gate_up_bias"]
    if "down_bias" in weights:
        wd["db"] = weights["down_bias"]
    return wd


def _a2a_body(xb, idxb, cwb, wd, *, ep, ep_axis, E, E_loc, C, D, K, act2,
              gated=True, tp_axis=None, platform=None, fp8=False,
              fused_act=None):
    """The per-device token-exchange block. Requires `ep_axis` (and, when
    ``tp_axis`` is set, that axis too) to be MANUAL in the calling context —
    either a2a_experts' own shard_map, or a pipeline region already manual
    over {pp, ep} (parallel.pp ep_manual mode, tp_axis=None)."""
    Bl, Sl, _ = xb.shape
    T = Bl * Sl
    xt = xb.reshape(T, D)
    flat = idxb.reshape(T * K)
    order = _name_ckpt(
        jnp.argsort(flat, stable=True), "moe_sort_order"
    )  # sorted-pick → original-pick
    inv_order = _name_ckpt(jnp.argsort(order), "moe_sort_order_inv")
    sorted_e = flat[order]
    # [T*K, D] picks sorted by global expert id; gather-only VJP (the K-fold
    # dense sum) instead of autodiff's scatter-add transpose
    xs = _dispatch_take(xt, order, inv_order, K)

    counts = jnp.bincount(flat, length=E).astype(jnp.int32)
    peer_counts = counts.reshape(ep, E_loc).sum(-1)
    peer_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(peer_counts)[:-1]]
    )
    peer_of = sorted_e // E_loc
    pos_in_peer = jnp.arange(T * K, dtype=jnp.int32) - peer_off[peer_of]
    keep = pos_in_peer < C  # over-capacity picks drop (zero contribution)
    dst = jnp.where(keep, peer_of * C + pos_in_peer, ep * C)
    # slot r of peer p holds pick peer_off[p] + r%C (picks are sorted, hence
    # peer-contiguous) — the send buffer is a gather, not an .at[].set
    slot = jnp.arange(ep * C, dtype=jnp.int32)
    slot_c = slot % C
    slot_valid = slot_c < peer_counts[slot // C]
    src = jnp.minimum(peer_off[slot // C] + slot_c, T * K - 1)

    send_x = _slot_pack(xs, src, dst, slot_valid)
    send_id = jnp.where(slot_valid, sorted_e[src] % E_loc, E_loc)
    a2a = lambda a: jax.lax.all_to_all(
        a, ep_axis, split_axis=0, concat_axis=0, tiled=True
    )
    recv_x, recv_id = a2a(send_x), a2a(send_id)  # [ep*C, ...] by sender

    order2 = _name_ckpt(
        jnp.argsort(recv_id, stable=True), "moe_sort_inv"
    )  # sentinel E_loc sorts last
    inv_order2 = _name_ckpt(jnp.argsort(order2), "moe_sort_inv2")
    xs2 = _perm_take(recv_x, order2, inv_order2)
    sid = jnp.minimum(recv_id[order2], E_loc - 1)
    gsz = jnp.bincount(recv_id, length=E_loc).astype(jnp.int32)  # sentinel drops

    w_g = wd["gw"].astype(xs2.dtype)
    w_d = wd["dw"].astype(xs2.dtype)
    if fused_act is not None:
        # one-kernel local expert MLP (ops/fused_expert_mlp): the [rows, 2I]
        # gate_up output and the [rows, I] activation never touch HBM —
        # the same win the single-chip ragged_fused backend gets, on the
        # post-exchange rows. The down bias stays OUTSIDE the kernel when
        # tp shards the experts (it must land on one tp shard only).
        act_kind, limit = fused_act
        from automodel_tpu.ops.fused_expert_mlp import fused_expert_mlp

        w_u = wd["uw"].astype(xs2.dtype)
        gb = wd["gb"].astype(xs2.dtype) if "gb" in wd else None
        ub = wd["ub"].astype(xs2.dtype) if "ub" in wd else None
        db = wd.get("db")
        db_in_kernel = db if tp_axis is None else None
        y = fused_expert_mlp(
            xs2, w_g, w_u, w_d, gsz,
            gb, ub,
            None if db_in_kernel is None else db_in_kernel.astype(xs2.dtype),
            act_kind, limit, platform, None,
        )
        if db is not None and tp_axis is not None:
            y = y + jnp.where(
                jax.lax.axis_index(tp_axis) == 0, db.astype(y.dtype)[sid], 0.0
            )
    else:
        if fp8:
            xs2 = fp8_qdq_tensor(xs2)
            w_g, w_d = fp8_qdq_blockwise(w_g), fp8_qdq_blockwise(w_d)
        g = ragged_dot(xs2, w_g, gsz, platform=platform)
        if "gb" in wd:
            g = g + wd["gb"].astype(g.dtype)[sid]
        if gated:
            w_u = wd["uw"].astype(xs2.dtype)
            if fp8:
                w_u = fp8_qdq_blockwise(w_u)
            u = ragged_dot(xs2, w_u, gsz, platform=platform)
            if "ub" in wd:
                u = u + wd["ub"].astype(u.dtype)[sid]
        else:  # non-gated (relu2): one projection, act2 ignores its 2nd operand
            u = g
        h_mid = act2(g, u)
        if fp8:
            h_mid = fp8_qdq_tensor(h_mid)
        y = ragged_dot(h_mid, w_d, gsz, platform=platform)
        if "db" in wd:
            if tp_axis is not None:  # partial over tp: bias on one shard only
                y = y + jnp.where(
                    jax.lax.axis_index(tp_axis) == 0,
                    wd["db"].astype(y.dtype)[sid], 0.0,
                )
            else:
                y = y + wd["db"].astype(y.dtype)[sid]
    # permutations invert as forward GATHERS (out[p[i]] = y[i] is exactly
    # y[argsort(p)]), and every gather here carries a gather-only custom VJP
    # — the EP backward contains no XLA scatter (VERDICT r4 weak #3; jax
    # 0.9's shard_map infers vma through custom_vjp cleanly, which blocked
    # this in r4).
    y = _perm_take(y, inv_order2, order2)  # back to recv order
    y = a2a(y)  # [ep*C, D] back in my send layout
    y = _slot_unpack(y, dst, src, slot_valid)  # picks; dropped → 0
    y = _perm_take(y, inv_order, order)  # original pick order

    # picks of token t are rows [t*K, t*K+K) → combine is a dense reshape
    # + weighted K-fold sum, no scatter in the forward
    out = jnp.einsum(
        "tkd,tk->td",
        y.reshape(T, K, D),
        cwb.reshape(T, K),
        preferred_element_type=jnp.float32,
    )
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)  # down-proj partials, deferred to [T, D]
    return out.astype(xb.dtype).reshape(Bl, Sl, D)


def a2a_experts_manual(
    x: jnp.ndarray,  # [B_loc, S_loc, D] — the LOCAL ep shard
    gate_out: GateOutput,  # over the local tokens
    weights: dict,
    cfg: MoEConfig,
    act2: Act,
    *,
    ep: int,
    ep_axis: str = "ep",
    platform: str | None = None,
    fp8: bool = False,
    fused_act=None,
) -> jnp.ndarray:
    """a2a dispatch for contexts where `ep` is ALREADY a manual axis (the
    pp×ep pipeline region). tp must not shard the expert weights here
    (parallel.pp restricts ep_manual mode to tp=1)."""
    Bl, Sl, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    if E % ep:
        raise ValueError(f"num_experts={E} must be divisible by ep={ep}")
    E_loc = E // ep
    Tl = Bl * Sl
    cap = Tl * min(K, E_loc)  # strict per-peer worst case → dropless
    if cfg.a2a_capacity_factor is not None:
        cap = min(cap, int(math.ceil(cfg.a2a_capacity_factor * Tl * K / ep)))
    C = -(-cap // 8) * 8

    wd = _a2a_weights(weights, cfg)

    idx = gate_out.topk_idx.reshape(Bl, Sl, K)
    cw = gate_out.topk_weights.reshape(Bl, Sl, K)
    return _a2a_body(
        x, idx, cw, wd,
        ep=ep, ep_axis=ep_axis, E=E, E_loc=E_loc, C=C, D=D, K=K,
        act2=act2, gated=cfg.gated, tp_axis=None, platform=platform, fp8=fp8,
        fused_act=fused_act,
    )


# Registry with a UNIFORM call shape — x is [B, S, D]; every entry accepts
# (and ignores where irrelevant) ctx/constrain/platform, so the dispatch in
# moe.layer stays one registry call as kwargs accrete. The per-backend
# functions above keep their natural signatures for direct/test use.
def _noop_constrain(a, spec):
    return a


_warned_fp8_backend: set = set()


def _warn_fp8_unsupported(name: str) -> None:
    if name not in _warned_fp8_backend:
        _warned_fp8_backend.add(name)
        import logging

        logging.getLogger(__name__).warning(
            "fp8_experts=True but experts=%r does not implement the fp8 "
            "path — running in full precision (use 'ragged' or 'a2a').", name
        )


def _run_dense(x, gate_out, weights, cfg, act2, *, ctx=None,
               constrain=_noop_constrain, platform=None, fp8=False,
               act_name="silu"):
    if fp8:
        _warn_fp8_unsupported("dense")
    B, S, D = x.shape
    return dense_experts(x.reshape(-1, D), gate_out, weights, cfg, act2).reshape(B, S, D)


def _run_gspmd(x, gate_out, weights, cfg, act2, *, ctx=None,
               constrain=_noop_constrain, platform=None, fp8=False,
               act_name="silu"):
    if fp8:
        _warn_fp8_unsupported("gspmd")
    return gspmd_experts(x, gate_out, weights, cfg, act2, constrain=constrain)


def _run_ragged(x, gate_out, weights, cfg, act2, *, ctx=None,
                constrain=_noop_constrain, platform=None, fp8=False,
                act_name="silu"):
    B, S, D = x.shape
    return ragged_experts(
        x.reshape(-1, D), gate_out, weights, cfg, act2, platform=platform, fp8=fp8
    ).reshape(B, S, D)


def _run_a2a(x, gate_out, weights, cfg, act2, *, ctx=None,
             constrain=_noop_constrain, platform=None, fp8=False,
             act_name="silu"):
    return a2a_experts(x, gate_out, weights, cfg, act2, ctx, platform=platform,
                       fp8=fp8)


def _run_a2a_fused(x, gate_out, weights, cfg, act2, *, ctx=None,
                   constrain=_noop_constrain, platform=None, fp8=False,
                   act_name="silu"):
    """a2a token exchange + the one-kernel local expert MLP: EP training
    gets the same per-layer HBM savings as the single-chip ragged_fused
    backend (reference capability: DeepEP dispatch feeding TE's fused
    epilogues)."""
    fused_act = _fused_act_of(cfg, act_name, fp8)
    return a2a_experts(x, gate_out, weights, cfg, act2, ctx, platform=platform,
                       fp8=fp8, fused_act=fused_act)


def ragged_fused_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    weights: dict,
    cfg: MoEConfig,
    act2: Act,  # unused — the kernel applies the activation from cfg
    platform: str | None = None,
    act_name: str = "silu",
) -> jnp.ndarray:
    """ragged_experts with the WHOLE expert MLP in one Pallas kernel
    (ops/fused_expert_mlp): the [T·K, 2I] gate_up output and the [T·K, I]
    activation never touch HBM. Same dropless sort + permutation-gather
    dispatch/combine; backward recomputes through the two-gmm composition."""
    from automodel_tpu.ops.fused_expert_mlp import fused_expert_mlp

    act_kind, limit = _fused_act_of(cfg, act_name, fp8=False)
    T, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    flat_expert = gate_out.topk_idx.reshape(-1)
    order = _name_ckpt(jnp.argsort(flat_expert), "moe_sort_order")
    inv = _name_ckpt(jnp.argsort(order), "moe_sort_inv")
    group_sizes = gate_out.expert_counts.astype(jnp.int32)
    xs = _dispatch_take(x, order, inv, K)
    gw, uw = _split_gate_up(weights["gate_up"], cfg.interleaved_gate_up)
    gb = ub = db = None
    if "gate_up_bias" in weights:  # gpt-oss expert biases, per I-chunk in-kernel
        gb, ub = _split_gate_up(
            weights["gate_up_bias"], cfg.interleaved_gate_up
        )
        gb, ub = gb.astype(xs.dtype), ub.astype(xs.dtype)
    if "down_bias" in weights:
        db = weights["down_bias"].astype(xs.dtype)
    ys = fused_expert_mlp(
        xs, gw.astype(xs.dtype), uw.astype(xs.dtype),
        weights["down"].astype(xs.dtype), group_sizes,
        gb, ub, db, act_kind, limit, platform, None,
    )
    out = _sorted_combine(ys, gate_out.topk_weights, order, inv, K)
    return out.astype(x.dtype)


def _run_ragged_fused(x, gate_out, weights, cfg, act2, *, ctx=None,
                      constrain=_noop_constrain, platform=None, fp8=False,
                      act_name="silu"):
    # validate the full envelope incl. fp8 (raise, matching a2a_fused — a
    # config must not abort on one mesh topology and silently drop
    # quantization on another)
    _fused_act_of(cfg, act_name, fp8)
    B, S, D = x.shape
    return ragged_fused_experts(
        x.reshape(-1, D), gate_out, weights, cfg, act2, platform=platform,
        act_name=act_name,
    ).reshape(B, S, D)


EXPERT_BACKENDS = {
    "ragged_fused": _run_ragged_fused,
    "dense": _run_dense,
    "gspmd": _run_gspmd,
    "ragged": _run_ragged,
    "a2a": _run_a2a,
    "a2a_fused": _run_a2a_fused,
}
