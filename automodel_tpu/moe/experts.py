"""Expert computation backends.

Parity: reference `GroupedExperts*` (components/moe/experts.py:158,478,763,
946) — four CUDA-era backends (loop/grouped_mm, FP8, DeepEP, TE). TPU-native
backends:

- ``dense``  — every expert processes every token, combine by routing weight
  (einsum). O(E/K) extra FLOPs; numerics reference + tiny-model tests.
- ``gspmd``  — capacity-based dispatch/combine einsums (the GSPMD MoE
  formulation proven on TPU pods: Switch/GLaM). Expert dim sharded on the
  ``ep`` mesh axis; XLA inserts the all-to-all that DeepEP hand-codes on
  GPUs (reference fused_a2a.py → here compiler-scheduled ICI collectives).
  Tokens over capacity are dropped (capacity_factor; the aux-free bias and
  aux loss keep loads balanced so drops stay rare).
- ``ragged`` — dropless sort + `jax.lax.ragged_dot` grouped matmul
  (megablocks-style). Best single-slice path; EP via shard_map a2a is the
  planned extension.

All backends take fused gate_up weights [E, D, 2I] and down [E, I, D];
SwiGLU-family activation.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.gate import GateOutput

Act = Callable[[jnp.ndarray], jnp.ndarray]


def _ffn(h: jnp.ndarray, gate_up: jnp.ndarray, down: jnp.ndarray, act: Act) -> jnp.ndarray:
    """h: [..., D] → [..., D] through fused-SwiGLU expert weights (no expert
    dim — caller has already selected/mapped the expert axis)."""
    gu = h @ gate_up.astype(h.dtype)
    g, u = jnp.split(gu, 2, axis=-1)
    return (act(g) * u) @ down.astype(h.dtype)


def dense_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    gate_up: jnp.ndarray,  # [E, D, 2I]
    down: jnp.ndarray,  # [E, I, D]
    cfg: MoEConfig,
    act: Act,
) -> jnp.ndarray:
    E = cfg.num_experts
    # combine weights [T, E]
    cw = jnp.zeros((x.shape[0], E), x.dtype)
    cw = cw.at[
        jnp.arange(x.shape[0])[:, None], gate_out.topk_idx
    ].add(gate_out.topk_weights)
    ys = jax.vmap(lambda gu, dn: _ffn(x, gu, dn, act), in_axes=0, out_axes=0)(
        gate_up, down
    )  # [E, T, D]
    return jnp.einsum("etd,te->td", ys, cw)


def gspmd_experts(
    x: jnp.ndarray,  # [B, S, D] — batch groups kept for sharded dispatch
    gate_out: GateOutput,  # computed over T = B*S flattened tokens
    gate_up: jnp.ndarray,
    down: jnp.ndarray,
    cfg: MoEConfig,
    act: Act,
    constrain: Callable = lambda a, spec: a,
) -> jnp.ndarray:
    """Capacity-based dispatch/combine (GSPMD MoE). Returns [B, S, D]."""
    B, S, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    cap = max(K, int(math.ceil(S * K / E * cfg.capacity_factor)))

    idx = gate_out.topk_idx.reshape(B, S, K)
    w = gate_out.topk_weights.reshape(B, S, K).astype(jnp.float32)

    # position of each (token, k) pick inside its expert's buffer, in
    # token-major priority order (reference dispatch order)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B,S,K,E]
    flat = onehot.reshape(B, S * K, E)
    pos = (jnp.cumsum(flat, axis=1) - flat).reshape(B, S, K, E)  # [B,S,K,E]
    pos = jnp.einsum("bske,bske->bsk", pos, onehot).astype(jnp.int32)
    keep = pos < cap
    pos_oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32) * keep[..., None]
    # dispatch/combine tensors [B, S, E, C]
    disp = jnp.einsum("bske,bskc->bsec", onehot, pos_oh)
    comb = jnp.einsum("bsk,bske,bskc->bsec", w, onehot, pos_oh)

    expert_in = jnp.einsum("bsec,bsd->ebcd", disp, x.astype(jnp.float32)).astype(
        x.dtype
    )
    expert_in = constrain(expert_in, ("expert", "expert_batch", None, None))
    expert_out = jax.vmap(lambda h, gu, dn: _ffn(h, gu, dn, act))(
        expert_in, gate_up, down
    )  # [E, B, C, D]
    expert_out = constrain(expert_out, ("expert", "expert_batch", None, None))
    out = jnp.einsum(
        "bsec,ebcd->bsd", comb, expert_out.astype(jnp.float32)
    )
    return out.astype(x.dtype)


def ragged_experts(
    x: jnp.ndarray,  # [T, D]
    gate_out: GateOutput,
    gate_up: jnp.ndarray,
    down: jnp.ndarray,
    cfg: MoEConfig,
    act: Act,
) -> jnp.ndarray:
    """Dropless sort + ragged_dot grouped matmul (single-slice hot path)."""
    T, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    flat_expert = gate_out.topk_idx.reshape(-1)  # [T*K]
    order = jnp.argsort(flat_expert)  # stable
    token_of = order // K
    xs = x[token_of]  # [T*K, D] sorted by expert
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    gu = jax.lax.ragged_dot(xs, gate_up.astype(xs.dtype), group_sizes)
    g, u = jnp.split(gu, 2, axis=-1)
    ys = jax.lax.ragged_dot((act(g) * u), down.astype(xs.dtype), group_sizes)

    wflat = gate_out.topk_weights.reshape(-1)[order]  # aligned with ys
    out = jnp.zeros((T, D), jnp.float32)
    out = out.at[token_of].add(ys.astype(jnp.float32) * wflat[:, None].astype(jnp.float32))
    return out.astype(x.dtype)


EXPERT_BACKENDS = {
    "dense": dense_experts,
    "gspmd": gspmd_experts,
    "ragged": ragged_experts,
}
