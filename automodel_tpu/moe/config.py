"""MoE configuration.

Parity: reference `MoEConfig` (components/moe/config.py:88) — routed/shared
expert counts, top-k, grouped routing, score function, aux-loss and aux-free
balancing knobs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int
    num_experts_per_tok: int
    moe_intermediate_size: int
    num_shared_experts: int = 0
    shared_expert_intermediate_size: int = 0
    # routing
    score_func: str = "softmax"  # softmax | sigmoid
    route_scale: float = 1.0
    norm_topk_prob: bool = False
    softmax_before_topk: bool = True  # score then pick (False: softmax over picked)
    n_group: int = 1  # node-limited (grouped) routing
    topk_group: int = 1
    # balancing
    aux_loss_coeff: float = 0.0  # sequence-level aux loss (DeepSeek style)
    bias_update_factor: float = 0.0  # aux-free bias balancing (V3); 0 = off
    expert_bias: bool = False  # e_score_correction_bias present
    # which layers are MoE: first `num_dense_layers` stay dense MLP
    num_dense_layers: int = 0
    # shared-expert gating (qwen2-moe style sigmoid gate on shared output)
    shared_expert_gate: bool = False
    # dispatch capacity factor for the gspmd (einsum) dispatcher
    capacity_factor: float = 1.25
    # a2a dispatcher per-peer buffer bound, × the balanced load T*K/ep.
    # None = strict worst case (dropless by construction); set ~2.0 to bound
    # memory on perf runs (over-capacity picks then contribute zero, like the
    # reference's bounded dispatch buffers).
    a2a_capacity_factor: Optional[float] = None
    # gpt-oss-style experts: gate/up interleaved on the fused dim, bias terms
    # on both projections, clamped (up+1)*glu activation, and a learned
    # linear bias on the router that feeds both selection and weights
    interleaved_gate_up: bool = False
    expert_mlp_bias: bool = False
    activation: str = "swiglu"  # swiglu | swiglu_oai | relu2 (non-gated)
    # step-3.5 per-layer clamp: silu(gate) capped at +limit, up clamped to
    # [-limit, limit] (reference step3p5 MoEConfig.activation_limit)
    activation_limit: Optional[float] = None
    router_linear_bias: bool = False

    @property
    def gated(self) -> bool:
        """Gated experts carry fused [.., D, 2I] gate_up weights; non-gated
        (nemotron relu2) carry [.., D, I] up-only weights."""
        return self.activation != "relu2"

    def __post_init__(self):
        if self.score_func not in ("softmax", "sigmoid"):
            raise ValueError(f"score_func {self.score_func!r}")
        if self.num_experts % self.n_group != 0:
            raise ValueError("num_experts must divide into n_group groups")
        if self.topk_group > self.n_group:
            raise ValueError("topk_group > n_group")
        if self.expert_bias and self.score_func == "softmax":
            # V3 pairs the correction bias with sigmoid scoring
            pass

    @classmethod
    def from_hf(cls, get: Any) -> "Optional[MoEConfig]":
        """Build from an HF config getter fn (model-family adapters call this
        with their own field-name mapping on top)."""
        n = get("num_experts", None) or get("n_routed_experts", None)
        if not n:
            return None
        return cls(
            num_experts=n,
            num_experts_per_tok=get("num_experts_per_tok", None)
            or get("num_experts_per_token", 2),
            moe_intermediate_size=get("moe_intermediate_size", None)
            or get("intermediate_size"),
            num_shared_experts=get("n_shared_experts", 0) or 0,
            shared_expert_intermediate_size=get("shared_expert_intermediate_size", 0)
            or 0,
            score_func=get("scoring_func", "softmax"),
            route_scale=get("routed_scaling_factor", 1.0) or 1.0,
            norm_topk_prob=bool(get("norm_topk_prob", False)),
            n_group=get("n_group", 1) or 1,
            topk_group=get("topk_group", 1) or 1,
            aux_loss_coeff=get("router_aux_loss_coef", 0.0) or 0.0,
            num_dense_layers=get("first_k_dense_replace", 0) or 0,
        )
