"""MoE router (gate).

Parity: reference `Gate` (components/moe/layers.py:202) — softmax/sigmoid
scoring, grouped top-k with node-limited routing, aux-free bias balancing
(`update_bias`), sequence-level aux loss — and `FakeBalancedGate`
(layers.py:117) for deterministic balanced-routing benchmarks.

Functional: `gate(...)` is pure; the aux-free bias is a non-trainable leaf in
the param tree updated OUTSIDE the gradient (update_gate_bias), mirroring the
reference's buffer + post-optimizer-step update (train_ft.py:1341).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig


class GateOutput(NamedTuple):
    topk_idx: jnp.ndarray  # [T, K] int32 expert ids
    topk_weights: jnp.ndarray  # [T, K] combine weights (compute dtype)
    expert_counts: jnp.ndarray  # [E] int32 tokens routed per expert (pre-drop)
    aux_loss: jnp.ndarray  # scalar f32 (0 when disabled)


def _score(logits: jnp.ndarray, cfg: MoEConfig) -> jnp.ndarray:
    if cfg.score_func == "sigmoid":
        return jax.nn.sigmoid(logits)
    return jax.nn.softmax(logits, axis=-1)


def gate(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    cfg: MoEConfig,
    bias: Optional[jnp.ndarray] = None,
    seq_len: Optional[int] = None,
    linear_bias: Optional[jnp.ndarray] = None,
) -> GateOutput:
    """Route tokens. x: [T, D], weight: [D, E], bias: [E] aux-free correction.

    Returns combine weights built from the ORIGINAL scores (the bias only
    affects selection — reference layers.py:202 semantics). `linear_bias` is
    a LEARNED router bias that feeds both selection and weights (gpt-oss).
    """
    T = x.shape[0]
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = (x.astype(jnp.float32) @ weight.astype(jnp.float32))  # [T, E]
    if linear_bias is not None:
        logits = logits + linear_bias.astype(jnp.float32)

    if cfg.softmax_before_topk or cfg.score_func == "sigmoid":
        scores = _score(logits, cfg)
        choice = scores + bias.astype(jnp.float32) if bias is not None else scores
    else:
        scores = logits
        choice = logits + bias.astype(jnp.float32) if bias is not None else logits

    if cfg.n_group > 1:
        # node-limited routing: keep only the best `topk_group` groups per
        # token (group score = sum of its top-2 choice scores)
        g = cfg.n_group
        grouped = choice.reshape(T, g, E // g)
        top2 = jax.lax.top_k(grouped, min(2, E // g))[0].sum(axis=-1)  # [T, g]
        _, top_groups = jax.lax.top_k(top2, cfg.topk_group)  # [T, topk_group]
        group_mask = jnp.zeros((T, g), bool).at[
            jnp.arange(T)[:, None], top_groups
        ].set(True)
        choice = jnp.where(
            jnp.repeat(group_mask, E // g, axis=1), choice, -jnp.inf
        )

    _, topk_idx = jax.lax.top_k(choice, K)  # [T, K]
    topk_scores = jnp.take_along_axis(scores, topk_idx, axis=1)

    if not cfg.softmax_before_topk and cfg.score_func == "softmax":
        topk_weights = jax.nn.softmax(topk_scores, axis=-1)
    else:
        topk_weights = topk_scores
        if cfg.norm_topk_prob:
            topk_weights = topk_weights / jnp.maximum(
                topk_weights.sum(axis=-1, keepdims=True), 1e-20
            )
    topk_weights = topk_weights * cfg.route_scale

    one_hot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [T, K, E]
    counts = one_hot.sum(axis=(0, 1))  # [E]

    aux = jnp.float32(0.0)
    if cfg.aux_loss_coeff > 0:
        # sequence-level aux loss (DeepSeek style): within each sequence,
        # fraction routed to expert e × mean prob of e; reduce over experts.
        probs = (
            scores if cfg.score_func == "softmax" else jax.nn.softmax(logits, axis=-1)
        )
        if seq_len is not None and T % seq_len == 0:
            S = seq_len
            B = T // S
            f = one_hot.reshape(B, S, K, E).sum(axis=(1, 2)) * (E / (K * S))  # [B,E]
            p = probs.reshape(B, S, E).mean(axis=1)  # [B, E]
            aux = (f * p).sum(axis=-1).mean() * cfg.aux_loss_coeff
        else:
            f = counts * (E / (K * T))
            p = probs.mean(axis=0)
            aux = (f * p).sum() * cfg.aux_loss_coeff

    return GateOutput(
        topk_idx.astype(jnp.int32),
        topk_weights.astype(x.dtype),
        counts.astype(jnp.int32),
        aux,
    )


def fake_balanced_gate(
    x: jnp.ndarray, cfg: MoEConfig, offset: int = 0
) -> GateOutput:
    """Deterministic perfectly-balanced routing for perf benchmarking
    (reference: FakeBalancedGate, moe/layers.py:117). Token t goes to experts
    (t*K + j + offset) mod E with uniform weights 1/K."""
    T = x.shape[0]
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    t = jnp.arange(T, dtype=jnp.int32)[:, None]
    j = jnp.arange(K, dtype=jnp.int32)[None, :]
    idx = (t * K + j + offset) % E
    w = jnp.full((T, K), 1.0 / K, x.dtype) * cfg.route_scale
    counts = jnp.zeros((E,), jnp.int32).at[idx.reshape(-1)].add(1)
    return GateOutput(idx, w, counts, jnp.float32(0.0))


def update_gate_bias(
    bias: jnp.ndarray, expert_counts: jnp.ndarray, update_factor: float
) -> jnp.ndarray:
    """Aux-free load balancing (reference: Gate.update_bias, layers.py:202;
    applied post-optimizer-step, train_ft.py:1341): push the selection bias
    of under-loaded experts up and over-loaded experts down by sign(error)."""
    c = expert_counts.astype(jnp.float32)
    err = c.mean() - c
    return bias + jnp.sign(err) * update_factor
