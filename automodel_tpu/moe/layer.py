"""The MoE block: gate → experts → (shared experts) → combine.

Parity: reference `MoE` (components/moe/layers.py:516) — routed experts plus
optional always-on shared experts (with optional sigmoid shared-expert gate),
gate aux outputs surfaced for load-balance metrics and aux-free bias updates.
The reference overlaps shared experts on a second CUDA stream (layers.py:41);
here both branches sit in one XLA program and the scheduler overlaps them.
"""

from __future__ import annotations

import logging
from typing import Callable, NamedTuple, Optional

import jax.numpy as jnp

logger = logging.getLogger(__name__)

from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.experts import EXPERT_BACKENDS
from automodel_tpu.moe.gate import GateOutput, fake_balanced_gate, gate


class MoEAux(NamedTuple):
    expert_counts: jnp.ndarray  # [E] int32
    aux_loss: jnp.ndarray  # scalar f32


def make_act2(cfg: MoEConfig, base_act: Callable) -> Callable:
    """Two-argument gated activation from the config."""
    if cfg.activation == "swiglu_oai":
        # gpt-oss: clamp, swish(1.702*g), (up+1) shift
        # (modeling_gpt_oss.py GptOssExperts.forward)
        def act2(g, u):
            g = jnp.minimum(g, 7.0)
            u = jnp.clip(u, -7.0, 7.0)
            import jax

            return (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))

        return act2
    if cfg.activation_limit is not None:
        if cfg.activation != "swiglu":
            raise NotImplementedError(
                f"activation_limit is only defined for gated swiglu experts "
                f"(step3p5), not activation={cfg.activation!r}"
            )
        lim = float(cfg.activation_limit)

        def act2_lim(g, u):
            g = jnp.minimum(base_act(g), lim)
            return g * jnp.clip(u, -lim, lim)

        return act2_lim
    if cfg.activation == "relu2":
        # nemotron-v3 non-gated experts: square-ReLU on the single up
        # projection (the u operand is the same array, ignored)
        import jax

        return lambda g, u: jnp.square(jax.nn.relu(g))
    return lambda g, u: base_act(g) * u


def moe_block(
    x: jnp.ndarray,  # [B, S, D]
    mp: dict,
    cfg: MoEConfig,
    act: Callable,
    experts_backend: str = "gspmd",
    fake_gate: bool = False,
    constrain: Callable = lambda a, s: a,
    platform: Optional[str] = None,
    fp8: bool = False,
    act_name: str = "silu",
) -> tuple[jnp.ndarray, MoEAux]:
    B, S, D = x.shape
    xt = x.reshape(-1, D)

    if fake_gate:
        gout = fake_balanced_gate(xt, cfg)
    else:
        gout = gate(
            xt,
            mp["router"]["weight"],
            cfg,
            bias=mp["router"].get("bias"),
            seq_len=S,
            linear_bias=mp["router"].get("linear_bias"),
        )

    act2 = make_act2(cfg, act)
    # mesh-aware backends (a2a) need the real Mesh for their shard_map
    # region; make_constrain attaches it to the constrain callback
    ctx = getattr(constrain, "mesh_ctx", None)
    if experts_backend in ("a2a", "a2a_fused") and ctx is None:
        logger.warning(
            "experts=%r but the constrain callback carries no mesh_ctx "
            "(use parallel.plans.make_constrain, or a custom wrapper must "
            "preserve the attribute); falling back to the single-slice "
            "ragged path — NO expert-parallel token exchange will happen.",
            experts_backend,
        )
    # a callable backend (e.g. the pipeline's ep-manual a2a binding) uses the
    # registry's uniform signature directly
    backend_fn = (
        experts_backend if callable(experts_backend)
        else EXPERT_BACKENDS[experts_backend]
    )
    routed = backend_fn(
        x, gout, mp["experts"], cfg, act2,
        ctx=ctx, constrain=constrain, platform=platform, fp8=fp8,
        act_name=act_name,
    )

    out = routed
    if "shared" in mp:
        sp = mp["shared"]
        u = xt @ sp["up_proj"]["kernel"].astype(xt.dtype)
        if "gate_proj" in sp:
            g = xt @ sp["gate_proj"]["kernel"].astype(xt.dtype)
            if cfg.activation_limit is not None:
                lim = float(cfg.activation_limit)
                mid = jnp.minimum(act(g), lim) * jnp.clip(u, -lim, lim)
            else:
                mid = act(g) * u
        else:  # non-gated shared expert (nemotron relu2)
            mid = act2(u, u)
        shared = mid @ sp["down_proj"]["kernel"].astype(xt.dtype)
        if "shared_gate" in mp:
            sg = jnp.asarray(xt @ mp["shared_gate"]["kernel"].astype(xt.dtype))
            shared = shared * jnp.asarray(jnp.reciprocal(1 + jnp.exp(-sg)))
        out = out + shared.reshape(B, S, D)

    return out, MoEAux(gout.expert_counts, gout.aux_loss)


def init_moe_params(
    key,
    cfg: MoEConfig,
    hidden_size: int,
    dtype,
    n_layers: Optional[int] = None,
) -> dict:
    """Init one MoE block's params; with n_layers, leaves get a leading
    stacked layer axis (lax.scan layout shared with the dense family)."""
    import jax

    def shape(*s):
        return (n_layers, *s) if n_layers else s

    D, E, I = hidden_size, cfg.num_experts, cfg.moe_intermediate_size
    k = jax.random.split(key, 6)

    def init(kk, *s, fan_in):
        return (
            jax.random.normal(kk, shape(*s), jnp.float32) / (fan_in**0.5)
        ).astype(dtype)

    p = {
        "router": {"weight": init(k[0], D, E, fan_in=D)},
        "experts": {
            "gate_up": init(k[1], E, D, (2 * I if cfg.gated else I), fan_in=D),
            "down": init(k[2], E, I, D, fan_in=I),
        },
    }
    if cfg.bias_update_factor > 0 or cfg.expert_bias:
        p["router"]["bias"] = jnp.zeros(shape(E), jnp.float32)
    if cfg.router_linear_bias:
        p["router"]["linear_bias"] = jnp.zeros(shape(E), jnp.float32)
    if cfg.expert_mlp_bias:
        p["experts"]["gate_up_bias"] = jnp.zeros(shape(E, (2 * I if cfg.gated else I)), dtype)
        p["experts"]["down_bias"] = jnp.zeros(shape(E, D), dtype)
    if cfg.num_shared_experts > 0:
        SI = cfg.shared_expert_intermediate_size or cfg.moe_intermediate_size
        SI = SI * cfg.num_shared_experts
        p["shared"] = {
            "up_proj": {"kernel": init(k[4], D, SI, fan_in=D)},
            "down_proj": {"kernel": init(k[5], SI, D, fan_in=SI)},
        }
        if cfg.gated:
            p["shared"]["gate_proj"] = {"kernel": init(k[3], D, SI, fan_in=D)}
        if cfg.shared_expert_gate:
            p["shared_gate"] = {"kernel": jnp.zeros(shape(D, 1), dtype)}
    return p


# Sharding rules for MoE params (logical dims → mesh axes via MeshContext):
# expert dim on `expert` (=ep), expert-FSDP on `expert_fsdp` (=dp_shard,cp),
# expert intermediate on `tensor` — mirrors the reference's dual-mesh design
# (experts on (ep, ep_shard); moe/parallelizer.py:159-277) as pure annotation.
MOE_SHARDING_RULES: list[tuple[str, tuple]] = [
    (r"router/weight$", (None, None)),
    (r"router/(bias|linear_bias)$", (None,)),
    (r"experts/gate_up$", ("expert", "expert_fsdp", "tensor")),
    (r"experts/down$", ("expert", "tensor", "expert_fsdp")),
    (r"experts/gate_up_bias$", ("expert", "tensor")),
    (r"experts/down_bias$", ("expert", None)),
    (r"shared/(gate|up)_proj/kernel$", ("fsdp", "tensor")),
    (r"shared/down_proj/kernel$", ("tensor", "fsdp")),
    (r"shared_gate/kernel$", (None, None)),
]
