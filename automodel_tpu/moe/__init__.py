from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.gate import (
    GateOutput,
    fake_balanced_gate,
    gate,
    update_gate_bias,
)
from automodel_tpu.moe.experts import EXPERT_BACKENDS
from automodel_tpu.moe.layer import MOE_SHARDING_RULES, MoEAux, init_moe_params, moe_block

__all__ = [
    "MoEConfig",
    "GateOutput",
    "gate",
    "fake_balanced_gate",
    "update_gate_bias",
    "EXPERT_BACKENDS",
    "MOE_SHARDING_RULES",
    "MoEAux",
    "init_moe_params",
    "moe_block",
]
