"""Preference optimization (DPO / ORPO) recipe.

A thin subclass of the finetune recipe: the preference loss replaces the
CE loss at the ``_make_train_step`` seam, the preference collator replaces
the default collator at the ``_build_dataloader`` seam, and EVERYTHING
else — checkpointing, telemetry, anomaly flags, non-finite policy, goodput
ledger, prefetch pipeline — is inherited unchanged.

DPO (Rafailov et al. 2023): the frozen reference policy is a COPY of the
initial params passed to the jitted step as the ``bound`` argument (the
LoRA-base pattern — a closure over a device tree would bake it into every
lowering as a constant), so one forward per side per policy:

    margin = β·((logπ_c − logπref_c) − (logπ_r − logπref_r))
    loss   = −[(1−ls)·logσ(margin) + ls·logσ(−margin)]

ORPO (Hong et al. 2024): reference-free — CE on the chosen response plus a
β-weighted odds-ratio penalty over length-normalized likelihoods; no bound
tree, half the memory.

The loss returns n = PAIR count (not tokens): build_train_step's global
normalization then turns the summed pair losses into the mean per-pair
loss, exactly as it turns summed token losses into mean token loss.

YAML over train_ft: the dataset yields preference examples
(chosen_/rejected_ input_ids+labels — data/chat.py PreferenceDataset, or
any dataset emitting those keys), plus:

  posttrain: {algo: dpo|orpo, beta: 0.1, label_smoothing: 0.0}
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.data.collators import IGNORE_INDEX, preference_collater
from automodel_tpu.data.loader import DataLoader
from automodel_tpu.posttrain.config import PosttrainConfig
from automodel_tpu.recipes.train_ft import (
    TrainFinetuneRecipeForNextTokenPrediction,
)
from automodel_tpu.training.train_step import build_eval_step

logger = logging.getLogger(__name__)


def sequence_logprobs(model, params, mb, side, constrain):
    """Per-side forward → (summed response logprob [B], token count [B]).

    Labels follow the collator convention (already shifted, IGNORE_INDEX
    off-response), so the label mask IS the response mask."""
    ids = mb[f"{side}_input_ids"]
    labels = mb[f"{side}_labels"]
    kw = {}
    pos = mb.get(f"{side}_position_ids")
    if pos is not None:
        kw["position_ids"] = pos
    out = model(params, ids, constrain=constrain, **kw)
    logits = out[0] if isinstance(out, tuple) else out
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    mask = labels != IGNORE_INDEX
    safe = jnp.where(mask, labels, 0)
    tok_lp = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    tok_lp = jnp.where(mask, tok_lp, 0.0)
    return tok_lp.sum(axis=-1), mask.sum(axis=-1)


def _log1mexp(x):
    """log(1 − eˣ) for x < 0, stable near 0 (clamped: a response with
    mean logprob ≈ 0 would otherwise produce −inf odds)."""
    x = jnp.minimum(x, -1e-6)
    return jnp.log(-jnp.expm1(x))


def make_preference_loss(model, constrain, algo, beta, label_smoothing):
    """Build the (params, mb[, ref]) → (loss_sum, n_pairs, extras) loss.

    extras carry pair-summed auxiliaries; ``metric_extras`` (consumed
    in-jit by build_train_step) renormalizes them by the PAIR count — the
    same denominator the loss uses — into ``dpo_loss`` and
    ``accept_margin`` (docs/observability.md)."""
    ls = float(label_smoothing)

    def dpo_loss(params, mb, ref):
        pi_c, _ = sequence_logprobs(model, params, mb, "chosen", constrain)
        pi_r, _ = sequence_logprobs(model, params, mb, "rejected", constrain)
        ref_c, _ = sequence_logprobs(model, ref, mb, "chosen", constrain)
        ref_r, _ = sequence_logprobs(model, ref, mb, "rejected", constrain)
        margin = beta * ((pi_c - ref_c) - (pi_r - ref_r))
        pair_loss = -(
            (1.0 - ls) * jax.nn.log_sigmoid(margin)
            + ls * jax.nn.log_sigmoid(-margin)
        )
        n = jnp.int32(margin.shape[0])
        extras = {
            "dpo_loss_sum": pair_loss.sum(),
            "margin_sum": margin.sum(),
            "pairs": jnp.float32(margin.shape[0]),
        }
        return pair_loss.sum(), n, extras

    def orpo_loss(params, mb):
        pi_c, n_c = sequence_logprobs(model, params, mb, "chosen", constrain)
        pi_r, n_r = sequence_logprobs(model, params, mb, "rejected", constrain)
        # length-normalized (mean per-token) logprobs → odds ratio
        mean_c = pi_c / jnp.maximum(n_c, 1)
        mean_r = pi_r / jnp.maximum(n_r, 1)
        odds_c = mean_c - _log1mexp(mean_c)
        odds_r = mean_r - _log1mexp(mean_r)
        margin = odds_c - odds_r
        # NLL on the chosen response (per-token mean keeps the two terms on
        # comparable scales regardless of response length) + OR penalty
        pair_loss = -mean_c - beta * jax.nn.log_sigmoid(margin)
        n = jnp.int32(margin.shape[0])
        extras = {
            "dpo_loss_sum": pair_loss.sum(),
            "margin_sum": margin.sum(),
            "pairs": jnp.float32(margin.shape[0]),
        }
        return pair_loss.sum(), n, extras

    loss_fn = dpo_loss if algo == "dpo" else orpo_loss

    def metric_extras(extras_sum, denom):
        pairs = jnp.maximum(extras_sum["pairs"], 1.0)
        return {
            "dpo_loss": extras_sum["dpo_loss_sum"] / pairs,
            "accept_margin": extras_sum["margin_sum"] / pairs,
        }

    loss_fn.metric_extras = metric_extras
    return loss_fn


class TrainPreferenceRecipe(TrainFinetuneRecipeForNextTokenPrediction):
    """DPO/ORPO over preference-pair batches."""

    def setup(self) -> None:
        super().setup()
        cfg = self.cfg
        self.pt_cfg = PosttrainConfig.from_dict(dict(cfg.get("posttrain") or {}))
        if self.pt_cfg.algo not in ("dpo", "orpo"):
            raise ValueError(
                f"posttrain.algo={self.pt_cfg.algo!r}: this recipe runs "
                "dpo|orpo (grpo has its own recipe — `automodel grpo`)"
            )
        if self.peft_config is not None:
            raise ValueError(
                "posttrain + peft is not supported yet: the DPO reference "
                "tree and the LoRA base tree would both ride the single "
                "`bound` argument of the jitted step"
            )
        self.loss_fn = make_preference_loss(
            self.model,
            self.auto.constrain,
            self.pt_cfg.algo,
            self.pt_cfg.beta,
            self.pt_cfg.label_smoothing,
        )
        if self.pt_cfg.algo == "dpo":
            # frozen reference = the pre-posttraining policy. A DEEP copy:
            # build_train_step donates state.params, and at step 1 those
            # are the very buffers self.auto.params still points at — an
            # aliased reference tree would be invalidated by the first
            # optimizer step.
            self.loss_fn.bound_params = jax.tree.map(
                jnp.copy, self.auto.params
            )
        self.train_step = self._make_train_step(self.loss_fn)
        self.eval_step = build_eval_step(self.loss_fn)
        logger.info(
            "%s: beta=%.3f label_smoothing=%.2f",
            self.pt_cfg.algo.upper(), self.pt_cfg.beta,
            self.pt_cfg.label_smoothing,
        )

    def _build_dataloader(self, dataset_cfg, dl_cfg) -> DataLoader:
        loader = super()._build_dataloader(dataset_cfg, dl_cfg)
        # pair collation (chosen_/rejected_ keys, one shared pad length so
        # the two per-side forwards share a jit shape); called from
        # super().setup(), so the override is live from the first batch
        loader.collate_fn = preference_collater
        return loader


def main(cfg: ConfigNode) -> dict:
    recipe = TrainPreferenceRecipe(cfg)
    recipe.setup()
    return recipe.run_train_validation_loop()
