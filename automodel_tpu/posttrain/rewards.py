"""Reward functions for GRPO.

A reward fn is any callable ``(prompt_ids, completion_ids, **kwargs) ->
float``; ``resolve_reward_fn`` turns a ``reward:`` config section into one
— bare names resolve against this module, dotted paths import. Rewards run
host-side between rollouts and the optimizer step (the ``reward`` goodput
segment), so they may be arbitrary Python — string matching, a verifier,
an RPC to a judge.
"""

from __future__ import annotations

import importlib
from typing import Callable, Sequence

from automodel_tpu.posttrain.config import RewardConfig

RewardFn = Callable[..., float]


def target_token_frequency(
    prompt_ids: Sequence[int],
    completion_ids: Sequence[int],
    token_id: int = 0,
) -> float:
    """Toy reward: fraction of completion tokens equal to ``token_id``.

    The e2e-testable objective — a policy that learns anything at all
    learns to emit ``token_id``, so reward_mean rising is a direct
    learning signal with no model-quality confounders."""
    if not completion_ids:
        return 0.0
    return sum(1 for t in completion_ids if int(t) == int(token_id)) / len(
        completion_ids
    )


def completion_length(
    prompt_ids: Sequence[int],
    completion_ids: Sequence[int],
    target_len: int = 8,
) -> float:
    """Toy reward: negative distance from a target completion length."""
    return -abs(len(completion_ids) - int(target_len))


def resolve_reward_fn(cfg: RewardConfig) -> RewardFn:
    """``reward:`` section → bound callable. Bare names resolve here;
    dotted paths import (``mypkg.rewards.judge``). kwargs are bound."""
    name = cfg.fn
    if "." in name:
        mod_name, _, attr = name.rpartition(".")
        try:
            fn = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            raise ValueError(f"reward.fn={name!r} failed to import: {e}")
    else:
        fn = globals().get(name)
        if fn is None or not callable(fn):
            builtin = sorted(
                k for k, v in globals().items()
                if callable(v) and not k.startswith("_")
                and k not in ("resolve_reward_fn",)
            )
            raise ValueError(
                f"reward.fn={name!r} is not a built-in reward "
                f"(available: {builtin}) and is not a dotted path"
            )
    kwargs = dict(cfg.kwargs or {})
    if not kwargs:
        return fn

    def bound(prompt_ids, completion_ids, **extra):
        return fn(prompt_ids, completion_ids, **{**kwargs, **extra})

    bound.__name__ = getattr(fn, "__name__", name)
    return bound
