"""Strict config sections for the post-training subsystem.

Same unknown-key discipline as the serving sections (engine._cfg_dict):
a typo'd key raises TypeError at construction, and the example-YAML
walker (tests/test_examples_yaml.py) pins that behavior for the
``posttrain:`` / ``rollout:`` / ``reward:`` sections.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

_ALGOS = ("dpo", "orpo", "grpo")


def _strict(cls, d: Optional[dict], section: str):
    d = dict(d or {})
    d.pop("_target_", None)
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - known
    if unknown:
        raise TypeError(f"unknown {section} keys: {sorted(unknown)}")
    return cls(**d)


@dataclasses.dataclass(frozen=True)
class PosttrainConfig:
    """The ``posttrain:`` YAML section — algorithm + objective knobs."""

    algo: str = "dpo"  # dpo | orpo | grpo
    # DPO/ORPO: preference-margin scale (β); ORPO: odds-ratio penalty weight
    beta: float = 0.1
    # DPO: mass given to the flipped pair (conservative labels)
    label_smoothing: float = 0.0
    # GRPO: PPO-style ratio clip half-width
    clip_eps: float = 0.2
    # GRPO: weight of the KL-to-reference penalty
    kl_coef: float = 0.05
    # GRPO: hot-swap the rollout engine onto the current policy every N
    # optimizer steps (1 = fully on-policy)
    sync_weights_every_steps: int = 1

    def __post_init__(self):
        if self.algo not in _ALGOS:
            raise ValueError(
                f"posttrain.algo={self.algo!r} (want one of {_ALGOS})"
            )
        if self.beta <= 0:
            raise ValueError(f"posttrain.beta={self.beta} must be > 0")
        if not (0.0 <= self.label_smoothing < 0.5):
            raise ValueError(
                f"posttrain.label_smoothing={self.label_smoothing} "
                "(want 0 <= ls < 0.5)"
            )
        if self.clip_eps <= 0:
            raise ValueError(f"posttrain.clip_eps={self.clip_eps}")
        if self.kl_coef < 0:
            raise ValueError(f"posttrain.kl_coef={self.kl_coef}")
        if self.sync_weights_every_steps < 1:
            raise ValueError(
                "posttrain.sync_weights_every_steps="
                f"{self.sync_weights_every_steps} must be >= 1"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PosttrainConfig":
        return _strict(cls, d, "posttrain")


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    """The ``rollout:`` YAML section — how GRPO generates completions.

    ``engine: in_process`` builds a ``ServingEngine`` inside the trainer
    process over (a hot-swapped copy of) the current policy; ``engine:
    fleet`` POSTs to a running fleet router (``router_url``) whose replicas
    are kept current by the router's rolling update."""

    group_size: int = 4  # G completions per prompt
    max_new_tokens: int = 16
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    engine: str = "in_process"  # in_process | fleet
    router_url: Optional[str] = None
    timeout_s: float = 120.0  # per-request budget on the fleet path
    # overrides for the in-process ServingEngine's serving section
    # (slots/block_size/num_blocks/...), validated by ServeConfig itself
    serving: Optional[dict] = None

    def __post_init__(self):
        if self.group_size < 2:
            # a 1-completion group has zero-variance advantages — the
            # group-relative baseline needs at least a pair
            raise ValueError(
                f"rollout.group_size={self.group_size} must be >= 2"
            )
        if self.max_new_tokens < 1:
            raise ValueError(
                f"rollout.max_new_tokens={self.max_new_tokens}"
            )
        if self.engine not in ("in_process", "fleet"):
            raise ValueError(
                f"rollout.engine={self.engine!r} (want in_process|fleet)"
            )
        if self.engine == "fleet" and not self.router_url:
            raise ValueError(
                "rollout.engine=fleet requires rollout.router_url"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RolloutConfig":
        return _strict(cls, d, "rollout")


@dataclasses.dataclass(frozen=True)
class RewardConfig:
    """The ``reward:`` YAML section — a pluggable reward function.

    ``fn`` is a bare name resolved against ``posttrain.rewards`` or a
    dotted import path; the callable receives
    ``(prompt_ids, completion_ids, **kwargs)`` and returns a float."""

    fn: str = "target_token_frequency"
    kwargs: Any = None  # dict of keyword arguments bound onto fn

    def __post_init__(self):
        if not self.fn:
            raise ValueError("reward.fn must name a reward function")
        if self.kwargs is not None and not isinstance(self.kwargs, dict):
            raise ValueError(
                f"reward.kwargs must be a mapping, got {type(self.kwargs).__name__}"
            )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RewardConfig":
        d = dict(d or {})
        if "kwargs" in d and d["kwargs"] is not None:
            d["kwargs"] = dict(d["kwargs"])
        return _strict(cls, d, "reward")
