"""GRPO recipe: the serving stack as the rollout generator.

Each optimizer step is a closed loop (Shao et al. 2024, DeepSeekMath):

  1. hot-swap the CURRENT policy into the rollout engine
     (``engine.swap_weights`` — the same live-swap primitive the fleet's
     rolling update uses), every ``posttrain.sync_weights_every_steps``
  2. sample ``rollout.group_size`` completions per prompt from a
     ``ServingEngine`` (in-process) or a fleet router (``rollout.engine:
     fleet``), with per-token behavior logprobs (``return_logprobs``)
  3. score completions with the pluggable ``reward:`` fn, normalize
     group-relative: adv = (r − mean_group) / (std_group + ε)
  4. one PPO-style clipped update with a k3 KL penalty to the FROZEN
     initial policy, through the inherited ``_make_train_step`` seam —
     anomaly flags, the non-finite policy, and checkpointing all apply
     to the RL update exactly as they do to supervised steps.

``train_step`` here is a HOST wrapper around the inner jitted step: the
base loop keeps driving batches (of prompts), telemetry, and resilience
unchanged; the wrapper turns each prompt batch into a rollout batch.
Rollout and reward wall time are first-class goodput segments
(``rollout``/``reward``, telemetry/goodput.py) and the rollout phase is a
trace span whose children are the engine's per-request spans.

Behavior logprobs are log π under the model's RAW distribution
(generation/sampling.py sample_with_logprobs), so at sync steps the
importance ratios start at exactly 1 and the update is on-policy.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.data.collators import IGNORE_INDEX, _round_up
from automodel_tpu.posttrain.config import (
    PosttrainConfig,
    RewardConfig,
    RolloutConfig,
)
from automodel_tpu.posttrain.rewards import resolve_reward_fn
from automodel_tpu.recipes.train_ft import (
    TrainFinetuneRecipeForNextTokenPrediction,
)
from automodel_tpu.telemetry.tracing import Tracer, TracingConfig

logger = logging.getLogger(__name__)

# rollout batches pad the time axis up to this multiple: one XLA program
# per bucket instead of one per (prompt+completion) length
_SEQ_BUCKET = 16


def make_grpo_loss(model, constrain, clip_eps, kl_coef):
    """(params, mb) → (loss_sum, n_completion_tokens, extras).

    mb carries input_ids/labels/position_ids [B, S] (labels = next-token
    ids on completion positions, IGNORE_INDEX elsewhere), behavior_ and
    ref_logprobs [B, S] aligned with labels, advantages [B]. n = completion
    token count, so build_train_step's global normalization yields the
    mean per-token objective."""
    eps = float(clip_eps)
    beta = float(kl_coef)

    def loss_fn(params, mb):
        ids, labels = mb["input_ids"], mb["labels"]
        out = model(
            params, ids, constrain=constrain, position_ids=mb["position_ids"]
        )
        logits = out[0] if isinstance(out, tuple) else out
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        mask = labels != IGNORE_INDEX
        safe = jnp.where(mask, labels, 0)
        pi_lp = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        ratio = jnp.exp(pi_lp - mb["behavior_logprobs"])
        adv = mb["advantages"][:, None].astype(jnp.float32)
        clipped = jnp.clip(ratio, 1.0 - eps, 1.0 + eps)
        obj = jnp.minimum(ratio * adv, clipped * adv)
        # k3 estimator (Schulman): unbiased, guaranteed non-negative —
        # exp(Δ) − Δ − 1 with Δ = ref − π
        d = mb["ref_logprobs"] - pi_lp
        kl = jnp.exp(d) - d - 1.0
        loss_tok = -(obj - beta * kl)
        loss_sum = jnp.where(mask, loss_tok, 0.0).sum()
        n = mask.sum().astype(jnp.int32)
        extras = {"kl_sum": jnp.where(mask, kl, 0.0).sum()}
        return loss_sum, n, extras

    # in-jit (build_train_step): mean per-token KL over the SAME global
    # token denominator as the loss
    loss_fn.metric_extras = lambda ex, denom: {
        "kl_to_ref": ex["kl_sum"] / denom
    }
    return loss_fn


def _build_ref_logprob_fn(model, constrain):
    """Jitted (ref_params, ids, pos, labels) → per-token ref logprobs
    [B, S] (0 off-mask). The frozen tree is a REAL argument, not a
    closure — a captured device tree would be baked into the lowering."""

    @jax.jit
    def ref_lp(ref_params, ids, pos, labels):
        out = model(ref_params, ids, constrain=constrain, position_ids=pos)
        logits = out[0] if isinstance(out, tuple) else out
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        mask = labels != IGNORE_INDEX
        safe = jnp.where(mask, labels, 0)
        tok = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.where(mask, tok, 0.0)

    return ref_lp


def _post_json(url: str, payload: dict, timeout_s: float) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _get_json(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


class GRPORecipe(TrainFinetuneRecipeForNextTokenPrediction):
    """The dataset yields PROMPTS (plain ``input_ids`` examples); the
    wrapper turns each prompt batch into a G-way rollout batch."""

    def setup(self) -> None:
        super().setup()
        cfg = self.cfg
        self.pt_cfg = PosttrainConfig.from_dict(dict(cfg.get("posttrain") or {}))
        if self.pt_cfg.algo != "grpo":
            raise ValueError(
                f"posttrain.algo={self.pt_cfg.algo!r}: this recipe runs "
                "grpo (dpo/orpo have their own recipe — `automodel dpo`)"
            )
        if self.peft_config is not None:
            raise ValueError("posttrain + peft is not supported yet")
        self.rollout_cfg = RolloutConfig.from_dict(dict(cfg.get("rollout") or {}))
        self.reward_fn = resolve_reward_fn(
            RewardConfig.from_dict(dict(cfg.get("reward") or {}))
        )

        # frozen KL reference = the pre-RL policy. Deep copy: the inner
        # step donates state.params, which at step 1 ARE these buffers.
        self._ref_params = jax.tree.map(jnp.copy, self.auto.params)
        self.loss_fn = make_grpo_loss(
            self.model, self.auto.constrain,
            self.pt_cfg.clip_eps, self.pt_cfg.kl_coef,
        )
        self._inner_step = self._make_train_step(self.loss_fn)
        self._ref_lp_fn = _build_ref_logprob_fn(self.model, self.auto.constrain)
        # the loop drives THIS; it runs rollout+reward on the host, then
        # the inner jitted update (a bound method carries no `.trace`, so
        # cost attribution skips itself automatically)
        self.train_step = self._grpo_step
        self._opt_steps = 0

        # rollout-phase spans (+ the engine's per-request child spans) go
        # to the metrics JSONL like every other span in the system
        self.tracer = Tracer.from_config(
            TracingConfig.from_dict(dict(cfg.get("tracing") or {})),
            f"grpo-{os.getpid()}",
            lambda rec: self.metric_logger.log(rec),
        )
        if self.rollout_cfg.engine == "in_process":
            self._setup_in_process_engine()
        else:
            self._setup_fleet()
        logger.info(
            "GRPO: G=%d max_new_tokens=%d clip_eps=%.2f kl_coef=%.3f "
            "engine=%s sync_every=%d",
            self.rollout_cfg.group_size, self.rollout_cfg.max_new_tokens,
            self.pt_cfg.clip_eps, self.pt_cfg.kl_coef,
            self.rollout_cfg.engine, self.pt_cfg.sync_weights_every_steps,
        )

    # -- rollout backends ---------------------------------------------------
    def _setup_in_process_engine(self) -> None:
        from automodel_tpu.generation.engine import GenerationConfig
        from automodel_tpu.serving.engine import ServeConfig, ServingEngine

        rcfg = self.rollout_cfg
        # a SEPARATE AutoModel view with COPIED params: swap_weights
        # rebinds rollout_auto.params (must not touch the trainer's auto),
        # and the copies mean a donated trainer buffer can never be the
        # engine's serving tree
        rollout_auto = copy.copy(self.auto)
        rollout_auto.params = jax.tree.map(jnp.copy, self.auto.params)
        serve_cfg = ServeConfig.from_dict(dict(rcfg.serving or {}))
        gen_cfg = GenerationConfig(
            max_new_tokens=rcfg.max_new_tokens,
            temperature=rcfg.temperature,
            top_k=rcfg.top_k,
            top_p=rcfg.top_p,
            seed=self.cfg.get("seed", 42),
        )
        self._engine = ServingEngine(
            rollout_auto, serve_cfg, gen_cfg, tracer=self.tracer
        )

    def _setup_fleet(self) -> None:
        """Fleet mode: completions come from a running router; weight sync
        is the router's ROLLING UPDATE, with this trainer process as the
        AKV1 ``weights_fetch`` peer (the replicas pull the new tree from
        us, leaf-streamed)."""
        from automodel_tpu.serving.fleet.kv_transfer import KVTransferServer

        self._live_params = jax.tree.map(jnp.copy, self.auto.params)
        # geometry is validated only for KV handoff frames; a weights-only
        # listener never receives one
        self._kv_server = KVTransferServer(
            {
                "layers": 1, "block_size": 1, "num_kv_heads": 1,
                "head_dim": 1, "kv_cache_dtype": "bf16",
            },
            weights_handler=self._serve_weights,
        ).start()
        logger.info(
            "GRPO fleet mode: weights peer on port %d, router %s",
            self._kv_server.port, self.rollout_cfg.router_url,
        )

    def _serve_weights(self):
        from automodel_tpu.checkpoint.checkpointer import param_tree_signature
        from automodel_tpu.serving.engine import _tree_path_name

        params = self._live_params  # GIL-atomic snapshot, never mutated
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        return param_tree_signature(params), [
            (_tree_path_name(path), leaf) for path, leaf in leaves
        ]

    def _sync_weights(self, state) -> None:
        """Push the CURRENT policy into the rollout backend. Copies first:
        swap_weights/device_put on an already-placed tree aliases it, and
        the next optimizer step donates these exact buffers."""
        snapshot = jax.tree.map(jnp.copy, state.params)
        if self.rollout_cfg.engine == "in_process":
            self._engine.swap_weights(snapshot)
            return
        self._live_params = snapshot
        url = self.rollout_cfg.router_url.rstrip("/")
        _post_json(
            url + "/rolling_update",
            {
                "peer": {"host": "127.0.0.1", "port": self._kv_server.port},
                "timeout_s": self.rollout_cfg.timeout_s,
            },
            timeout_s=self.rollout_cfg.timeout_s,
        )
        # the update runs on a router background thread; rollouts must not
        # start until the fleet converges (on-policy sampling is the point)
        deadline = time.monotonic() + self.rollout_cfg.timeout_s
        while time.monotonic() < deadline:
            st = _get_json(url + "/stats", timeout_s=5.0)
            ru = st.get("rolling_update")
            if ru is not None and not ru.get("active"):
                if ru.get("failed"):
                    raise RuntimeError(
                        f"rolling update left replicas on OLD weights: "
                        f"{ru['failed']} — refusing off-policy rollouts"
                    )
                return
            time.sleep(0.05)
        raise RuntimeError(
            "fleet rolling update did not converge within "
            f"{self.rollout_cfg.timeout_s}s"
        )

    def _rollout(self, prompts: list, trace_ctx) -> list:
        """prompts → ``groups[b][g] = {"tokens", "logprobs"}``."""
        G = self.rollout_cfg.group_size
        if self.rollout_cfg.engine == "fleet":
            url = self.rollout_cfg.router_url.rstrip("/") + "/generate"

            def one(p):
                resp = _post_json(
                    url,
                    {
                        "prompt_ids": [int(t) for t in p],
                        "max_new_tokens": self.rollout_cfg.max_new_tokens,
                        "return_logprobs": True,
                    },
                    timeout_s=self.rollout_cfg.timeout_s,
                )
                if "tokens" not in resp:
                    raise RuntimeError(f"fleet rollout failed: {resp}")
                return {
                    "tokens": [int(t) for t in resp["tokens"]],
                    "logprobs": [float(x) for x in resp.get("logprobs") or []],
                }

            with ThreadPoolExecutor(max_workers=8) as pool:
                flat = list(pool.map(one, [p for p in prompts for _ in range(G)]))
            return [flat[b * G : (b + 1) * G] for b in range(len(prompts))]

        eng = self._engine
        rid_of: dict[str, tuple] = {}
        for b, p in enumerate(prompts):
            for g in range(G):
                rid = eng.submit(
                    [int(t) for t in p],
                    max_new_tokens=self.rollout_cfg.max_new_tokens,
                    return_logprobs=True,
                    trace=trace_ctx,
                )
                rid_of[rid] = (b, g)
        groups = [[None] * G for _ in prompts]
        while not eng.idle():
            for rec in eng.step():
                b, g = rid_of[rec["request_id"]]
                if rec.get("completion_reason") not in ("stop", "length"):
                    raise RuntimeError(
                        f"rollout request {rec['request_id']} failed: "
                        f"{rec.get('completion_reason')}"
                    )
                groups[b][g] = {
                    "tokens": [int(t) for t in rec["tokens"]],
                    "logprobs": [float(x) for x in rec.get("logprobs") or []],
                }
        return groups

    # -- the step -----------------------------------------------------------
    def _grpo_step(self, state, batch):
        rcfg, G = self.rollout_cfg, self.rollout_cfg.group_size
        step_no = self.step_scheduler.step
        # prompt rows out of the placed [A, B, S] batch (A folds to its
        # first microbatch — rollout batching replaces grad accumulation)
        ids = np.asarray(jax.device_get(batch["input_ids"]))[0]
        pos = np.asarray(jax.device_get(batch["position_ids"]))[0]
        lens = pos.max(axis=-1).astype(np.int64) + 1
        prompts = [ids[b, : lens[b]].tolist() for b in range(ids.shape[0])]

        if self._opt_steps % self.pt_cfg.sync_weights_every_steps == 0:
            self._sync_weights(state)

        t0 = time.perf_counter()
        span = (
            self.tracer.span(
                None, "rollout", step=step_no,
                prompts=len(prompts), group_size=G,
            )
            if self.tracer is not None
            else None
        )
        with self.ledger.segment("rollout", step=step_no):
            if span is not None:
                with span as ctx:
                    groups = self._rollout(prompts, ctx)
            else:
                groups = self._rollout(prompts, None)
        rollout_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        with self.ledger.segment("reward", step=step_no):
            rewards = np.asarray(
                [
                    [self.reward_fn(p, c["tokens"]) for c in grp]
                    for p, grp in zip(prompts, groups)
                ],
                dtype=np.float32,
            )  # [B, G]
        reward_s = time.perf_counter() - t0

        # group-relative advantages: each prompt's G completions are their
        # own baseline — no value network
        adv = (rewards - rewards.mean(axis=1, keepdims=True)) / (
            rewards.std(axis=1, keepdims=True) + 1e-6
        )

        stacked = self._build_rollout_batch(prompts, groups, adv.reshape(-1))
        state, metrics = self._inner_step(state, self._place_group(stacked))
        metrics = dict(metrics)
        metrics["reward_mean"] = float(rewards.mean())
        metrics["rollout_s"] = round(rollout_s, 6)
        metrics["reward_s"] = round(reward_s, 6)
        self._opt_steps += 1
        return state, metrics

    def _build_rollout_batch(self, prompts, groups, advantages) -> dict:
        """Flattened [B·G] rollouts → the [1, B·G, S] arrays the inner step
        consumes. Labels are the completion tokens under the shifted
        convention (labels[t] = ids[t+1] when t+1 is generated), and
        behavior_logprobs sit at the SAME positions — the logprob the
        engine reported for generated token i aligns with label position
        prompt_len + i − 1."""
        flat = [
            (p, c["tokens"], c["logprobs"])
            for p, grp in zip(prompts, groups)
            for c in grp
        ]
        B = len(flat)
        S = _round_up(
            max(len(p) + len(t) for p, t, _ in flat), _SEQ_BUCKET
        )
        input_ids = np.zeros((B, S), np.int32)
        labels = np.full((B, S), IGNORE_INDEX, np.int32)
        position_ids = np.zeros((B, S), np.int32)
        behavior = np.zeros((B, S), np.float32)
        for r, (p, toks, lps) in enumerate(flat):
            L, total = len(p), len(p) + len(toks)
            input_ids[r, :total] = np.asarray(list(p) + list(toks), np.int32)
            position_ids[r, :total] = np.arange(total)
            labels[r, L - 1 : total - 1] = input_ids[r, L:total]
            behavior[r, L - 1 : total - 1] = np.asarray(
                lps[: len(toks)], np.float32
            )
        ref = np.asarray(
            jax.device_get(
                self._ref_lp_fn(self._ref_params, input_ids, position_ids, labels)
            ),
            np.float32,
        )
        return {
            "input_ids": input_ids[None],
            "labels": labels[None],
            "position_ids": position_ids[None],
            "behavior_logprobs": behavior[None],
            "ref_logprobs": ref[None],
            "advantages": np.asarray(advantages, np.float32)[None],
        }


def main(cfg: ConfigNode) -> dict:
    recipe = GRPORecipe(cfg)
    recipe.setup()
    return recipe.run_train_validation_loop()
