"""Jitted prefill + token-at-a-time decode.

Two programs, compiled once each (Pope et al. §3.1's prefill/generate
split):

- **prefill**: the whole padded prompt batch through the model's ordinary
  packed segment-ids attention path (pads sit in segment 0, prompts in
  segment 1; right-padding + causality keeps real tokens clean), writing
  every layer's post-RoPE K/V into the cache, returning each slot's
  last-real-token logits.
- **decode**: a ``lax.while_loop`` feeding each sampled token back through
  the model with ``cache=(KVCache, CacheContext)`` — one token per slot per
  iteration, RoPE evaluated at the slot's own position offset
  (``position_ids = lengths``), stop-token handling with early exit when
  every slot is done.

Model output convention (the cache-capable families): calling with a cache
returns ``(primary, new_cache)`` where primary is ``logits`` for dense
models and ``(logits, aux)`` for MoE — ``_logits_of`` normalizes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from automodel_tpu.generation import kv_cache
from automodel_tpu.generation.sampling import SamplingConfig, sample


def _logits_of(primary: Any) -> jnp.ndarray:
    return primary[0] if isinstance(primary, tuple) else primary


def build_prefill_fn(apply: Callable) -> Callable:
    """``apply(params, input_ids, **kw)`` → jitted
    ``prefill(params, input_ids [B,S], lengths [B], cache)`` →
    ``(last_logits [B,V] fp32, cache)``."""

    def prefill(params, input_ids, lengths, cache):
        B, S = input_ids.shape
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
        )
        segment_ids = (positions < lengths[:, None]).astype(jnp.int32)
        kvc, ctx = kv_cache.prefill_ctx(cache, S, lengths)
        primary, new_cache = apply(
            params, input_ids, position_ids=positions, segment_ids=segment_ids,
            cache=(kvc, ctx),
        )
        logits = _logits_of(primary)
        last = logits[jnp.arange(B), lengths - 1].astype(jnp.float32)
        return last, new_cache

    return jax.jit(prefill)


def build_decode_fn(
    apply: Callable,
    sampling: SamplingConfig,
    max_new_tokens: int,
    eos_ids: Sequence[int] = (),
    pad_id: int = 0,
) -> Callable:
    """Jitted ``decode(params, cache, first_token [B], key)`` →
    ``(result dict, cache)``.

    ``first_token`` is the token sampled from the prefill logits (already
    counted as generated token 0); each loop iteration writes the current
    token's K/V at its slot's position and samples the next. The loop exits
    at ``max_new_tokens`` or as soon as every slot has emitted a stop token
    (``steps`` in the result shows the actual iteration count — the early
    exit is observable)."""
    eos_ids = tuple(int(e) for e in eos_ids)

    def is_eos(tok: jnp.ndarray) -> jnp.ndarray:
        if not eos_ids:
            return jnp.zeros(tok.shape, bool)
        m = tok == eos_ids[0]
        for e in eos_ids[1:]:
            m = m | (tok == e)
        return m

    def decode(params, cache, first_token, key):
        B = first_token.shape[0]
        tokens = jnp.full((B, max_new_tokens), pad_id, jnp.int32)
        tokens = tokens.at[:, 0].set(first_token)
        done0 = is_eos(first_token)

        def cond(carry):
            _, _, _, done, i, _ = carry
            return (i < max_new_tokens) & ~jnp.all(done)

        def body(carry):
            cache, tokens, cur, done, i, n_gen = carry
            kvc, ctx = kv_cache.decode_ctx(cache)
            primary, cache = apply(
                params, cur[:, None], position_ids=ctx.q_pos[:, None],
                cache=(kvc, ctx),
            )
            logits = _logits_of(primary)[:, -1].astype(jnp.float32)
            nxt = sample(logits, jax.random.fold_in(key, i), sampling)
            nxt = jnp.where(done, jnp.int32(pad_id), nxt)
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt[:, None], (jnp.int32(0), i)
            )
            n_gen = n_gen + jnp.where(done, 0, 1).astype(jnp.int32)
            done = done | is_eos(nxt)
            return (cache, tokens, nxt, done, i + 1, n_gen)

        carry = (
            cache, tokens, first_token, done0,
            jnp.int32(1), jnp.ones((B,), jnp.int32),
        )
        cache, tokens, _, done, i, n_gen = jax.lax.while_loop(
            cond, body, carry
        )
        # i starts at 1 (slot 0 holds first_token), so body iterations —
        # the observable loop-length for the early-exit contract — are i-1
        return (
            {"tokens": tokens, "n_generated": n_gen, "steps": i - 1},
            cache,
        )

    return jax.jit(decode)
