"""Mesh-sharded KV cache pytree.

Layout (Pope et al. §3.2, the contiguous-cache formulation): per-layer keys
and values stacked on a leading layer axis — ``[L, B, C, N_kv, H]`` — so the
decode step threads the cache through the SAME ``lax.scan`` over stacked
layer params the training forward uses (cache slices ride the scan as
xs/ys; compile time stays constant in depth). Keys are stored POST-RoPE, so
decode never re-rotates history.

Two layouts, one code path:

- **full**: capacity = prompt + max_new_tokens; every position owns a slot.
- **ring**: homogeneous sliding-window models (mistral-style) cap capacity
  at the window — slot = position % capacity, old tokens are overwritten
  exactly when the window would mask them anyway.

Validity is governed by per-slot **position tags** (``pos [B, C]``, -1 =
empty), not by the write itself: padded-prompt junk is written (the scatter
is dense) but tagged -1, and the attention mask derives from tags —
``tag >= 0 & tag <= q_pos & (q_pos - tag < window)`` — which makes full,
ring, and mixed-window-per-layer masking one expression.

Writes are ``dynamic_update_slice``: prefill writes the whole prompt block
at offset 0 (ring: the last-C tail, rolled into slot order), decode writes
one token per slot at its own offset (vmapped dus → per-slot scatter).

Ring caveat (documented in docs/generation.md): prompts right-padded past a
slot's true length write junk into ring slots; junk is never ATTENDED (tag
-1) but, once the ring has wrapped during PREFILL (S_padded > capacity), a
pad position p evicts real position p - C that a short slot still needed —
in the worst case (len <= S_padded - C) a slot's entire in-window history.
The engine therefore rejects ragged batches whose padded prompt wraps the
ring (equal-length batches, or ragged ones fitting the window, are exact).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """The cache pytree. ``window`` is static metadata (ring layout when it
    equals the capacity and the model's layers are homogeneously windowed);
    everything else is arrays so the whole object jits/shards cleanly."""

    k: jnp.ndarray  # [L, B, C, N_kv, H], post-RoPE
    v: jnp.ndarray  # [L, B, C, N_kv, H]
    pos: jnp.ndarray  # [B, C] int32 position tags; -1 = empty slot
    lengths: jnp.ndarray  # [B] int32 tokens committed per slot
    window: Optional[int] = dataclasses.field(
        default=None, metadata={"static": True}
    )

    @property
    def capacity(self) -> int:
        return self.k.shape[2]

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def nbytes(self) -> int:
        """Global logical cache footprint (telemetry census semantics)."""
        return int(self.k.nbytes + self.v.nbytes + self.pos.nbytes + self.lengths.nbytes)

    def replace(self, **kw) -> "KVCache":
        return dataclasses.replace(self, **kw)


def init_cache(
    num_layers: int,
    batch: int,
    capacity: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    window: Optional[int] = None,
) -> KVCache:
    """Empty cache. ``window`` (homogeneous sliding-window models) caps the
    useful capacity — callers pass ``capacity=min(window, total_len)`` to get
    the ring layout; a larger capacity still works, it just wastes HBM."""
    shape = (num_layers, batch, capacity, num_kv_heads, head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        pos=jnp.full((batch, capacity), -1, jnp.int32),
        lengths=jnp.zeros((batch,), jnp.int32),
        window=window,
    )


def usable_axes(mesh_ctx, dim: int, logical: str):
    """The mesh axes a LOGICAL axis resolves to, IF their product divides
    ``dim`` — else None (replicate). The shared drop-to-replicated rule for
    placing inference caches/pools: tiny eval batches or non-dividing KV
    heads on big meshes must degrade, not crash."""
    import numpy as np

    axes = mesh_ctx.resolve((logical,))
    names = axes[0] if len(axes) else None
    if names is None:
        return None
    names = names if isinstance(names, tuple) else (names,)
    deg = int(np.prod([mesh_ctx.mesh.shape[a] for a in names]))
    return names if deg > 0 and dim % deg == 0 else None


def place_cache(cache: KVCache, mesh_ctx) -> KVCache:
    """Shard a host-built cache onto the mesh: batch over the data axes,
    KV heads over tensor — the Pope et al. decode layout where each TP
    shard holds its own heads' cache and no cache collective ever runs.
    Axes that don't divide the cache dims are dropped (replicated) — tiny
    eval batches on big meshes must not crash generation."""
    if mesh_ctx is None:
        return cache

    b_ax = usable_axes(mesh_ctx, cache.batch, "batch")
    t_ax = usable_axes(mesh_ctx, cache.k.shape[3], "tensor")
    from jax.sharding import NamedSharding, PartitionSpec as P

    kv_s = NamedSharding(mesh_ctx.mesh, P(None, b_ax, None, t_ax, None))
    host_s = NamedSharding(mesh_ctx.mesh, P(None, None))
    return cache.replace(
        k=jax.device_put(cache.k, kv_s),
        v=jax.device_put(cache.v, kv_s),
        pos=jax.device_put(cache.pos, host_s),
        lengths=jax.device_put(cache.lengths, NamedSharding(mesh_ctx.mesh, P(None))),
    )


@dataclasses.dataclass
class CacheContext:
    """Per-forward write/attend plan, derived ONCE per model call and closed
    over by the layer scan (only the k/v slices ride the scan as xs/ys —
    tags and positions are shared by every layer).

    ``mode``: 'prefill' (attend normally over the incoming block, write it),
    'decode' (write one token per slot, attend the query over the cache),
    'chunk' (serving/: write a prompt CHUNK at each slot's own offset and
    attend the chunk's queries over the whole cache under per-query tag
    masks — the chunked-prefill path that lets a long prompt interleave
    with a running decode wave instead of stalling it), or 'paged'
    (serving/: the cache IS the block pool ``[NB, BS, Nkv, H]`` per layer —
    no gathered view exists; writes scatter token rows through the per-slot
    block tables and attention runs the fused Pallas paged kernel
    (ops/paged_attention.py) that indexes the pool in place, dequantizing
    int8 blocks on the fly).
    """

    mode: str  # "prefill" | "decode" | "chunk" | "paged"
    capacity: int
    q_pos: jnp.ndarray  # [B] decode query position / [B] prompt lengths
    pos: jnp.ndarray  # [B, C] tags AFTER this call's write
    slots: Optional[jnp.ndarray] = None  # [B] decode write slot
    prompt_len: int = 0  # static padded prompt/chunk length (prefill/chunk)
    start: Optional[jnp.ndarray] = None  # [B] chunk write offset (absolute)
    # paged mode only: per-slot block tables + precomputed write targets
    # (inactive slots already routed to scratch block 0 by paged_ctx)
    tables: Optional[jnp.ndarray] = None  # [B, NBseq] int32
    write_block: Optional[jnp.ndarray] = None  # [B, S] int32
    write_off: Optional[jnp.ndarray] = None  # [B, S] int32
    paged_interpret: bool = False  # run the Pallas kernel interpreted (CPU)

    @property
    def decode(self) -> bool:
        return self.mode == "decode"

    @property
    def attends_cache(self) -> bool:
        """True when the attention path must attend over the CACHE under the
        position-tag mask (decode, chunked prefill, paged decode/verify)
        instead of over the incoming block (ordinary whole-prompt
        prefill)."""
        return self.mode in ("decode", "chunk", "paged")

    # -- writes --------------------------------------------------------------
    def write(
        self, ck: jnp.ndarray, cv: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Write this layer's new keys/values. ck/cv: [B, C, N_kv, H];
        k/v: [B, S, N_kv, H] (S = prompt length in prefill, chunk length in
        chunk mode, 1 in decode). Paged mode: ck/cv are the layer's POOL
        slice — ``[NB, BS, N_kv, H]``, or ``(int8 values, fp32 scales)``
        when the pool is quantized — and the write scatters the S token
        rows through the block table (quantize-on-write for int8)."""
        if self.mode == "paged":
            return (
                _paged_scatter(ck, k, self.write_block, self.write_off),
                _paged_scatter(cv, v, self.write_block, self.write_off),
            )
        if self.mode == "chunk":
            # per-slot chunk write at the slot's own absolute offset (full
            # layout only: position == slot). dynamic_update_slice takes
            # traced starts, so one compiled program serves every offset.
            write = jax.vmap(
                lambda cb, nb, s: jax.lax.dynamic_update_slice(cb, nb, (s, 0, 0))
            )
            return (
                write(ck, k.astype(ck.dtype), self.start),
                write(cv, v.astype(cv.dtype), self.start),
            )
        if self.mode == "prefill":
            S, C = self.prompt_len, self.capacity
            if S <= C:
                ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, 0, 0))
            else:
                # ring: only the last C positions survive; position p lands
                # in slot p % C, which for the contiguous tail [S-C, S) is a
                # roll — a dense overwrite, no scatter
                shift = (S - C) % C
                ck = jnp.roll(k[:, S - C :].astype(ck.dtype), shift, axis=1)
                cv = jnp.roll(v[:, S - C :].astype(cv.dtype), shift, axis=1)
            return ck, cv
        # decode: one token per slot at its own offset
        write = jax.vmap(
            lambda cb, nb, s: jax.lax.dynamic_update_slice(cb, nb, (s, 0, 0))
        )
        return (
            write(ck, k.astype(ck.dtype), self.slots),
            write(cv, v.astype(cv.dtype), self.slots),
        )

    # -- attend --------------------------------------------------------------
    def attend(
        self,
        q: jnp.ndarray,
        layer_kv: tuple,
        *,
        sliding_window: Optional[int] = None,
        scale: Optional[float] = None,
        logits_soft_cap: Optional[float] = None,
    ) -> jnp.ndarray:
        """Cache-attending attention for this mode — the single dispatch
        point the model attention blocks call when ``attends_cache``.
        ``layer_kv`` is the layer's just-written cache pair from ``write``.
        Decode/chunk: ``sdpa_decode`` over the (gathered) cache under the
        position-tag mask. Paged: the fused Pallas kernel indexes the block
        pool in place through the tables (ops/paged_attention.py)."""
        if self.mode == "paged":
            from automodel_tpu.ops import paged_attention as _pa

            ck, cv = layer_kv
            kq, ks = ck if isinstance(ck, tuple) else (ck, None)
            vq, vs = cv if isinstance(cv, tuple) else (cv, None)
            return _pa.paged_attend(
                q, kq, vq, self.tables, self.q_pos, ks, vs,
                scale=scale, sliding_window=sliding_window,
                logits_soft_cap=logits_soft_cap,
                interpret=self.paged_interpret,
            )
        from automodel_tpu.ops.attention import sdpa_decode

        return sdpa_decode(
            q, layer_kv[0], layer_kv[1],
            kv_mask=self.attend_mask(sliding_window),
            scale=scale, logits_soft_cap=logits_soft_cap,
        )

    def attend_mask(self, sliding_window: Optional[int] = None) -> jnp.ndarray:
        """Valid-slot mask for cache-attending modes. Decode: ``[B, C]`` —
        which cache slots the single query may attend. Chunk: ``[B, S, C]``
        — per-QUERY validity (query s sits at absolute position start+s, so
        later chunk tokens attend earlier ones causally through the cache).
        Per-layer ``sliding_window`` (mixed full/windowed stacks) narrows
        the mask; the ring layout needs no extra handling in decode because
        eviction and window expiry coincide by construction."""
        tags = self.pos
        if self.mode == "chunk":
            q_abs = self.start[:, None] + jnp.arange(
                self.prompt_len, dtype=jnp.int32
            )[None, :]  # [B, S]
            valid = (tags >= 0)[:, None, :] & (
                tags[:, None, :] <= q_abs[:, :, None]
            )
            if sliding_window is not None:
                valid = valid & (
                    q_abs[:, :, None] - tags[:, None, :] < sliding_window
                )
            return valid
        q = self.q_pos[:, None]
        valid = (tags >= 0) & (tags <= q)
        if sliding_window is not None:
            valid = valid & (q - tags < sliding_window)
        return valid


def prefill_ctx(cache: KVCache, prompt_len: int, lengths: jnp.ndarray) -> tuple[KVCache, CacheContext]:
    """Plan the prompt write: returns the cache with tags/lengths updated
    (k/v update per layer inside the model) and the shared context."""
    C = cache.capacity
    S = int(prompt_len)
    if S <= C:
        written = jnp.arange(S, dtype=jnp.int32)  # slot j holds position j
        tags = jnp.where(
            written[None, :] < lengths[:, None], written[None, :], -1
        )
        pos = jax.lax.dynamic_update_slice(cache.pos, tags.astype(jnp.int32), (0, 0))
    else:
        # ring tail [S-C, S): slot j holds position S-C + ((j-(S-C)) % C)
        j = jnp.arange(C, dtype=jnp.int32)
        written = S - C + ((j - (S - C)) % C)
        pos = jnp.where(
            written[None, :] < lengths[:, None], written[None, :], -1
        ).astype(jnp.int32)
    new_cache = cache.replace(pos=pos, lengths=lengths.astype(jnp.int32))
    ctx = CacheContext(
        mode="prefill", capacity=C, q_pos=lengths.astype(jnp.int32),
        pos=pos, prompt_len=S,
    )
    return new_cache, ctx


def chunk_ctx(
    cache: KVCache, chunk_len: int, start: jnp.ndarray, real_len: jnp.ndarray
) -> tuple[KVCache, CacheContext]:
    """Plan a chunked-prefill call (serving/): ``chunk_len`` (static, padded)
    tokens per slot, written at absolute positions ``[start, start+real_len)``
    of a FULL-layout cache (chunking a ring layout is unsupported — the
    serving engine keeps windowed models on the full layout and lets the
    per-layer window masks narrow attention instead). Positions at or past
    ``start + real_len`` are tagged -1, so chunk padding is written but never
    attended and the next chunk overwrites it. ``start``/``real_len``: [B]
    int32 (traced — one compiled program serves every offset)."""
    C = cache.capacity
    j = jnp.arange(C, dtype=jnp.int32)
    end = (start + real_len).astype(jnp.int32)
    pos = jnp.where(j[None, :] < end[:, None], j[None, :], -1).astype(jnp.int32)
    new_cache = cache.replace(pos=pos, lengths=end)
    ctx = CacheContext(
        mode="chunk", capacity=C, q_pos=end, pos=pos,
        prompt_len=int(chunk_len), start=start.astype(jnp.int32),
    )
    return new_cache, ctx


def layer_slice(side, i: int):
    """Layer ``i`` of a cache side — a plain ``[L, ...]`` array or the
    paged-int8 ``(values, scales)`` pair (models' per-layer loop path)."""
    return jax.tree.map(lambda x: x[i], side)


def layer_range(side, start: int, stop: Optional[int] = None):
    """Layers ``[start:stop]`` of a cache side, pytree-aware (the mixed
    dense/MoE stacks scan disjoint layer ranges)."""
    return jax.tree.map(lambda x: x[start:stop], side)


def stack_layer_sides(sides: list):
    """Inverse of ``layer_slice`` over a per-layer list (pytree-aware
    ``jnp.stack``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *sides)


def concat_layer_sides(parts: list):
    """Concatenate per-range cache sides back into one ``[L, ...]`` side
    (pytree-aware ``jnp.concatenate`` — the inverse of ``layer_range``)."""
    if len(parts) == 1:
        return parts[0]
    return jax.tree.map(lambda *xs: jnp.concatenate(xs), *parts)


def _paged_scatter(side, new, blk: jnp.ndarray, off: jnp.ndarray):
    """Scatter ``new`` [B, S, Nkv, H] token rows into one layer's pool slice
    at (blk, off) [B, S] — the paged write. ``side`` is the raw pool array
    [NB, BS, Nkv, H] or, when the pool is int8, ``(values, scales)`` with
    quantize-on-write (ops/paged_attention.quantize_kv_rows)."""
    if isinstance(side, tuple):
        from automodel_tpu.ops.paged_attention import quantize_kv_rows

        vals, scales = side
        q, s = quantize_kv_rows(new)
        return (
            vals.at[blk, off].set(q),
            scales.at[blk, off].set(s.astype(scales.dtype)),
        )
    return side.at[blk, off].set(new.astype(side.dtype))


def paged_write_targets(
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    q_len: int,
    active: jnp.ndarray,
    block_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(block, offset) ``[B, S]`` for S token rows written at absolute
    positions ``lengths..lengths+S-1`` through the block tables; inactive
    slots route to scratch block 0. The ONE spelling of paged write-target
    math — both the fused path (``paged_ctx``) and the gather path's
    scatter-back (serving/paged.py) resolve targets here, so the two
    backends can never write token rows to different cells."""
    pos = lengths[:, None].astype(jnp.int32) + jnp.arange(q_len, dtype=jnp.int32)[None, :]
    idx = jnp.clip(pos // block_size, 0, tables.shape[1] - 1)
    blk = jnp.where(
        active[:, None], jnp.take_along_axis(tables, idx, axis=1), 0
    ).astype(jnp.int32)
    off = jnp.where(active[:, None], pos % block_size, 0).astype(jnp.int32)
    return blk, off


def paged_ctx(
    cache: KVCache,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    q_len: int,
    active: jnp.ndarray,
    block_size: int,
    interpret: bool = False,
) -> tuple[KVCache, CacheContext]:
    """Plan a paged decode/verify call (serving/ fused path): ``q_len``
    tokens per slot (1 for decode, spec_k+1 for the speculative verify
    forward) written at absolute positions ``[lengths, lengths + q_len)``
    straight into the BLOCK POOL through the per-slot ``tables`` —
    ``cache.k``/``cache.v`` here are the pool arrays ``[L, NB, BS, Nkv,
    H]`` (or ``(values, scales)`` pairs when int8), not a gathered view.
    Inactive slots write to scratch block 0. Validity needs no position
    tags: the kernel masks ``pos <= lengths + qi`` directly."""
    blk, off = paged_write_targets(tables, lengths, q_len, active, block_size)
    ctx = CacheContext(
        mode="paged", capacity=cache.capacity if not isinstance(cache.k, tuple) else 0,
        q_pos=lengths.astype(jnp.int32), pos=cache.pos,
        prompt_len=int(q_len), tables=tables.astype(jnp.int32),
        write_block=blk, write_off=off, paged_interpret=bool(interpret),
    )
    return cache.replace(lengths=lengths.astype(jnp.int32) + q_len), ctx


def decode_ctx(cache: KVCache) -> tuple[KVCache, CacheContext]:
    """Plan a single-token step: the new token sits at position lengths[b],
    slot lengths[b] % C; its tag is set BEFORE attention so the token
    attends to itself."""
    C = cache.capacity
    q_pos = cache.lengths
    slots = (q_pos % C).astype(jnp.int32)
    pos = jax.vmap(lambda row, s, p: row.at[s].set(p))(cache.pos, slots, q_pos)
    new_cache = cache.replace(pos=pos, lengths=cache.lengths + 1)
    ctx = CacheContext(
        mode="decode", capacity=C, q_pos=q_pos, pos=pos, slots=slots
    )
    return new_cache, ctx
