"""GenerationEngine: slot-based batched decoding over an AutoModel.

The facade ties the pieces together: ``from_pretrained``/``from_config`` →
MeshContext-sharded KV cache → jitted prefill → jitted while_loop decode →
detokenize. Each prompt owns a **slot** (a batch row) with its own length,
position offset and stop state; slots are padded to a common prompt length
(the packed segment-ids prefill masks the pads) and decode one token per
slot per step.

Also the `automodel_tpu generate` CLI entry point (``main``): YAML drives
model/mesh exactly like the training recipes, a ``generation:`` section
drives the engine, ``--prompt`` rides the ordinary dotted-override parser.
Without a tokenizer (tiny from-config models) prompts are whitespace- or
comma-separated token ids and completions print as token ids — the same
end-to-end path, minus the vocab.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.generation import kv_cache
from automodel_tpu.generation.loop import build_decode_fn, build_prefill_fn
from automodel_tpu.generation.sampling import SamplingConfig, sample
from automodel_tpu.training.rng import sampling_key

logger = logging.getLogger(__name__)


class GenerationUnsupported(ValueError):
    """The model family has no KV-cache decode path (benchmark/eval callers
    turn this into a null-with-recorded-reason leg, never a silent skip)."""


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """The `generation:` YAML section."""

    max_new_tokens: int = 64
    max_length: Optional[int] = None  # hard context cap (prompt + new)
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: bool = False
    eos_token_id: Any = None  # int | [int] | None
    pad_token_id: int = 0
    seed: int = 0
    # pad prompts up to a multiple so repeated calls reuse one compiled
    # prefill instead of retracing per prompt length
    pad_to_multiple: int = 16

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "GenerationConfig":
        d = dict(d or {})
        d.pop("_target_", None)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @property
    def eos_ids(self) -> tuple:
        e = self.eos_token_id
        if e is None:
            return ()
        return tuple(e) if isinstance(e, (list, tuple)) else (int(e),)

    @property
    def sampling(self) -> SamplingConfig:
        return SamplingConfig(
            temperature=0.0 if self.greedy else self.temperature,
            top_k=self.top_k,
            top_p=self.top_p,
        )


def _model_max_positions(mcfg: Any) -> Optional[int]:
    for attr in ("max_position_embeddings", "n_positions"):
        v = getattr(mcfg, attr, None)
        if v:
            return int(v)
    return None


def _ring_window(mcfg: Any) -> Optional[int]:
    """Ring layout is only sound when EVERY layer is windowed with the same
    window (mistral-style). Mixed stacks (qwen2 max_window_layers, gemma
    alternating) keep the full layout; per-layer masks come from the tags."""
    window = getattr(mcfg, "sliding_window", None)
    if window is None:
        return None
    if getattr(mcfg, "max_window_layers", 0):
        return None
    return int(window)


class GenerationEngine:
    """Facade over (AutoModel, GenerationConfig[, tokenizer]).

    ``generate_ids`` takes/returns token ids (always available);
    ``generate`` adds tokenizer encode/decode around it. Pass ``params``
    explicitly to decode with weights other than the AutoModel's initial
    tree (train_ft's in-training eval generation passes the live
    ``state.params``)."""

    def __init__(self, auto: Any, config: Optional[GenerationConfig] = None, tokenizer: Any = None):
        if not getattr(auto.model, "supports_kv_cache", False):
            raise GenerationUnsupported(
                f"{type(auto.model).__name__} has no KV-cache decode path; "
                "cache-capable families: llama-generic (llama/qwen2/qwen3/"
                "mistral/phi3), gpt2, qwen3_moe"
            )
        self.auto = auto
        self.model = auto.model
        self.config = config or GenerationConfig()
        self.tokenizer = tokenizer
        mcfg = self.model.config
        self._num_layers = int(mcfg.num_layers)
        self._num_kv_heads = int(mcfg.num_kv_heads)
        self._head_dim = int(mcfg.head_dim)
        self._window = _ring_window(mcfg)
        self._max_positions = _model_max_positions(mcfg)
        self._cache_dtype = self.model.backend.compute_jnp_dtype

        constrain = auto.constrain

        def apply(params, ids, **kw):
            return self.model(params, ids, constrain=constrain, **kw)

        self._prefill = build_prefill_fn(apply)
        self._decode = build_decode_fn(
            apply,
            self.config.sampling,
            self.config.max_new_tokens,
            eos_ids=self.config.eos_ids,
            pad_id=self.config.pad_token_id,
        )
        # per-host deterministic base stream; the decode loop folds the
        # step index in per token (training/rng.sampling_key)
        self._base_key = sampling_key(self.config.seed)
        # cost attribution (telemetry/profiling/): when armed, the next
        # generate_ids also records measured FLOPs/bytes of the prefill and
        # decode programs (abstract host trace; decode's while body counts
        # once = per-token cost)
        self.collect_program_costs = False
        self.program_costs: dict = {}

    # -- cache ---------------------------------------------------------------
    def _make_cache(
        self, batch: int, prompt_len: int, lengths: np.ndarray
    ) -> kv_cache.KVCache:
        total = prompt_len + self.config.max_new_tokens
        hard_cap = self.config.max_length or self._max_positions
        if hard_cap and total > hard_cap and self._window is None:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens "
                f"({self.config.max_new_tokens}) = {total} exceeds the "
                f"context limit {hard_cap}"
            )
        capacity = total if self._window is None else min(total, self._window)
        if (
            self._window is not None
            and prompt_len > capacity
            and int(lengths.min()) < prompt_len
        ):
            # ring prefill writes only the padded tail [S-C, S): a short
            # slot's pad positions would evict real in-window history (the
            # worst case loses the slot's ENTIRE window) — reject loudly
            # rather than decode garbage (kv_cache.py ring caveat)
            raise ValueError(
                f"ragged prompt batch (lengths {int(lengths.min())}..."
                f"{int(lengths.max())}, padded {prompt_len}) wraps the ring "
                f"cache (window {capacity}): short slots would lose "
                "in-window history. Use equal-length prompts or prompts "
                "that fit the window"
            )
        cache = kv_cache.init_cache(
            self._num_layers, batch, capacity,
            self._num_kv_heads, self._head_dim,
            dtype=self._cache_dtype, window=self._window,
        )
        return kv_cache.place_cache(cache, self.auto.mesh_ctx)

    # -- generation ----------------------------------------------------------
    def generate_ids(
        self, prompts: Sequence[Sequence[int]], params: Any = None
    ) -> dict:
        """prompts: per-slot token-id lists → dict with per-slot completions
        (``tokens``) and timing stats (``ttft_s``, ``decode_tps``, ...)."""
        if not prompts:
            raise ValueError("generate_ids needs at least one prompt")
        if any(len(p) == 0 for p in prompts):
            raise ValueError("empty prompt (every slot needs >= 1 token)")
        params = self.auto.params if params is None else params
        B = len(prompts)
        lengths = np.array([len(p) for p in prompts], np.int32)
        m = max(int(self.config.pad_to_multiple), 1)
        S = int(-(-int(lengths.max()) // m) * m)
        ids = np.full((B, S), self.config.pad_token_id, np.int32)
        for b, p in enumerate(prompts):
            ids[b, : len(p)] = np.asarray(p, np.int32)

        cache = self._make_cache(B, S, lengths)
        cache_bytes = cache.nbytes
        if self.collect_program_costs and "prefill" not in self.program_costs:
            self._record_cost(
                "prefill", self._prefill,
                params, jnp.asarray(ids), jnp.asarray(lengths), cache,
            )
        t0 = time.perf_counter()
        last_logits, cache = self._prefill(
            params, jnp.asarray(ids), jnp.asarray(lengths), cache
        )
        first = sample(
            last_logits, jax.random.fold_in(self._base_key, 0),
            self.config.sampling,
        )
        first = jax.block_until_ready(first)
        ttft_s = time.perf_counter() - t0

        if self.collect_program_costs and "decode" not in self.program_costs:
            self._record_cost(
                "decode", self._decode, params, cache, first, self._base_key
            )
        t1 = time.perf_counter()
        result, cache = self._decode(params, cache, first, self._base_key)
        result = jax.device_get(result)
        decode_s = time.perf_counter() - t1

        tokens = np.asarray(result["tokens"])
        n_gen = np.asarray(result["n_generated"])
        steps = int(result["steps"])
        # decode throughput counts the tokens the DECODE program produced
        # (the first token came out of prefill and is charged to ttft)
        decode_tokens = int(n_gen.sum()) - B
        completions = [tokens[b, : int(n_gen[b])].tolist() for b in range(B)]
        return {
            "tokens": completions,
            "n_generated": n_gen.tolist(),
            "gen_tokens": int(n_gen.sum()),
            "prefill_tokens": int(lengths.sum()),
            "decode_steps": steps,
            "ttft_s": ttft_s,
            "decode_s": decode_s,
            "decode_tps": decode_tokens / decode_s if decode_s > 0 else 0.0,
            "cache_bytes": cache_bytes,
        }

    def _record_cost(self, name: str, jit_fn, *args) -> None:
        from automodel_tpu.telemetry.profiling import record_program_cost

        record_program_cost(self.program_costs, name, jit_fn, *args)

    def generate(self, prompts: Sequence[str], params: Any = None) -> dict:
        """Text in, text out (requires a tokenizer). Returns the
        ``generate_ids`` dict plus ``texts``."""
        if self.tokenizer is None:
            raise ValueError(
                "generate() needs a tokenizer; use generate_ids() or "
                "configure generation.tokenizer"
            )
        encoded = [
            self.tokenizer(p, add_special_tokens=True)["input_ids"]
            if callable(self.tokenizer)
            else self.tokenizer.encode(p)
            for p in prompts
        ]
        out = self.generate_ids(encoded, params=params)
        out["texts"] = [
            self.tokenizer.decode(t, skip_special_tokens=True)
            for t in out["tokens"]
        ]
        return out


# -- CLI ----------------------------------------------------------------------


def _parse_id_prompt(p: str) -> Optional[list[int]]:
    toks = p.replace(",", " ").split()
    try:
        return [int(t) for t in toks] if toks else None
    except ValueError:
        return None


def resolve_tokenizer(tok_cfg: Any, fallback_path: Optional[str] = None) -> Any:
    """The generation.tokenizer resolution ladder, shared by the generate
    CLI and train_ft's in-training eval sampling: a ``_target_`` ConfigNode
    instantiates, a path string goes through data.tokenizer.build_tokenizer,
    otherwise ``fallback_path`` (the model checkpoint's own tokenizer) is
    tried; unresolvable → None (token-id mode), with a warning."""
    from automodel_tpu.config.loader import ConfigNode

    if isinstance(tok_cfg, ConfigNode):
        return tok_cfg.instantiate()
    from automodel_tpu.data.tokenizer import build_tokenizer

    path = tok_cfg if isinstance(tok_cfg, str) else fallback_path
    if not path:
        return None
    try:
        return build_tokenizer(path)
    except Exception as e:
        logger.warning("no tokenizer from %s (%s); token-id mode", path, e)
        return None


def build_auto_from_model_section(
    mcfg: Any, mesh_ctx: Any, seed: int = 0
) -> Any:
    """AutoModel from a ``model:``-shaped section (``pretrained_model_name_
    or_path`` or ``hf_config`` + ``backend``) on an EXISTING mesh — the
    tail of the `generate`/`serve` CLI ladder, also how the serving
    engine builds its speculative-decoding draft model
    (``serving.speculative.draft:``, same schema) onto the target's mesh."""
    from automodel_tpu import auto_model

    get = mcfg.get if hasattr(mcfg, "get") else dict(mcfg).get
    backend = dict(get("backend", {}) or {})
    if get("pretrained_model_name_or_path"):
        return auto_model.from_pretrained(
            get("pretrained_model_name_or_path"), mesh_ctx, backend
        )
    hf = get("hf_config")
    if hf is None:
        raise ValueError(
            "model section needs pretrained_model_name_or_path or hf_config"
        )
    return auto_model.from_config(
        hf.to_dict() if hasattr(hf, "to_dict") else dict(hf),
        mesh_ctx, backend, seed=seed,
    )


def build_auto_from_cfg(cfg: Any) -> Any:
    """Model + mesh from the same YAML sections the recipes use — shared by
    the `generate` and `serve` CLIs (serving/server.py)."""
    from automodel_tpu.config.loader import ConfigNode
    from automodel_tpu.parallel.mesh import MeshConfig, build_mesh

    dist = cfg.get("distributed", ConfigNode())
    degrees = {
        k: dist.get(k, -1 if k == "dp_shard" else 1)
        for k in ("dp_replicate", "dp_shard", "tp", "cp", "pp", "ep")
    }
    platform = dist.get("platform", None)
    devices = jax.devices(platform) if platform else None
    mesh_ctx = build_mesh(MeshConfig(**degrees), devices=devices)
    return build_auto_from_model_section(
        cfg.model, mesh_ctx, seed=cfg.get("seed", 0)
    )


def main(cfg: Any) -> int:
    """`automodel_tpu generate -c cfg.yaml [--prompt '...']`"""
    from automodel_tpu.loggers.log_utils import setup_logging

    setup_logging()
    auto = build_auto_from_cfg(cfg)
    mcfg = cfg.model

    gen_section = dict(cfg.get("generation", {}) or {})
    gen_config = GenerationConfig.from_dict(gen_section)
    tokenizer = resolve_tokenizer(
        gen_section.get("tokenizer"), mcfg.get("pretrained_model_name_or_path")
    )
    engine = GenerationEngine(auto, gen_config, tokenizer=tokenizer)

    prompts = cfg.get("prompt") or gen_section.get("prompts")
    prompt_ids = gen_section.get("prompt_ids")
    if prompts is None and prompt_ids is None:
        print("no prompt: pass --prompt '...' or set generation.prompts / generation.prompt_ids")
        return 2
    if isinstance(prompts, str):
        prompts = [prompts]
    prompts = list(prompts or [])

    if prompt_ids is not None:
        out = engine.generate_ids([list(map(int, p)) for p in prompt_ids])
        texts = [" ".join(map(str, t)) for t in out["tokens"]]
        shown = [" ".join(map(str, p)) for p in prompt_ids]
    elif tokenizer is not None:
        out = engine.generate(prompts)
        texts, shown = out["texts"], prompts
    else:
        ids = [_parse_id_prompt(p) for p in prompts]
        if any(i is None for i in ids):
            print(
                "no tokenizer available: prompts must be token ids "
                "(e.g. --prompt '1 2 3') or configure generation.tokenizer"
            )
            return 2
        out = engine.generate_ids(ids)
        texts = [" ".join(map(str, t)) for t in out["tokens"]]
        shown = prompts
    for p, t in zip(shown, texts):
        print(f"prompt: {p}")
        print(f"completion: {t}")
    stats = {k: out[k] for k in (
        "ttft_s", "decode_tps", "gen_tokens", "prefill_tokens",
        "decode_steps", "cache_bytes",
    )}
    print(json.dumps({"event": "generation", **stats}))
    return 0
