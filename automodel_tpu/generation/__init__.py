"""TPU-native generation subsystem.

The standard TPU-inference formulation (Pope et al., "Efficiently Scaling
Transformer Inference"): a pjit-sharded contiguous KV cache written with
``dynamic_update_slice``, ONE jitted prefill program (reusing the packed
segment-ids attention path over the whole padded prompt batch) and ONE
jitted single-token decode program (a ``lax.while_loop`` that feeds each
sampled token back through the model with its KV cache).

    kv_cache.py   mesh-sharded cache pytree: full layout + ring-buffer
                  layout for homogeneous sliding-window models
    sampling.py   greedy / temperature / top-k / top-p (threaded PRNG)
    loop.py       jitted prefill + while_loop decode with stop tokens
    engine.py     GenerationEngine facade over from_pretrained + MeshContext
                  (slot-based batched decoding) + the CLI entry point
"""

from automodel_tpu.generation.engine import GenerationConfig, GenerationEngine
from automodel_tpu.generation.kv_cache import KVCache, init_cache
from automodel_tpu.generation.sampling import SamplingConfig, sample

__all__ = [
    "GenerationConfig",
    "GenerationEngine",
    "KVCache",
    "SamplingConfig",
    "init_cache",
    "sample",
]
