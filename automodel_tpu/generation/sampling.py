"""Token sampling: greedy / temperature / top-k / top-p.

All transforms are jit-traceable with a STATIC config (the frozen dataclass
hashes), so the decode while_loop compiles one program per sampling recipe.
The PRNG is threaded explicitly: callers derive a per-host base key via
``training.rng.sampling_key`` and fold the decode step index in per token —
multi-host generation never samples identical streams, and the same
(seed, host, step) always reproduces the same token.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """temperature <= 0 means greedy (HF convention do_sample=False);
    top_k/top_p restrict the support BEFORE renormalization (HF order:
    temperature → top-k → top-p)."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None

    def __post_init__(self):
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k={self.top_k} must be >= 1")
        if self.top_p is not None and not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p={self.top_p} must be in (0, 1]")

    @property
    def greedy(self) -> bool:
        return self.temperature is None or self.temperature <= 0.0


_NEG_INF = jnp.float32(-1e30)


def _apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    k = min(k, logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of descending-prob tokens
    whose cumulative probability reaches p (the top token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    # a token is kept iff the cumulative mass BEFORE it is < p
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < p], axis=-1
    )
    # threshold logit = smallest kept logit; everything below it is cut
    kth = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < kth, _NEG_INF, logits)


def _filter_logits(logits: jnp.ndarray, config: SamplingConfig) -> jnp.ndarray:
    """The shared transform pipeline (temperature → top-k → top-p, HF
    order) over ``[..., V]`` fp32 logits. ``sample`` draws from these;
    ``speculative_verify`` needs the SAME filtered distributions for both
    target and draft so the rejection rule reproduces exactly what a
    non-speculative sampler would draw."""
    logits = logits / jnp.float32(config.temperature)
    if config.top_k is not None:
        logits = _apply_top_k(logits, config.top_k)
    if config.top_p is not None and config.top_p < 1.0:
        logits = _apply_top_p(logits, config.top_p)
    return logits


def sample(
    logits: jnp.ndarray, key: jax.Array, config: SamplingConfig
) -> jnp.ndarray:
    """logits [B, V] → token ids [B] int32."""
    logits = logits.astype(jnp.float32)
    if config.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, _filter_logits(logits, config), axis=-1
    ).astype(jnp.int32)


def sample_with_logprobs(
    logits: jnp.ndarray, key: jax.Array, config: SamplingConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """logits [B, V] → (token ids [B] int32, logprobs [B] float32).

    The logprob is ``log_softmax`` of the RAW (unfiltered, untempered)
    logits gathered at the sampled id — i.e. log π(a|s) under the model's
    full distribution, which is what importance ratios (GRPO/PPO) need and
    what a full-forward recompute reproduces exactly. Filtering/temperature
    shape WHICH token is drawn (identical stream to ``sample`` for the same
    key), not the reported probability."""
    logits = logits.astype(jnp.float32)
    ids = sample(logits, key, config)
    logp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), ids[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    return ids, logp


def speculative_verify(
    target_logits: jnp.ndarray,
    draft_logits: Optional[jnp.ndarray],
    draft_tokens: jnp.ndarray,
    key: jax.Array,
    config: SamplingConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The draft-and-verify acceptance rule (Leviathan et al., "Fast
    Inference from Transformers via Speculative Decoding", 2023).

    ``target_logits`` [B, S, V] are the ONE batched verify forward's
    outputs over the S = k+1 fed tokens ``[cur, d_1..d_k]`` (row i is the
    target's distribution for the token FOLLOWING fed token i);
    ``draft_tokens`` [B, k] are the draft's proposals, ``draft_logits``
    [B, k, V] the distributions it drew them from (ignored under greedy).

    → ``(tokens [B, S] int32, n_commit [B] int32 in 1..S)``: commit
    ``tokens[:, :n]`` — the accepted draft prefix plus one
    correction/bonus token. Greedy is the exact-match degenerate case:
    accept while ``d_i == argmax(target_i)``, corrections are the target
    argmax — committed tokens are bit-identical to the non-speculative
    greedy stream, which is the exactness guarantee the parity tests pin.
    Sampled mode implements the standard rejection rule (accept d_i w.p.
    ``min(1, p_i(d_i)/q_i(d_i))``, resample rejections from
    ``norm(max(p-q, 0))``, bonus from ``p_k``), which preserves the target
    distribution exactly in expectation."""
    B, S, V = target_logits.shape
    k = S - 1
    target_logits = target_logits.astype(jnp.float32)
    if config.greedy:
        t = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # [B, S]
        match = draft_tokens == t[:, :k]
        n_acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
        # accepted drafts ARE the target argmaxes, so the committed stream
        # is just the target row — prefix length n_acc + 1
        return t, (n_acc + 1).astype(jnp.int32)
    p = jax.nn.softmax(_filter_logits(target_logits, config), axis=-1)
    q = jax.nn.softmax(
        _filter_logits(draft_logits.astype(jnp.float32), config), axis=-1
    )
    p_d = jnp.take_along_axis(p[:, :k], draft_tokens[..., None], axis=-1)[..., 0]
    q_d = jnp.take_along_axis(q, draft_tokens[..., None], axis=-1)[..., 0]
    key_u, key_c = jax.random.split(key)
    u = jax.random.uniform(key_u, (B, k))
    accept = u < p_d / jnp.maximum(q_d, 1e-30)
    n_acc = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)
    # correction distribution per draft position (residual), bonus at S-1
    resid = jnp.maximum(p[:, :k] - q, 0.0)
    rsum = resid.sum(axis=-1, keepdims=True)
    resid = jnp.where(rsum > 0, resid / jnp.maximum(rsum, 1e-30), p[:, :k])
    corr_probs = jnp.concatenate([resid, p[:, k:]], axis=1)  # [B, S, V]
    c = jax.random.categorical(
        key_c, jnp.log(jnp.maximum(corr_probs, 1e-30)), axis=-1
    ).astype(jnp.int32)
    drafts_pad = jnp.concatenate(
        [draft_tokens.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    idx = jnp.arange(S, dtype=jnp.int32)[None, :]
    tokens = jnp.where(idx < n_acc[:, None], drafts_pad, c)
    return tokens, (n_acc + 1).astype(jnp.int32)
