"""Token sampling: greedy / temperature / top-k / top-p.

All transforms are jit-traceable with a STATIC config (the frozen dataclass
hashes), so the decode while_loop compiles one program per sampling recipe.
The PRNG is threaded explicitly: callers derive a per-host base key via
``training.rng.sampling_key`` and fold the decode step index in per token —
multi-host generation never samples identical streams, and the same
(seed, host, step) always reproduces the same token.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """temperature <= 0 means greedy (HF convention do_sample=False);
    top_k/top_p restrict the support BEFORE renormalization (HF order:
    temperature → top-k → top-p)."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None

    def __post_init__(self):
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k={self.top_k} must be >= 1")
        if self.top_p is not None and not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p={self.top_p} must be in (0, 1]")

    @property
    def greedy(self) -> bool:
        return self.temperature is None or self.temperature <= 0.0


_NEG_INF = jnp.float32(-1e30)


def _apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    k = min(k, logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of descending-prob tokens
    whose cumulative probability reaches p (the top token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    # a token is kept iff the cumulative mass BEFORE it is < p
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < p], axis=-1
    )
    # threshold logit = smallest kept logit; everything below it is cut
    kth = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < kth, _NEG_INF, logits)


def sample(
    logits: jnp.ndarray, key: jax.Array, config: SamplingConfig
) -> jnp.ndarray:
    """logits [B, V] → token ids [B] int32."""
    logits = logits.astype(jnp.float32)
    if config.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.float32(config.temperature)
    if config.top_k is not None:
        logits = _apply_top_k(logits, config.top_k)
    if config.top_p is not None and config.top_p < 1.0:
        logits = _apply_top_p(logits, config.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
