"""CLI entry point.

Parity: the reference `automodel` CLI (_cli/app.py:202-245):
``automodel <command> <domain> -c cfg.yaml [--dotted.overrides]``. On TPU
there is no torchrun spawn — single-controller JAX runs the recipe in-process
(multi-host via `jax.distributed.initialize` when coordinator env vars are
present). Slurm/k8s submission lives in automodel_tpu.launcher.
"""

from __future__ import annotations

import sys

from automodel_tpu.config.arg_parser import parse_args_and_load_config

COMMANDS = ("finetune", "pretrain", "kd", "dpo", "grpo", "benchmark", "mine")
DOMAINS = ("llm", "vlm", "biencoder")


def _usage() -> str:
    return (
        "usage: automodel_tpu <finetune|pretrain|kd|dpo|grpo|benchmark|mine> <llm|vlm|biencoder> "
        "-c config.yaml [--dotted.key=value ...]\n"
        "       automodel_tpu dpo llm -c config.yaml   (preference optimization — DPO/ORPO over chosen/rejected pairs; posttrain: section)\n"
        "       automodel_tpu grpo llm -c config.yaml  (RL post-training — serving-engine rollouts, pluggable reward:, group-relative advantages, live weight hot-swap)\n"
        "       automodel_tpu generate -c config.yaml [--prompt '...'] [--dotted.key=value ...]\n"
        "       automodel_tpu serve -c config.yaml [--dotted.key=value ...]  (stdin-JSONL; serving.http.port for HTTP; GET /metrics /healthz /readyz; SIGTERM drains gracefully)\n"
        "       automodel_tpu route -c config.yaml [--dotted.key=value ...]  (fleet router over N serve replicas: fleet.replicas/fleet.dns; prefix-affinity + retry; same HTTP front contract; slo: section arms burn-rate alerting)\n"
        "       automodel_tpu fleet-status [-c config.yaml] [--router URL] [--watch] [--json]  (live per-replica health table: role/ready/queue/occupancy/hit-rate/accept-rate/firing SLOs, from the router's federated state or direct replica probes)\n"
        "       automodel_tpu profile -c config.yaml [--profiling.mode=train|generate] [--dotted.key=value ...]\n"
        "       automodel_tpu report <train_metrics.jsonl> [--strict]\n"
        "       automodel_tpu goodput <run-dir | goodput.jsonl> [--json]  (wall-clock decomposition of a training run across restart attempts; joins flight-recorder hang/desync evidence)\n"
        "       automodel_tpu trace <metrics.jsonl> [...] [--chrome out.json] [--md out.md] [--trace-id PREFIX]  (join multi-process span JSONLs into per-request waterfalls)\n"
        "       automodel_tpu verify-ckpt <ckpt_dir> [--no-checksums] [--json]"
    )


def _crash_is_preemption_collateral(cfg) -> bool:
    """Multi-host requeue wiring (resilience/preemption.py): when ONE host
    of a multi-host job is preempted it exits the requeue code, but its
    peers die of broken collectives with ordinary exceptions. The preempted
    host drops a marker into the shared checkpoint root at SIGTERM time; a
    crash here while that marker is FRESH is preemption collateral and must
    requeue too, or the launcher burns its backoff budget on spot churn."""
    from automodel_tpu.checkpoint.checkpointer import CheckpointingConfig
    from automodel_tpu.resilience import peer_preemption_fresh

    ccfg = dict(cfg.get("checkpoint", {}) or {})
    if not ccfg.get("enabled", False):
        return False
    # default from the dataclass, not a re-typed literal: the trainer writes
    # the marker into CheckpointingConfig.checkpoint_dir, and the two paths
    # must never drift apart
    return peer_preemption_fresh(
        ccfg.get("checkpoint_dir", CheckpointingConfig.checkpoint_dir)
    )


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `report` takes a JSONL path, not a domain: validate + summarize a
    # metrics file (telemetry/report.py — same linter bench.py uses)
    if argv and argv[0] == "report":
        from automodel_tpu.telemetry.report import main as report_main

        return report_main(argv[1:])
    # `goodput` rolls a run dir's goodput.jsonl into a per-attempt +
    # whole-run wall-clock decomposition (telemetry/goodput.py) — no
    # config, no device runtime
    if argv and argv[0] == "goodput":
        from automodel_tpu.telemetry.goodput import main as goodput_main

        return goodput_main(argv[1:])
    # `trace` assembles span records from N per-process metrics JSONLs into
    # per-request waterfalls (markdown + Chrome-trace JSON) —
    # telemetry/tracing.py. No config, no device runtime.
    if argv and argv[0] == "trace":
        from automodel_tpu.telemetry.tracing import main as trace_main

        return trace_main(argv[1:])
    # `verify-ckpt` audits a checkpoint tree's manifests (integrity + layout
    # markers) without loading arrays — checkpoint/verify.py
    if argv and argv[0] == "verify-ckpt":
        from automodel_tpu.checkpoint.verify import main as verify_main

        return verify_main(argv[1:])
    # `generate` runs the inference engine (generation/engine.py): model +
    # mesh from the same YAML sections the recipes use, a `generation:`
    # section for sampling/lengths, `--prompt` via the dotted overrides
    if argv and argv[0] == "generate":
        from automodel_tpu.generation.engine import main as generate_main
        from automodel_tpu.parallel.mesh import initialize_distributed

        cfg = parse_args_and_load_config(argv[1:])
        initialize_distributed()
        return generate_main(cfg)
    # `serve` runs the continuous-batching serving engine (serving/):
    # stdin-JSONL by default, a local HTTP front when serving.http.port is
    # set; model/mesh from the same YAML sections as `generate`
    if argv and argv[0] == "serve":
        from automodel_tpu.parallel.mesh import initialize_distributed
        from automodel_tpu.serving.server import main as serve_main

        cfg = parse_args_and_load_config(argv[1:])
        initialize_distributed()
        return serve_main(cfg)
    # `route` runs the fleet router (serving/fleet/router.py): spreads
    # requests over N `serve` replicas with prefix-affinity placement,
    # disaggregated prefill/decode, and failure-aware retry. No model is
    # built and no device runtime initializes — a router needs no chip.
    if argv and argv[0] == "route":
        from automodel_tpu.serving.fleet.router import main as route_main

        cfg = parse_args_and_load_config(argv[1:])
        return route_main(cfg)
    # `fleet-status` renders the live per-replica health table (role,
    # readiness, queue depth, occupancy, hit/accept rates, firing SLOs)
    # from the router's federated /stats — or probes replicas directly
    # when no router runs. Plain argparse, no config machinery, no jax.
    if argv and argv[0] == "fleet-status":
        from automodel_tpu.serving.fleet.status import main as status_main

        return status_main(argv[1:])
    # `profile` opens a jax.profiler trace window around N steps of the
    # configured workload and GENERATES the PROFILE artifacts (structured
    # report.json + PROFILE.md) — telemetry/profiling/runner.py
    if argv and argv[0] == "profile":
        from automodel_tpu.parallel.mesh import initialize_distributed
        from automodel_tpu.telemetry.profiling.runner import main as profile_main

        cfg = parse_args_and_load_config(argv[1:])
        initialize_distributed()
        return profile_main(cfg)
    if len(argv) < 2 or argv[0] in ("-h", "--help"):
        print(_usage())
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    command, domain = argv[0], argv[1]
    if command not in COMMANDS:
        print(f"Unknown command {command!r}. {_usage()}")
        return 2
    if domain not in DOMAINS:
        print(f"Unknown domain {domain!r}. {_usage()}")
        return 2
    cfg = parse_args_and_load_config(argv[2:])

    # a `slurm:`/`k8s:` section outside the corresponding cluster submits
    # instead of running (reference: _cli/app.py:125-199 Slurm; its k8s path
    # is a stub at :333 — see launcher/k8s.py)
    import os

    def _launch_section(key: str, in_cluster_env: str, submit_fn):
        if cfg.get(key) is None or in_cluster_env in os.environ:
            return None
        section = dict(cfg.get(key) or {})
        section.pop("_target_", None)
        cfg_path = next(
            (argv[2:][i + 1] for i, a in enumerate(argv[2:]) if a in ("-c", "--config")),
            None,
        )
        return submit_fn(section, cfg_path)

    def _slurm(section, cfg_path):
        from automodel_tpu.launcher.slurm import SlurmConfig, submit

        return submit(SlurmConfig(**section), command, domain, cfg_path)

    def _k8s(section, cfg_path):
        from automodel_tpu.launcher.k8s import K8sConfig, submit

        apply = section.pop("apply", True)
        return submit(K8sConfig(**section), command, domain, cfg_path, apply=apply)

    for key, env, fn in (("slurm", "SLURM_JOB_ID", _slurm), ("k8s", "KUBERNETES_SERVICE_HOST", _k8s)):
        submitted = _launch_section(key, env, fn)
        if submitted is not None:
            print(f"submitted {submitted}")
            return 0

    from automodel_tpu.parallel.mesh import initialize_distributed

    initialize_distributed()

    recipe_modules = {
        ("finetune", "llm"): "automodel_tpu.recipes.train_ft",
        ("pretrain", "llm"): "automodel_tpu.recipes.train_ft",
        ("benchmark", "llm"): "automodel_tpu.recipes.benchmark",
        ("kd", "llm"): "automodel_tpu.recipes.kd",
        ("dpo", "llm"): "automodel_tpu.posttrain.dpo",
        ("grpo", "llm"): "automodel_tpu.posttrain.grpo",
        ("finetune", "vlm"): "automodel_tpu.recipes.finetune_vlm",
        ("finetune", "biencoder"): "automodel_tpu.recipes.train_biencoder",
        ("mine", "biencoder"): "automodel_tpu.recipes.mine_hard_negatives",
    }
    module_name = recipe_modules.get((command, domain))
    if module_name is not None:
        import importlib

        try:
            module = importlib.import_module(module_name)
        except ModuleNotFoundError as e:
            if e.name != module_name:
                raise
            module = None
        if module is not None:
            from automodel_tpu.resilience import REQUEUE_EXIT_CODE, TrainingPreempted

            from automodel_tpu.resilience import DesyncError

            try:
                module.main(cfg)
            except DesyncError as e:
                # a desynced host is a REAL fault (bad code rev, data-order
                # bug, SDC) — never excused as preemption collateral, never
                # requeued into the same desync: fail loudly naming the host
                print(f"DESYNC: {e}", file=sys.stderr)
                return 1
            except TrainingPreempted as e:
                print(f"preempted: {e}", file=sys.stderr)
                if e.checkpoint_dir is None:
                    # nothing committed to resume from: requeueing would loop
                    # at zero progress forever — fail loudly instead so the
                    # launcher/operator sees a real failure
                    return 1
                # the emergency checkpoint is committed; exit with the
                # requeue code the launchers translate into a restart
                return REQUEUE_EXIT_CODE
            except Exception as e:
                if _crash_is_preemption_collateral(cfg):
                    print(
                        "crash while a peer host's preemption marker is "
                        f"fresh — requeueing as preemption collateral: {e!r}",
                        file=sys.stderr,
                    )
                    return REQUEUE_EXIT_CODE
                raise
            return 0
    print(f"{command} {domain} is not implemented yet")
    return 3


if __name__ == "__main__":
    raise SystemExit(main())
