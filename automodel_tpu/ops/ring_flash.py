"""Blockwise (flash) attention kernels for the ring/context-parallel path.

Parity: the reference runs TE fused attention inside its CP ring
(`cp_comm_type="p2p"`, components/moe/parallelizer.py:279-297) so ring steps
never materialize S² logits. Here: three Pallas kernels implementing the
standard flash decomposition — forward returning (normalized out, logsumexp),
and the dq / dkv backward passes that recompute probabilities from the saved
logsumexp. `parallel.cp` calls them once per ring step and merges the
per-step (out, lse) pairs with the online-softmax rule; the backward rides
dk/dv around the ring with their kv blocks.

Masking is positional: callers pass the GLOBAL position of every local row
(`q_pos`) / key (`kv_pos`), so one kernel serves the contiguous and zigzag
ring layouts, sliding windows, and non-causal attention; packed-sequence
segment ids compose on top. All accumulation is fp32.

Mosaic constraints shape the layouts: every in-kernel value is ≥2-D (1-D
bool/int reshapes don't lower), so q-aligned vectors ride as [.., S, 1]
blocks and kv-aligned ones as [.., 1, S], and size-1 block dims sit on
size-1 array dims (the tiling exemption).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from automodel_tpu.utils.compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _mask_tile(qp, kp, sq, sk, *, causal, window):
    """[bq, bkv] bool from [bq,1] q-side and [1,bkv] kv-side tiles."""
    m = sq == sk
    if causal:
        m = m & (qp >= kp)
    if window is not None:
        m = m & (qp - kp < window)
    return m


def _tile_alive(qp, kp, *, causal, window):
    """Scalar: does any (q, kv) pair in this tile pass the position mask?
    Position bounds only — segment masking rarely kills whole tiles. Lets
    @pl.when skip the matmuls on dead tiles (half of all tiles under
    causal; whole ring steps for not-yet-visible blocks)."""
    alive = jnp.bool_(True)
    if causal:
        alive = alive & (jnp.max(qp) >= jnp.min(kp))
    if window is not None:
        alive = alive & (jnp.min(qp) - jnp.max(kp) < window)
    return alive


def _fwd_kernel(qp_ref, kp_ref, sq_ref, sk_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal, window, scale, kv_steps):
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_tile_alive(qp_ref[...], kp_ref[...], causal=causal, window=window))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_tile(qp_ref[...], kp_ref[...], sq_ref[0], sk_ref[0],
                          causal=causal, window=window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev, l_prev = m_scr[...], l_scr[...]  # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)  # masked→0, no overflow
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=1, keepdims=True)
        acc = acc_scr[...] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...], l_scr[...], acc_scr[...] = m_new, l_new, acc

    @pl.when(kv_i == kv_steps - 1)
    def _():
        l = l_scr[...]
        safe = jnp.maximum(l, 1e-30)
        o_ref[0] = jnp.where(l > 0, acc_scr[...] / safe, 0.0).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(l > 0, m_scr[...] + jnp.log(safe), NEG_INF)


def _dq_kernel(qp_ref, kp_ref, sq_ref, sk_ref, q_ref, k_ref, v_ref,
               do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
               *, causal, window, scale, kv_steps):
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_tile_alive(qp_ref[...], kp_ref[...], causal=causal, window=window))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_tile(qp_ref[...], kp_ref[...], sq_ref[0], sk_ref[0],
                          causal=causal, window=window)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        dp = jax.lax.dot_general(
            do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dq_scr[...] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kv_i == kv_steps - 1)
    def _():
        dq_ref[0] = dq_scr[...]


def _dkv_kernel(qp_ref, kp_ref, sq_ref, sk_ref, q_ref, k_ref, v_ref,
                do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, causal, window, scale, q_steps):
    q_i = pl.program_id(2)

    @pl.when(q_i == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_tile_alive(qp_ref[...], kp_ref[...], causal=causal, window=window))
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_tile(qp_ref[...], kp_ref[...], sq_ref[0], sk_ref[0],
                          causal=causal, window=window)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        do = do_ref[0].astype(jnp.float32)
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dk_scr[...] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(q_i == q_steps - 1)
    def _():
        dk_ref[0] = dk_scr[...]
        dv_ref[0] = dv_scr[...]


def _pick_block(s: int, pref: int = 512) -> int:
    """Largest 128-multiple ≤ pref dividing s, preferring pref itself. Large
    tiles amortize per-grid-step overhead (at 256² tiles a 32k ring step is
    >100k grid steps and overhead dominates); bounded by VMEM via pref."""
    for b in [pref] + [c for c in (1024, 512, 256, 128) if c < pref]:
        if s % b == 0:
            return b
    return s  # small/odd seq: single tile (interpret/test sizes)


def _prep(q, k, v, q_pos, kv_pos, seg_q, seg_kv):
    """Flatten heads into the leading dim and lift vectors to 2-D:
    q-aligned → [.., Sq, 1], kv-aligned → [.., 1, Sk]."""
    B, Sq, N, H = q.shape
    Sk, Nkv = k.shape[1], k.shape[2]
    qf = q.transpose(0, 2, 1, 3).reshape(B * N, Sq, H)
    kf = k.transpose(0, 2, 1, 3).reshape(B * Nkv, Sk, H)
    vf = v.transpose(0, 2, 1, 3).reshape(B * Nkv, Sk, H)
    qp = q_pos.astype(jnp.int32)[:, None]            # [Sq, 1]
    kp = kv_pos.astype(jnp.int32)[None, :]           # [1, Sk]
    sq = seg_q.astype(jnp.int32)[:, :, None]         # [B, Sq, 1]
    sk = seg_kv.astype(jnp.int32)[:, None, :]        # [B, 1, Sk]
    return qf, kf, vf, qp, kp, sq, sk


def _specs(B, N, Nkv, H, bq, bkv, *, kv_major=False):
    """Block specs; grid is (bn, qt, kt) or with kv_major (bn, kt, qt)."""
    rep = N // Nkv

    def ix(fn):
        if kv_major:
            return lambda bn, kt, qt: fn(bn, qt, kt)
        return lambda bn, qt, kt: fn(bn, qt, kt)

    qpos = pl.BlockSpec((bq, 1), ix(lambda bn, qt, kt: (qt, 0)))
    kpos = pl.BlockSpec((1, bkv), ix(lambda bn, qt, kt: (0, kt)))
    segq = pl.BlockSpec((1, bq, 1), ix(lambda bn, qt, kt: (bn // N, qt, 0)))
    segk = pl.BlockSpec((1, 1, bkv), ix(lambda bn, qt, kt: (bn // N, 0, kt)))
    qspec = pl.BlockSpec((1, bq, H), ix(lambda bn, qt, kt: (bn, qt, 0)))
    kspec = pl.BlockSpec(
        (1, bkv, H),
        ix(lambda bn, qt, kt: ((bn // N) * Nkv + (bn % N) // rep, kt, 0)),
    )
    lspec = pl.BlockSpec((1, bq, 1), ix(lambda bn, qt, kt: (bn, qt, 0)))
    return qpos, kpos, segq, segk, qspec, kspec, lspec


def flash_block_fwd(q, k, v, q_pos, kv_pos, seg_q, seg_kv, *,
                    causal, window, scale, interpret=False,
                    block_q=None, block_kv=None):
    """q [B,Sq,N,H] × k/v [B,Sk,Nkv,H] → (out [B,Sq,N,H], lse [B,N,Sq]).
    ``block_q``/``block_kv`` override the static preferences — the per-chip
    autotune table (ops/autotune.py) threads through here."""
    B, Sq, N, H = q.shape
    Sk, Nkv = k.shape[1], k.shape[2]
    bq = _pick_block(Sq, block_q or 512)
    bkv = _pick_block(Sk, block_kv or 1024)
    qf, kf, vf, qp, kp, sq, sk = _prep(q, k, v, q_pos, kv_pos, seg_q, seg_kv)
    qpos, kpos, segq, segk, qspec, kspec, lspec = _specs(B, N, Nkv, H, bq, bkv)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, window=window,
                          scale=scale, kv_steps=Sk // bkv),
        grid=(B * N, Sq // bq, Sk // bkv),
        in_specs=[qpos, kpos, segq, segk, qspec, kspec, kspec],
        out_specs=[qspec, lspec],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, Sq, H), q.dtype),
            jax.ShapeDtypeStruct((B * N, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, H), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, kp, sq, sk, qf, kf, vf)
    return (
        out.reshape(B, N, Sq, H).transpose(0, 2, 1, 3),
        lse.reshape(B, N, Sq),
    )


def flash_block_bwd(q, k, v, do, lse, delta, q_pos, kv_pos, seg_q, seg_kv, *,
                    causal, window, scale, interpret=False,
                    block_q=None, block_kv=None):
    """Backward for one kv block: → (dq [B,Sq,N,H] f32, dk, dv [B,Sk,Nkv,H]
    f32). `lse`/`delta` are [B,N,Sq] (global logsumexp / rowsum(do·out))."""
    B, Sq, N, H = q.shape
    Sk, Nkv = k.shape[1], k.shape[2]
    rep = N // Nkv
    bq = _pick_block(Sq, block_q or 512)
    bkv = _pick_block(Sk, block_kv or 1024)
    qf, kf, vf, qp, kp, sq, sk = _prep(q, k, v, q_pos, kv_pos, seg_q, seg_kv)
    dof = do.transpose(0, 2, 1, 3).reshape(B * N, Sq, H)
    lsef = lse.reshape(B * N, Sq, 1)
    deltaf = delta.reshape(B * N, Sq, 1)
    args = (qp, kp, sq, sk, qf, kf, vf, dof, lsef, deltaf)

    qpos, kpos, segq, segk, qspec, kspec, lspec = _specs(B, N, Nkv, H, bq, bkv)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, window=window,
                          scale=scale, kv_steps=Sk // bkv),
        grid=(B * N, Sq // bq, Sk // bkv),
        in_specs=[qpos, kpos, segq, segk, qspec, kspec, kspec, qspec, lspec, lspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B * N, Sq, H), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, H), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    # dkv: kv tile outer, q tiles inner (accumulate over queries)
    qpos2, kpos2, segq2, segk2, qspec2, kspec2, lspec2 = _specs(
        B, N, Nkv, H, bq, bkv, kv_major=True
    )
    dkv_out = pl.BlockSpec((1, bkv, H), lambda bn, kt, qt: (bn, kt, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, window=window,
                          scale=scale, q_steps=Sq // bq),
        grid=(B * N, Sk // bkv, Sq // bq),
        in_specs=[qpos2, kpos2, segq2, segk2, qspec2, kspec2, kspec2,
                  qspec2, lspec2, lspec2],
        out_specs=[dkv_out, dkv_out],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, Sk, H), jnp.float32),
            jax.ShapeDtypeStruct((B * N, Sk, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, H), jnp.float32),
            pltpu.VMEM((bkv, H), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    # GQA: per-q-head dk/dv reduce onto their kv head
    dk = dk.reshape(B, Nkv, rep, Sk, H).sum(axis=2).transpose(0, 2, 1, 3)
    dv = dv.reshape(B, Nkv, rep, Sk, H).sum(axis=2).transpose(0, 2, 1, 3)
    dq = dq.reshape(B, N, Sq, H).transpose(0, 2, 1, 3)
    return dq, dk, dv


def flash_attention(
    q, k, v, *,
    causal=True, scale=None, segment_ids=None, sliding_window=None,
    sinks=None, block_q=None, block_kv=None, interpret=False,
):
    """Non-ring single-chip entry over the SAME blockwise kernels the CP
    ring uses — one kv "ring step" covering the whole sequence. This is the
    in-tree alternative to the library splash kernel: positional masking
    with per-tile dead-tile skipping (a 128-token sliding window kills
    almost every kv tile), native GQA, packed-segment ids, gpt-oss sinks
    (folded post-merge exactly as parallel/cp.py does), and no head_dim
    divisibility constraint — head_dim 64 runs as-is. `ops/attention.flash`
    races this against splash per shape via the autotune table.

    q [B,S,N,H] × k/v [B,S,Nkv,H] → [B,S,N,H] in q.dtype; differentiable
    (custom_vjp on the flash identities, d_sinks included)."""
    B, S, N, H = q.shape
    scale = scale if scale is not None else 1.0 / (H**0.5)
    window = sliding_window
    Sp = -(-S // 128) * 128
    pad = Sp - S
    if pad:
        zeros = lambda a: jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0)))
        q, k, v = zeros(q), zeros(k), zeros(v)
    if segment_ids is None:
        # padded tokens get segment -1 ≠ any real id → never attended; a
        # fully-padded q row comes out 0 via the all-masked guard and is
        # sliced off below
        seg0 = jnp.zeros((B, S), jnp.int32)
    else:
        seg0 = segment_ids.astype(jnp.int32)
    if pad:
        seg0 = jnp.pad(seg0, ((0, 0), (0, pad)), constant_values=-1)
    pos = jnp.arange(Sp, dtype=jnp.int32)
    kw = dict(causal=causal, window=window, scale=scale, interpret=interpret,
              block_q=block_q, block_kv=block_kv)

    def _fwd_impl(q, k, v, seg, sk):
        out, lse = flash_block_fwd(q, k, v, pos, pos, seg, seg, **kw)
        if sk is not None:
            # the sink is one zero-value virtual key: fold it post-merge —
            # lse' = logaddexp(lse, sink), out' = out·exp(lse − lse'). The
            # saved lse' makes the blockwise backward exact (p = exp(s −
            # lse') are the extended-softmax probabilities).
            s_b = sk.astype(jnp.float32)[None, :, None]  # [1, n, 1]
            lse_ext = jnp.logaddexp(lse, s_b)
            out = out.astype(jnp.float32) * jnp.exp(lse - lse_ext).transpose(
                0, 2, 1
            )[..., None]
            lse = lse_ext
        return out.astype(q.dtype), lse

    @jax.custom_vjp
    def attn(q, k, v, seg, sk):
        return _fwd_impl(q, k, v, seg, sk)[0]

    def attn_fwd(q, k, v, seg, sk):
        out, lse = _fwd_impl(q, k, v, seg, sk)
        return out, (q, k, v, seg, sk, out, lse)

    def attn_bwd(res, dout):
        q, k, v, seg, sk, out, lse = res
        do32 = dout.astype(jnp.float32)
        delta = (do32 * out.astype(jnp.float32)).sum(-1).transpose(0, 2, 1)
        dq, dk, dv = flash_block_bwd(
            q, k, v, dout, lse, delta, pos, pos, seg, seg, **kw
        )
        import numpy as np

        ct_seg = np.zeros(seg.shape, jax.dtypes.float0)
        ct_sk = None
        if sk is not None:
            # sink column of the flash backward: dp_sink = dO·v_sink = 0, so
            # ds_sink = p_sink·(0 − Δ); summed over its (b, s) broadcast
            p_sink = jnp.exp(sk.astype(jnp.float32)[None, :, None] - lse)
            ct_sk = (-(p_sink * delta).sum(axis=(0, 2))).astype(sk.dtype)
        return (
            dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            ct_seg, ct_sk,
        )

    attn.defvjp(attn_fwd, attn_bwd)
    out = attn(q, k, v, seg0, sinks)
    return out[:, :S] if pad else out


def merge_partials(out_a, lse_a, out_t, lse_t):
    """Online-softmax merge of two independently-normalized partial
    attentions. out: [B,S,N,H] fp32, lse: [B,N,S] fp32."""
    m = jnp.maximum(lse_a, lse_t)
    # all-masked rows have lse == NEG_INF on both sides; keep them at 0/NEG_INF
    alive = m > NEG_INF / 2
    wa = jnp.where(alive, jnp.exp(lse_a - m), 0.0)
    wt = jnp.where(alive, jnp.exp(lse_t - m), 0.0)
    denom = wa + wt
    wa_n = (wa / jnp.maximum(denom, 1e-30)).transpose(0, 2, 1)[..., None]
    wt_n = (wt / jnp.maximum(denom, 1e-30)).transpose(0, 2, 1)[..., None]
    out = out_a * wa_n + out_t * wt_n
    lse = jnp.where(alive, m + jnp.log(jnp.maximum(denom, 1e-30)), NEG_INF)
    return out, lse
