"""Rotary position embeddings.

Parity: reference models carry per-family rope_utils (e.g.
components/models/llama/rope_utils.py) supporting default / llama3 / yarn
scalings; TE provides fused RoPE on GPU. On TPU we precompute cos/sin tables
once per step (cheap) and let XLA fuse the elementwise application into the
surrounding matmuls — a fused kernel buys nothing here.

Convention: interleaved-half ("rotate_half") layout matching HF transformers,
so weights are interchangeable without permutation.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeConfig:
    theta: float = 10000.0
    scaling: str | None = None  # None | "llama3" | "linear" | "yarn"
    factor: float = 1.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192
    # yarn
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    mscale: float = 1.0
    mscale_all_dim: float = 0.0
    attention_factor: float | None = None  # HF yarn cos/sin multiplier

    SUPPORTED_SCALINGS = (None, "llama3", "linear", "yarn")

    def __post_init__(self):
        if self.scaling not in self.SUPPORTED_SCALINGS:
            raise ValueError(
                f"Unsupported rope_scaling type {self.scaling!r}; "
                f"supported: {self.SUPPORTED_SCALINGS}"
            )

    @staticmethod
    def from_hf(cfg) -> "RopeConfig":
        """Build from an HF config object / dict (rope_scaling conventions)."""
        get = lambda k, d=None: (cfg.get(k, d) if isinstance(cfg, dict) else getattr(cfg, k, d))
        rs = get("rope_scaling") or {}
        rtype = rs.get("rope_type", rs.get("type"))
        return RopeConfig(
            theta=get("rope_theta", 10000.0),
            scaling=None if rtype in (None, "default") else rtype,
            factor=rs.get("factor", 1.0),
            low_freq_factor=rs.get("low_freq_factor", 1.0),
            high_freq_factor=rs.get("high_freq_factor", 4.0),
            original_max_position=rs.get(
                "original_max_position_embeddings", get("max_position_embeddings", 8192)
            ),
            beta_fast=rs.get("beta_fast", 32.0),
            beta_slow=rs.get("beta_slow", 1.0),
            mscale=rs.get("mscale", 1.0),
            mscale_all_dim=rs.get("mscale_all_dim", 0.0),
            attention_factor=rs.get("attention_factor"),
        )


def _inv_freq(head_dim: int, cfg: RopeConfig) -> jnp.ndarray:
    inv = 1.0 / (cfg.theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if cfg.scaling == "linear":
        inv = inv / cfg.factor
    elif cfg.scaling == "llama3":
        # HF Llama-3 frequency-dependent scaling.
        low = cfg.original_max_position / cfg.low_freq_factor
        high = cfg.original_max_position / cfg.high_freq_factor
        wavelen = 2 * math.pi / inv
        smooth = (cfg.original_max_position / wavelen - cfg.low_freq_factor) / (
            cfg.high_freq_factor - cfg.low_freq_factor
        )
        scaled = jnp.where(
            wavelen < high,
            inv,
            jnp.where(wavelen > low, inv / cfg.factor, (1 - smooth) * inv / cfg.factor + smooth * inv),
        )
        inv = scaled
    elif cfg.scaling == "yarn":
        # DeepSeek/Qwen YaRN ramp (state-of-practice formulation).
        dim = head_dim

        def find_dim(n_rot: float) -> float:
            return (dim * math.log(cfg.original_max_position / (n_rot * 2 * math.pi))) / (
                2 * math.log(cfg.theta)
            )

        low = max(math.floor(find_dim(cfg.beta_fast)), 0)
        high = min(math.ceil(find_dim(cfg.beta_slow)), dim - 1)
        ramp = jnp.clip(
            (jnp.arange(dim // 2, dtype=jnp.float32) - low) / max(high - low, 1e-3), 0, 1
        )
        inv = inv / cfg.factor * ramp + inv * (1 - ramp)
    elif cfg.scaling is not None:
        raise ValueError(f"Unsupported rope scaling {cfg.scaling!r}")
    return inv


def _attention_factor(cfg: RopeConfig) -> float:
    """HF yarn multiplies cos/sin by attention_factor (0.1·ln(factor)+1 when
    unset). Models that fold the correction into the softmax scale instead
    (DeepSeek MLA) use yarn_mscale() and a RopeConfig with factor<=1 here."""
    if cfg.scaling != "yarn":
        return 1.0
    if cfg.attention_factor is not None:
        return cfg.attention_factor
    if cfg.factor > 1.0:
        return 0.1 * math.log(cfg.factor) + 1.0
    return 1.0


def yarn_mscale(cfg: RopeConfig) -> float:
    """Attention magnitude correction used by YaRN models (DeepSeek MLA)."""
    if cfg.scaling != "yarn" or cfg.factor <= 1.0:
        return 1.0

    def get(scale: float) -> float:
        return 0.1 * scale * math.log(cfg.factor) + 1.0 if scale > 0 else 1.0

    return get(cfg.mscale) / get(cfg.mscale_all_dim)


def rope_table(
    position_ids: jnp.ndarray, head_dim: int, cfg: RopeConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables [..., seq, head_dim] for given positions (fp32)."""
    inv = _inv_freq(head_dim, cfg)
    freqs = position_ids[..., None].astype(jnp.float32) * inv  # [..., S, hd/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    f = _attention_factor(cfg)
    return jnp.cos(emb) * f, jnp.sin(emb) * f


def mrope_table(
    position_ids: jnp.ndarray,  # [3, B, S] — t/h/w grid positions
    head_dim: int,
    cfg: RopeConfig,
    mrope_section: tuple[int, int, int],
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Interleaved multi-axis RoPE (Qwen3-VL: HF apply_interleaved_mrope,
    modeling_qwen3_vl_moe.py:830) — frequency slot i takes the H axis when
    i≡1 (mod 3) and i < 3·section_h, the W axis when i≡2 (mod 3) and
    i < 3·section_w, else the T axis. Returns cos/sin [B, S, head_dim]."""
    inv = _inv_freq(head_dim, cfg)
    freqs = position_ids[..., None].astype(jnp.float32) * inv  # [3, B, S, hd/2]
    i = jnp.arange(head_dim // 2)
    take_h = (i % 3 == 1) & (i < 3 * mrope_section[1])
    take_w = (i % 3 == 2) & (i < 3 * mrope_section[2])
    half = jnp.where(take_h, freqs[1], freqs[0])
    half = jnp.where(take_w, freqs[2], half)
    emb = jnp.concatenate([half, half], axis=-1)
    f = _attention_factor(cfg)
    return jnp.cos(emb) * f, jnp.sin(emb) * f


def apply_rope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    interleave: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply rotate-half RoPE. q/k: [B, S, N, H]; cos/sin: [B, S, H].

    ``interleave``: checkpoint stores pair-interleaved rope dims (DeepSeek
    MLA, HF `rope_interleave` / apply_rotary_pos_emb_interleave) — deinterleave
    [x0,y0,x1,y1,...] → [x0,x1,...,y0,y1,...] before the rotation.

    Partial rotary (GLM-4 / phi-style ``partial_rotary_factor``): when the
    table's last dim is smaller than the head dim, only the first
    ``rotary_dim`` channels rotate and the rest pass through (HF
    apply_rotary_pos_emb slices the same way).
    """

    def deint(x: jnp.ndarray) -> jnp.ndarray:
        *lead, d = x.shape
        return x.reshape(*lead, d // 2, 2).swapaxes(-1, -2).reshape(*lead, d)

    def rot(x: jnp.ndarray) -> jnp.ndarray:
        half = x.shape[-1] // 2
        x1, x2 = x[..., :half], x[..., half:]
        return jnp.concatenate([-x2, x1], axis=-1)

    rotary_dim = cos.shape[-1]
    q_pass = k_pass = None
    if rotary_dim < q.shape[-1]:
        q, q_pass = q[..., :rotary_dim], q[..., rotary_dim:]
        k, k_pass = k[..., :rotary_dim], k[..., rotary_dim:]
    if interleave:
        q, k = deint(q), deint(k)
    c = cos[..., None, :].astype(q.dtype)
    s = sin[..., None, :].astype(q.dtype)
    q, k = q * c + rot(q) * s, k * c + rot(k) * s
    if q_pass is not None:
        q = jnp.concatenate([q, q_pass], axis=-1)
        k = jnp.concatenate([k, k_pass], axis=-1)
    return q, k
