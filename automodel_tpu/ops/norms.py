"""RMSNorm.

Parity: reference selects rms_norm ∈ {torch, torch_fp32, te} per model
(components/models/common/utils.py:139). Here the XLA formulation is the
default — XLA fuses it into neighbouring ops, which is what TE's fused kernel
buys on GPU — with fp32 accumulation always on (the `torch_fp32` behavior).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm with fp32 accumulation, cast back to x.dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(dtype)


def rms_norm_gemma(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """Gemma-style RMSNorm: (1 + scale) multiplier, fp32 accumulation."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * (1.0 + scale.astype(jnp.float32))).astype(dtype)


def layer_norm(
    x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-6
) -> jnp.ndarray:
    """Full LayerNorm (mean+variance) with fp32 accumulation."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
