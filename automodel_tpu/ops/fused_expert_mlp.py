"""Fused MoE expert MLP: gate_up matmul + gated activation + down matmul in
ONE Pallas kernel, with a purpose-tiled Pallas manual backward.

Forward motivation (PROFILE_MOE_r04.md): the two-kernel expert path writes
the [T·K, 2I] gate_up output and the [T·K, I] activation to HBM and reads
them back (~600MB per layer at bench shape). Here both stay in VMEM: per
work unit (m-tile × group) the kernel loops I-chunks on the grid, computing
``acc += act(lhs @ Wgu[:, chunk]) @ Wd[chunk, :]`` with an fp32 accumulator
— the down-projection contraction is summable over I-chunks, so the
intermediate never materializes. Rows are lhs-masked (write-only outputs;
boundary tiles accumulate across consecutive work units like
ops/grouped_matmul._tgmm).

Backward motivation (PROFILE_MOE_r05.md): the r5 backward composed generic
``_tgmm``/transpose-GEMM calls and gave the forward win back (34.40 ms
fused FWD+BWD vs 33.53 unfused; gmm2-class tiles ran 84.3 TFLOP/s vs
gmm1's 107.0). The backward here is three purpose-tiled kernels that fold
the dgate·dup activation-backward elementwise chain (and the sentinel-tail
``dout`` mask) in-kernel, so ``dg``/``du``/``mid`` never materialize in HBM
and ``lhs`` is read once for both weight grads:

- ``_bwd_gu``   — dWg, dWu (+ dgb, dub row sums) in one pass over lhs.
- ``_bwd_dwd``  — dWd (+ ddb) with the activation mid recomputed in-kernel.
- ``_bwd_dx``   — dlhs = dg·Wg^T + du·Wu^T fused over I-chunks.

Tile shapes consult the per-chip autotune registry (ops/autotune.py, swept
by tools/kernel_bench.py); the NaN-tail masking semantics from PR 5 are
preserved bit-for-bit — every row outside a work unit's window (boundary
rows of the neighbouring group AND the a2a sentinel tail) is zeroed on the
``dout`` side in-kernel, where 0·NaN can no longer survive.

Same dropless semantics and work-unit plan as ops/grouped_matmul (reference
capability: the fused SwiGLU+GEMM epilogues TE/DeepEP provide on GPU).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from automodel_tpu.utils.compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()

from automodel_tpu.ops.grouped_matmul import (
    _interpret_requested,
    _pallas_eligible,
    _plan,
    _round_up,
    ragged_dot,
)


def _kernel(wg, wt, ws, we, lhs_ref, wg_ref, wu_ref, wd_ref, *rest,
            tm, n_ic, act_kind, limit, W, has_bias):
    if has_bias:
        gb_ref, ub_ref, db_ref, out_ref, acc = rest
    else:
        out_ref, acc = rest
    w = pl.program_id(0)
    ic = pl.program_id(1)
    t = wt[w]
    first = jnp.logical_or(w == 0, wt[jnp.maximum(w - 1, 0)] != t)
    last = jnp.logical_or(w == W - 1, wt[jnp.minimum(w + 1, W - 1)] != t)

    @pl.when(jnp.logical_and(ic == 0, first))
    def _():
        acc[...] = jnp.zeros_like(acc)

    rows = t * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    lmask = (rows >= ws[w]) & (rows < we[w])
    lhs = jnp.where(lmask, lhs_ref[...], jnp.zeros_like(lhs_ref))

    # gate and up are SEPARATE operands blocked straight from the stored
    # [G, D, I] layout — an interleaved [G, D, 2I] operand would need a
    # host-side concat + transpose whose AD transpose leaks a non-default
    # layout onto the weight grads, forcing full-size fp32 relayout copies
    # in every downstream elementwise consumer (optimizer, grad-norm)
    g = jax.lax.dot_general(
        lhs, wg_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [tm, ic_size]
    u = jax.lax.dot_general(
        lhs, wu_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if has_bias:
        g = g + gb_ref[0, 0, 0].astype(jnp.float32)
        u = u + ub_ref[0, 0, 0].astype(jnp.float32)
        # gpt-oss-style expert biases: once added, masked rows are no longer
        # zero (act(bias)·Wd ≠ 0) — re-mask mid before the down contraction
        # and gate the down bias on the same row window (each work unit adds
        # it exactly once, on its first I-chunk, to its own rows only).
        @pl.when(ic == 0)
        def _():
            acc[...] += jnp.where(
                lmask, db_ref[0, 0].astype(jnp.float32), 0.0
            )
    mid = _act_core(g, u, act_kind, limit)
    if has_bias:
        mid = jnp.where(lmask, mid, 0.0)
    acc[...] += jax.lax.dot_general(
        mid.astype(lhs_ref.dtype), wd_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(jnp.logical_and(ic == n_ic - 1, last))
    def _():
        out_ref[...] = acc[...].astype(out_ref.dtype)


_IC_CANDS = (512, 384, 256, 128)


def _divisor_chunk(n128: int, cap: int = 512) -> int:
    """Largest 128-multiple ≤ cap dividing the 128-padded dim — a
    non-divisor pads up to a chunk multiple and burns the padding as real
    matmul work (I=768 with ic=512 pads to 1024: +33% expert FLOPs,
    measured 29.4% vs 31.5% MFU on the qwen-style bench fingerprint).
    128 divides any 128-multiple, so this always finds a divisor."""
    return next(c for c in _IC_CANDS if c <= cap and c <= n128 and n128 % c == 0)


def _fwd(lhs, gate, up, down, group_sizes, gb, ub, db, act_kind, limit,
         interpret):
    """lhs [M, D] sorted by group; gate/up [G, D, I] (pre-split halves);
    down [G, I, D]; optional per-expert biases gb/ub [G, I], db [G, D]
    (gpt-oss) → [M, D]."""
    M, D = lhs.shape
    G, _, I = gate.shape
    has_bias = gb is not None or ub is not None or db is not None
    tm = 512
    Dp = _round_up(D, 128)
    I128 = _round_up(I, 128)
    ic = _divisor_chunk(I128)

    def _vmem(tm_, ic_):
        # double-buffered input blocks + output + fp32 accumulator; must stay
        # under the ~16MB scoped-vmem stack (Mosaic rejects the kernel at
        # compile otherwise — hit at D=1536 with the 512/512 tiles)
        return (
            2 * (tm_ * Dp * 2)          # lhs
            + 2 * (Dp * 2 * ic_ * 2)    # wgu chunk
            + 2 * (ic_ * Dp * 2)        # wd chunk
            + 2 * (tm_ * Dp * 2)        # out
            + tm_ * Dp * 4              # acc scratch
        )

    while _vmem(tm, ic) > 14 * 1024 * 1024 and tm > 256:
        tm //= 2
    while _vmem(tm, ic) > 14 * 1024 * 1024:
        smaller = [c for c in _IC_CANDS if c < ic and I128 % c == 0]
        if not smaller:
            break
        ic = smaller[0]
    Mp, Ip = _round_up(M, tm), _round_up(I128, ic)
    if (Mp, Dp) != (M, D):
        lhs = jnp.pad(lhs, ((0, Mp - M), (0, Dp - D)))
    if (Dp, Ip) != (D, I):
        gate = jnp.pad(gate, ((0, 0), (0, Dp - D), (0, Ip - I)))
        up = jnp.pad(up, ((0, 0), (0, Dp - D), (0, Ip - I)))
        down = jnp.pad(down, ((0, 0), (0, Ip - I), (0, Dp - D)))
    # gate/up/down are blocked DIRECTLY from their stored [G, D, I] /
    # [G, I, D] layouts — no concat, no transpose: a transposed weight
    # operand's AD transpose emits the weight grads in a non-default layout,
    # and every fp32 elementwise consumer downstream (Adam, grad-norm) then
    # pays a full-size relayout copy (2.25GB per stacked expert tensor at
    # the MoE bench shape; the difference between fitting and OOM on 16GB)
    n_ic = Ip // ic

    operands = [lhs, gate, up, down]
    in_specs = [
        pl.BlockSpec((tm, Dp), lambda w, i, wg, wt, ws, we: (wt[w], 0)),
        pl.BlockSpec((1, Dp, ic), lambda w, i, wg, wt, ws, we: (wg[w], 0, i)),
        pl.BlockSpec((1, Dp, ic), lambda w, i, wg, wt, ws, we: (wg[w], 0, i)),
        pl.BlockSpec((1, ic, Dp), lambda w, i, wg, wt, ws, we: (wg[w], i, 0)),
    ]
    if has_bias:
        zeros_i = jnp.zeros((G, I), lhs.dtype)
        gb = zeros_i if gb is None else gb
        ub = zeros_i if ub is None else ub
        db = jnp.zeros((G, D), lhs.dtype) if db is None else db
        # the unit axis before the lane dim keeps Mosaic's sublane tiling
        # rule satisfied (block dim == array dim == 1); without it a block
        # of 1 over the G (resp. n_ic) sublane axis fails lowering
        gb = jnp.pad(gb, ((0, 0), (0, Ip - I))).reshape(G, n_ic, 1, ic)
        ub = jnp.pad(ub, ((0, 0), (0, Ip - I))).reshape(G, n_ic, 1, ic)
        operands += [
            gb, ub, jnp.pad(db, ((0, 0), (0, Dp - D))).reshape(G, 1, Dp)
        ]
        in_specs += [
            pl.BlockSpec(
                (1, 1, 1, ic), lambda w, i, wg, wt, ws, we: (wg[w], i, 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, 1, ic), lambda w, i, wg, wt, ws, we: (wg[w], i, 0, 0)
            ),
            pl.BlockSpec((1, 1, Dp), lambda w, i, wg, wt, ws, we: (wg[w], 0, 0)),
        ]

    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G

    # inside a check_vma shard_map region (the a2a_fused EP path) the
    # pallas_call output aval must carry the manual-axes vma explicitly
    from automodel_tpu.ops.grouped_matmul import _out_sds

    out_sds = _out_sds((Mp, Dp), lhs.dtype, lhs, gate, up, down)

    out = pl.pallas_call(
        functools.partial(
            _kernel, tm=tm, n_ic=n_ic, act_kind=act_kind, limit=limit, W=W,
            has_bias=has_bias,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(W, n_ic),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (tm, Dp), lambda w, i, wg, wt, ws, we: (wt[w], 0)
            ),
            scratch_shapes=[pltpu.VMEM((tm, Dp), jnp.float32)],
        ),
        out_shape=out_sds,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, *operands)
    return out[:M, :D]


def _reference(lhs, gate, up, down, group_sizes, gb, ub, db, act_kind, limit,
               platform):
    """The two-grouped-matmul composition — the backward path and the
    numerics reference."""
    gu_g = ragged_dot(lhs, gate, group_sizes, platform=platform)
    gu_u = ragged_dot(lhs, up, group_sizes, platform=platform)
    if gb is not None or ub is not None or db is not None:
        # row r belongs to group g iff cumsum[g-1] <= r < cumsum[g]
        bounds = jnp.cumsum(group_sizes.astype(jnp.int32))
        row_g = jnp.searchsorted(
            bounds, jnp.arange(lhs.shape[0], dtype=jnp.int32), side="right"
        )
    if gb is not None:
        gu_g = gu_g + gb.astype(gu_g.dtype)[row_g]
    if ub is not None:
        gu_u = gu_u + ub.astype(gu_u.dtype)[row_g]
    if act_kind == "swiglu_oai":
        g = jnp.minimum(gu_g, 7.0)
        u = jnp.clip(gu_u, -7.0, 7.0)
        mid = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    else:
        mid = jax.nn.silu(gu_g)
        if limit is not None:
            mid = jnp.minimum(mid, limit)
            gu_u = jnp.clip(gu_u, -limit, limit)
        mid = mid * gu_u
    out = ragged_dot(mid.astype(lhs.dtype), down, group_sizes, platform=platform)
    if db is not None:
        out = out + db.astype(out.dtype)[row_g]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def fused_expert_mlp(lhs, gate, up, down, group_sizes,
                     gb=None, ub=None, db=None,
                     act_kind="swiglu", limit=None, platform=None,
                     interpret=None):
    """Forward through the fused kernel; backward through the purpose-tiled
    manual kernels below (the bwd needs the g/u intermediates anyway — a
    remat-style re-run of the cheap gate_up GEMMs feeds them without ever
    materializing the activation chain)."""
    if interpret is None:
        interpret = _interpret_requested()
    if not (interpret or _pallas_eligible(platform)):
        return _reference(lhs, gate, up, down, group_sizes, gb, ub, db,
                          act_kind, limit, platform)
    return _fwd(lhs, gate, up, down, group_sizes, gb, ub, db, act_kind, limit,
                interpret)


def _vjp_fwd(lhs, gate, up, down, group_sizes, gb, ub, db,
             act_kind, limit, platform, interpret):
    y = fused_expert_mlp(
        lhs, gate, up, down, group_sizes, gb, ub, db,
        act_kind, limit, platform, interpret
    )
    return y, (lhs, gate, up, down, group_sizes, gb, ub, db)


def _act_core(g32, u32, act_kind, limit):
    """The post-bias elementwise activation on fp32 values — ONE definition
    shared by the forward kernel, the backward kernels (which jax.vjp it
    tile-wise for exact clamp-aware derivatives), and `_act_fn`."""
    if act_kind == "swiglu_oai":
        gc = jnp.minimum(g32, 7.0)
        uc = jnp.clip(u32, -7.0, 7.0)
        return (uc + 1.0) * (gc * jax.nn.sigmoid(1.702 * gc))
    mid = jax.nn.silu(g32)
    if limit is not None:
        mid = jnp.minimum(mid, limit)
        u32 = jnp.clip(u32, -limit, limit)
    return mid * u32


def _act_fn(g, u, act_kind, limit):
    """The post-bias elementwise activation, in fp32 internally (matches the
    kernel); jax.vjp of THIS gives exact clamp-aware derivatives."""
    return _act_core(
        g.astype(jnp.float32), u.astype(jnp.float32), act_kind, limit
    ).astype(g.dtype)


def _act_grads(g, u, dmid, act_kind, limit):
    """(dg, du) fp32 of the elementwise chain — the exact jax.vjp of
    `_act_core`, evaluated tile-wise inside the backward kernels (all VPU
    work; the MXU contraction overlaps it)."""
    g32, u32 = g.astype(jnp.float32), u.astype(jnp.float32)
    _, vjp = jax.vjp(lambda a, b: _act_core(a, b, act_kind, limit), g32, u32)
    return vjp(dmid.astype(jnp.float32))


# -- purpose-tiled backward kernels -----------------------------------------
#
# All three share the grouped-matmul work-unit plan (scalar-prefetched
# (group, m-tile, row-window) tuples) and fold the activation backward and
# the row-window mask in-kernel. The row window doubles as the sentinel-tail
# mask: rows past sum(group_sizes) belong to no window, so their NaN/Inf
# garbage is zeroed on the dout side BEFORE any contraction — the PR 5
# semantics, now without the external [M, N] selects.

_VMEM_BUDGET = 12 * 1024 * 1024


def _autotune_tiles(key, names, budget_fn, fallback):
    from automodel_tpu.ops import autotune

    tiles = autotune.valid_tiles(autotune.lookup(key), names, budget_fn)
    return tiles if tiles is not None else fallback


# the per-kernel VMEM-budget models are module-level so the sweep driver
# (tools/kernel_bench.py) filters candidates with the SAME predicate the
# kernel validates entries against — they can never drift apart


def _bwd_gu_budget_ok(tm, tk, tn, itemsize):
    need = (
        2 * itemsize * tm * tk          # lhs block
        + 3 * 2 * itemsize * tm * tn    # g / u / dmid blocks
        + 2 * 2 * 4 * tk * tn           # dWg / dWu fp32 slabs
    )
    return need <= _VMEM_BUDGET


def _bwd_dwd_budget_ok(tm, tk, tn, itemsize):
    need = (
        2 * 2 * itemsize * tm * tk      # g / u blocks
        + 2 * itemsize * tm * tn        # dy block
        + 2 * 4 * tk * tn               # dWd fp32 slab
    )
    return need <= _VMEM_BUDGET


def _bwd_dx_budget_ok(tm, tn, ic, itemsize):
    need = (
        3 * 2 * itemsize * tm * ic      # g / u / dmid chunks
        + 2 * 2 * itemsize * tn * ic    # gate / up chunks
        + 2 * itemsize * tm * tn        # out block
        + 4 * tm * tn                   # acc scratch
    )
    return need <= _VMEM_BUDGET


def _bwd_gu_tiles(D, I, dtype):
    from automodel_tpu.ops import autotune

    it = jnp.dtype(dtype).itemsize
    ok = lambda tm, tk, tn: _bwd_gu_budget_ok(tm, tk, tn, it)
    fb_tk = _divisor_chunk(_round_up(D, 128))
    fb_tn = _divisor_chunk(_round_up(I, 128))
    fb = (512, fb_tk, fb_tn)
    while not ok(*fb) and fb[0] > 128:
        fb = (fb[0] // 2, fb_tk, fb_tn)
    return _autotune_tiles(
        autotune.moe_bwd_gu_key(D, I, dtype), ("tm", "tk", "tn"), ok, fb
    )


def _bwd_dwd_tiles(I, D, dtype):
    from automodel_tpu.ops import autotune

    it = jnp.dtype(dtype).itemsize
    ok = lambda tm, tk, tn: _bwd_dwd_budget_ok(tm, tk, tn, it)
    fb = (512, _divisor_chunk(_round_up(I, 128)), _divisor_chunk(_round_up(D, 128)))
    while not ok(*fb) and fb[0] > 128:
        fb = (fb[0] // 2, fb[1], fb[2])
    return _autotune_tiles(
        autotune.moe_bwd_dwd_key(I, D, dtype), ("tm", "tk", "tn"), ok, fb
    )


def _bwd_dx_tiles(D, I, dtype):
    from automodel_tpu.ops import autotune

    it = jnp.dtype(dtype).itemsize
    ok = lambda tm, tn, ic: _bwd_dx_budget_ok(tm, tn, ic, it)
    fb = (512, _divisor_chunk(_round_up(D, 128)), _divisor_chunk(_round_up(I, 128)))
    while not ok(*fb) and fb[0] > 128:
        fb = (fb[0] // 2, fb[1], fb[2])
    return _autotune_tiles(
        autotune.moe_bwd_dx_key(D, I, dtype), ("tm", "tn", "ic"), ok, fb
    )


def _bwd_gu_kernel(wg, wt, ws, we, lhs_ref, g_ref, u_ref, dmid_ref,
                   dwg_ref, dwu_ref, *rest, tm, act_kind, limit, has_bias):
    if has_bias:
        dgb_ref, dub_ref = rest
    w = pl.program_id(2)
    rows = wt[w] * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    mask = (rows >= ws[w]) & (rows < we[w])
    dg, du = _act_grads(g_ref[...], u_ref[...], dmid_ref[...], act_kind, limit)
    # dout mask folded in-kernel: rows outside this unit's window are the
    # neighbouring group's rows (boundary tile) or the a2a sentinel tail —
    # whose g/u/dmid can be NaN, which an lhs-only mask cannot neutralize
    dg = jnp.where(mask, dg, 0.0)
    du = jnp.where(mask, du, 0.0)
    lhs = jnp.where(mask, lhs_ref[...], jnp.zeros_like(lhs_ref))
    first = jnp.logical_or(w == 0, wg[jnp.maximum(w - 1, 0)] != wg[w])
    acc_g = jax.lax.dot_general(
        lhs, dg.astype(lhs_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_u = jax.lax.dot_general(
        lhs, du.astype(lhs_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cur = dwg_ref[0]
    dwg_ref[0] = acc_g + jnp.where(first, jnp.zeros_like(cur), cur)
    cur = dwu_ref[0]
    dwu_ref[0] = acc_u + jnp.where(first, jnp.zeros_like(cur), cur)
    if has_bias:
        # bias grads are the dg/du row sums — the [1, tn] accumulator rides
        # the same first-visitor rule. Its block index ignores the k grid
        # dim, so every k pass recomputes and rewrites the IDENTICAL totals
        # (same rows, same dg) — the final write-back is always correct.
        cur = dgb_ref[0]
        dgb_ref[0] = dg.sum(axis=0, keepdims=True) + jnp.where(
            first, jnp.zeros_like(cur), cur
        )
        cur = dub_ref[0]
        dub_ref[0] = du.sum(axis=0, keepdims=True) + jnp.where(
            first, jnp.zeros_like(cur), cur
        )


def _bwd_gu(lhs, g, u, dmid, group_sizes, act_kind, limit, interpret,
            has_bias):
    """One pass over lhs → (dWg [G,D,I] f32, dWu, dgb [G,I] f32 | None,
    dub | None). The dgate·dup chain runs in-kernel on the g/u/dmid tiles."""
    from automodel_tpu.ops.grouped_matmul import _out_sds

    M, D = lhs.shape
    _, I = g.shape
    G = group_sizes.shape[0]
    tm, tk, tn = _bwd_gu_tiles(D, I, lhs.dtype)
    Mp, Kp, Np = _round_up(M, tm), _round_up(D, tk), _round_up(I, tn)
    if (Mp, Kp) != (M, D):
        lhs = jnp.pad(lhs, ((0, Mp - M), (0, Kp - D)))
    if (Mp, Np) != (M, I):
        pad = ((0, Mp - M), (0, Np - I))
        g, u, dmid = jnp.pad(g, pad), jnp.pad(u, pad), jnp.pad(dmid, pad)
    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G
    grid = (Kp // tk, Np // tn, W)
    in_specs = [
        pl.BlockSpec((tm, tk), lambda k, n, w, wg, wt, ws, we: (wt[w], k)),
        pl.BlockSpec((tm, tn), lambda k, n, w, wg, wt, ws, we: (wt[w], n)),
        pl.BlockSpec((tm, tn), lambda k, n, w, wg, wt, ws, we: (wt[w], n)),
        pl.BlockSpec((tm, tn), lambda k, n, w, wg, wt, ws, we: (wt[w], n)),
    ]
    slab = pl.BlockSpec((1, tk, tn), lambda k, n, w, wg, wt, ws, we: (wg[w], k, n))
    out_specs = [slab, slab]
    out_shapes = [
        _out_sds((G, Kp, Np), jnp.float32, lhs, g, u, dmid),
        _out_sds((G, Kp, Np), jnp.float32, lhs, g, u, dmid),
    ]
    if has_bias:
        brow = pl.BlockSpec((1, 1, tn), lambda k, n, w, wg, wt, ws, we: (wg[w], 0, n))
        out_specs += [brow, brow]
        out_shapes += [
            _out_sds((G, 1, Np), jnp.float32, g, dmid),
            _out_sds((G, 1, Np), jnp.float32, u, dmid),
        ]
    outs = pl.pallas_call(
        functools.partial(
            _bwd_gu_kernel, tm=tm, act_kind=act_kind, limit=limit,
            has_bias=has_bias,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
        ),
        out_shape=out_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, lhs, g, u, dmid)
    nz = (group_sizes > 0)
    dwg = jnp.where(nz[:, None, None], outs[0][:, :D, :I], 0.0)
    dwu = jnp.where(nz[:, None, None], outs[1][:, :D, :I], 0.0)
    if not has_bias:
        return dwg, dwu, None, None
    dgb = jnp.where(nz[:, None], outs[2][:, 0, :I], 0.0)
    dub = jnp.where(nz[:, None], outs[3][:, 0, :I], 0.0)
    return dwg, dwu, dgb, dub


def _bwd_dwd_kernel(wg, wt, ws, we, g_ref, u_ref, dy_ref, dwd_ref, *rest,
                    tm, act_kind, limit, want_db):
    if want_db:
        (ddb_ref,) = rest
    w = pl.program_id(2)
    rows = wt[w] * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    mask = (rows >= ws[w]) & (rows < we[w])
    mid = _act_core(
        g_ref[...].astype(jnp.float32), u_ref[...].astype(jnp.float32),
        act_kind, limit,
    )
    mid = jnp.where(mask, mid, 0.0)
    # dy's sentinel tail is masked here, in-kernel — the external dy_m
    # select the composed backward paid per [M, D] is gone
    dy = jnp.where(mask, dy_ref[...], jnp.zeros_like(dy_ref))
    first = jnp.logical_or(w == 0, wg[jnp.maximum(w - 1, 0)] != wg[w])
    acc = jax.lax.dot_general(
        mid.astype(dy_ref.dtype), dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cur = dwd_ref[0]
    dwd_ref[0] = acc + jnp.where(first, jnp.zeros_like(cur), cur)
    if want_db:
        # same rewrite-per-k-pass rule as the gu kernel's bias rows
        cur = ddb_ref[0]
        ddb_ref[0] = dy.astype(jnp.float32).sum(axis=0, keepdims=True) + jnp.where(
            first, jnp.zeros_like(cur), cur
        )


def _bwd_dwd(g, u, dy, group_sizes, act_kind, limit, interpret, want_db):
    """Down-proj transpose GEMM with the activation mid recomputed in-kernel
    → (dWd [G,I,D] f32, ddb [G,D] f32 | None)."""
    from automodel_tpu.ops.grouped_matmul import _out_sds

    M, I = g.shape
    _, D = dy.shape
    G = group_sizes.shape[0]
    tm, tk, tn = _bwd_dwd_tiles(I, D, g.dtype)
    Mp, Kp, Np = _round_up(M, tm), _round_up(I, tk), _round_up(D, tn)
    if (Mp, Kp) != (M, I):
        pad = ((0, Mp - M), (0, Kp - I))
        g, u = jnp.pad(g, pad), jnp.pad(u, pad)
    if (Mp, Np) != (M, D):
        dy = jnp.pad(dy, ((0, Mp - M), (0, Np - D)))
    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G
    grid = (Kp // tk, Np // tn, W)
    in_specs = [
        pl.BlockSpec((tm, tk), lambda k, n, w, wg, wt, ws, we: (wt[w], k)),
        pl.BlockSpec((tm, tk), lambda k, n, w, wg, wt, ws, we: (wt[w], k)),
        pl.BlockSpec((tm, tn), lambda k, n, w, wg, wt, ws, we: (wt[w], n)),
    ]
    out_specs = [
        pl.BlockSpec((1, tk, tn), lambda k, n, w, wg, wt, ws, we: (wg[w], k, n)),
    ]
    out_shapes = [_out_sds((G, Kp, Np), jnp.float32, g, u, dy)]
    if want_db:
        out_specs.append(
            pl.BlockSpec((1, 1, tn), lambda k, n, w, wg, wt, ws, we: (wg[w], 0, n))
        )
        out_shapes.append(_out_sds((G, 1, Np), jnp.float32, dy))
    outs = pl.pallas_call(
        functools.partial(
            _bwd_dwd_kernel, tm=tm, act_kind=act_kind, limit=limit,
            want_db=want_db,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
        ),
        out_shape=out_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, g, u, dy)
    nz = (group_sizes > 0)
    dwd = jnp.where(nz[:, None, None], outs[0][:, :I, :D], 0.0)
    ddb = jnp.where(nz[:, None], outs[1][:, 0, :D], 0.0) if want_db else None
    return dwd, ddb


def _bwd_dx_kernel(wg, wt, ws, we, g_ref, u_ref, dmid_ref, gate_ref, up_ref,
                   out_ref, acc, *, tm, n_ic, act_kind, limit, W):
    w = pl.program_id(1)
    i = pl.program_id(2)
    t = wt[w]
    first = jnp.logical_or(w == 0, wt[jnp.maximum(w - 1, 0)] != t)
    last = jnp.logical_or(w == W - 1, wt[jnp.minimum(w + 1, W - 1)] != t)

    @pl.when(jnp.logical_and(i == 0, first))
    def _():
        acc[...] = jnp.zeros_like(acc)

    rows = t * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    mask = (rows >= ws[w]) & (rows < we[w])
    dg, du = _act_grads(g_ref[...], u_ref[...], dmid_ref[...], act_kind, limit)
    # boundary tiles: the other group's rows must not meet THIS group's
    # weights — mask before the contraction (accumulation across consecutive
    # work units blends the two groups' halves, exactly like the forward)
    dg = jnp.where(mask, dg, 0.0)
    du = jnp.where(mask, du, 0.0)
    cd = out_ref.dtype
    acc[...] += jax.lax.dot_general(
        dg.astype(cd), gate_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + jax.lax.dot_general(
        du.astype(cd), up_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(jnp.logical_and(i == n_ic - 1, last))
    def _():
        out_ref[...] = acc[...].astype(cd)


def _bwd_dx(g, u, dmid, gate, up, group_sizes, interpret, act_kind, limit):
    """dlhs = dg·Wg^T + du·Wu^T in one kernel, I-chunked with an fp32
    accumulator (the forward's summable-contraction trick, transposed).
    Sentinel-tail rows come out zero or stay unwritten — the a2a consumer
    never reads them (ragged_dot precondition)."""
    from automodel_tpu.ops.grouped_matmul import _out_sds

    M, I = g.shape
    G, D, _ = gate.shape
    tm, tn, ic = _bwd_dx_tiles(D, I, g.dtype)
    Mp, Np, Ip = _round_up(M, tm), _round_up(D, tn), _round_up(I, ic)
    if (Mp, Ip) != (M, I):
        pad = ((0, Mp - M), (0, Ip - I))
        g, u, dmid = jnp.pad(g, pad), jnp.pad(u, pad), jnp.pad(dmid, pad)
    if (Np, Ip) != (D, I):
        wpad = ((0, 0), (0, Np - D), (0, Ip - I))
        gate, up = jnp.pad(gate, wpad), jnp.pad(up, wpad)
    n_ic = Ip // ic
    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G
    grid = (Np // tn, W, n_ic)
    mrow = pl.BlockSpec((tm, ic), lambda n, w, i, wg, wt, ws, we: (wt[w], i))
    wslab = pl.BlockSpec((1, tn, ic), lambda n, w, i, wg, wt, ws, we: (wg[w], n, i))
    out = pl.pallas_call(
        functools.partial(
            _bwd_dx_kernel, tm=tm, n_ic=n_ic, act_kind=act_kind, limit=limit,
            W=W,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[mrow, mrow, mrow, wslab, wslab],
            out_specs=pl.BlockSpec(
                (tm, tn), lambda n, w, i, wg, wt, ws, we: (wt[w], n)
            ),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=_out_sds((Mp, Np), g.dtype, g, u, dmid, gate, up),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, g, u, dmid, gate, up)
    return out[:M, :D]


def _fused_bwd_enabled() -> bool:
    """AUTOMODEL_FUSED_BWD=0 falls back to the r5 composed-tgmm backward —
    the A/B knob tools/kernel_bench.py races and a safety valve for a chip
    where the purpose-tiled kernels regress."""
    return os.environ.get("AUTOMODEL_FUSED_BWD", "1") != "0"


def _vjp_bwd(act_kind, limit, platform, interpret, res, dy):
    from automodel_tpu.ops.grouped_matmul import (
        _match_vma,
        _pallas_eligible,
    )

    lhs, gate, up, down, group_sizes, gb, ub, db = res
    if interpret is None:
        interpret = _interpret_requested()
    mv = lambda ct, p: None if ct is None else _match_vma(ct, p)

    if not (interpret or _pallas_eligible(platform)):
        # non-pallas backends: AD through the XLA composition
        def f(args):
            lhs_, g_, u_, d_, gb_, ub_, db_ = args
            return _reference(lhs_, g_, u_, d_, group_sizes, gb_, ub_, db_,
                              act_kind, limit, platform)

        _, vjp = jax.vjp(f, (lhs, gate, up, down, gb, ub, db))
        (dl, dg_, du_, dd, dgb, dub, ddb), = vjp(dy)
        return (
            mv(dl, lhs), mv(dg_, gate), mv(du_, up), mv(dd, down), None,
            mv(dgb, gb), mv(dub, ub), mv(ddb, db),
        )

    if not _fused_bwd_enabled():
        return _vjp_bwd_composed(
            act_kind, limit, platform, interpret, res, dy, mv
        )

    # purpose-tiled manual backward: recompute the two cheap gate_up GEMMs
    # (g, u) and the dmid transpose GEMM, then run the three fused kernels.
    # vs the r5 composed backward this never materializes mid/dg/du (or
    # their masked copies), reads lhs once for both weight grads, and folds
    # the sentinel-tail dout mask + the bias-grad row sums in-kernel:
    # 6 grouped passes total vs 8 + five [M, N]-sized selects/elementwise
    # round trips.
    kw = dict(platform=platform, interpret=interpret)
    M = lhs.shape[0]
    G = gate.shape[0]
    g = ragged_dot(lhs, gate, group_sizes, **kw)
    u = ragged_dot(lhs, up, group_sizes, **kw)
    has_bias = gb is not None or ub is not None or db is not None
    if has_bias:
        bounds = jnp.cumsum(group_sizes.astype(jnp.int32))
        valid = (jnp.arange(M, dtype=jnp.int32) < bounds[-1])[:, None]
        row_g = jnp.searchsorted(
            bounds, jnp.arange(M, dtype=jnp.int32), side="right"
        )
        # tail rows land on row_g == G: clamp the gather index explicitly
        # and zero the gathered bias under the mask — never rely on XLA's
        # out-of-bounds clamp semantics for rows whose content is garbage
        # anyway
        row_gc = jnp.minimum(row_g, G - 1)
    if gb is not None:
        g = g + jnp.where(valid, gb.astype(g.dtype)[row_gc], 0)
    if ub is not None:
        u = u + jnp.where(valid, ub.astype(u.dtype)[row_gc], 0)

    dmid = ragged_dot(dy, down, group_sizes, transpose_rhs=True, **kw)
    dWd, ddb = _bwd_dwd(
        g, u, dy, group_sizes, act_kind, limit, interpret, db is not None
    )
    dWg, dWu, dgb, dub = _bwd_gu(
        lhs, g, u, dmid, group_sizes, act_kind, limit, interpret,
        gb is not None or ub is not None,
    )
    # dlhs tail rows stay zero/uninitialized — they ARE the sentinel tail,
    # and the a2a consumer never reads them (ragged_dot precondition)
    dlhs = _bwd_dx(g, u, dmid, gate, up, group_sizes, interpret, act_kind,
                   limit)
    return (
        mv(dlhs.astype(lhs.dtype), lhs),
        mv(dWg.astype(gate.dtype), gate),
        mv(dWu.astype(up.dtype), up),
        mv(dWd.astype(down.dtype), down),
        None,
        mv(dgb.astype(gb.dtype), gb) if gb is not None else None,
        mv(dub.astype(ub.dtype), ub) if ub is not None else None,
        mv(ddb.astype(db.dtype), db) if db is not None else None,
    )


def _vjp_bwd_composed(act_kind, limit, platform, interpret, res, dy, mv):
    """The r5 manual backward: generic _tgmm/ragged_dot composition with
    external tail masks. Kept verbatim behind AUTOMODEL_FUSED_BWD=0 as the
    kernel-bench A/B baseline."""
    from automodel_tpu.ops.grouped_matmul import _tgmm

    lhs, gate, up, down, group_sizes, gb, ub, db = res
    kw = dict(platform=platform, interpret=interpret)
    M = lhs.shape[0]
    G = gate.shape[0]
    g = ragged_dot(lhs, gate, group_sizes, **kw)
    u = ragged_dot(lhs, up, group_sizes, **kw)
    # rows past sum(group_sizes) (the a2a sentinel tail) are uninitialized
    # in every ragged_dot/_tgmm output AND in the a2a cotangents (dy). Zero
    # one-hot rows and the _tgmm kernel's in-tile lhs mask both rely on
    # 0·x = 0 with FINITE x — NaN/Inf garbage survives them (0·NaN = NaN),
    # so every contraction that reduces over rows (seg_sum, and the dout
    # operand of each _tgmm) gets an explicit zero-mask first. The mask is
    # one [M, 1] compare broadcast into the selects — backward-only cost.
    bounds = jnp.cumsum(group_sizes.astype(jnp.int32))
    valid = (jnp.arange(M, dtype=jnp.int32) < bounds[-1])[:, None]
    has_bias = gb is not None or ub is not None or db is not None
    if has_bias:
        row_g = jnp.searchsorted(
            bounds, jnp.arange(M, dtype=jnp.int32), side="right"
        )
        row_gc = jnp.minimum(row_g, G - 1)
        onehot = jax.nn.one_hot(row_g, G, dtype=lhs.dtype)  # [M, G]
    if gb is not None:
        g = g + jnp.where(valid, gb.astype(g.dtype)[row_gc], 0)
    if ub is not None:
        u = u + jnp.where(valid, ub.astype(u.dtype)[row_gc], 0)

    mid, act_vjp = jax.vjp(
        lambda g_, u_: _act_fn(g_, u_, act_kind, limit), g, u
    )
    dy_m = jnp.where(valid, dy, 0)
    dmid = ragged_dot(dy, down, group_sizes, transpose_rhs=True, **kw)
    dWd = _tgmm(mid, dy_m, group_sizes, interpret=interpret)
    dg_, du_ = act_vjp(dmid)
    dg_m = jnp.where(valid, dg_, 0)
    du_m = jnp.where(valid, du_, 0)
    dlhs = (
        ragged_dot(dg_, gate, group_sizes, transpose_rhs=True, **kw)
        + ragged_dot(du_, up, group_sizes, transpose_rhs=True, **kw)
    )
    dWg = _tgmm(lhs, dg_m, group_sizes, interpret=interpret)
    dWu = _tgmm(lhs, du_m, group_sizes, interpret=interpret)

    def seg_sum(ct):  # [M, N] (tail pre-masked) → per-expert sums [G, N]
        return jax.lax.dot_general(
            onehot, ct, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dgb = seg_sum(dg_m).astype(gb.dtype) if gb is not None else None
    dub = seg_sum(du_m).astype(ub.dtype) if ub is not None else None
    ddb = seg_sum(dy_m).astype(db.dtype) if db is not None else None
    return (
        mv(dlhs.astype(lhs.dtype), lhs),
        mv(dWg.astype(gate.dtype), gate),
        mv(dWu.astype(up.dtype), up),
        mv(dWd.astype(down.dtype), down),
        None,
        mv(dgb, gb), mv(dub, ub), mv(ddb, db),
    )


fused_expert_mlp.defvjp(_vjp_fwd, _vjp_bwd)
