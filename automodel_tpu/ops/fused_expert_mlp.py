"""Fused MoE expert MLP: gate_up matmul + gated activation + down matmul in
ONE Pallas kernel (forward only; the backward recomputes through the
separate grouped matmuls).

Motivation (PROFILE_MOE_r04.md): the two-kernel expert path writes the
[T·K, 2I] gate_up output and the [T·K, I] activation to HBM and reads them
back (~600MB per layer at bench shape). Here both stay in VMEM: per work
unit (m-tile × group) the kernel loops I-chunks on the grid, computing
``acc += act(lhs @ Wgu[:, chunk]) @ Wd[chunk, :]`` with an fp32 accumulator
— the down-projection contraction is summable over I-chunks, so the
intermediate never materializes. Rows are lhs-masked (write-only outputs;
boundary tiles accumulate across consecutive work units like
ops/grouped_matmul._tgmm).

Same dropless semantics and work-unit plan as ops/grouped_matmul (reference
capability: the fused SwiGLU+GEMM epilogues TE/DeepEP provide on GPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from automodel_tpu.ops.grouped_matmul import (
    _interpret_requested,
    _pallas_eligible,
    _plan,
    _round_up,
    ragged_dot,
)


def _kernel(wg, wt, ws, we, lhs_ref, wgu_ref, wd_ref, out_ref, acc,
            *, tm, n_ic, act_kind, limit, W):
    w = pl.program_id(0)
    ic = pl.program_id(1)
    t = wt[w]
    first = jnp.logical_or(w == 0, wt[jnp.maximum(w - 1, 0)] != t)
    last = jnp.logical_or(w == W - 1, wt[jnp.minimum(w + 1, W - 1)] != t)

    @pl.when(jnp.logical_and(ic == 0, first))
    def _():
        acc[...] = jnp.zeros_like(acc)

    rows = t * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    lmask = (rows >= ws[w]) & (rows < we[w])
    lhs = jnp.where(lmask, lhs_ref[...], jnp.zeros_like(lhs_ref))

    gu = jax.lax.dot_general(
        lhs, wgu_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [tm, 2*ic_size]
    half = gu.shape[-1] // 2
    g, u = gu[:, :half], gu[:, half:]
    if act_kind == "swiglu_oai":
        g = jnp.minimum(g, 7.0)
        u = jnp.clip(u, -7.0, 7.0)
        mid = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    else:
        mid = jax.nn.silu(g)
        if limit is not None:
            mid = jnp.minimum(mid, limit)
            u = jnp.clip(u, -limit, limit)
        mid = mid * u
    acc[...] += jax.lax.dot_general(
        mid.astype(lhs_ref.dtype), wd_ref[0, 0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(jnp.logical_and(ic == n_ic - 1, last))
    def _():
        out_ref[...] = acc[...].astype(out_ref.dtype)


def _fwd(lhs, gate, up, down, group_sizes, act_kind, limit, interpret):
    """lhs [M, D] sorted by group; gate/up [G, D, I] (pre-split halves);
    down [G, I, D] → [M, D]."""
    M, D = lhs.shape
    G, _, I = gate.shape
    tm = 512
    ic = min(_round_up(I, 128), 512)
    Mp, Dp, Ip = _round_up(M, tm), _round_up(D, 128), _round_up(I, ic)
    if (Mp, Dp) != (M, D):
        lhs = jnp.pad(lhs, ((0, Mp - M), (0, Dp - D)))
    if (Dp, Ip) != (D, I):
        gate = jnp.pad(gate, ((0, 0), (0, Dp - D), (0, Ip - I)))
        up = jnp.pad(up, ((0, 0), (0, Dp - D), (0, Ip - I)))
        down = jnp.pad(down, ((0, 0), (0, Ip - I), (0, Dp - D)))
    # interleave [gate_chunk | up_chunk] per I-chunk so one rhs block carries
    # both halves of the chunk
    n_ic = Ip // ic
    wgu = jnp.concatenate(
        [gate.reshape(G, Dp, n_ic, ic), up.reshape(G, Dp, n_ic, ic)], axis=-1
    )  # [G, Dp, n_ic, 2ic]
    wgu = wgu.transpose(0, 2, 1, 3).reshape(G, n_ic, Dp, 2 * ic)
    wd = down.reshape(G, n_ic, ic, Dp)

    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G

    out = pl.pallas_call(
        functools.partial(
            _kernel, tm=tm, n_ic=n_ic, act_kind=act_kind, limit=limit, W=W
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(W, n_ic),
            in_specs=[
                pl.BlockSpec((tm, Dp), lambda w, i, wg, wt, ws, we: (wt[w], 0)),
                pl.BlockSpec(
                    (1, 1, Dp, 2 * ic),
                    lambda w, i, wg, wt, ws, we: (wg[w], i, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, ic, Dp), lambda w, i, wg, wt, ws, we: (wg[w], i, 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (tm, Dp), lambda w, i, wg, wt, ws, we: (wt[w], 0)
            ),
            scratch_shapes=[pltpu.VMEM((tm, Dp), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((Mp, Dp), lhs.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, lhs, wgu, wd)
    return out[:M, :D]


def _reference(lhs, gate, up, down, group_sizes, act_kind, limit, platform):
    """The two-grouped-matmul composition — the backward path and the
    numerics reference."""
    gu_g = ragged_dot(lhs, gate, group_sizes, platform=platform)
    gu_u = ragged_dot(lhs, up, group_sizes, platform=platform)
    if act_kind == "swiglu_oai":
        g = jnp.minimum(gu_g, 7.0)
        u = jnp.clip(gu_u, -7.0, 7.0)
        mid = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    else:
        mid = jax.nn.silu(gu_g)
        if limit is not None:
            mid = jnp.minimum(mid, limit)
            gu_u = jnp.clip(gu_u, -limit, limit)
        mid = mid * gu_u
    return ragged_dot(mid.astype(lhs.dtype), down, group_sizes, platform=platform)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def fused_expert_mlp(lhs, gate, up, down, group_sizes,
                     act_kind="swiglu", limit=None, platform=None,
                     interpret=None):
    """Forward through the fused kernel; backward recomputes via the
    composition (the standard fused-fwd/recompute-bwd trade: the fwd —
    which remat re-runs — saves the HBM round trips; the bwd needs the
    intermediates anyway)."""
    if interpret is None:
        interpret = _interpret_requested()
    if not (interpret or _pallas_eligible(platform)):
        return _reference(lhs, gate, up, down, group_sizes, act_kind, limit, platform)
    return _fwd(lhs, gate, up, down, group_sizes, act_kind, limit, interpret)


def _vjp_fwd(lhs, gate, up, down, group_sizes, act_kind, limit, platform, interpret):
    y = fused_expert_mlp(
        lhs, gate, up, down, group_sizes, act_kind, limit, platform, interpret
    )
    return y, (lhs, gate, up, down, group_sizes)


def _vjp_bwd(act_kind, limit, platform, interpret, res, dy):
    lhs, gate, up, down, group_sizes = res

    def f(args):
        lhs_, g_, u_, d_ = args
        return _reference(lhs_, g_, u_, d_, group_sizes, act_kind, limit, platform)

    _, vjp = jax.vjp(f, (lhs, gate, up, down))
    (dl, dg, du, dd), = vjp(dy)
    return dl, dg, du, dd, None


fused_expert_mlp.defvjp(_vjp_fwd, _vjp_bwd)
