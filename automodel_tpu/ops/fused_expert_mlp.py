"""Fused MoE expert MLP: gate_up matmul + gated activation + down matmul in
ONE Pallas kernel (forward only; the backward recomputes through the
separate grouped matmuls).

Motivation (PROFILE_MOE_r04.md): the two-kernel expert path writes the
[T·K, 2I] gate_up output and the [T·K, I] activation to HBM and reads them
back (~600MB per layer at bench shape). Here both stay in VMEM: per work
unit (m-tile × group) the kernel loops I-chunks on the grid, computing
``acc += act(lhs @ Wgu[:, chunk]) @ Wd[chunk, :]`` with an fp32 accumulator
— the down-projection contraction is summable over I-chunks, so the
intermediate never materializes. Rows are lhs-masked (write-only outputs;
boundary tiles accumulate across consecutive work units like
ops/grouped_matmul._tgmm).

Same dropless semantics and work-unit plan as ops/grouped_matmul (reference
capability: the fused SwiGLU+GEMM epilogues TE/DeepEP provide on GPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from automodel_tpu.utils.compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()

from automodel_tpu.ops.grouped_matmul import (
    _interpret_requested,
    _pallas_eligible,
    _plan,
    _round_up,
    ragged_dot,
)


def _kernel(wg, wt, ws, we, lhs_ref, wg_ref, wu_ref, wd_ref, *rest,
            tm, n_ic, act_kind, limit, W, has_bias):
    if has_bias:
        gb_ref, ub_ref, db_ref, out_ref, acc = rest
    else:
        out_ref, acc = rest
    w = pl.program_id(0)
    ic = pl.program_id(1)
    t = wt[w]
    first = jnp.logical_or(w == 0, wt[jnp.maximum(w - 1, 0)] != t)
    last = jnp.logical_or(w == W - 1, wt[jnp.minimum(w + 1, W - 1)] != t)

    @pl.when(jnp.logical_and(ic == 0, first))
    def _():
        acc[...] = jnp.zeros_like(acc)

    rows = t * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    lmask = (rows >= ws[w]) & (rows < we[w])
    lhs = jnp.where(lmask, lhs_ref[...], jnp.zeros_like(lhs_ref))

    # gate and up are SEPARATE operands blocked straight from the stored
    # [G, D, I] layout — an interleaved [G, D, 2I] operand would need a
    # host-side concat + transpose whose AD transpose leaks a non-default
    # layout onto the weight grads, forcing full-size fp32 relayout copies
    # in every downstream elementwise consumer (optimizer, grad-norm)
    g = jax.lax.dot_general(
        lhs, wg_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [tm, ic_size]
    u = jax.lax.dot_general(
        lhs, wu_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if has_bias:
        g = g + gb_ref[0, 0, 0].astype(jnp.float32)
        u = u + ub_ref[0, 0, 0].astype(jnp.float32)
        # gpt-oss-style expert biases: once added, masked rows are no longer
        # zero (act(bias)·Wd ≠ 0) — re-mask mid before the down contraction
        # and gate the down bias on the same row window (each work unit adds
        # it exactly once, on its first I-chunk, to its own rows only).
        @pl.when(ic == 0)
        def _():
            acc[...] += jnp.where(
                lmask, db_ref[0, 0].astype(jnp.float32), 0.0
            )
    if act_kind == "swiglu_oai":
        g = jnp.minimum(g, 7.0)
        u = jnp.clip(u, -7.0, 7.0)
        mid = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    else:
        mid = jax.nn.silu(g)
        if limit is not None:
            mid = jnp.minimum(mid, limit)
            u = jnp.clip(u, -limit, limit)
        mid = mid * u
    if has_bias:
        mid = jnp.where(lmask, mid, 0.0)
    acc[...] += jax.lax.dot_general(
        mid.astype(lhs_ref.dtype), wd_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(jnp.logical_and(ic == n_ic - 1, last))
    def _():
        out_ref[...] = acc[...].astype(out_ref.dtype)


def _fwd(lhs, gate, up, down, group_sizes, gb, ub, db, act_kind, limit,
         interpret):
    """lhs [M, D] sorted by group; gate/up [G, D, I] (pre-split halves);
    down [G, I, D]; optional per-expert biases gb/ub [G, I], db [G, D]
    (gpt-oss) → [M, D]."""
    M, D = lhs.shape
    G, _, I = gate.shape
    has_bias = gb is not None or ub is not None or db is not None
    tm = 512
    Dp = _round_up(D, 128)
    # I-chunk: largest 128-multiple ≤512 that divides the 128-padded I —
    # a non-divisor pads I up to a chunk multiple and burns the padding as
    # real matmul work (I=768 with ic=512 pads to 1024: +33% expert FLOPs,
    # measured 29.4% vs 31.5% MFU on the qwen-style bench fingerprint)
    I128 = _round_up(I, 128)
    _IC_CANDS = (512, 384, 256, 128)
    # 128 divides any I128, so this always finds a divisor
    ic = next(c for c in _IC_CANDS if c <= I128 and I128 % c == 0)

    def _vmem(tm_, ic_):
        # double-buffered input blocks + output + fp32 accumulator; must stay
        # under the ~16MB scoped-vmem stack (Mosaic rejects the kernel at
        # compile otherwise — hit at D=1536 with the 512/512 tiles)
        return (
            2 * (tm_ * Dp * 2)          # lhs
            + 2 * (Dp * 2 * ic_ * 2)    # wgu chunk
            + 2 * (ic_ * Dp * 2)        # wd chunk
            + 2 * (tm_ * Dp * 2)        # out
            + tm_ * Dp * 4              # acc scratch
        )

    while _vmem(tm, ic) > 14 * 1024 * 1024 and tm > 256:
        tm //= 2
    while _vmem(tm, ic) > 14 * 1024 * 1024:
        smaller = [c for c in _IC_CANDS if c < ic and I128 % c == 0]
        if not smaller:
            break
        ic = smaller[0]
    Mp, Ip = _round_up(M, tm), _round_up(I128, ic)
    if (Mp, Dp) != (M, D):
        lhs = jnp.pad(lhs, ((0, Mp - M), (0, Dp - D)))
    if (Dp, Ip) != (D, I):
        gate = jnp.pad(gate, ((0, 0), (0, Dp - D), (0, Ip - I)))
        up = jnp.pad(up, ((0, 0), (0, Dp - D), (0, Ip - I)))
        down = jnp.pad(down, ((0, 0), (0, Ip - I), (0, Dp - D)))
    # gate/up/down are blocked DIRECTLY from their stored [G, D, I] /
    # [G, I, D] layouts — no concat, no transpose: a transposed weight
    # operand's AD transpose emits the weight grads in a non-default layout,
    # and every fp32 elementwise consumer downstream (Adam, grad-norm) then
    # pays a full-size relayout copy (2.25GB per stacked expert tensor at
    # the MoE bench shape; the difference between fitting and OOM on 16GB)
    n_ic = Ip // ic

    operands = [lhs, gate, up, down]
    in_specs = [
        pl.BlockSpec((tm, Dp), lambda w, i, wg, wt, ws, we: (wt[w], 0)),
        pl.BlockSpec((1, Dp, ic), lambda w, i, wg, wt, ws, we: (wg[w], 0, i)),
        pl.BlockSpec((1, Dp, ic), lambda w, i, wg, wt, ws, we: (wg[w], 0, i)),
        pl.BlockSpec((1, ic, Dp), lambda w, i, wg, wt, ws, we: (wg[w], i, 0)),
    ]
    if has_bias:
        zeros_i = jnp.zeros((G, I), lhs.dtype)
        gb = zeros_i if gb is None else gb
        ub = zeros_i if ub is None else ub
        db = jnp.zeros((G, D), lhs.dtype) if db is None else db
        # the unit axis before the lane dim keeps Mosaic's sublane tiling
        # rule satisfied (block dim == array dim == 1); without it a block
        # of 1 over the G (resp. n_ic) sublane axis fails lowering
        gb = jnp.pad(gb, ((0, 0), (0, Ip - I))).reshape(G, n_ic, 1, ic)
        ub = jnp.pad(ub, ((0, 0), (0, Ip - I))).reshape(G, n_ic, 1, ic)
        operands += [
            gb, ub, jnp.pad(db, ((0, 0), (0, Dp - D))).reshape(G, 1, Dp)
        ]
        in_specs += [
            pl.BlockSpec(
                (1, 1, 1, ic), lambda w, i, wg, wt, ws, we: (wg[w], i, 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, 1, ic), lambda w, i, wg, wt, ws, we: (wg[w], i, 0, 0)
            ),
            pl.BlockSpec((1, 1, Dp), lambda w, i, wg, wt, ws, we: (wg[w], 0, 0)),
        ]

    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G

    # inside a check_vma shard_map region (the a2a_fused EP path) the
    # pallas_call output aval must carry the manual-axes vma explicitly
    from automodel_tpu.ops.grouped_matmul import _out_sds

    out_sds = _out_sds((Mp, Dp), lhs.dtype, lhs, gate, up, down)

    out = pl.pallas_call(
        functools.partial(
            _kernel, tm=tm, n_ic=n_ic, act_kind=act_kind, limit=limit, W=W,
            has_bias=has_bias,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(W, n_ic),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (tm, Dp), lambda w, i, wg, wt, ws, we: (wt[w], 0)
            ),
            scratch_shapes=[pltpu.VMEM((tm, Dp), jnp.float32)],
        ),
        out_shape=out_sds,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, *operands)
    return out[:M, :D]


def _reference(lhs, gate, up, down, group_sizes, gb, ub, db, act_kind, limit,
               platform):
    """The two-grouped-matmul composition — the backward path and the
    numerics reference."""
    gu_g = ragged_dot(lhs, gate, group_sizes, platform=platform)
    gu_u = ragged_dot(lhs, up, group_sizes, platform=platform)
    if gb is not None or ub is not None or db is not None:
        # row r belongs to group g iff cumsum[g-1] <= r < cumsum[g]
        bounds = jnp.cumsum(group_sizes.astype(jnp.int32))
        row_g = jnp.searchsorted(
            bounds, jnp.arange(lhs.shape[0], dtype=jnp.int32), side="right"
        )
    if gb is not None:
        gu_g = gu_g + gb.astype(gu_g.dtype)[row_g]
    if ub is not None:
        gu_u = gu_u + ub.astype(gu_u.dtype)[row_g]
    if act_kind == "swiglu_oai":
        g = jnp.minimum(gu_g, 7.0)
        u = jnp.clip(gu_u, -7.0, 7.0)
        mid = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    else:
        mid = jax.nn.silu(gu_g)
        if limit is not None:
            mid = jnp.minimum(mid, limit)
            gu_u = jnp.clip(gu_u, -limit, limit)
        mid = mid * gu_u
    out = ragged_dot(mid.astype(lhs.dtype), down, group_sizes, platform=platform)
    if db is not None:
        out = out + db.astype(out.dtype)[row_g]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def fused_expert_mlp(lhs, gate, up, down, group_sizes,
                     gb=None, ub=None, db=None,
                     act_kind="swiglu", limit=None, platform=None,
                     interpret=None):
    """Forward through the fused kernel; backward recomputes via the
    composition (the standard fused-fwd/recompute-bwd trade: the fwd —
    which remat re-runs — saves the HBM round trips; the bwd needs the
    intermediates anyway)."""
    if interpret is None:
        interpret = _interpret_requested()
    if not (interpret or _pallas_eligible(platform)):
        return _reference(lhs, gate, up, down, group_sizes, gb, ub, db,
                          act_kind, limit, platform)
    return _fwd(lhs, gate, up, down, group_sizes, gb, ub, db, act_kind, limit,
                interpret)


def _vjp_fwd(lhs, gate, up, down, group_sizes, gb, ub, db,
             act_kind, limit, platform, interpret):
    y = fused_expert_mlp(
        lhs, gate, up, down, group_sizes, gb, ub, db,
        act_kind, limit, platform, interpret
    )
    return y, (lhs, gate, up, down, group_sizes, gb, ub, db)


def _act_fn(g, u, act_kind, limit):
    """The post-bias elementwise activation, in fp32 internally (matches the
    kernel); jax.vjp of THIS gives exact clamp-aware derivatives."""
    g32, u32 = g.astype(jnp.float32), u.astype(jnp.float32)
    if act_kind == "swiglu_oai":
        gc = jnp.minimum(g32, 7.0)
        uc = jnp.clip(u32, -7.0, 7.0)
        mid = (uc + 1.0) * (gc * jax.nn.sigmoid(1.702 * gc))
    else:
        mid = jax.nn.silu(g32)
        if limit is not None:
            mid = jnp.minimum(mid, limit)
            u32 = jnp.clip(u32, -limit, limit)
        mid = mid * u32
    return mid.astype(g.dtype)


def _vjp_bwd(act_kind, limit, platform, interpret, res, dy):
    from automodel_tpu.ops.grouped_matmul import (
        _match_vma,
        _pallas_eligible,
        _tgmm,
    )

    lhs, gate, up, down, group_sizes, gb, ub, db = res
    if interpret is None:
        interpret = _interpret_requested()
    mv = lambda ct, p: None if ct is None else _match_vma(ct, p)

    if not (interpret or _pallas_eligible(platform)):
        # non-pallas backends: AD through the XLA composition
        def f(args):
            lhs_, g_, u_, d_, gb_, ub_, db_ = args
            return _reference(lhs_, g_, u_, d_, group_sizes, gb_, ub_, db_,
                              act_kind, limit, platform)

        _, vjp = jax.vjp(f, (lhs, gate, up, down, gb, ub, db))
        (dl, dg_, du_, dd, dgb, dub, ddb), = vjp(dy)
        return (
            mv(dl, lhs), mv(dg_, gate), mv(du_, up), mv(dd, down), None,
            mv(dgb, gb), mv(dub, ub), mv(ddb, db),
        )

    # manual backward on the pallas kernels — vs jax.vjp(_reference) this
    # skips the down-projection forward (its output is dead in the bwd),
    # contracts the weight transposes in-kernel (transpose_rhs — no
    # materialized W^T copies), and computes bias grads as small dense dots
    # instead of the gather-transpose scatter-adds the profile billed at
    # ~1.6ms each: 8 grouped passes total vs ~12 + 3 scatters.
    kw = dict(platform=platform, interpret=interpret)
    M = lhs.shape[0]
    G = gate.shape[0]
    g = ragged_dot(lhs, gate, group_sizes, **kw)
    u = ragged_dot(lhs, up, group_sizes, **kw)
    # rows past sum(group_sizes) (the a2a sentinel tail) are uninitialized
    # in every ragged_dot/_tgmm output AND in the a2a cotangents (dy). Zero
    # one-hot rows and the _tgmm kernel's in-tile lhs mask both rely on
    # 0·x = 0 with FINITE x — NaN/Inf garbage survives them (0·NaN = NaN),
    # so every contraction that reduces over rows (seg_sum, and the dout
    # operand of each _tgmm) gets an explicit zero-mask first. The mask is
    # one [M, 1] compare broadcast into the selects — backward-only cost.
    bounds = jnp.cumsum(group_sizes.astype(jnp.int32))
    valid = (jnp.arange(M, dtype=jnp.int32) < bounds[-1])[:, None]
    has_bias = gb is not None or ub is not None or db is not None
    if has_bias:
        row_g = jnp.searchsorted(
            bounds, jnp.arange(M, dtype=jnp.int32), side="right"
        )
        # tail rows land on row_g == G: clamp the gather index explicitly
        # and zero the gathered bias under the mask — never rely on XLA's
        # out-of-bounds clamp semantics for rows whose content is garbage
        # anyway
        row_gc = jnp.minimum(row_g, G - 1)
        onehot = jax.nn.one_hot(row_g, G, dtype=lhs.dtype)  # [M, G]
    if gb is not None:
        g = g + jnp.where(valid, gb.astype(g.dtype)[row_gc], 0)
    if ub is not None:
        u = u + jnp.where(valid, ub.astype(u.dtype)[row_gc], 0)

    mid, act_vjp = jax.vjp(
        lambda g_, u_: _act_fn(g_, u_, act_kind, limit), g, u
    )
    dy_m = jnp.where(valid, dy, 0)
    dmid = ragged_dot(dy, down, group_sizes, transpose_rhs=True, **kw)
    dWd = _tgmm(mid, dy_m, group_sizes, interpret=interpret)
    dg_, du_ = act_vjp(dmid)
    dg_m = jnp.where(valid, dg_, 0)
    du_m = jnp.where(valid, du_, 0)
    # dlhs tail rows stay uninitialized — they ARE the sentinel tail, and
    # the a2a consumer never reads them (ragged_dot precondition)
    dlhs = (
        ragged_dot(dg_, gate, group_sizes, transpose_rhs=True, **kw)
        + ragged_dot(du_, up, group_sizes, transpose_rhs=True, **kw)
    )
    dWg = _tgmm(lhs, dg_m, group_sizes, interpret=interpret)
    dWu = _tgmm(lhs, du_m, group_sizes, interpret=interpret)

    def seg_sum(ct):  # [M, N] (tail pre-masked) → per-expert sums [G, N]
        return jax.lax.dot_general(
            onehot, ct, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dgb = seg_sum(dg_m).astype(gb.dtype) if gb is not None else None
    dub = seg_sum(du_m).astype(ub.dtype) if ub is not None else None
    ddb = seg_sum(dy_m).astype(db.dtype) if db is not None else None
    return (
        mv(dlhs.astype(lhs.dtype), lhs),
        mv(dWg.astype(gate.dtype), gate),
        mv(dWu.astype(up.dtype), up),
        mv(dWd.astype(down.dtype), down),
        None,
        mv(dgb, gb), mv(dub, ub), mv(ddb, db),
    )


fused_expert_mlp.defvjp(_vjp_fwd, _vjp_bwd)
