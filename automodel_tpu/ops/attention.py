"""Attention backends.

Parity: the reference switches attn ∈ {te, sdpa, flex} per model
(components/attention/utils.py:25-65). TPU-native backends:

- ``"sdpa"``  — pure-XLA scaled dot-product attention (always available;
  reference-quality numerics; used on CPU tests).
- ``"flash"`` — Pallas TPU flash attention (jax.experimental.pallas.ops.tpu),
  the MXU-tiled kernel path. Falls back to sdpa off-TPU.
- ``"ring"``  — context-parallel ring attention over the ``cp`` mesh axis
  (automodel_tpu.parallel.cp), selected by the parallelism layer.

All backends take BSNH layout (batch, seq, heads, head_dim) and support GQA
via n_kv_heads < n_heads, causal masking, and optional segment ids for packed
(THD-equivalent) sequences — the reference handles packed sequences via TE THD
kernels (cp_utils.py:187-337); here segment ids express the same block-causal
structure.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, N_kv, H] → [B, S, N_kv*n_rep, H] (GQA expansion)."""
    if n_rep == 1:
        return x
    b, s, nkv, h = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, nkv, n_rep, h)).reshape(
        b, s, nkv * n_rep, h
    )


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    sinks: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """XLA scaled dot-product attention. q: [B,S,N,H], k/v: [B,S,Nkv,H].

    ``sinks``: per-head learned sink logits [N] — an extra virtual key that
    absorbs probability mass (gpt-oss; modeling_gpt_oss.py:258: softmax over
    [logits, sink] then drop the sink column).
    """
    b, sq, n, h = q.shape
    n_kv = k.shape[2]
    k = repeat_kv(k, n // n_kv)
    v = repeat_kv(v, n // n_kv)
    scale = scale if scale is not None else 1.0 / (h**0.5)
    logits = jnp.einsum("bqnh,bknh->bnqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if logits_soft_cap is not None:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    sk = k.shape[1]
    mask = jnp.ones((sq, sk), dtype=bool)
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
    if sliding_window is not None:
        pos_q = jnp.arange(sq)[:, None] + (sk - sq)
        pos_k = jnp.arange(sk)[None, :]
        mask = mask & (pos_q - pos_k < sliding_window)
    mask = mask[None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = mask & seg
    logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    if sinks is not None:
        sink_col = jnp.broadcast_to(
            sinks.astype(jnp.float32)[None, :, None, None], (b, n, sq, 1)
        )
        combined = jnp.concatenate([logits, sink_col], axis=-1)
        probs = jax.nn.softmax(combined, axis=-1)[..., :-1].astype(q.dtype)
    else:
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "logits_soft_cap", "sliding_window", "block_q", "block_kv"),
)
def _pallas_flash(
    q, k, v, segment_ids, *, causal, scale, logits_soft_cap, sliding_window, block_q, block_kv
):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
        SegmentIds,
    )

    # pallas kernel wants BNSH layout
    qt = q.transpose(0, 2, 1, 3)
    n, n_kv = q.shape[2], k.shape[2]
    kt = repeat_kv(k, n // n_kv).transpose(0, 2, 1, 3)
    vt = repeat_kv(v, n // n_kv).transpose(0, 2, 1, 3)
    seg = SegmentIds(q=segment_ids, kv=segment_ids) if segment_ids is not None else None
    sq, skv = qt.shape[2], kt.shape[2]
    bs = BlockSizes(
        block_q=min(block_q, sq),
        block_k_major=min(block_kv, skv),
        block_k=min(block_kv, skv),
        block_b=1,
        block_q_major_dkv=min(block_q, sq),
        block_k_major_dkv=min(block_kv, skv),
        block_k_dkv=min(block_kv, skv),
        block_q_dkv=min(block_q, sq),
        block_k_major_dq=min(block_kv, skv),
        block_k_dq=min(block_kv, skv),
        block_q_dq=min(block_q, sq),
    )
    out = flash_attention(
        qt, kt, vt, segment_ids=seg, causal=causal, sm_scale=scale, block_sizes=bs
    )
    return out.transpose(0, 2, 1, 3)


def flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: int = 512,
    block_kv: int = 512,
) -> jnp.ndarray:
    """Pallas TPU flash attention; transparently falls back to sdpa when the
    kernel does not apply (non-TPU backend, soft cap, sliding window, or
    head_dim not MXU-tileable)."""
    h = q.shape[-1]
    on_tpu = jax.devices()[0].platform == "tpu"
    if (
        not on_tpu
        or logits_soft_cap is not None
        or sliding_window is not None
        or h % 128 != 0
        or q.shape[1] % 128 != 0
    ):
        return sdpa(
            q,
            k,
            v,
            causal=causal,
            scale=scale,
            segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )
    scale = scale if scale is not None else 1.0 / (h**0.5)
    return _pallas_flash(
        q,
        k,
        v,
        segment_ids,
        causal=causal,
        scale=scale,
        logits_soft_cap=logits_soft_cap,
        sliding_window=sliding_window,
        block_q=block_q,
        block_kv=block_kv,
    )


def _ring_not_installed(*args, **kwargs):
    raise RuntimeError(
        "attention backend 'ring' needs a mesh: call "
        "automodel_tpu.parallel.cp.install_ring_backend(mesh_ctx) first "
        "(auto_model does this when backend.attn == 'ring')."
    )


ATTENTION_BACKENDS = {
    "sdpa": sdpa,
    "flash": flash,
    "ring": _ring_not_installed,
}


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    backend: str = "sdpa",
    **kwargs,
) -> jnp.ndarray:
    try:
        fn = ATTENTION_BACKENDS[backend]
    except KeyError:
        raise ValueError(
            f"Unknown attention backend {backend!r}; available: {sorted(ATTENTION_BACKENDS)}"
        )
    return fn(q, k, v, **kwargs)
