"""Attention backends.

Parity: the reference switches attn ∈ {te, sdpa, flex} per model
(components/attention/utils.py:25-65). TPU-native backends:

- ``"sdpa"``  — pure-XLA scaled dot-product attention (always available;
  reference-quality numerics; used on CPU tests).
- ``"flash"`` — Pallas TPU flash attention (jax.experimental.pallas.ops.tpu),
  the MXU-tiled kernel path. Falls back to sdpa off-TPU.
- ``"ring"``  — context-parallel ring attention over the ``cp`` mesh axis
  (automodel_tpu.parallel.cp), selected by the parallelism layer.

All backends take BSNH layout (batch, seq, heads, head_dim) and support GQA
via n_kv_heads < n_heads, causal masking, and optional segment ids for packed
(THD-equivalent) sequences — the reference handles packed sequences via TE THD
kernels (cp_utils.py:187-337); here segment ids express the same block-causal
structure.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, N_kv, H] → [B, S, N_kv*n_rep, H] (GQA expansion)."""
    if n_rep == 1:
        return x
    b, s, nkv, h = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, nkv, n_rep, h)).reshape(
        b, s, nkv * n_rep, h
    )


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    sinks: Optional[jnp.ndarray] = None,
    bidir_groups: Optional[jnp.ndarray] = None,
    attn_bias: Optional[jnp.ndarray] = None,
    kv_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """XLA scaled dot-product attention. q: [B,S,N,H], k/v: [B,S,Nkv,H].

    ``kv_mask``: [B, Sk] bool — per-key validity, ANDed onto the mask. The
    KV-cache decode path (generation/) expresses slot validity this way
    (position-tag masks subsume causality/window there, so decode calls pass
    causal=False and let the tags do the masking). A [B, Sq, Sk] mask gives
    PER-QUERY validity — the chunked-prefill path (serving/) uses it so each
    chunk token attends exactly its causal cache prefix.

    ``attn_bias``: additive fp32 bias [B, 1|N, Sq, Sk] applied after scaling
    (DeepSeek-V3.2 sparse top-k mask; TE core_attention_bias equivalent).

    ``sinks``: per-head learned sink logits [N] — an extra virtual key that
    absorbs probability mass (gpt-oss; modeling_gpt_oss.py:258: softmax over
    [logits, sink] then drop the sink column).

    ``bidir_groups``: [B, S] int group ids, -1 for ordinary causal tokens —
    tokens sharing a nonnegative group attend to each other BIDIRECTIONALLY
    (ORed onto the causal/window mask), the gemma-3 image-block rule
    (modeling_gemma3.py token_type_ids_mask_function).
    """
    b, sq, n, h = q.shape
    n_kv = k.shape[2]
    k = repeat_kv(k, n // n_kv)
    v = repeat_kv(v, n // n_kv)
    scale = scale if scale is not None else 1.0 / (h**0.5)
    logits = jnp.einsum("bqnh,bknh->bnqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if attn_bias is not None:
        logits = logits + attn_bias.astype(logits.dtype)
    if logits_soft_cap is not None:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    sk = k.shape[1]
    mask = jnp.ones((sq, sk), dtype=bool)
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
    if sliding_window is not None:
        pos_q = jnp.arange(sq)[:, None] + (sk - sq)
        pos_k = jnp.arange(sk)[None, :]
        mask = mask & (pos_q - pos_k < sliding_window)
    mask = mask[None, None]
    if bidir_groups is not None:
        gq = bidir_groups[:, None, :, None]
        gk = bidir_groups[:, None, None, :]
        mask = mask | ((gq >= 0) & (gq == gk))
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = mask & seg
    if kv_mask is not None:
        mask = mask & (
            kv_mask[:, None, :, :] if kv_mask.ndim == 3
            else kv_mask[:, None, None, :]
        )
    logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    if sinks is not None:
        sink_col = jnp.broadcast_to(
            sinks.astype(jnp.float32)[None, :, None, None], (b, n, sq, 1)
        )
        combined = jnp.concatenate([logits, sink_col], axis=-1)
        probs = jax.nn.softmax(combined, axis=-1)[..., :-1].astype(q.dtype)
    else:
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


def sdpa_decode(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    kv_mask: jnp.ndarray,
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Cache-attending attention for decode AND chunked prefill.

    q: [B, Sq, N, H] (Sq = 1 for single-token decode, the chunk length for
    serving/'s chunked prefill), k/v: [B, C, Nkv, H] (the cache), kv_mask:
    [B, C] (decode) or [B, Sq, C] (per-query, chunk) valid-slot mask
    (generation.kv_cache position tags — these already encode causality and
    any sliding window, so no causal mask is applied here). One fused XLA
    program: a [B, N, 1, C] decode logits block is VPU work, so decode never
    needs (or benefits from) splash — the MXU tile is 128 wide and a 1-row
    query can't fill it."""
    return sdpa(
        q, k, v,
        causal=False, scale=scale, logits_soft_cap=logits_soft_cap,
        sinks=sinks, kv_mask=kv_mask,
    )


def _pick_block(pref: int, s: int) -> int:
    """Largest TPU-friendly block (multiple of 128, splash requirement) that
    divides s. s is always a 128 multiple here, so 128 is a valid floor even
    when pref is smaller or not 128-aligned."""
    for b in (pref, 512, 256, 128):
        if b <= pref and b % 128 == 0 and s % b == 0:
            return b
    return 128


def _autotune_entry(head_dim: int, window: Optional[int], causal: bool):
    """Per-shape backend + block selection from the per-chip autotune table
    (ops/autotune.py; swept by tools/kernel_bench.py). The static 512/512
    splash blocks are sized for head_dim 128 — at head_dim 64 the MXU runs
    half-empty (PROFILE_MOE_r05: 59.3 TFLOP/s fwd+bwd ≈ 30% of v5e peak) —
    so the table carries measured blocks per (head_dim, window, causal)
    shape and, where the in-tree blockwise kernel (ops/ring_flash) wins the
    race, routes the shape there. No entry → splash with the static
    defaults (exactly the pre-table behavior)."""
    from automodel_tpu.ops import autotune

    entry = autotune.lookup(autotune.attn_key(head_dim, window, causal))
    if entry is None:
        return None
    out = {"backend": entry.get("backend", "splash")}
    blocks = autotune.valid_tiles(entry, ("block_q", "block_kv"), None)
    if blocks is not None:
        out["block_q"], out["block_kv"] = blocks
    return out


_SPLASH_SINKS_SUPPORTED: Optional[bool] = None


def _splash_supports_sinks() -> bool:
    """Whether this jax build's splash kernel takes a ``sinks`` argument
    (one signature inspection, cached)."""
    global _SPLASH_SINKS_SUPPORTED
    if _SPLASH_SINKS_SUPPORTED is None:
        import inspect

        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sak,
        )

        _SPLASH_SINKS_SUPPORTED = "sinks" in inspect.signature(
            sak._splash_attention
        ).parameters
    return _SPLASH_SINKS_SUPPORTED


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "logits_soft_cap", "sliding_window", "block_q",
        "block_kv", "interpret",
    ),
)
def _splash_flash(
    q, k, v, segment_ids, sinks,
    *, causal, scale, logits_soft_cap, sliding_window, block_q, block_kv,
    interpret=False,
):
    """Splash attention (pallas TPU): native GQA (no repeat_kv materialize),
    sliding-window via LocalMask, logit soft cap, segment ids, and gpt-oss
    attention sinks — the TE-universality equivalent
    (reference components/attention/utils.py:25-65)."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sak,
        splash_attention_mask as sam,
    )

    B, S, N, H = q.shape
    # pad seq to a 128 multiple instead of losing the fused kernel; padded q
    # rows are sliced off, padded kv is never attended (causal) / segmented out
    Sp = -(-S // 128) * 128
    pad = Sp - S
    if pad:
        zeros = lambda a: jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0)))
        q, k, v = zeros(q), zeros(k), zeros(v)
        if segment_ids is None:
            segment_ids = jnp.concatenate(
                [
                    jnp.ones((B, S), jnp.int32),
                    jnp.zeros((B, pad), jnp.int32),
                ],
                axis=1,
            )
        else:
            segment_ids = jnp.pad(
                segment_ids, ((0, 0), (0, pad)), constant_values=-1
            )

    qt = (q * scale).transpose(0, 2, 1, 3)  # [B, N, S, H], pre-scaled
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    if sliding_window is not None:
        base = sam.LocalMask((Sp, Sp), window_size=(sliding_window - 1, 0), offset=0)
    elif causal:
        base = sam.CausalMask((Sp, Sp))
    else:
        base = sam.FullMask((Sp, Sp))
    mask = sam.MultiHeadMask([base] * N)
    bq = _pick_block(block_q, Sp)
    bkv = _pick_block(block_kv, Sp)
    kernel = sak.make_splash_mha(
        mask,
        block_sizes=sak.BlockSizes(
            block_q=bq, block_kv=bkv,
            block_q_dkv=bq, block_kv_dkv=bkv,
            block_q_dq=bq, block_kv_dq=bkv,
        ),
        head_shards=1,
        q_seq_shards=1,
        attn_logits_soft_cap=logits_soft_cap,
        interpret=interpret,
    )
    seg = (
        sak.SegmentIds(q=segment_ids, kv=segment_ids)
        if segment_ids is not None
        else None
    )
    # older jax builds ship a splash kernel without the `sinks` parameter
    # (_splash_attention has no such arg): passing it positionally breaks
    # EVERY splash call, sinks or not. Omit the argument when it is None so
    # sink-less models keep the fused kernel on those builds; an actual
    # sinks tensor on such a build still fails loudly below (the capability
    # is genuinely missing — silently dropping the sinks would mis-compute).
    call = (qt, kt, vt, seg)
    axes: tuple = (0, 0, 0, 0 if seg is not None else None)
    if sinks is not None or _splash_supports_sinks():
        call += (sinks,)
        axes += (None,)
    out = jax.vmap(kernel, in_axes=axes)(*call)
    out = out.transpose(0, 2, 1, 3).astype(q.dtype)
    return out[:, :S] if pad else out


_warned_fallback: set = set()


def _fallback_loudly(reason: str):
    if reason not in _warned_fallback:
        _warned_fallback.add(reason)
        import logging

        logging.getLogger(__name__).warning(
            "flash attention falling back to XLA sdpa (%s) — O(S^2) "
            "materialized attention; expect a large perf cliff on TPU.", reason
        )


def flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    sinks: Optional[jnp.ndarray] = None,
    block_q: int = 512,
    block_kv: int = 512,
    platform: Optional[str] = None,
) -> jnp.ndarray:
    """Pallas TPU flash (splash) attention: causal/sliding-window/soft-cap/
    segments/sinks all stay on the fused kernel; sequences are padded to 128
    internally. Falls back to sdpa ONLY off-TPU or for ANY non-causal
    attention (splash's LocalMask enforces causality, so even non-causal
    windowed must not route there), and logs loudly when it does."""
    h = q.shape[-1]
    if q.shape[1] == 1:
        # single-query decode: the splash MXU tiling pads the query to a
        # 128-row block — 127/128 of the kernel is wasted — while the XLA
        # sdpa lowers to one VPU-bound fused program. Not a fallback (no
        # warning): decode is DESIGNED to never require splash.
        return sdpa(
            q, k, v,
            causal=causal, scale=scale, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap, sliding_window=sliding_window,
            sinks=sinks,
        )
    reason = None
    if not _flash_eligible(platform):
        reason = "not running on TPU"
    elif not causal:
        # splash LocalMask silently enforces causality, so non-causal windowed
        # attention must not route there; non-causal dense lacks a kernel win
        reason = "non-causal attention"
    if reason is not None:
        _fallback_loudly(reason)
        return sdpa(
            q, k, v,
            causal=causal, scale=scale, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap, sliding_window=sliding_window,
            sinks=sinks,
        )
    scale = scale if scale is not None else 1.0 / (h**0.5)
    entry = _autotune_entry(h, sliding_window, causal)
    entry_backend = entry.get("backend", "splash") if entry is not None else None
    # an explicit attn_block_q/attn_block_kv in the backend config wins
    # outright: it pins the splash path with the caller's blocks (explicit
    # tuning was done against splash — rerouting it to the block kernel
    # would hand one kernel's blocks to the other). The table only acts on
    # the STATIC 512/512 defaults; soft cap also forces splash (the
    # blockwise kernels don't carry it).
    default_blocks = (block_q, block_kv) == (512, 512)
    take_block_path = (
        entry_backend == "block" and logits_soft_cap is None and default_blocks
    )
    if entry is not None and default_blocks and (
        take_block_path or entry_backend == "splash"
    ):
        # only the path the entry was raced on inherits its blocks — a
        # block-backend entry forced onto splash (soft cap) keeps splash's
        # static defaults rather than the other kernel's measured blocks
        block_q = entry.get("block_q", block_q)
        block_kv = entry.get("block_kv", block_kv)
    if take_block_path:
        from automodel_tpu.ops import ring_flash

        return ring_flash.flash_attention(
            q, k, v,
            causal=causal, scale=scale, segment_ids=segment_ids,
            sliding_window=sliding_window, sinks=sinks,
            block_q=block_q, block_kv=block_kv,
            interpret=_interpret_requested(),
        )
    return _splash_flash(
        q, k, v, segment_ids, sinks,
        causal=causal, scale=scale, logits_soft_cap=logits_soft_cap,
        sliding_window=sliding_window, block_q=block_q, block_kv=block_kv,
        interpret=_interpret_requested(),
    )


def _ring_not_installed(*args, **kwargs):
    raise RuntimeError(
        "attention backend 'ring' needs a mesh: call "
        "automodel_tpu.parallel.cp.install_ring_backend(mesh_ctx) first "
        "(auto_model does this when backend.attn == 'ring')."
    )


ATTENTION_BACKENDS = {
    "sdpa": sdpa,
    "flash": flash,
    "ring": _ring_not_installed,
}


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    backend: str = "sdpa",
    platform: Optional[str] = None,
    **kwargs,
) -> jnp.ndarray:
    try:
        fn = ATTENTION_BACKENDS[backend]
    except KeyError:
        raise ValueError(
            f"Unknown attention backend {backend!r}; available: {sorted(ATTENTION_BACKENDS)}"
        )
    if backend == "flash":
        kwargs["platform"] = platform
    return fn(q, k, v, **kwargs)


def windowed_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    backend: str,
    is_sliding: jnp.ndarray,
    window: Optional[int],
    dynamic_window: jnp.ndarray,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    logits_soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,
    bidir_groups: Optional[jnp.ndarray] = None,
    block_q: int = 512,
    block_kv: int = 512,
    platform: Optional[str] = None,
) -> jnp.ndarray:
    """Attention for scanned layer stacks that mix full and sliding-window
    layers (Gemma-2/3, GPT-OSS). The per-layer layer type rides the scan as
    the traced `is_sliding` flag; the flash path needs a STATIC window for
    its splash mask, so it branches with `lax.cond` between two static-mask
    kernels (both compile once; one executes per layer). The sdpa path takes
    the traced `dynamic_window` bound directly (window = S on full layers)."""
    if backend not in ATTENTION_BACKENDS:
        raise ValueError(
            f"Unknown attention backend {backend!r}; available: {sorted(ATTENTION_BACKENDS)}"
        )
    if bidir_groups is not None:
        # data-dependent OR-mask (gemma-3 image blocks): splash masks are
        # static, so this runs on sdpa until a custom dynamic-mask kernel
        if backend == "flash":
            _fallback_loudly("bidirectional image-block mask")
        return sdpa(
            q, k, v,
            causal=causal, scale=scale, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap, sliding_window=dynamic_window,
            sinks=sinks, bidir_groups=bidir_groups,
        )
    if backend == "flash" and window is not None and _flash_eligible(platform):
        kw = dict(
            causal=causal, scale=scale, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap, sinks=sinks,
            block_q=block_q, block_kv=block_kv, platform=platform,
        )
        if not isinstance(is_sliding, jax.core.Tracer):
            # static flag (unrolled layer loop): compile exactly one kernel
            return flash(q, k, v, sliding_window=window if bool(is_sliding) else None, **kw)
        return jax.lax.cond(
            is_sliding,
            lambda: flash(q, k, v, sliding_window=window, **kw),
            lambda: flash(q, k, v, sliding_window=None, **kw),
        )
    if backend == "flash" and window is None and _flash_eligible(platform):
        return flash(
            q, k, v,
            causal=causal, scale=scale, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap, sinks=sinks,
            block_q=block_q, block_kv=block_kv, platform=platform,
        )
    if backend == "ring":
        return ATTENTION_BACKENDS["ring"](
            q, k, v,
            causal=causal, scale=scale, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap, sliding_window=dynamic_window,
            sinks=sinks,
        )
    if backend == "flash":
        _fallback_loudly("not running on TPU")
    return sdpa(
        q, k, v,
        causal=causal, scale=scale, segment_ids=segment_ids,
        logits_soft_cap=logits_soft_cap, sliding_window=dynamic_window,
        sinks=sinks,
    )


def _interpret_requested() -> bool:
    """AUTOMODEL_FLASH_INTERPRET=1 runs the splash kernel through the pallas
    interpreter — the REAL kernel code path, executable on CPU (tests)."""
    import os

    return os.environ.get("AUTOMODEL_FLASH_INTERPRET", "0") == "1"


def _flash_eligible(platform: Optional[str] = None) -> bool:
    from automodel_tpu.ops.platform_check import is_tpu_platform

    return _interpret_requested() or is_tpu_platform(platform)
