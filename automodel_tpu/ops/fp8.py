"""FP8 training path via XLA fp8 dtypes.

Parity: reference quantization/fp8.py:130 (torchao float8 tensorwise
recipe) + the TE-FP8 `BackendConfig.te_fp8` path. TPU-native: quantize
both matmul operands to float8_e4m3fn with per-tensor dynamic (current
amax) scales and run the dot on fp8 inputs with an fp32 accumulator —
XLA lowers fp8 dots onto the MXU's fp8 path on hardware that has one.
Gradients flow through a custom VJP that quantizes the incoming cotangent
to float8_e5m2 (wider range, like the standard fwd-e4m3/bwd-e5m2 recipe)
before the two backward matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def _quantize(x: jnp.ndarray, dtype, max_val: float):
    amax = jax.lax.stop_gradient(jnp.max(jnp.abs(x.astype(jnp.float32))))
    scale = jnp.maximum(amax, 1e-12) / max_val
    q = (x.astype(jnp.float32) / scale).astype(dtype)
    return q, scale


def _fp8_matmul(qa, qb, sa, sb):
    out = jax.lax.dot_general(
        qa, qb, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out * (sa * sb)


@jax.custom_vjp
def fp8_dot(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """x [..., K] @ w [K, N] with both operands in fp8 (e4m3). Output fp32 —
    callers cast to their compute dtype."""
    qx, sx = _quantize(x, jnp.float8_e4m3fn, E4M3_MAX)
    qw, sw = _quantize(w, jnp.float8_e4m3fn, E4M3_MAX)
    return _fp8_matmul(qx, qw, sx, sw)


def _fwd(x, w):
    qx, sx = _quantize(x, jnp.float8_e4m3fn, E4M3_MAX)
    qw, sw = _quantize(w, jnp.float8_e4m3fn, E4M3_MAX)
    # dtype-carrying empties: residual pytrees may only hold arrays
    dt_x = jnp.zeros((0,), x.dtype)
    dt_w = jnp.zeros((0,), w.dtype)
    return _fp8_matmul(qx, qw, sx, sw), (qx, sx, qw, sw, dt_x, dt_w)


def _bwd(res, g):
    qx, sx, qw, sw, dt_x, dt_w = res
    x_dtype, w_dtype = dt_x.dtype, dt_w.dtype
    qg, sg = _quantize(g, jnp.float8_e5m2, E5M2_MAX)
    # dx = g @ w.T ; dw = x.T @ g — both in fp8 with fp32 accumulation
    dx = jax.lax.dot_general(
        qg, qw, (((qg.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (sg * sw)
    lead = tuple(range(qx.ndim - 1))
    dw = jax.lax.dot_general(
        qx, qg, ((lead, lead), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (sx * sg)
    return dx.astype(x_dtype), dw.astype(w_dtype)


fp8_dot.defvjp(_fwd, _bwd)


def maybe_fp8_dot(x: jnp.ndarray, w: jnp.ndarray, enabled: bool) -> jnp.ndarray:
    """``enabled`` comes straight from BackendConfig.fp8 at each call site —
    NOT a module global: trace-time mutable state interleaves wrongly when
    two models with different fp8 settings trace in one process, and a jit
    traced under one setting silently caches it (r2 VERDICT weak #8)."""
    if enabled:
        return fp8_dot(x, w).astype(x.dtype)
    return x @ w.astype(x.dtype)




def fp8_qdq_blockwise(w: jnp.ndarray, block: int = 128) -> jnp.ndarray:
    """e4m3 quantize-dequantize with `block`×`block` scales over the last two
    dims and a straight-through gradient — the reference GroupedExpertsFP8
    scale granularity (components/moe/experts.py:478,540-570, 128×128
    blockwise). Runs as QDQ + fp32-accumulated matmul on TPUs without an fp8
    MXU path; the numerics match the scaled-fp8 grouped mm."""
    *lead, din, dout = w.shape
    pi = (-din) % block
    po = (-dout) % block
    wp = jnp.pad(w, [(0, 0)] * len(lead) + [(0, pi), (0, po)]) if (pi or po) else w
    Din, Dout = wp.shape[-2], wp.shape[-1]
    g = wp.reshape(*lead, Din // block, block, Dout // block, block)
    amax = jax.lax.stop_gradient(
        jnp.abs(g.astype(jnp.float32)).max(axis=(-3, -1), keepdims=True)
    )
    scale = jnp.maximum(amax, 1e-12) / E4M3_MAX
    q = (g.astype(jnp.float32) / scale).astype(jnp.float8_e4m3fn)
    deq = (q.astype(jnp.float32) * scale).reshape(*lead, Din, Dout)
    deq = deq[..., :din, :dout].astype(w.dtype)
    return w + jax.lax.stop_gradient(deq - w)


def fp8_qdq_tensor(x: jnp.ndarray) -> jnp.ndarray:
    """Per-tensor dynamic e4m3 quantize-dequantize with STE (activations)."""
    q, s = _quantize(x, jnp.float8_e4m3fn, E4M3_MAX)
    deq = (q.astype(jnp.float32) * s).astype(x.dtype)
    return x + jax.lax.stop_gradient(deq - x)
