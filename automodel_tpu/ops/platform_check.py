"""Shared "can this Pallas kernel run here?" check.

Used by ops.attention (splash flash) and ops.grouped_matmul (MoE gmm) so the
two kernels can't drift in how they decide the mesh is a TPU. Per-kernel
interpret-mode env switches stay with each kernel.
"""

from __future__ import annotations

import jax


def is_tpu_platform(platform: str | None = None) -> bool:
    """`platform` (from BackendConfig.platform, resolved off the MeshContext)
    is authoritative when known — the process default device may belong to a
    DIFFERENT backend than the mesh the computation runs on (e.g. a CPU mesh
    on an image whose sitecustomize registers a TPU client). The
    default-device heuristic below is only the no-mesh fallback."""
    if platform is not None:
        return platform == "tpu"
    try:
        # honor an explicitly pinned default device (tests pin CPU while a
        # TPU is still visible in jax.devices()); jax also accepts platform
        # strings ('tpu') as jax_default_device
        dd = jax.config.jax_default_device
        if isinstance(dd, str):
            return dd == "tpu"
        dev = dd if dd is not None else jax.devices()[0]
        return getattr(dev, "platform", None) == "tpu"
    except Exception:
        return False
