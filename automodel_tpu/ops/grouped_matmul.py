"""Pallas grouped matmul (megablocks-style) — the MoE expert hot path.

Parity: reference `GroupedExperts` grouped GEMM (components/moe/experts.py:158
via torch `_grouped_mm`). On TPU the idiomatic lowering is `lax.ragged_dot`,
but this image's AOT compile helper crashes lowering ragged_dot at bench-scale
token counts, and XLA's lowering isn't tuned for the sorted-by-expert MoE
layout anyway — so this is a hand-scheduled Pallas kernel:

  out[m, n] = sum_k lhs[m, k] @ rhs[g(m), k, n]

with `lhs` rows sorted by group and `group_sizes[g]` rows per group.

Scheduling: the grid iterates over *work units* — (m-tile, group) pairs that
actually overlap — computed at trace time from `group_sizes` with jnp ops and
handed to the kernel via scalar prefetch (group/tile id + row window per
unit). A tile spanning a group boundary is visited once per group, with a row
mask selecting each group's rows; consecutive units on the same output tile
keep it resident in VMEM (TPU grids are sequential), so the read-modify-write
blend needs no atomics. Worst case `M/tm + G` units, i.e. O(1) overhead per
group boundary — dropless, no capacity factor, no padding per expert.

The backward needs two more kernels: dlhs is just gmm against `rhs`
transposed, and drhs is a transposed grouped matmul (`_tgmm`) accumulating
`lhs_g^T @ dout_g` per group over that group's row tiles (same work-unit
plan, output tile = the group's [K, N] slab, fp32 accumulation in place).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from automodel_tpu.utils.compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()


def _interpret_requested() -> bool:
    return os.environ.get("AUTOMODEL_GMM_INTERPRET", "0") == "1"


def _pallas_eligible(platform: str | None = None) -> bool:
    from automodel_tpu.ops.platform_check import is_tpu_platform

    return _interpret_requested() or is_tpu_platform(platform)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _out_sds(shape, dtype, *operands):
    """ShapeDtypeStruct carrying the union of the operands' vma — inside a
    check_vma shard_map region (the a2a/a2a_fused EP paths) a pallas_call
    must state how its output varies over the manual axes."""
    from automodel_tpu.utils.compat import vma_of

    vmas = [vma_of(o) for o in operands]
    if any(vmas):
        vma = frozenset().union(*[v for v in vmas if v])
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _plan(group_sizes: jnp.ndarray, m_padded: int, tm: int, num_groups: int):
    """Work-unit schedule: for each of W = m_padded/tm + G grid steps, the
    (group, m-tile, row-window) it computes. All jnp — `group_sizes` is a
    traced value; the plan rides to the kernel as scalar prefetch."""
    gs = group_sizes.astype(jnp.int32)
    ends = jnp.cumsum(gs)
    starts = ends - gs
    first = starts // tm
    last = jnp.maximum(ends - 1, starts) // tm
    ntiles = jnp.where(gs > 0, last - first + 1, 0)
    wstart = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(ntiles)[:-1]])
    total = wstart[-1] + ntiles[-1]

    W = m_padded // tm + num_groups
    i = jnp.arange(W, dtype=jnp.int32)
    valid = i < total
    j = jnp.clip(i, 0, jnp.maximum(total - 1, 0))
    # last group whose first work unit is ≤ j; runs of equal wstart (empty
    # groups) resolve to the run's last member, which is the non-empty one
    g = (jnp.searchsorted(wstart, j, side="right") - 1).astype(jnp.int32)
    tile = first[g] + (j - wstart[g])
    # row window; invalid (clamped) units get an empty window → masked no-op
    row_s = jnp.where(valid, starts[g], 0)
    row_e = jnp.where(valid, ends[g], 0)
    return g, tile.astype(jnp.int32), row_s, row_e


def _pick_tiles(k: int, n: int, itemsize: int) -> tuple[int, int]:
    """(tm, tn) fitting lhs/rhs/out double-buffered blocks in ~12MB VMEM."""
    budget = 12 * 1024 * 1024
    for tm in (512, 256, 128):
        for tn in (512, 256, 128):
            need = 2 * itemsize * (tm * k + k * tn + tm * tn)
            if need <= budget:
                return tm, tn
    return 128, 128


def _gmm_tiles(K: int, N: int, dtype, transpose_rhs: bool) -> tuple[int, int]:
    """(tm, tn) for _gmm: the per-chip autotune entry when one exists and
    fits the VMEM budget, else the static ladder above."""
    from automodel_tpu.ops import autotune

    it = jnp.dtype(dtype).itemsize
    kp = _round_up(K, 128)

    def ok(tm, tn):
        return 2 * it * (tm * kp + kp * tn + tm * tn) <= 12 * 1024 * 1024

    tiles = autotune.valid_tiles(
        autotune.lookup(autotune.gmm_key(K, N, dtype, transpose_rhs)),
        ("tm", "tn"), ok,
    )
    return tiles if tiles is not None else _pick_tiles(kp, _round_up(N, 128), it)


def _tgmm_budget_ok(tm, tk, tn, itemsize):
    """VMEM model for _tgmm blocks — module-level so tools/kernel_bench.py
    filters sweep candidates with the same predicate."""
    need = 2 * itemsize * (tm * tk + tm * tn) + 2 * 4 * tk * tn
    return need <= 12 * 1024 * 1024


def _tgmm_tiles(K: int, N: int, dtype) -> tuple[int, int, int]:
    """(tm, tk, tn) for _tgmm. The contraction runs over the tm rows, so a
    bigger tm means more MXU passes per [tk, tn] slab write-back — the
    re-tiling lever PROFILE_MOE_r05 showed the default 512 leaving ~20% on
    the table (gmm2-class 84.3 TFLOP/s vs gmm1's 107.0). Autotune entries
    (tools/kernel_bench.py) win when feasible; the fallback keeps the
    conservative 512 ladder."""
    from automodel_tpu.ops import autotune

    it = jnp.dtype(dtype).itemsize
    ok = lambda tm, tk, tn: _tgmm_budget_ok(tm, tk, tn, it)

    tiles = autotune.valid_tiles(
        autotune.lookup(autotune.tgmm_key(K, N, dtype)), ("tm", "tk", "tn"), ok,
    )
    if tiles is not None:
        return tiles
    tm, tn = _pick_tiles(_round_up(K, 128), _round_up(N, 128), it)
    tk = min(_round_up(K, 128), 512)
    return tm, tk, tn


def _gmm_kernel(wg, wt, ws, we, lhs_ref, rhs_ref, out_ref, *, tm, tn,
                transpose_rhs=False):
    w = pl.program_id(1)
    t = wt[w]
    acc = jax.lax.dot_general(
        lhs_ref[...],
        rhs_ref[0],
        (((1,), (1,) if transpose_rhs else (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    rows = t * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 0)
    mask = (rows >= ws[w]) & (rows < we[w])
    # same-tile successor: keep the previous visitor's rows; first visitor
    # zero-fills (uninitialized VMEM is only ever read through the select)
    same = jnp.logical_and(w > 0, wt[jnp.maximum(w - 1, 0)] == t)
    cur = out_ref[...]
    prev = jnp.where(same, cur, jnp.zeros_like(cur))
    out_ref[...] = jnp.where(mask, acc.astype(cur.dtype), prev)


def _gmm(lhs: jnp.ndarray, rhs: jnp.ndarray, group_sizes: jnp.ndarray,
         interpret: bool = False, transpose_rhs: bool = False) -> jnp.ndarray:
    """lhs [M, K] (rows sorted by group) @ rhs [G, K, N] → [M, N].

    ``transpose_rhs``: rhs is [G, N, K] and contracts on its LAST dim —
    the backward's dlhs = dout @ W^T without materializing a transposed
    copy of the stacked weights (rhs.swapaxes(1, 2) costs a full relayout
    write per call)."""
    M, K = lhs.shape
    if transpose_rhs:
        G, N, _ = rhs.shape
    else:
        G, _, N = rhs.shape
    out_dtype = lhs.dtype
    tm, tn = _gmm_tiles(K, N, lhs.dtype, transpose_rhs)
    Mp, Kp, Np = _round_up(M, tm), _round_up(K, 128), _round_up(N, tn)
    if (Mp, Kp) != (M, K):
        lhs = jnp.pad(lhs, ((0, Mp - M), (0, Kp - K)))
    if transpose_rhs:
        if (Kp, Np) != (K, N):
            rhs = jnp.pad(rhs, ((0, 0), (0, Np - N), (0, Kp - K)))
    elif (Kp, Np) != (K, N):
        rhs = jnp.pad(rhs, ((0, 0), (0, Kp - K), (0, Np - N)))

    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G
    grid = (Np // tn, W)

    rhs_spec = (
        pl.BlockSpec((1, tn, Kp), lambda n, w, wg, wt, ws, we: (wg[w], n, 0))
        if transpose_rhs
        else pl.BlockSpec((1, Kp, tn), lambda n, w, wg, wt, ws, we: (wg[w], 0, n))
    )
    out = pl.pallas_call(
        functools.partial(
            _gmm_kernel, tm=tm, tn=tn, transpose_rhs=transpose_rhs
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, Kp), lambda n, w, wg, wt, ws, we: (wt[w], 0)),
                rhs_spec,
            ],
            out_specs=pl.BlockSpec((tm, tn), lambda n, w, wg, wt, ws, we: (wt[w], n)),
        ),
        out_shape=_out_sds((Mp, Np), out_dtype, lhs, rhs),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, lhs, rhs)
    return out[:M, :N]


def _tgmm_kernel(wg, wt, ws, we, lhs_ref, dout_ref, out_ref, *, tm):
    w = pl.program_id(2)
    rows = wt[w] * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    mask = (rows >= ws[w]) & (rows < we[w])
    lhs_tile = lhs_ref[...]
    lhs = jnp.where(mask, lhs_tile, jnp.zeros_like(lhs_tile))
    acc = jax.lax.dot_general(
        lhs,
        dout_ref[...],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    first = jnp.logical_or(w == 0, wg[jnp.maximum(w - 1, 0)] != wg[w])
    cur = out_ref[0]
    out_ref[0] = acc + jnp.where(first, jnp.zeros_like(cur), cur)


def _tgmm(lhs: jnp.ndarray, dout: jnp.ndarray, group_sizes: jnp.ndarray,
          interpret: bool = False) -> jnp.ndarray:
    """Per-group lhs_g^T @ dout_g: [M, K] × [M, N] → [G, K, N] fp32."""
    M, K = lhs.shape
    _, N = dout.shape
    G = group_sizes.shape[0]
    tm, tk, tn = _tgmm_tiles(K, N, lhs.dtype)
    Mp, Kp, Np = _round_up(M, tm), _round_up(K, tk), _round_up(N, tn)
    if (Mp, Kp) != (M, K):
        lhs = jnp.pad(lhs, ((0, Mp - M), (0, Kp - K)))
    if (Mp, Np) != (M, N):
        dout = jnp.pad(dout, ((0, Mp - M), (0, Np - N)))

    wg, wt, ws, we = _plan(group_sizes, Mp, tm, G)
    W = Mp // tm + G
    grid = (Kp // tk, Np // tn, W)

    out = pl.pallas_call(
        functools.partial(_tgmm_kernel, tm=tm),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda k, n, w, wg, wt, ws, we: (wt[w], k)),
                pl.BlockSpec((tm, tn), lambda k, n, w, wg, wt, ws, we: (wt[w], n)),
            ],
            out_specs=pl.BlockSpec(
                (1, tk, tn), lambda k, n, w, wg, wt, ws, we: (wg[w], k, n)
            ),
        ),
        out_shape=_out_sds((G, Kp, Np), jnp.float32, lhs, dout),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(wg, wt, ws, we, lhs, dout)
    # empty groups are never visited → force their slabs to zero
    out = jnp.where((group_sizes > 0)[:, None, None], out[:, :K, :N], 0.0)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _grouped_matmul(lhs, rhs, group_sizes, interpret=False, transpose_rhs=False):
    return _gmm(lhs, rhs, group_sizes, interpret=interpret,
                transpose_rhs=transpose_rhs)


def _grouped_matmul_fwd(lhs, rhs, group_sizes, interpret, transpose_rhs):
    return (
        _gmm(lhs, rhs, group_sizes, interpret=interpret,
             transpose_rhs=transpose_rhs),
        (lhs, rhs, group_sizes),
    )


def _match_vma(ct, primal):
    """Inside a check_vma shard_map region a custom-VJP cotangent must vary
    exactly as its primal does. A cotangent naturally varies over the UNION
    of the incoming gradient's and the other operand's axes; any axis the
    primal does not vary over means the primal was (conceptually) broadcast
    there — whose AD transpose is the psum this inserts (the replicated-
    weight gradient reduction shard_map's own transpose would have done)."""
    from automodel_tpu.utils.compat import vma_of

    want = vma_of(primal)
    have = vma_of(ct)
    if want is not None and have is not None and have - want:
        ct = jax.lax.psum(ct, tuple(sorted(have - want)))
    return ct


def _grouped_matmul_bwd(interpret, transpose_rhs, res, dout):
    lhs, rhs, group_sizes = res
    # dlhs contracts rhs on the axis OPPOSITE the forward's — both cases run
    # straight off the stored layout (no rhs.swapaxes materialization)
    dlhs = _gmm(dout, rhs, group_sizes, interpret=interpret,
                transpose_rhs=not transpose_rhs)
    if transpose_rhs:
        # y = lhs @ rhs^T → drhs[g, n, k] = Σ_m dout[m, n] · lhs[m, k]
        drhs = _tgmm(dout, lhs, group_sizes, interpret=interpret)
    else:
        drhs = _tgmm(lhs, dout, group_sizes, interpret=interpret)
    return (
        _match_vma(dlhs.astype(lhs.dtype), lhs),
        _match_vma(drhs.astype(rhs.dtype), rhs),
        None,
    )


_grouped_matmul.defvjp(_grouped_matmul_fwd, _grouped_matmul_bwd)


def ragged_dot(
    lhs: jnp.ndarray,
    rhs: jnp.ndarray,
    group_sizes: jnp.ndarray,
    *,
    interpret: bool | None = None,
    platform: str | None = None,
    transpose_rhs: bool = False,
) -> jnp.ndarray:
    """Drop-in for `jax.lax.ragged_dot`: Pallas gmm on TPU (or under
    AUTOMODEL_GMM_INTERPRET=1 anywhere), XLA's ragged_dot elsewhere.

    PRECONDITION (TPU path): rows at indices >= sum(group_sizes) are NOT
    covered by any work unit and return uninitialized memory — callers must
    either have sum(group_sizes) == lhs rows (the MoE dispatch paths do:
    group sizes are exact bincounts of the picks) or never read the tail
    (the a2a path's sentinel rows route to an explicit zero row instead).
    Zeroing the tail here would cost an [M, N] select per call on the
    hottest op in the MoE step."""
    if interpret is None:
        interpret = _interpret_requested()
    if not (interpret or _pallas_eligible(platform)):
        if transpose_rhs:
            rhs = rhs.swapaxes(1, 2)
        return jax.lax.ragged_dot(lhs, rhs, group_sizes)
    return _grouped_matmul(lhs, rhs, group_sizes, interpret, transpose_rhs)
