"""Persistent per-chip-kind kernel autotune registry.

The hand-scheduled Pallas kernels (grouped matmuls, the fused expert MLP and
its manual backward, splash/block flash attention) each have tile/block
shapes that decide whether the MXU runs full or half-empty — the classic
block-shape-tuning problem FlashAttention-2 and the megablocks grouped-GEMM
line solved by matching tiles to the *problem* shape instead of one static
default. PROFILE_MOE_r05.md is the local evidence: gmm2 runs 84.3 TFLOP/s
vs gmm1's 107.0 on the same chip purely from tile choice, and splash at
head_dim 64 runs at 30% of peak with blocks sized for head_dim 128.

This module is the measured-once, persisted table those kernels consult:

- ``autotune_defaults.json`` (committed, next to this file) holds per
  chip-kind entries — the v5e defaults ship in-tree so a fresh checkout
  gets tuned shapes without a sweep.
- ``AUTOMODEL_AUTOTUNE_TABLE=<path.json>`` layers a runtime table (same
  schema) over the defaults — the file ``tools/kernel_bench.py`` writes
  under a run's ``output_dir``. Runtime entries win.
- ``tools/kernel_bench.py --write-defaults`` merges a sweep's winners back
  into the committed defaults for the measured chip kind.

Entries are plain dicts; the consuming kernel validates them (VMEM budget,
alignment) and falls back to its built-in heuristic on anything infeasible —
a stale or hand-edited table can cost performance, never correctness.

Table schema::

    {"format_version": 1,
     "chips": {"<device_kind>": {"<entry key>": {..., "source": "..."}}}}

Entry keys are built by the ``*_key`` helpers below so the sweep driver and
the kernels can never disagree on the spelling.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Any, Optional

DEFAULTS_PATH = Path(__file__).with_name("autotune_defaults.json")
ENV_TABLE = "AUTOMODEL_AUTOTUNE_TABLE"
FORMAT_VERSION = 1

_lock = threading.Lock()
# (path, mtime) -> parsed chips dict; invalidated explicitly (tests, sweeps)
_cache: dict[str, tuple[float, dict]] = {}


# -- entry keys (one spelling, shared with tools/kernel_bench.py) -----------


def tgmm_key(k: int, n: int, dtype: Any) -> str:
    """Transposed grouped matmul [M,K]x[M,N] -> [G,K,N]."""
    return f"tgmm:k{k}:n{n}:{_dt(dtype)}"


def gmm_key(k: int, n: int, dtype: Any, transpose_rhs: bool) -> str:
    """Grouped matmul [M,K]@[G,K,N] (or [G,N,K] transposed)."""
    return f"gmm:k{k}:n{n}:{_dt(dtype)}:{'t' if transpose_rhs else 'n'}"


def moe_bwd_gu_key(d: int, i: int, dtype: Any) -> str:
    """Fused activation-backward + dual tgmm (dWg/dWu/dgb/dub)."""
    return f"moe_bwd_gu:d{d}:i{i}:{_dt(dtype)}"


def moe_bwd_dwd_key(i: int, d: int, dtype: Any) -> str:
    """Fused mid-recompute + down-proj transpose GEMM (dWd/ddb)."""
    return f"moe_bwd_dwd:i{i}:d{d}:{_dt(dtype)}"


def moe_bwd_dx_key(d: int, i: int, dtype: Any) -> str:
    """Fused activation-backward + dual weight-transpose GEMM (dx)."""
    return f"moe_bwd_dx:d{d}:i{i}:{_dt(dtype)}"


def attn_key(head_dim: int, window: Optional[int], causal: bool) -> str:
    """Flash-attention backend + block selection per problem shape."""
    return f"attn:h{head_dim}:w{window or 0}:{'c' if causal else 'nc'}"


def paged_key(head_dim: int, block_size: int, kv_dtype: str) -> str:
    """Paged-attention decode backend per (head_dim, KV block size, pool
    dtype): ``backend`` ∈ {"fused" (ops/paged_attention.py Pallas kernel),
    "gather" (XLA gather → sdpa_decode → scatter baseline)} — raced by
    tools/kernel_bench.py, consulted by serving/engine.py when
    ``serving.decode_kernel: auto``."""
    return f"paged:h{head_dim}:bs{block_size}:{kv_dtype}"


def _dt(dtype: Any) -> str:
    import jax.numpy as jnp

    return jnp.dtype(dtype).name


# -- chip identity ----------------------------------------------------------


def chip_key() -> str:
    """``jax.Device.device_kind`` of the first device ("TPU v5 lite", "cpu",
    ...); "unknown" when the backend cannot initialize. Matching against the
    table is exact-then-prefix, same scheme as utils.flops_utils."""
    try:
        import jax

        return getattr(jax.devices()[0], "device_kind", "") or "unknown"
    except Exception:
        return "unknown"


def _match_chip(chips: dict, chip: str) -> Optional[dict]:
    if chip in chips:
        return chips[chip]
    low = chip.lower()
    for k, v in chips.items():
        if low.startswith(k.lower()) or k.lower().startswith(low):
            return v
    return None


# -- table loading / lookup -------------------------------------------------


def _load(path: Path) -> dict:
    """chips dict of one table file, mtime-cached; unreadable/garbage files
    read as empty (a broken table must cost tuning, not training)."""
    key = str(path)
    try:
        mtime = path.stat().st_mtime
    except OSError:
        return {}
    with _lock:
        hit = _cache.get(key)
        if hit is not None and hit[0] == mtime:
            return hit[1]
    try:
        raw = json.loads(path.read_text())
        chips = raw.get("chips", {}) if isinstance(raw, dict) else {}
        if not isinstance(chips, dict):
            chips = {}
    except Exception:
        chips = {}
    with _lock:
        _cache[key] = (mtime, chips)
    return chips


def clear_cache() -> None:
    with _lock:
        _cache.clear()


def _tables() -> list[dict]:
    """Chips dicts in *ascending* precedence (later wins)."""
    out = [_load(DEFAULTS_PATH)]
    env = os.environ.get(ENV_TABLE)
    if env:
        out.append(_load(Path(env)))
    return out


def lookup(key: str, chip: Optional[str] = None) -> Optional[dict]:
    """The entry for ``key`` on ``chip`` (default: the running chip kind), or
    None — the caller then uses its built-in heuristic. Runtime table
    (``AUTOMODEL_AUTOTUNE_TABLE``) entries shadow committed defaults."""
    chip = chip if chip is not None else chip_key()
    entry: Optional[dict] = None
    for chips in _tables():
        per_chip = _match_chip(chips, chip)
        if per_chip and key in per_chip and isinstance(per_chip[key], dict):
            entry = per_chip[key]
    return entry


def table_info(chip: Optional[str] = None) -> dict:
    """Provenance stamp for bench/profile artifacts: which chip key resolved,
    how many DISTINCT entries apply (runtime-shadowed defaults counted
    once), and which files supplied them."""
    chip = chip if chip is not None else chip_key()
    sources = []
    keys: set[str] = set()
    paths = [DEFAULTS_PATH] + (
        [Path(os.environ[ENV_TABLE])] if os.environ.get(ENV_TABLE) else []
    )
    for p in paths:
        per_chip = _match_chip(_load(p), chip)
        if per_chip:
            sources.append(str(p))
            keys.update(per_chip)
    return {"chip": chip, "entries": len(keys), "sources": sources}


# -- recording (tools/kernel_bench.py) --------------------------------------


def save_table(path: str | Path, entries: dict, chip: Optional[str] = None) -> Path:
    """Write (or merge into) a table file at ``path`` with ``entries`` for
    ``chip``. Existing entries for other chips/keys in the file survive."""
    path = Path(path)
    chip = chip if chip is not None else chip_key()
    existing: dict = {}
    if path.exists():
        try:
            existing = json.loads(path.read_text())
        except Exception:
            existing = {}
    if not isinstance(existing, dict):
        existing = {}
    chips = existing.get("chips")
    if not isinstance(chips, dict):
        chips = {}
    per_chip = dict(chips.get(chip) or {})
    per_chip.update(entries)
    chips[chip] = per_chip
    out = {"format_version": FORMAT_VERSION, "chips": chips}
    if "comment" in existing:
        out["comment"] = existing["comment"]
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps(out, indent=2, sort_keys=True) + "\n")
    tmp.replace(path)
    clear_cache()
    return path


# -- entry validation helpers ----------------------------------------------


def valid_tiles(
    entry: Optional[dict],
    names: tuple[str, ...],
    budget_fn,
    *,
    multiple: int = 128,
) -> Optional[tuple[int, ...]]:
    """Extract ``names`` (e.g. ("tm", "tk", "tn")) from an entry, enforcing
    positive ints, ``multiple``-alignment, and the caller's feasibility
    check: ``budget_fn(*tiles) -> bool`` (typically a VMEM-budget model;
    pass None to skip). A falsy result or an exception reads as infeasible.
    → tiles tuple, or None — the caller falls back to its heuristic."""
    if not entry:
        return None
    tiles = []
    for n in names:
        v = entry.get(n)
        if not isinstance(v, int) or v <= 0 or v % multiple:
            return None
        tiles.append(v)
    try:
        if budget_fn is not None and not budget_fn(*tiles):
            return None
    except Exception:
        return None
    return tuple(tiles)
