"""Fused Pallas paged-attention decode kernel + int8 KV-block quantization.

The serving decode path (serving/paged.py) historically expressed paged
attention as XLA ops: per-slot block tables GATHER the block pool into a
contiguous ``[L, B, C_view, Nkv, H]`` view, the view feeds ``sdpa_decode``,
and the written token SCATTERS back — a full round trip of every resident
sequence's KV through HBM per decoded token. docs/serving.md named that
gather as the known limitation; this module is the fix: one kernel that
indexes the pool **in place** through the block tables (the vLLM
PagedAttention idea, Kwon et al. 2023, as a Mosaic kernel), dequantizing
int8 blocks on the fly, so per-token HBM traffic drops to the KV actually
attended.

Mechanics: grid ``(B, blocks_per_sequence)``; the per-slot block table and
lengths ride as **scalar-prefetch** operands so each grid step's BlockSpec
``index_map`` DMAs exactly the pool block ``tables[b, j]`` into VMEM —
no gather materialization, no copy of cold blocks past a sequence's
length (dead blocks are skipped via ``pl.when``). Online-softmax
accumulators live in VMEM scratch across the block dimension, GQA is
native (kv heads never repeat-materialize), and queries may be a chunk
(``Sq = k+1`` for the speculative verify forward) with per-query causal
masking against absolute positions.

Int8 KV blocks: values are stored per-(token row, kv head) — scale
``amax / 127`` alongside the pool as ``[*, NB, BS, Nkv]`` fp32 (the
row-granular refinement of the per-block scale layouts in ``ops/fp8.py``
/ ``checkpoint/quant_io.py``: incremental single-token writes can never
force a whole-block rescale). ``quantize_kv_rows`` is the write-side
transform (quantize-on-scatter), the kernel (and the gather fallback)
dequantize on read; quantize∘dequantize is exactly idempotent, so chunked
prefill's rewrite-the-view scatter does not drift.

The gather path stays in serving/paged.py as the fallback / A-B baseline
(``AUTOMODEL_PAGED_DECODE=gather``); ``tools/kernel_bench.py`` races the
two per (head_dim, block_size, kv dtype) into the autotune registry
(``autotune.paged_key``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)
INT8_MAX = 127.0

# per-grid-step VMEM budget for entry validation / sweep filtering — one
# block of k+v (+scales) plus the whole query/output/accumulator set must
# fit with double-buffering headroom
_VMEM_BUDGET = 12 * 1024 * 1024


# -- int8 KV-block quantization ----------------------------------------------


def quantize_kv_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``[..., Nkv, H]`` → (int8 values, fp32 scales ``[..., Nkv]``).
    Symmetric per-(row, kv-head) absmax scaling: each written token row owns
    its scale, so single-token decode writes and whole-table prefill
    scatters use the same transform and never rescale neighbours."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / INT8_MAX
    q = jnp.clip(jnp.round(xf / scale[..., None]), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of ``quantize_kv_rows`` (scale broadcast over H)."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)).astype(dtype)


# -- feasibility (shared with tools/kernel_bench.py sweep filtering) ---------


def _paged_budget_ok(
    block_size: int, nkv: int, head_dim: int, sq: int, rep: int,
    itemsize: int, quantized: bool = False,
) -> bool:
    kv = 2 * block_size * nkv * head_dim * itemsize
    if quantized:
        kv += 2 * block_size * nkv * 4
    rows = nkv * sq * rep
    qo = 2 * rows * head_dim * 4
    scratch = (2 * rows + rows * head_dim) * 4
    return 2 * kv + qo + scratch <= _VMEM_BUDGET


# -- kernel ------------------------------------------------------------------


def _paged_kernel(
    tables_ref, lengths_ref,  # scalar prefetch
    q_ref, k_ref, v_ref, ks_ref, vs_ref,  # ks/vs absent when not quantized
    o_ref, m_scr, l_scr, acc_scr,
    *, nkv, rep, sq, bs, nbseq, window, soft_cap, quantized,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    sr = sq * rep
    length = lengths_ref[b]

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # dead-block skipping: query rows sit at absolute positions
    # length..length+sq-1 and attend pos <= their own position (the row at
    # `length` was scattered into the pool BEFORE this attend, decode_ctx
    # style), so blocks entirely past length+sq-1 — and, under a window,
    # entirely before length-window+1 — contribute nothing
    alive = j * bs <= length + sq - 1
    if window is not None:
        alive = alive & ((j + 1) * bs - 1 > length - window)

    @pl.when(alive)
    def _():
        k = k_ref[0].astype(jnp.float32)  # [BS, Nkv, H]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0][..., None]
            v = v * vs_ref[0][..., None]
        pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        # per-query absolute position: q rows are g-major then (qi, rep)
        qi = jax.lax.broadcasted_iota(jnp.int32, (sr, 1), 0) // rep
        q_abs = length + qi  # [SR, 1]
        mask = pos <= q_abs  # [SR, BS]
        if window is not None:
            mask = mask & (q_abs - pos < window)
        for g in range(nkv):
            qg = q_ref[0, g * sr : (g + 1) * sr, :].astype(jnp.float32)
            s = jax.lax.dot_general(
                qg, k[:, g], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [SR, BS]
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)
            s = jnp.where(mask, s, NEG_INF)
            m_prev = m_scr[g * sr : (g + 1) * sr]
            l_prev = l_scr[g * sr : (g + 1) * sr]
            m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            corr = jnp.exp(m_prev - m_new)
            m_scr[g * sr : (g + 1) * sr] = m_new
            l_scr[g * sr : (g + 1) * sr] = l_prev * corr + p.sum(
                axis=1, keepdims=True
            )
            acc_scr[g * sr : (g + 1) * sr] = acc_scr[
                g * sr : (g + 1) * sr
            ] * corr + jax.lax.dot_general(
                p, v[:, g], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(j == nbseq - 1)
    def _():
        l = l_scr[...]
        safe = jnp.maximum(l, 1e-30)
        o_ref[0] = jnp.where(l > 0, acc_scr[...] / safe, 0.0).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "sliding_window", "logits_soft_cap", "interpret",
    ),
)
def paged_attend(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    logits_soft_cap: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Paged decode/verify attention, in place over the block pool.

    q ``[B, Sq, N, H]`` (Sq = 1 for decode, the verify chunk for
    speculative decoding); k_pool/v_pool ``[NB, BS, Nkv, H]`` (one layer's
    pool slice; int8 with ``k_scale``/``v_scale`` ``[NB, BS, Nkv]`` fp32);
    tables ``[B, NBseq]`` int32 block tables; lengths ``[B]`` int32 — the
    absolute position of query row 0 (rows ``length..length+Sq-1`` must
    already be scattered into the pool; row qi attends pos ≤ length+qi).
    → ``[B, Sq, N, H]`` in q.dtype. Equals ``sdpa_decode`` over the
    gathered (dequantized) view to fp32 accumulation order.
    """
    B, Sq, N, H = q.shape
    NB, BS, Nkv, _ = k_pool.shape
    NBseq = tables.shape[1]
    rep = N // Nkv
    SR = Sq * rep
    quantized = k_scale is not None
    scale = scale if scale is not None else 1.0 / (H**0.5)
    # g-major row layout: row g*SR + qi*rep + r holds (head g*rep+r, query qi)
    qf = (
        (q * jnp.asarray(scale, q.dtype))
        .reshape(B, Sq, Nkv, rep, H)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, Nkv * SR, H)
    )

    def ix_q(b, j, tbl, lens):
        return (b, 0, 0)

    def _live_j(b, j, lens):
        # dead-block DMA skip: blocks past the last attended position
        # (length + Sq - 1) re-fetch the LAST live block instead — Pallas
        # skips the copy when consecutive grid steps resolve to the same
        # block index, so per-token HBM traffic tracks the KV actually
        # attended, not the static table width. The kernel's pl.when
        # already skips their compute, and masking never reads them.
        return jnp.minimum(j, (lens[b] + (Sq - 1)) // BS)

    def ix_kv(b, j, tbl, lens):
        return (tbl[b, _live_j(b, j, lens)], 0, 0, 0)

    def ix_scale(b, j, tbl, lens):
        return (tbl[b, _live_j(b, j, lens)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, Nkv * SR, H), ix_q),
        pl.BlockSpec((1, BS, Nkv, H), ix_kv),
        pl.BlockSpec((1, BS, Nkv, H), ix_kv),
    ]
    args = [qf, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, BS, Nkv), ix_scale),
            pl.BlockSpec((1, BS, Nkv), ix_scale),
        ]
        args += [k_scale, v_scale]
    kernel = functools.partial(
        _paged_kernel,
        nkv=Nkv, rep=rep, sq=Sq, bs=BS, nbseq=NBseq,
        window=sliding_window, soft_cap=logits_soft_cap, quantized=quantized,
    )
    if not quantized:
        # keep one kernel body: bind the absent scale refs to None
        kernel = _without_scales(kernel)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, NBseq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Nkv * SR, H), ix_q),
        scratch_shapes=[
            pltpu.VMEM((Nkv * SR, 1), jnp.float32),
            pltpu.VMEM((Nkv * SR, 1), jnp.float32),
            pltpu.VMEM((Nkv * SR, H), jnp.float32),
        ],
    )
    from automodel_tpu.utils.compat import pallas_tpu_compiler_params

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Nkv * SR, H), q.dtype),
        compiler_params=pallas_tpu_compiler_params()(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), *args)
    return (
        out.reshape(B, Nkv, Sq, rep, H).transpose(0, 2, 1, 3, 4).reshape(B, Sq, N, H)
    )


def _without_scales(kernel):
    def wrapped(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                m_scr, l_scr, acc_scr):
        return kernel(
            tables_ref, lengths_ref, q_ref, k_ref, v_ref, None, None,
            o_ref, m_scr, l_scr, acc_scr,
        )

    return wrapped
