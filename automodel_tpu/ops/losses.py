"""Loss functions.

Parity with the reference loss zoo (components/loss/): MaskedCrossEntropy
(masked_ce.py:22), ChunkedCrossEntropy (chunked_ce.py:43), and
FusedLinearCrossEntropy (linear_ce.py:119 — cut-cross-entropy that never
materializes full logits). TPU-native formulations:

- masked CE: one fused XLA softmax-CE over fp32 logits.
- chunked CE: lax.scan over vocab— no wait, over sequence chunks, so the
  [tokens, vocab] logits buffer never exceeds chunk_size×vocab.
- linear CE: the chunked formulation but taking hidden states + lm_head and
  doing the final projection inside the chunk loop — the memory win of
  cut-cross-entropy without a custom kernel, letting XLA fuse projection and
  log-softmax per chunk.

All losses return (summed_loss, num_valid_tokens) so callers can normalize
globally across the dp_cp mesh group (reference: reduce_loss,
distributed/utils.py:185) — per-token mean requires the GLOBAL token count.

Labels use the HF convention: ignore_index (-100) marks padding; callers
pre-shift labels for next-token prediction.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

IGNORE_INDEX = -100


def _usable_chunks(t: int, requested: int) -> int:
    """Largest divisor of t that is <= requested; warns (at trace time) when
    the memory bound degrades from what the caller asked for."""
    nc = 1
    for d in range(min(requested, t), 0, -1):
        if t % d == 0:
            nc = d
            break
    if nc != requested:
        logger.warning(
            "token count %d not divisible by num_chunks=%d; using %d chunks "
            "(pad the batch for the full memory bound)", t, requested, nc,
        )
    return nc


def _ce_sum(logits: jnp.ndarray, labels: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Summed CE over valid tokens. logits [T, V] (any float dtype), labels [T]."""
    valid = labels != IGNORE_INDEX
    safe_labels = jnp.where(valid, labels, 0)
    logits32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits32, axis=-1)
    picked = jnp.take_along_axis(logits32, safe_labels[:, None], axis=-1)[:, 0]
    loss = jnp.where(valid, lse - picked, 0.0)
    return loss.sum(), valid.sum()


def masked_cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(sum_loss, n_valid). logits [..., V], labels [...]."""
    v = logits.shape[-1]
    return _ce_sum(logits.reshape(-1, v), labels.reshape(-1))


def chunked_cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, num_chunks: int = 8
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CE over token chunks; bounds the fp32 logits working set.

    Token count must be divisible by num_chunks (pad batches accordingly).
    """
    v = logits.shape[-1]
    flat_logits = logits.reshape(-1, v)
    flat_labels = labels.reshape(-1)
    t = flat_logits.shape[0]
    num_chunks = _usable_chunks(t, num_chunks)
    flat_logits = flat_logits.reshape(num_chunks, t // num_chunks, v)
    flat_labels = flat_labels.reshape(num_chunks, t // num_chunks)

    # checkpoint the body: scan's AD otherwise STACKS each chunk's fp32
    # softmax residuals across iterations — a [chunks, chunk_t, V] buffer
    # that exceeds the unchunked working set it was meant to avoid
    @jax.checkpoint
    def body(carry, chunk):
        lg, lb = chunk
        s, n = _ce_sum(lg, lb)
        return (carry[0] + s, carry[1] + n), None

    (loss, n), _ = jax.lax.scan(body, (jnp.float32(0.0), jnp.int32(0)), (flat_logits, flat_labels))
    return loss, n


def fused_linear_cross_entropy(
    hidden: jnp.ndarray,
    lm_head_kernel: jnp.ndarray,
    labels: jnp.ndarray,
    num_chunks: int = 16,
    logits_soft_cap: float | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CE from hidden states + lm_head without materializing [T, V] logits.

    hidden [..., D], lm_head_kernel [D, V], labels [...]. The projection runs
    inside the chunk scan so peak memory is chunk×V (reference capability:
    FusedLinearCrossEntropy via cut-cross-entropy, loss/linear_ce.py:119).
    """
    d = hidden.shape[-1]
    flat_h = hidden.reshape(-1, d)
    flat_labels = labels.reshape(-1)
    t = flat_h.shape[0]
    num_chunks = _usable_chunks(t, num_chunks)
    flat_h = flat_h.reshape(num_chunks, t // num_chunks, d)
    flat_labels = flat_labels.reshape(num_chunks, t // num_chunks)

    # checkpoint the body, else scan's AD stacks every chunk's fp32 logits
    # as residuals — f32[chunks, chunk_t, V] (4GB at the MoE bench shape,
    # the round-5 OOM) — exactly the buffer this function exists to avoid.
    # The backward recomputes h @ lm_head per chunk (cut-cross-entropy's
    # trade: one extra [chunk, D]x[D, V] matmul per chunk).
    @jax.checkpoint
    def body(carry, chunk):
        h, lb = chunk
        logits = h @ lm_head_kernel
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        s, n = _ce_sum(logits, lb)
        return (carry[0] + s, carry[1] + n), None

    (loss, n), _ = jax.lax.scan(body, (jnp.float32(0.0), jnp.int32(0)), (flat_h, flat_labels))
    return loss, n


def vocab_parallel_cross_entropy(
    hidden: jnp.ndarray,
    lm_head_kernel: jnp.ndarray,
    labels: jnp.ndarray,
    mesh_ctx,
    logits_soft_cap: float | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """TP loss-parallel CE: the lm_head projection AND the softmax run with
    the vocab dim sharded over the ``tensor`` axis — full [T, V] logits never
    exist on any device (reference: TEParallelCrossEntropy,
    loss/te_parallel_ce.py:113 over Triton online-softmax kernels; here a
    shard_map online softmax with psum/pmax collectives over ICI).

    hidden [..., D] (replicated over tensor), lm_head_kernel [D, V] sharded
    on V, labels [...]. Returns (loss_sum fp32, n_valid) replicated.
    """
    from automodel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = mesh_ctx.mesh
    tp = mesh.shape["tp"]
    d = hidden.shape[-1]
    flat_h = hidden.reshape(-1, d)
    flat_labels = labels.reshape(-1)
    if tp == 1:
        return fused_linear_cross_entropy(
            hidden, lm_head_kernel, labels, logits_soft_cap=logits_soft_cap
        )

    def body(h, kern, lb):
        # local shard: kern [D, V/tp]
        vl = kern.shape[-1]
        logits = (h @ kern).astype(jnp.float32)
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        # max shift is gradient-free (lse is invariant to it) and pmax has
        # no differentiation rule — stop the gradient BEFORE pmax so the
        # collective only ever sees constants
        m = jax.lax.pmax(jax.lax.stop_gradient(logits.max(-1)), "tp")  # [T]
        z = jax.lax.psum(jnp.exp(logits - m[:, None]).sum(-1), "tp")
        lse = jnp.log(z) + m
        off = jax.lax.axis_index("tp") * vl
        local = (lb >= off) & (lb < off + vl)
        idx = jnp.clip(lb - off, 0, vl - 1)
        picked = jnp.take_along_axis(logits, idx[:, None], 1)[:, 0]
        correct = jax.lax.psum(jnp.where(local, picked, 0.0), "tp")
        valid = lb != IGNORE_INDEX
        loss = jnp.where(valid, lse - correct, 0.0)
        # post-psum the value is identical on every tp shard; out_specs must
        # name the manual axis, so return [1]-per-shard and slice one copy
        return loss.sum()[None], valid.sum(dtype=jnp.int32)[None]

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, "tp"), P()),
        out_specs=(P("tp"), P("tp")),
        axis_names={"tp"},
        check_vma=False,
    )
    # partial-manual shard_map only traces under jit; harmless inside an
    # outer jit (the train step), makes eager calls work too
    loss, n = jax.jit(mapped)(flat_h, lm_head_kernel, flat_labels)
    return loss[0], n[0]


def kd_loss(
    student_logits: jnp.ndarray,
    teacher_logits: jnp.ndarray,
    labels: jnp.ndarray,
    temperature: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Forward-KL knowledge distillation (reference: loss/kd_loss.py:21).

    Returns (sum over valid tokens of KL(teacher || student), n_valid).
    """
    v = student_logits.shape[-1]
    s = student_logits.reshape(-1, v).astype(jnp.float32) / temperature
    t = teacher_logits.reshape(-1, v).astype(jnp.float32) / temperature
    lb = labels.reshape(-1)
    valid = lb != IGNORE_INDEX
    t_logp = jax.nn.log_softmax(t, axis=-1)
    s_logp = jax.nn.log_softmax(s, axis=-1)
    kl = jnp.sum(jnp.exp(t_logp) * (t_logp - s_logp), axis=-1) * (temperature**2)
    return jnp.where(valid, kl, 0.0).sum(), valid.sum()


def nemotron_parse_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    coordinate_weight: float = 10.0,
    class_token_start_idx: int = 50000,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Coordinate-weighted CE (reference NemotronParseLoss,
    models/nemotron_parse/nemotron_parse_loss.py:21-122): tokens with label
    id >= class_token_start_idx (bbox coordinate/class tokens in the OCR
    vocab) get their per-token loss multiplied by coordinate_weight; the sum
    is normalized by the UNWEIGHTED valid-token count (the reference divides
    by valid_tokens / num_label_tokens, both plain counts). Returns
    (weighted sum, n_valid) in the framework's standard loss contract."""
    v = logits.shape[-1]
    flat = logits.reshape(-1, v).astype(jnp.float32)
    lb = labels.reshape(-1)
    valid = lb != IGNORE_INDEX
    safe = jnp.where(valid, lb, 0)
    lse = jax.nn.logsumexp(flat, axis=-1)
    picked = jnp.take_along_axis(flat, safe[:, None], axis=-1)[:, 0]
    per_tok = jnp.where(valid, lse - picked, 0.0)
    w = jnp.where(lb >= class_token_start_idx, coordinate_weight, 1.0).astype(
        jnp.float32
    )
    return (per_tok * w).sum(), valid.sum()


LOSS_REGISTRY = {
    "masked_ce": masked_cross_entropy,
    "chunked_ce": chunked_cross_entropy,
    "fused_linear_ce": fused_linear_cross_entropy,
    "kd": kd_loss,
    "nemotron_parse": nemotron_parse_cross_entropy,
}


def build_loss(name: str = "masked_ce", **kwargs):
    fn = LOSS_REGISTRY[name]
    return functools.partial(fn, **kwargs) if kwargs else fn
