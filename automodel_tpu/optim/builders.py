"""Optimizer construction from config.

Parity: the reference instantiates plain ``_target_: torch.optim.*`` from
YAML (SURVEY.md §2.7). Here optimizers are optax chains; a YAML node like

    optimizer:
      _target_: automodel_tpu.optim.build_optimizer
      name: adamw
      lr: 1.e-4
      weight_decay: 0.01
      betas: [0.9, 0.95]
      grad_clip_norm: 1.0
      lr_schedule: {style: cosine, warmup_steps: 100, decay_steps: 1000}

builds clip → scale_by_adam → weight-decay → schedule. ``_target_:
optax.adamw``-style direct nodes also work through ConfigNode.instantiate.
"""

from __future__ import annotations

from typing import Any, Sequence

import optax

from automodel_tpu.optim.scheduler import build_lr_schedule

_SCALERS = {
    "adamw": lambda betas, eps: optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
    "adam": lambda betas, eps: optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
    "lion": lambda betas, eps: optax.scale_by_lion(b1=betas[0], b2=betas[1]),
    "sgd": lambda betas, eps: optax.trace(decay=betas[0]),
    "adafactor": None,  # handled specially
}


def build_optimizer(
    name: str = "adamw",
    lr: float = 1e-4,
    weight_decay: float = 0.0,
    betas: Sequence[float] = (0.9, 0.999),
    eps: float = 1e-8,
    grad_clip_norm: float | None = None,
    lr_schedule: Any | None = None,
    **sched_kwargs: Any,
) -> optax.GradientTransformation:
    # YAML 1.1 parses dotless scientific notation (`lr: 1e-2`) as a string;
    # coerce here so config-file values behave like `1.0e-2`
    lr, weight_decay, eps = float(lr), float(weight_decay), float(eps)
    betas = tuple(float(b) for b in betas)
    if grad_clip_norm is not None:
        grad_clip_norm = float(grad_clip_norm)
    if lr_schedule is not None:
        sched_kwargs = dict(lr_schedule)
    schedule = (
        build_lr_schedule(lr=lr, **sched_kwargs) if sched_kwargs else optax.constant_schedule(lr)
    )
    parts: list[optax.GradientTransformation] = []
    if grad_clip_norm:
        parts.append(optax.clip_by_global_norm(grad_clip_norm))
    if name == "adafactor":
        parts.append(optax.adafactor(learning_rate=schedule, weight_decay_rate=weight_decay or None))
        return optax.chain(*parts)
    if name == "muon":
        # Muon for >=2-D weights with adam fallback inside optax.contrib.muon
        # (parity: the reference's Dion/Muon integration, optim/utils.py:151)
        from optax import contrib as _contrib

        parts.append(
            _contrib.muon(
                learning_rate=schedule,
                adam_b1=betas[0],
                adam_b2=betas[1],
                weight_decay=weight_decay,
            )
        )
        return optax.chain(*parts)
    if name not in _SCALERS:
        raise ValueError(f"Unknown optimizer {name!r}; available: {sorted(_SCALERS)}")
    parts.append(_SCALERS[name](tuple(betas), eps))
    if weight_decay and name in ("adamw", "lion"):
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_learning_rate(schedule))
    return optax.chain(*parts)
